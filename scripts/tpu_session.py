"""One-lease TPU perf session: every round-5 measurement in one process.

The tunnel lease is exclusive and wedges easily (see docs/gotchas.md and
the verify skill), so ALL hardware asks of the round run back-to-back in
one interpreter, one compile cache, one lease:

  1. probe          tiny matmul — bail fast if the tunnel is wedged
  2. resnet-sweep   batch {128,256,512} x scan {1,8} train + fwd-only
  3. loader-fed     best resnet config driven through
                    DistributedDataLoader(prefetch=2) + C++ prefetcher
  4. lm-sweep       transformer LM: batch {8,16} x scan {1,8} x
                    remat {off,on} + flash block retune at seq 1024
  5. summary        one JSON line per measurement + a 'best' block to
                    bake into bench.py env defaults

Usage:  python scripts/tpu_session.py [--budget 3000] [--skip resnet,lm]
Everything is try/except'd: a failing config prints its error and the
session moves on. Safe to re-run — compiled programs persist in
/tmp/fluxmpi_tpu_xla_cache.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_KEEP_PLATFORM = False  # set by --allow-cpu (rehearsal mode)


def _tpu_env(extra: dict | None = None) -> dict:
    """Child env for TPU work: strip a lingering JAX_PLATFORMS (e.g. cpu
    from the documented CPU-fallback workflow) so children land on the
    axon TPU backend the probe validated — resnet_sweep pins whatever
    JAX_PLATFORMS says, so leaving it set could silently run the headline
    sweep on CPU while reporting v5e MFU. Rehearsal mode keeps it."""
    env = dict(os.environ)
    if not _KEEP_PLATFORM:
        env.pop("JAX_PLATFORMS", None)
    # The package lives in a source checkout; children launched from
    # scripts/ (resnet_sweep) need the repo root on their import path
    # even when the launcher's shell never exported PYTHONPATH.
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p
    )
    env.update(extra or {})
    return env


def probe(timeout_s: float = 240.0) -> dict | None:
    """Liveness first: a hung tunnel must not eat the budget.

    Caveat measured in round 5 (BENCH_NOTES_r05.md): after an UNCLEAN
    client kill the next backend init blocks ~1500 s (server lease TTL)
    and then succeeds, so a short probe timeout right after a kill reads
    as "dead" when the chip is merely queued. Callers recovering from a
    kill should pass timeout_s > 1560. Corollary: this script's own
    run_child timeouts are the kill mechanism that arms that TTL — size
    child budgets so children finish by themselves whenever possible."""
    code = (
        "import os, jax;"
        # An explicit JAX_PLATFORMS (rehearsal mode) must be pinned in
        # the config too — the sitecustomize's force-registered axon
        # platform wins over the env var and hangs on a wedged tunnel.
        "p = os.environ.get('JAX_PLATFORMS');"
        "p and jax.config.update('jax_platforms', p);"
        "import jax.numpy as jnp;"
        "d = jax.devices();"
        "x = jnp.ones((256, 256), jnp.bfloat16);"
        "(x @ x).block_until_ready();"
        "import json;"
        "print(json.dumps({'platform': d[0].platform,"
        " 'kind': d[0].device_kind, 'n': len(d)}))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, env=_tpu_env(),
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        print(json.dumps({"probe_error": proc.stderr.strip()[-300:]}),
              flush=True)
        return None
    return json.loads(proc.stdout.strip().splitlines()[-1])


def run_child(argv: list[str], timeout_s: float, env: dict | None = None):
    """One measurement = one child process: an OOM/compile blowup in a
    config cannot take down the session (the XLA cache makes respawns
    cheap)."""
    full_env = _tpu_env(env)
    t0 = time.time()
    timed_out = False
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
            env=full_env, cwd=REPO,
        )
        stdout, stderr, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        # Children emit one JSON line per finished measurement; a timeout
        # must salvage the lines that completed, not discard the run.
        timed_out = True
        stdout = (e.stdout or b"")
        stderr = (e.stderr or b"")
        if isinstance(stdout, bytes):
            stdout = stdout.decode(errors="replace")
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        rc = -1
    out = []
    for line in (stdout or "").strip().splitlines():
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            pass
    res: dict = {"results": out, "wall_s": round(time.time() - t0, 1)}
    if timed_out:
        res["error"] = f"timeout {timeout_s}s (partial results salvaged)"
        if stderr:
            res["stderr_tail"] = stderr[-300:]
    elif rc != 0:
        if not out:
            return {"argv": argv[-2:], "error": (stderr or "")[-300:],
                    "wall_s": round(time.time() - t0, 1)}
        res["rc"] = rc  # crashed after emitting rows: partial, not clean
        res["stderr_tail"] = (stderr or "")[-300:]
    return res


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=3600.0)
    ap.add_argument("--skip", default="",
                    help="comma list: resnet,loader,lm,attention")
    ap.add_argument("--trace", action="store_true",
                    help="XPlane-trace the best resnet config")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="rehearsal mode: don't abort when the probe "
                         "lands on CPU (children label their platform)")
    args = ap.parse_args()
    if args.allow_cpu:
        global _KEEP_PLATFORM
        _KEEP_PLATFORM = True
    skip = set(s for s in args.skip.split(",") if s)
    t_start = time.monotonic()

    def remaining() -> float:
        return args.budget - (time.monotonic() - t_start)

    # The axon tunnel releases its exclusive lease minutes after the
    # previous holder exits; a single CPU-fallback probe right after a
    # kill is a race, not an outage. Retry with backoff before giving up.
    p = None
    for attempt in range(5):
        p = probe()
        print(json.dumps({"probe": p, "attempt": attempt}), flush=True)
        if p is None:
            # Hung probe: either a truly dead tunnel OR a chip queued
            # behind a stale lease (~1500 s TTL after an unclean kill —
            # see probe()'s docstring). One attempt must outlast the TTL
            # before we may conclude "dead"; only do it if the budget
            # survives the wait.
            if remaining() > 1800 + 900:
                p = probe(timeout_s=1800.0)
                print(json.dumps({"probe": p, "attempt": "lease-ttl"}),
                      flush=True)
            break
        if args.allow_cpu or p.get("platform") != "cpu":
            break
        if attempt == 4 or remaining() < 600:
            break
        time.sleep(120)
    if p is None or (p.get("platform") == "cpu" and not args.allow_cpu):
        print(json.dumps({"session": "aborted", "reason": "no live TPU"}),
              flush=True)
        return

    report: dict = {"probe": p, "sections": {}}
    # bench children: "" = use the env default (axon TPU); in rehearsal
    # pin them to the probe's platform so they can't hang on a wedged
    # tunnel.
    bench_platform = p.get("platform", "") if args.allow_cpu else ""

    # --- 2. ResNet sweep (the round's #1 ask) -------------------------
    if "resnet" not in skip and remaining() > 900:
        sweep_args = ["--batches", "128,256,512", "--scan", "1,8"]
        if args.allow_cpu:
            # rehearsal sizes: validate orchestration, not the chip
            sweep_args = ["--quick", "--batches", "2", "--scan", "1,2",
                          "--image", "32", "--dtype", "float32"]
        r = run_child(
            [sys.executable, "scripts/resnet_sweep.py", *sweep_args]
            + (["--trace"] if args.trace else []),
            min(2400.0, remaining() - 600),
        )
        report["sections"]["resnet_sweep"] = r
        print(json.dumps({"resnet_sweep": r}), flush=True)

    # --- 3. Loader-fed with the bench's own harness -------------------
    if "loader" not in skip and remaining() > 600:
        rows = (report["sections"].get("resnet_sweep") or {}).get("results", [])
        best = max(
            (x for x in rows if x.get("mode") == "train" and "mfu" in x),
            key=lambda x: x["mfu"], default=None,
        )
        env = {"FLUXMPI_TPU_BENCH_PLATFORM": bench_platform}
        if best:
            env["FLUXMPI_TPU_RESNET_BATCH"] = str(best["batch"])
            if best.get("scan", 1) > 1:
                env["FLUXMPI_TPU_BENCH_SCAN_STEPS"] = str(best["scan"])
        r = run_child(
            [sys.executable, "bench.py", "--child", "resnet50"],
            min(1200.0, remaining() - 300), env,
        )
        report["sections"]["resnet_bench_child"] = r
        print(json.dumps({"resnet_bench_child": r}), flush=True)

    # --- 4. Transformer LM sweep --------------------------------------
    if "lm" not in skip and remaining() > 300:
        lm_rows = []
        grid: list[dict] = [
            {},  # fused-CE head (default), r3 batch
            {"FLUXMPI_TPU_LM_FUSED_CE": "0"},  # dense-head A/B
            {"FLUXMPI_TPU_BENCH_SCAN_STEPS": "8"},
            {"FLUXMPI_TPU_LM_BATCH": "16"},
            {"FLUXMPI_TPU_LM_BATCH": "16",
             "FLUXMPI_TPU_BENCH_SCAN_STEPS": "8"},
            {"FLUXMPI_TPU_LM_BATCH": "32"},  # fused head frees the logits HBM
            {"FLUXMPI_TPU_BENCH_REMAT": "dots", "FLUXMPI_TPU_LM_BATCH": "32"},
            {"FLUXMPI_TPU_BENCH_REMAT": "1", "FLUXMPI_TPU_LM_BATCH": "32"},
            {"FLUXMPI_TPU_LM_BLOCK_Q": "512", "FLUXMPI_TPU_LM_BLOCK_K": "1024"},
            {"FLUXMPI_TPU_LM_BLOCK_Q": "256", "FLUXMPI_TPU_LM_BLOCK_K": "512"},
        ]
        for env in grid:
            if remaining() < 240:
                lm_rows.append({"env": env, "error": "budget exhausted"})
                break
            env = {"FLUXMPI_TPU_BENCH_PLATFORM": bench_platform, **env}
            r = run_child(
                [sys.executable, "bench.py", "--child", "transformer"],
                min(600.0, remaining() - 60), env,
            )
            row = {"env": {k: v for k, v in env.items()
                           if k != "FLUXMPI_TPU_BENCH_PLATFORM"}, **r}
            lm_rows.append(row)
            print(json.dumps({"lm": row}), flush=True)
        report["sections"]["lm_sweep"] = lm_rows

    # --- 4b. Band-only kernel compile probe (round-5 windowed flash
    # ring mode: causal=False + window has only ever compiled in
    # interpret mode) ---------------------------------------------------
    if "attention" not in skip and remaining() > 240:
        code = (
            "import jax, jax.numpy as jnp, numpy as np;"
            "from fluxmpi_tpu.ops import flash_attention_with_lse as f;"
            "q = jnp.ones((2, 256, 4, 64), jnp.bfloat16);"
            "o, l = f(q, q, q, causal=False, window=64,"
            " block_q=128, block_k=128);"
            "g = jax.grad(lambda q: f(q, q, q, causal=False, window=64,"
            " block_q=128, block_k=128)[0].astype(jnp.float32).sum())(q);"
            "import json;"
            "print(json.dumps({'band_kernel': 'ok',"
            " 'finite': bool(np.isfinite(np.asarray(g, np.float32)).all())}))"
        )
        r = run_child([sys.executable, "-c", code],
                      min(420.0, remaining() - 60))
        report["sections"]["band_kernel_probe"] = r
        print(json.dumps({"band_kernel_probe": r}), flush=True)

    # --- 5. Attention kernels (r4 layout change never TPU-validated) --
    if "attention" not in skip and remaining() > 300:
        r = run_child(
            [sys.executable, "bench.py", "--child", "attention"],
            min(900.0, remaining() - 30),
            {"FLUXMPI_TPU_BENCH_PLATFORM": bench_platform},
        )
        report["sections"]["attention"] = r
        print(json.dumps({"attention": r}), flush=True)

    with open("/tmp/tpu_session_report.json", "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({"session": "done",
                      "report": "/tmp/tpu_session_report.json",
                      "wall_s": round(time.monotonic() - t_start, 1)}),
          flush=True)


if __name__ == "__main__":
    main()
