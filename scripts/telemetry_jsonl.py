"""Shared JSONL-scan helper for the scripts/ report CLIs.

``goodput_report.py``, ``serving_report.py``, and ``fleet_report.py``
all read the same kind of artifact — per-host JSONL banks a run
appended to until it (possibly) died mid-write — and they must agree on
the tolerance contract:

- a missing/unreadable FILE is the caller's problem (collected into the
  returned ``errors`` list; one-shot report modes exit 2 on it, watch
  modes render it as a waiting state);
- a torn or corrupt LINE (a host killed mid-write — the very
  post-mortem these reports serve) is skipped with a stderr warning
  naming the tool, file, and line, and is NEVER fatal: the complete
  records around it still carry the data;
- non-object JSON lines are dropped silently (foreign stream noise).

Stdlib-only, no jax, no package import — the same runnable-anywhere
contract as the reports themselves. Imported as a sibling module: the
reports put their own directory on ``sys.path`` first, so both
``python scripts/goodput_report.py`` and the test suite's
import-by-file-path find it.
"""

from __future__ import annotations

import json
import sys
from typing import Any


def scan_jsonl(
    paths: list[str], tool: str
) -> tuple[list[tuple[str, int, dict]], list[str]]:
    """Every well-formed JSON object line across ``paths``, in
    file-then-line order.

    Returns ``(rows, errors)``: rows are ``(path, lineno, record)``
    triples; errors are per-file open/read failures (the caller decides
    whether those are fatal). ``tool`` names the report in the
    torn-line stderr warning.
    """
    rows: list[tuple[str, int, dict]] = []
    errors: list[str] = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                content = f.read()
        except OSError as exc:
            errors.append(f"{path}: {exc}")
            continue
        for i, line in enumerate(content.splitlines(), 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                print(
                    f"{tool}: skipping {path}:{i}: not JSON: {exc}",
                    file=sys.stderr,
                )
                continue
            if isinstance(rec, dict):
                rows.append((path, i, rec))
    return rows, errors


def process_of(rec: dict[str, Any]) -> int:
    """The record's host process index (0 when absent or invalid)."""
    proc = rec.get("process")
    return proc if isinstance(proc, int) else 0
