#!/bin/bash
# Relaunch loop for scripts/patient_session.py — the no-kill recovery
# mode for the axon lease TTL (BENCH_NOTES_r05.md). Rules it encodes:
#   - NEVER wrap the session in `timeout`: the kill is what arms the
#     ~1500 s TTL. The session blocks through a TTL-length init and
#     exits by itself in the erroring-service mode.
#   - Relaunch after the session exits ON ITS OWN (self-exits — clean
#     returns AND python exceptions — close the connection gracefully
#     and do not arm the TTL; only external kills do, and nothing here
#     kills), until an attempt reaches a real TPU or the cap is hit.
#   - Success is judged only on lines THIS attempt appended to the
#     results file (it is append-only across runs).
# Usage: nohup bash scripts/patient_watch.sh [budget] &
LOG=/tmp/patient_watch.log
OUT=/tmp/patient_session.jsonl
BUDGET=${1:-9000}
cd "$(dirname "$0")/.." || exit 1
touch "$OUT"
for i in $(seq 1 12); do
  before=$(wc -l < "$OUT")
  echo "[$(date -u +%H:%M:%S)] patient attempt $i (out lines: $before)" >> "$LOG"
  python -u scripts/patient_session.py --budget "$BUDGET" --out "$OUT" \
    >> /tmp/patient_session.log 2>&1
  rc=$?
  echo "[$(date -u +%H:%M:%S)] attempt $i exit rc=$rc" >> "$LOG"
  if tail -n +"$((before + 1))" "$OUT" | grep -q '"platform": "tpu"'; then
    echo "[$(date -u +%H:%M:%S)] TPU session ran - stopping loop" >> "$LOG"
    exit 0
  fi
  sleep 120
done
