#!/bin/bash
# Probe the axon TPU tunnel until it comes back; when it does, run the
# one-lease perf session immediately (scripts/tpu_session.py) so zero
# chip time is wasted waiting for a human/agent poll.
# One probe at a time, 10-min gaps (wedged-tunnel etiquette).
LOG=/tmp/tpu_watch.log
OK=/tmp/tpu_alive
SESSION_LOG=/tmp/tpu_session.log
cd "$(dirname "$0")/.." || exit 1
rm -f "$OK"
for i in $(seq 1 60); do
  echo "[$(date -u +%H:%M:%S)] probe attempt $i" >> "$LOG"
  # Every 4th attempt probes long enough (1800 s) to outlast the ~1500 s
  # stale-lease TTL (BENCH_NOTES_r05.md): after an unclean client kill,
  # backend init BLOCKS ~25 min then succeeds — a 300 s probe would call
  # that chip dead forever, and its own SIGKILL re-arms the TTL.
  PROBE_TIMEOUT=300
  if [ $((i % 4)) -eq 0 ]; then PROBE_TIMEOUT=1800; fi
  timeout $PROBE_TIMEOUT python -u -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((256,256), jnp.bfloat16)
(x@x).block_until_ready()
print('ALIVE', d[0].platform, d[0].device_kind, len(d))
" >> "$LOG" 2>&1
  rc=$?
  echo "[$(date -u +%H:%M:%S)] rc=$rc" >> "$LOG"
  if [ $rc -eq 0 ] && grep -q ALIVE "$LOG"; then
    touch "$OK"
    echo "[$(date -u +%H:%M:%S)] TPU ALIVE - starting one-lease session" >> "$LOG"
    timeout 5400 python scripts/tpu_session.py --budget 4500 --trace > "$SESSION_LOG" 2>&1
    echo "[$(date -u +%H:%M:%S)] session done rc=$? (report: /tmp/tpu_session_report.json)" >> "$LOG"
    exit 0
  fi
  sleep 600
done
