#!/bin/bash
# Probe the axon TPU tunnel until it comes back; log status to /tmp/tpu_watch.log.
# One probe at a time, 10-min gaps (wedged-tunnel etiquette).
LOG=/tmp/tpu_watch.log
OK=/tmp/tpu_alive
rm -f "$OK"
for i in $(seq 1 60); do
  echo "[$(date -u +%H:%M:%S)] probe attempt $i" >> "$LOG"
  timeout 300 python -u -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((256,256), jnp.bfloat16)
(x@x).block_until_ready()
print('ALIVE', d[0].platform, d[0].device_kind, len(d))
" >> "$LOG" 2>&1
  rc=$?
  echo "[$(date -u +%H:%M:%S)] rc=$rc" >> "$LOG"
  if [ $rc -eq 0 ] && grep -q ALIVE "$LOG"; then
    touch "$OK"
    echo "[$(date -u +%H:%M:%S)] TPU ALIVE — stopping watch" >> "$LOG"
    exit 0
  fi
  sleep 600
done
