"""ResNet-50 MFU sweep — one TPU session, many configs.

The round-3 verdict's top item: single-chip ResNet-50 MFU was 16.5%
(2643 img/s on v5e) while the transformer hits 45% on the same chip, so
the conv/BN path needs a profile-driven pass. This script measures, in
ONE process (one tunnel lease, one compile cache):

  1. per-chip batch sweep (128 / 256 / 512),
  2. forward-only vs full train step (locates fwd/bwd imbalance),
  3. BN-variant ablation (batch_stats sync on/off, f32 vs bf16 head),
  4. scan-steps ablation (--scan K: K optimizer updates per dispatch via
     make_train_step(scan_steps=K) — isolates host/tunnel dispatch
     latency, the prime suspect when per-step wall time is tens of ms),
  5. optional XPlane trace of the best config (--trace).

Usage:  python scripts/resnet_sweep.py [--quick] [--trace] [--scan 1,8]
Writes one JSON line per measurement; safe to tee into a log.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runs from a source checkout: `python scripts/resnet_sweep.py` puts
# scripts/ (not the repo root) at sys.path[0].
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def _sync(x):
    np.asarray(jax.device_get(x))


def steps_per_sec(step, state, data, warmup, steps):
    loss = None
    for _ in range(warmup):
        state, loss = step(state, data)
    _sync(loss)
    n1 = max(2, steps // 5)
    t0 = time.perf_counter()
    for _ in range(n1):
        state, loss = step(state, data)
    _sync(loss)
    t1 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, data)
    _sync(loss)
    t2 = time.perf_counter()
    dt = (t2 - t1) - (t1 - t0)
    n = steps - n1
    return (n / dt if dt > 0 else steps / (t2 - t1)), state


PEAK = 197e12  # v5e bf16


def _flops_per_image(image: int) -> float:
    """ResNet-50 forward FLOPs per image: 4.09 GFLOPs at 224px, scaling
    ~quadratically with image side (conv spatial extents) — keeps smoke
    runs at other sizes from reporting 224px-inflated MFU."""
    return 4.09e9 * (image / 224.0) ** 2


def bench_config(batch, *, train=True, steps=20, head_dtype=jnp.float32,
                 scan=1, image=224, dtype=jnp.bfloat16):
    import optax

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import ResNet50
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    mesh = fm.init(devices=jax.devices()[:1])
    model = ResNet50(num_classes=1000, dtype=dtype)
    x = jnp.ones((batch, image, image, 3), dtype)
    y = jnp.zeros((batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=False)
    params, mstate = variables["params"], variables.get("batch_stats")

    def loss_fn(p, ms, b):
        bx, by = b
        logits, updates = model.apply(
            {"params": p, "batch_stats": ms}, bx, train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(head_dtype), by
        ).mean()
        return loss, updates["batch_stats"]

    if train:
        step = make_train_step(
            loss_fn, optax.sgd(0.1, momentum=0.9), mesh=mesh, style="auto",
            scan_steps=scan,
        )
        state = replicate(
            TrainState.create(params, optax.sgd(0.1, momentum=0.9), mstate),
            mesh,
        )
        if scan > 1:
            # K distinct batches per dispatch; the measured rate below is
            # per CALL, so flops carries the factor K.
            x = jnp.broadcast_to(x, (scan, *x.shape))
            y = jnp.broadcast_to(y, (scan, *y.shape))
        flops = 3 * _flops_per_image(image) * batch * scan
    else:
        @jax.jit
        def fwd(p, ms, b):
            logits = model.apply(
                {"params": p, "batch_stats": ms}, b[0], train=False
            )
            return logits.astype(head_dtype).sum()

        def step(state, data):
            p, ms = state
            return state, fwd(p, ms, data)

        state = (params, mstate)
        flops = _flops_per_image(image) * batch

    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu import config as fm_config

    dp = fm_config.DP_AXIS_NAME
    spec = P(None, dp) if (train and scan > 1) else P(dp)
    data = shard_batch((x, y), mesh, spec=spec)
    t0 = time.perf_counter()
    rate, state = steps_per_sec(step, state, data, warmup=3, steps=steps)
    return {
        "batch": batch,
        "mode": "train" if train else "fwd",
        "scan": scan,
        "image": image,
        "dtype": jnp.dtype(dtype).name,
        "img_per_sec": round(batch * scan * rate, 1),
        "mfu": round(flops * rate / PEAK, 4),
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def main():
    # An explicit JAX_PLATFORMS must be pinned in the config too: the
    # container's sitecustomize force-registers the axon TPU platform,
    # which wins over the env var — and a wedged tunnel then HANGS
    # backend init instead of failing fast (see docs/gotchas.md).
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    try:  # persist compiled programs across sweep invocations
        jax.config.update(
            "jax_compilation_cache_dir", "/tmp/fluxmpi_tpu_xla_cache"
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--trace", action="store_true")
    ap.add_argument("--batches", default="128,256,512")
    ap.add_argument("--scan", default="1",
                    help="comma list of scan_steps to ablate (train only)")
    ap.add_argument("--image", type=int, default=224,
                    help="image side (small values = CPU plumbing smoke)")
    ap.add_argument("--dtype", default="bfloat16",
                    help="model/activation dtype (float32 for CPU smoke — "
                         "bf16 emulation on CPU is pathologically slow)")
    args = ap.parse_args()
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[args.dtype]

    batches = [int(b) for b in args.batches.split(",")]
    scans = [int(s) for s in args.scan.split(",")]
    if args.quick:
        batches = batches[:1]

    results = []
    for b in batches:
        for train in (True, False) if not args.quick else (True,):
            for scan in scans if train else [1]:
                try:
                    r = bench_config(
                        b, train=train, steps=10 if args.quick else 20,
                        scan=scan, image=args.image, dtype=dtype,
                    )
                except Exception as exc:
                    r = {"batch": b, "train": train, "scan": scan,
                         "error": repr(exc)[:200]}
                results.append(r)
                print(json.dumps(r), flush=True)

    if args.trace and results:
        best = max(
            (r for r in results if r.get("mode") == "train" and "mfu" in r),
            key=lambda r: r["mfu"],
            default=None,
        )
        if best:
            from fluxmpi_tpu.utils.profiling import profile_trace

            with profile_trace("/tmp/resnet_trace"):
                bench_config(best["batch"], train=True, steps=5,
                             scan=best.get("scan", 1), image=args.image,
                             dtype=dtype)
            print(json.dumps({"trace": "/tmp/resnet_trace"}), flush=True)


if __name__ == "__main__":
    main()
