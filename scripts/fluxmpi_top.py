#!/usr/bin/env python
"""fluxmpi_top: a refreshing terminal view of a running fleet.

Polls the live export plane's ``/status`` endpoint
(``init(export=...)`` / ``FLUXMPI_TPU_EXPORT_PORT``, see
docs/observability.md "Live export") across a host list and renders one
row per host — step count and live step rate, loss, goodput fraction
and MFU, heartbeat age, straggler flag, health verdict — plus an
anomaly ticker of the most recent triggers fleet-wide:

    $ python scripts/fluxmpi_top.py tpu-host-0 tpu-host-1:9307
    fluxmpi_top  2 host(s)  13:37:02  run 6a71-1919  phase running
    HOST             STEP     UP/S    LOSS  GOODPUT    MFU  HB AGE  HEALTH
    tpu-host-0       9600     81.2  0.0312    91.2%  0.412    2.1s  ok
    tpu-host-1       9600     80.9  0.0312    90.8%  0.409    2.3s  ok
    anomalies: (none)

Hosts running the model-internals plane (``init(model_stats=True)``)
additionally get a MODEL block — gradient noise scale (B_simple) and
the top-k layers by gradient norm, with a NONFINITE ticker naming the
offending layer when NaN provenance fired; the anomaly ticker renders
the triggering event's labels (layer / function), not just the rule id.
Hosts running the serving plane (``fluxmpi_tpu.serving``) additionally
get a SERVING block — active/queued requests, live decode step rate,
token counter, KV block utilization, completions/rejects, and an
SLO-violation ticker — rendered from the ``serving`` section of the
same ``/status`` snapshot. With the request-observability plane on
(``init(request_log=...)``), the ticker adds the live SLO burn rate,
TTFT p50/p99, the KV high watermark/fragmentation, and the worst
offenders by TTFT. Hosts saving checkpoints get a CHECKPOINT block —
last committed step and its tier (local/durable), whether async saves
are on, the in-flight background save's step and age, and the
superseded-request count; a live N→M resize
(``fluxmpi_tpu.fleet.resize``) adds a RESIZE block — current pipeline
phase (drain/save/handoff/reshard/completed), the from→to world sizes,
and the per-phase badput seconds attributed so far.

Targets are ``host``, ``host:port`` (default port 9307), or full URLs.
``--jsonl FILE...`` is the fallback for runs without an exporter: the
same view re-derived from the growing telemetry JSONL bank (last record
per process; heartbeat age from ``monitor.heartbeat_unix``) — health
then reads ``jsonl`` because there is no live probe to ask.

Usage:
    python scripts/fluxmpi_top.py HOST [HOST ...] [--interval N]
    python scripts/fluxmpi_top.py --jsonl run.*.jsonl [--interval N]
    python scripts/fluxmpi_top.py HOST --once [--json]

``--once`` renders a single frame and exits (scripting/tests); ``--json``
prints the raw per-host status objects as one JSON line instead of the
table. Exit codes (``--once``): 0 = at least one host reported;
2 = nothing reachable/readable.

Stdlib-only, no jax, no package import — runnable from a laptop against
a pod (the ``goodput_report.py`` / ``check_metrics_schema.py``
contract).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any

DEFAULT_PORT = 9307  # telemetry/export.py DEFAULT_PORT (kept in sync)

_CLEAR = "\x1b[2J\x1b[H"


def _base_url(target: str) -> str:
    if target.startswith(("http://", "https://")):
        return target.rstrip("/")
    if ":" not in target:
        target = f"{target}:{DEFAULT_PORT}"
    return f"http://{target}"


def fetch_status(target: str, timeout: float = 2.0) -> dict[str, Any] | None:
    """One host's ``/status`` snapshot, or None when unreachable/bad."""
    try:
        with urllib.request.urlopen(
            _base_url(target) + "/status", timeout=timeout
        ) as resp:
            rec = json.load(resp)
    except (urllib.error.URLError, OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


# ---------------------------------------------------------------------------
# JSONL fallback: the same row, re-derived from the metrics bank.
# ---------------------------------------------------------------------------


def _jsonl_statuses(paths: list[str]) -> dict[str, dict[str, Any]]:
    """Last flush record per process across the JSONL files, reshaped
    into /status-like objects (the subset the table renders). Torn lines
    are skipped — the bank is being written while we read it."""
    per_process: dict[int, dict[str, Any]] = {}
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                content = f.read()
        except OSError:
            continue
        for line in content.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn mid-write line: expected on a live bank
            if not isinstance(rec, dict) or not isinstance(
                rec.get("metrics"), list
            ):
                continue
            proc = rec.get("process")
            per_process[proc if isinstance(proc, int) else 0] = rec
    out: dict[str, dict[str, Any]] = {}
    for proc in sorted(per_process):
        rec = per_process[proc]
        flat: dict[str, float] = {}
        buckets: dict[str, float] = {}
        for m in rec["metrics"]:
            if not isinstance(m, dict) or "value" not in m:
                continue
            name = m.get("name")
            if name == "goodput.bucket_seconds":
                bucket = (m.get("labels") or {}).get("bucket")
                if isinstance(bucket, str):
                    buckets[bucket] = float(m["value"])
            elif isinstance(name, str) and not m.get("labels"):
                flat[name] = float(m["value"])
        goodput = None
        if "goodput.wall_seconds" in flat:
            goodput = {
                "wall_seconds": flat["goodput.wall_seconds"],
                "goodput_fraction": flat.get("goodput.fraction", 0.0),
                "updates": int(flat.get("goodput.updates", 0)),
                "mfu": flat.get("goodput.mfu"),
                "buckets": buckets,
            }
        hb_unix = flat.get("monitor.heartbeat_unix")
        monitor: dict[str, float] = {
            name[len("monitor."):]: value
            for name, value in flat.items()
            if name.startswith("monitor.")
        }
        out[f"proc{proc}"] = {
            "process": proc,
            "time_unix": rec.get("time_unix"),
            "train": {
                "updates": int(flat.get("train.steps", 0)),
                "loss": flat.get("train.loss"),
                "examples_per_sec": flat.get("train.examples_per_sec"),
            },
            "goodput": goodput,
            "anomaly": None,
            "monitor": monitor,
            "health": {"healthy": None, "source": "jsonl"},
            "heartbeat_age_override": (
                time.time() - hb_unix if hb_unix else None
            ),
        }
    return out


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _fmt(value: Any, spec: str, dash: str = "-") -> str:
    if value is None:
        return dash
    try:
        return format(value, spec)
    except (TypeError, ValueError):
        return dash


def _row(
    name: str,
    status: dict[str, Any] | None,
    rates: dict[str, tuple[float, float]],
    seen: dict[str, float] | None = None,
) -> str:
    if status is None:
        # A host that ANSWERED earlier in this session and then went
        # quiet is STALE (likely hung or dead mid-run — the interesting
        # case), with its last-seen age; one that never answered is
        # plain UNREACHABLE (wrong target, exporter not up yet).
        last = (seen or {}).get(name)
        if last is not None:
            return f"{name:<18} STALE (last seen {time.time() - last:.0f}s ago)"
        return f"{name:<18} UNREACHABLE"
    train = status.get("train") or {}
    updates = train.get("updates")
    # Live step rate from successive polls (cumulative counter delta);
    # the first poll has no delta yet.
    rate = None
    now = time.time()
    if isinstance(updates, (int, float)):
        prev = rates.get(name)
        if prev is not None and now > prev[0] and updates >= prev[1]:
            rate = (updates - prev[1]) / (now - prev[0])
        rates[name] = (now, float(updates))
    gp = status.get("goodput") or {}
    monitor = status.get("monitor") or {}
    hb_age = status.get("heartbeat_age_override")
    if hb_age is None:
        hb_age = monitor.get("heartbeat_age_seconds")
    health = status.get("health") or {}
    healthy = health.get("healthy")
    if healthy is None:
        verdict = health.get("source", "?")
    elif healthy:
        verdict = "ok"
    else:
        verdict = "STALLED"
    straggler = " *" if monitor.get("straggler") else ""
    frac = gp.get("goodput_fraction")
    return (
        f"{name:<18}"
        f"{_fmt(updates, '>8.0f'):>8} "
        f"{_fmt(rate, '>7.1f'):>7} "
        f"{_fmt(train.get('loss'), '>8.4g'):>8} "
        f"{_fmt(100 * frac if frac is not None else None, '>7.1f'):>7}% "
        f"{_fmt(gp.get('mfu'), '>6.3f'):>6} "
        f"{_fmt(hb_age, '>6.1f'):>6}s "
        f"{verdict}{straggler}"
    )


def _serving_rows(
    statuses: dict[str, dict[str, Any] | None],
    rates: dict[str, tuple[float, float]],
) -> list[str]:
    """The serving view: one row per host that carries a ``serving``
    board (the continuous-batching inference engine posts it to
    ``/status``) — active/queued requests, live decode step rate from
    counter deltas, KV block utilization, token and SLO counters — plus
    an SLO-violation ticker."""
    rows: list[str] = []
    tickers: list[str] = []
    now = time.time()
    for name, status in statuses.items():
        srv = (status or {}).get("serving")
        if not isinstance(srv, dict):
            continue
        if not rows:
            rows.append(
                f"{'SERVING':<18}{'ACT':>5} {'QUEUED':>7} {'STEP/S':>7} "
                f"{'TOKENS':>8} {'KV USE':>7} {'DONE':>6} {'REJ':>5}  PHASE"
            )
        steps = srv.get("decode_steps")
        rate = None
        if isinstance(steps, (int, float)):
            prev = rates.get(name + "#serving")
            if prev is not None and now > prev[0] and steps >= prev[1]:
                rate = (steps - prev[1]) / (now - prev[0])
            rates[name + "#serving"] = (now, float(steps))
        util = srv.get("kv_util")
        rows.append(
            f"{name:<18}"
            f"{_fmt(srv.get('active'), '>5.0f'):>5} "
            f"{_fmt(srv.get('queued'), '>7.0f'):>7} "
            f"{_fmt(rate, '>7.1f'):>7} "
            f"{_fmt(srv.get('tokens'), '>8.0f'):>8} "
            f"{_fmt(100 * util if util is not None else None, '>6.1f'):>6}% "
            f"{_fmt(srv.get('completed'), '>6.0f'):>6} "
            f"{_fmt(srv.get('rejected'), '>5.0f'):>5}  "
            f"{srv.get('phase', '?')}"
        )
        slo = srv.get("slo_violations")
        if isinstance(slo, (int, float)) and slo > 0:
            tickers.append(f"  {name}: {int(slo)} SLO violation(s)")
        # Request-observability extras (absent when the host runs
        # without init(request_log=...) — the board only carries them
        # when the observer is installed).
        if srv.get("requests_logged") is not None:
            burn = srv.get("burn_rate")
            p50, p99 = srv.get("ttft_p50"), srv.get("ttft_p99")
            peak = srv.get("kv_high_watermark")
            frag = srv.get("kv_fragmentation")
            line = (
                f"  {name}: burn {_fmt(burn, '.2f')}x  "
                f"ttft p50 {_fmt(p50, '.3f')}s p99 {_fmt(p99, '.3f')}s  "
                f"kv peak {_fmt(peak, '.0f')} "
                f"frag {_fmt(100 * frag if frag is not None else None, '.0f')}%"
            )
            offenders = srv.get("top_offenders")
            if isinstance(offenders, list) and offenders:
                line += "  worst " + " ".join(
                    f"#{o.get('request_id')} {_fmt(o.get('ttft_s'), '.3f')}s"
                    for o in offenders[:3]
                    if isinstance(o, dict)
                )
            tickers.append(line)
    if rows:
        rows.append("slo:" + (" (none)" if not tickers else ""))
        rows.extend(tickers)
    return rows


def _model_rows(statuses: dict[str, Any]) -> list[str]:
    """The MODEL block: one row per host whose ``/status`` carries a
    ``model`` board (the model-internals plane posts it at flush
    boundaries) — gradient noise scale (B_simple) and the top-k layers
    by gradient norm, plus a nonfinite-layer ticker when NaN provenance
    fired."""
    rows: list[str] = []
    tickers: list[str] = []
    for name, status in statuses.items():
        board = (status or {}).get("model")
        if not isinstance(board, dict):
            continue
        if not rows:
            rows.append(f"{'MODEL':<18}{'NOISE B':>9}  TOP LAYERS BY GRAD NORM")
        ns = board.get("noise_scale")
        top = board.get("top")
        top_str = "-"
        if isinstance(top, list) and top:
            top_str = "  ".join(
                f"{t.get('layer')}={_fmt(t.get('grad_norm'), '.3g')}"
                for t in top
                if isinstance(t, dict)
            )
        rows.append(f"{name:<18}{_fmt(ns, '>9.3g'):>9}  {top_str}")
        bad = board.get("nonfinite_layer")
        if isinstance(bad, str) and bad:
            tickers.append(
                f"  {name}: NONFINITE gradients in {bad} "
                f"(step {board.get('step')})"
            )
    rows.extend(tickers)
    return rows


def _parallel_rows(statuses: dict[str, Any]) -> list[str]:
    """The PARALLEL block: one row per host whose ``/status`` carries a
    ``parallel`` board (``init(parallel=)`` posts it; ``shard_state``
    refreshes the rule hit counts) — the resolved mesh shape, the
    effective data-parallel worker count, and how many parameter leaves
    each rule source (user table, TP table, FSDP fallback, replicated)
    claimed."""
    rows: list[str] = []
    for name, status in statuses.items():
        board = (status or {}).get("parallel")
        if not isinstance(board, dict):
            continue
        if not rows:
            rows.append(f"{'PARALLEL':<18}{'DP':>5}  MESH / RULE HITS")
        mesh = board.get("mesh")
        mesh_str = "-"
        if isinstance(mesh, dict) and mesh:
            mesh_str = "x".join(
                f"{axis}:{size}" for axis, size in mesh.items()
            )
        hits = board.get("rule_hits")
        hits_str = ""
        if isinstance(hits, dict) and hits:
            hits_str = "  " + " ".join(
                f"{source}={count}" for source, count in sorted(hits.items())
            )
        rows.append(
            f"{name:<18}"
            f"{_fmt(board.get('data_parallel_size'), '>5.0f'):>5}  "
            f"{mesh_str}{hits_str}"
        )
    return rows


def _autotune_rows(statuses: dict[str, Any]) -> list[str]:
    """The AUTOTUNE block: one row per host whose ``/status`` carries an
    ``autotune`` board (``parallel/autotune.autotune`` posts it when a
    layout search completes or a banked winner is reused) — the winning
    axes, the enumerate/prune/trial census, the best trial throughput,
    and whether the bank answered (``hit``) or trials ran (``tuned``)."""
    rows: list[str] = []
    for name, status in statuses.items():
        board = (status or {}).get("autotune")
        if not isinstance(board, dict):
            continue
        if not rows:
            rows.append(
                f"{'AUTOTUNE':<18}{'CAND':>5} {'PRUNED':>7} {'TRIALS':>7}"
                "  WINNER / BANK"
            )
        winner = board.get("winner")
        winner_str = "-"
        if isinstance(winner, dict) and winner:
            winner_str = "x".join(
                f"{axis}:{size}"
                for axis, size in winner.items()
                if isinstance(size, int) and size > 1
            ) or "dp:1"
        pruned = (board.get("pruned_memory") or 0) + (
            board.get("pruned_dominated") or 0
        )
        detail = f"{winner_str} [{board.get('bank', '?')}]"
        eps = board.get("best_examples_per_sec")
        if isinstance(eps, (int, float)):
            detail += f" {eps:.1f} ex/s"
        rows.append(
            f"{name:<18}"
            f"{_fmt(board.get('candidates'), '>5.0f'):>5} "
            f"{pruned:>7} "
            f"{_fmt(board.get('trials'), '>7.0f'):>7}  "
            f"{detail}"
        )
    return rows


def _checkpoint_rows(statuses: dict[str, Any]) -> list[str]:
    """The CHECKPOINT block: one row per host whose ``/status`` carries
    a ``checkpoint`` board (:class:`CheckpointManager` posts it after
    every save request and writer completion) — the last committed step
    and its tier, whether async saves are on, the in-flight background
    save's step and age, and the superseded-request count (overlapping
    async requests coalesced away)."""
    rows: list[str] = []
    now = time.time()
    for name, status in statuses.items():
        board = (status or {}).get("checkpoint")
        if not isinstance(board, dict):
            continue
        if not rows:
            rows.append(
                f"{'CHECKPOINT':<18}{'STEP':>8} {'TIER':>8} {'ASYNC':>6}"
                "  IN-FLIGHT / SUPERSEDED"
            )
        inflight_step = board.get("inflight_step")
        if isinstance(inflight_step, int):
            detail = f"step {inflight_step}"
            since = board.get("inflight_since_unix")
            if isinstance(since, (int, float)):
                detail += f" ({now - since:.1f}s)"
        else:
            detail = "(idle)"
        superseded = board.get("superseded")
        if isinstance(superseded, int) and superseded > 0:
            detail += f"  superseded {superseded}"
        rows.append(
            f"{name:<18}"
            f"{_fmt(board.get('last_committed_step'), '>8.0f'):>8} "
            f"{board.get('tier') or '-':>8} "
            f"{'on' if board.get('async') else 'off':>6}  "
            f"{detail}"
        )
    return rows


def _resize_rows(statuses: dict[str, Any]) -> list[str]:
    """The RESIZE block: one row per host whose ``/status`` carries a
    ``resize`` board (``fluxmpi_tpu.fleet.resize`` posts it as a live
    N→M resize moves through the drain→save→handoff→reshard pipeline)
    — the current phase, the from→to world sizes, the boundary step,
    and the per-phase badput seconds attributed so far."""
    rows: list[str] = []
    for name, status in statuses.items():
        board = (status or {}).get("resize")
        if not isinstance(board, dict):
            continue
        if not rows:
            rows.append(
                f"{'RESIZE':<18}{'PHASE':>10} {'WORLD':>8} {'STEP':>8}"
                "  BADPUT"
            )
        frm = board.get("from_processes")
        to = board.get("to_processes")
        world = (
            f"{frm}->{to}"
            if isinstance(frm, int) and isinstance(to, int)
            else "-"
        )
        phases = board.get("phase_seconds")
        if isinstance(phases, dict) and phases:
            badput = " ".join(
                f"{phase}={seconds:.2f}s"
                for phase, seconds in phases.items()
                if isinstance(seconds, (int, float))
            )
            total = board.get("badput_seconds")
            if isinstance(total, (int, float)):
                badput += f"  total {total:.2f}s"
        else:
            badput = "-"
        rows.append(
            f"{name:<18}"
            f"{board.get('phase') or '-':>10} "
            f"{world:>8} "
            f"{_fmt(board.get('step'), '>8.0f'):>8}  "
            f"{badput}"
        )
    return rows


def _fleet_rows(statuses: dict[str, Any]) -> list[str]:
    """The FLEET block: one row per host whose ``/status`` carries the
    cross-host collector's verdict board (the ``fleet`` section with a
    ``collects`` counter — ingredient-only boards feed the collector,
    not the eye) — fleet census, staleness count, and the current
    straggler verdict with cause, streak, and the convicting skew."""
    rows: list[str] = []
    for name, status in statuses.items():
        board = (status or {}).get("fleet")
        if not isinstance(board, dict) or "collects" not in board:
            continue
        if not rows:
            rows.append(
                f"{'FLEET':<18}{'HOSTS':>6} {'STALE':>6} {'COLLECTS':>9}"
                "  STRAGGLER"
            )
        straggler = board.get("straggler")
        if isinstance(straggler, str) and straggler:
            verdict = f"{straggler} {board.get('cause')}"
            streak = board.get("streak")
            if isinstance(streak, int) and streak > 1:
                verdict += f" x{streak}"
            skew = board.get("skew")
            if isinstance(skew, (int, float)):
                verdict += f" (skew {skew:.2f}x)"
        else:
            verdict = "(none)"
        rows.append(
            f"{name:<18}"
            f"{_fmt(board.get('hosts'), '>6.0f'):>6} "
            f"{_fmt(board.get('hosts_stale'), '>6.0f'):>6} "
            f"{_fmt(board.get('collects'), '>9.0f'):>9}  "
            f"{verdict}"
        )
    return rows


def render_frame(
    statuses: dict[str, dict[str, Any] | None],
    rates: dict[str, tuple[float, float]],
    seen: dict[str, float] | None = None,
) -> str:
    """One dashboard frame (pure string — tests assert on it).
    ``seen`` is the poll loop's host → last-answered stamp map: it
    turns a quiet host's row into STALE-with-age instead of a blank
    UNREACHABLE."""
    up = [s for s in statuses.values() if s]
    run_ids = sorted({s.get("run_id", "?") for s in up if s.get("run_id")})
    phases = sorted(
        {
            str((s.get("train") or {}).get("phase"))
            for s in up
            if (s.get("train") or {}).get("phase")
        }
    )
    head = (
        f"fluxmpi_top  {len(up)}/{len(statuses)} host(s)  "
        f"{time.strftime('%H:%M:%S')}"
    )
    if run_ids:
        head += f"  run {run_ids[0]}" + ("+" if len(run_ids) > 1 else "")
    if phases:
        head += f"  phase {','.join(phases)}"
    lines = [
        head,
        f"{'HOST':<18}{'STEP':>8} {'UP/S':>7} {'LOSS':>8} "
        f"{'GOODPUT':>8} {'MFU':>6} {'HB AGE':>7}  HEALTH",
    ]
    for name in statuses:
        lines.append(_row(name, statuses[name], rates, seen))
    tickers: list[str] = []
    for name, s in statuses.items():
        ev = (s or {}).get("anomaly")
        if isinstance(ev, dict) and ev.get("rule"):
            # The triggering event's labels, not just the rule id: a
            # steady_state_retrace names the recompiled function, the
            # model-internals rules (and NaN provenance) name the layer
            # — the "which" an operator otherwise digs out of bundles.
            detail = "".join(
                f" {key}={ev[key]}"
                for key in ("layer", "function")
                if isinstance(ev.get(key), str) and ev.get(key)
            )
            tickers.append(
                f"  {name}: {ev['rule']}{detail} "
                f"(value {ev.get('value_repr', ev.get('value'))} "
                f"at step {ev.get('step')})"
            )
    lines.append("anomalies:" + (" (none)" if not tickers else ""))
    lines.extend(tickers)
    lines.extend(_parallel_rows(statuses))
    lines.extend(_autotune_rows(statuses))
    lines.extend(_model_rows(statuses))
    lines.extend(_serving_rows(statuses, rates))
    lines.extend(_checkpoint_rows(statuses))
    lines.extend(_resize_rows(statuses))
    lines.extend(_fleet_rows(statuses))
    return "\n".join(lines)


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        description="Terminal dashboard over the live export plane "
        "(/status across a host list, or a telemetry JSONL bank)."
    )
    parser.add_argument(
        "targets", nargs="*",
        help="hosts to poll: host, host:port (default port "
        f"{DEFAULT_PORT}), or a full URL",
    )
    parser.add_argument(
        "--jsonl", nargs="+", default=None, metavar="FILE",
        help="fallback: derive the view from telemetry JSONL file(s) "
        "instead of polling /status",
    )
    parser.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between refreshes (default 2)",
    )
    parser.add_argument(
        "--timeout", type=float, default=2.0,
        help="per-host HTTP timeout in seconds (default 2)",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (scripting/tests)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="print raw per-host status JSON instead of the table",
    )
    args = parser.parse_args(argv)
    if bool(args.targets) == bool(args.jsonl):
        parser.error("pass either host targets or --jsonl FILE..., not both")
    if args.interval <= 0:
        parser.error("--interval must be > 0")

    rates: dict[str, tuple[float, float]] = {}
    # host -> wall stamp of its last successful /status answer: a host
    # that answered once and then went quiet renders STALE with that
    # age, not a memoryless UNREACHABLE.
    seen: dict[str, float] = {}
    while True:
        if args.jsonl:
            statuses: dict[str, dict[str, Any] | None] = dict(
                _jsonl_statuses(args.jsonl)
            )
            if not statuses:
                statuses = {path: None for path in args.jsonl}
        else:
            statuses = {
                t: fetch_status(t, timeout=args.timeout) for t in args.targets
            }
        now = time.time()
        for name, status in statuses.items():
            if status is not None:
                seen[name] = now
        if args.json:
            print(
                json.dumps(
                    {name: statuses[name] for name in statuses}
                )
            )
        else:
            frame = render_frame(statuses, rates, seen)
            if not args.once:
                sys.stdout.write(_CLEAR)
            print(frame, flush=True)
        if args.once:
            return 0 if any(statuses.values()) else 2
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main(sys.argv[1:]))
    except KeyboardInterrupt:
        raise SystemExit(0)
