"""On-hardware correctness check for the Pallas flash-attention kernels.

The test suite pins the kernels to CPU interpret mode (conftest), so
until a chip is attached the compiled Mosaic lowering itself is never
exercised. This script runs the forward AND both backward kernels on the
real TPU against the dense oracle (same segment semantics as the suite's
``tests/_oracles.py``) across the feature matrix: plain / causal /
windowed / segmented / GQA, in f32 (tight tolerance) and bf16
(production dtype, loose tolerance), plus in-kernel dropout determinism
and keep-rate sanity.

Usage:  python scripts/tpu_kernel_check.py   (one JSON line per case)
Exit code 1 if any case fails its tolerance.
"""

from __future__ import annotations

import json
import os
import sys
import zlib

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import jax

# The axon sitecustomize force-registers the TPU platform (jax_platforms
# becomes "axon,cpu") and WINS over the env var; honoring JAX_PLATFORMS
# here keeps a CPU rehearsal from dialing (and hanging on) a leased TPU.
# A rehearsal (--allow-cpu) must never touch the tunnel at all.
_p = os.environ.get("JAX_PLATFORMS") or (
    "cpu" if "--allow-cpu" in sys.argv else None
)
if _p:
    jax.config.update("jax_platforms", _p)

import jax.numpy as jnp
import numpy as np

from fluxmpi_tpu.ops import flash_attention
from fluxmpi_tpu.ops.flash_attention import padding_to_segment_ids


sys.path.insert(0, os.path.join(_ROOT, "tests"))
from _oracles import dense_seg_attention  # noqa: E402  (suite's single source)


def dense_oracle(q, k, v, qseg, kseg, causal=False, window=None):
    # The suite's oracle (single source for segment-mask semantics), plus
    # a GQA kv-head repeat and an f32 upcast for tight comparison.
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return dense_seg_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        qseg, kseg, causal=causal, window=window,
    )


_INTERPRET = False  # rehearsal mode (--allow-cpu): interpret-mode kernels


def run_case(name, *, seq=512, h=8, h_kv=None, d=64, causal=False,
             window=None, segments=False, dtype=jnp.float32, tol=2e-3):
    key = jax.random.PRNGKey(zlib.crc32(name.encode()) % (2**31))
    kq, kk, kv, kc, ks = jax.random.split(key, 5)
    b = 2
    h_kv = h_kv or h
    q = jax.random.normal(kq, (b, seq, h, d), dtype)
    k = jax.random.normal(kk, (b, seq, h_kv, d), dtype)
    v = jax.random.normal(kv, (b, seq, h_kv, d), dtype)
    cot = jax.random.normal(kc, (b, seq, h, d), jnp.float32)
    if segments:
        lengths = jax.random.randint(ks, (b,), seq // 2, seq)
        seg = padding_to_segment_ids(jnp.arange(seq)[None, :] < lengths[:, None])
        valid = (seg != 0).astype(jnp.float32)[:, :, None, None]
    else:
        seg = jnp.ones((b, seq), jnp.int32)
        valid = jnp.ones((b, seq, 1, 1), jnp.float32)

    def flash_loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal, window=window,
                            segment_ids=seg if segments else None,
                            interpret=_INTERPRET)
        return jnp.sum(o.astype(jnp.float32) * cot * valid), o

    def dense_loss(q, k, v):
        o = dense_oracle(q, k, v, seg, seg, causal=causal, window=window)
        return jnp.sum(o * cot * valid), o

    (_, o_f), g_f = jax.value_and_grad(flash_loss, (0, 1, 2),
                                       has_aux=True)(q, k, v)
    (_, o_d), g_d = jax.value_and_grad(dense_loss, (0, 1, 2),
                                       has_aux=True)(q, k, v)
    errs = {"out": float(jnp.max(jnp.abs(o_f.astype(jnp.float32) - o_d)
                                 * valid))}
    for nm, a, bb in zip(("dq", "dk", "dv"), g_f, g_d):
        errs[nm] = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                         - bb.astype(jnp.float32))))
    ok = all(e <= tol for e in errs.values())
    print(json.dumps({"case": name, "dtype": str(dtype.__name__ if hasattr(
        dtype, "__name__") else dtype), "ok": ok, "tol": tol,
        "max_abs_err": errs}), flush=True)
    return ok


def run_dropout_case():
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    b, seq, h, d = 2, 512, 4, 64
    q = jax.random.normal(kq, (b, seq, h, d))
    k = jax.random.normal(kk, (b, seq, h, d))
    v = jax.random.normal(kv, (b, seq, h, d))
    rate = 0.25
    seed = jnp.uint32(123)

    def att(s):
        return flash_attention(q, k, v, causal=True, dropout_rate=rate,
                               dropout_seed=s, interpret=_INTERPRET)

    o1, o2, o3 = att(seed), att(seed), att(jnp.uint32(456))
    deterministic = bool(jnp.array_equal(o1, o2))
    differs = bool(jnp.any(o1 != o3))
    o0 = flash_attention(q, k, v, causal=True, interpret=_INTERPRET)
    # With 1/keep scaling the mean magnitude is preserved in expectation;
    # a dropped-prob output differs from the no-dropout one almost surely.
    changed_frac = float(jnp.mean((o1 != o0).astype(jnp.float32)))
    ratio = float(jnp.mean(jnp.abs(o1)) / jnp.mean(jnp.abs(o0)))
    ok = deterministic and differs and changed_frac > 0.5 \
        and 0.8 < ratio < 1.3
    print(json.dumps({"case": "dropout", "ok": ok,
                      "deterministic": deterministic,
                      "seed_sensitivity": differs,
                      "changed_frac": round(changed_frac, 4),
                      "mean_abs_ratio": round(ratio, 4)}), flush=True)
    return ok


def main():
    global _INTERPRET
    if "--allow-cpu" in sys.argv:
        _INTERPRET = True
    quick = "--quick" in sys.argv  # plumbing rehearsal (interpret is slow)
    dev = jax.devices()[0]
    print(json.dumps({"platform": dev.platform,
                      "kind": dev.device_kind}), flush=True)
    if dev.platform != "tpu" and not _INTERPRET:
        print(json.dumps({"aborted": "not a TPU"}), flush=True)
        sys.exit(2)
    ok = True
    if quick:
        ok &= run_case("causal_f32", seq=256, causal=True)
        ok &= run_case("seg_gqa_window_f32", seq=256, segments=True,
                       causal=True, window=128, h_kv=2)
    else:
        ok &= run_case("plain_f32")
        ok &= run_case("causal_f32", causal=True)
        ok &= run_case("window_f32", causal=True, window=128)
        ok &= run_case("segments_f32", segments=True)
        ok &= run_case("gqa_causal_f32", causal=True, h_kv=2)
        ok &= run_case("causal_bf16", causal=True, dtype=jnp.bfloat16,
                       tol=3e-2)
        ok &= run_case("gqa_window_bf16", causal=True, window=128, h_kv=2,
                       dtype=jnp.bfloat16, tol=3e-2)
        ok &= run_case("long_causal_bf16", seq=2048, causal=True,
                       dtype=jnp.bfloat16, tol=3e-2)
        ok &= run_dropout_case()
    print(json.dumps({"all_ok": bool(ok)}), flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
