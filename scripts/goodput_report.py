#!/usr/bin/env python
"""Per-run goodput/badput breakdown from telemetry JSONL streams.

Reads the per-host JSONL files a run emitted (``init(telemetry=...)`` /
``FLUXMPI_TPU_TELEMETRY`` with the goodput plane enabled —
``init(goodput=True)`` / ``FLUXMPI_TPU_GOODPUT=1``), takes each
process's LAST record carrying ``goodput.*`` metrics (the gauges are
cumulative, so the newest line is the run total), and prints the
wall-clock attribution the fleet is managed on:

    $ python scripts/goodput_report.py run.*.jsonl
    host 0: wall 124.7s  goodput 91.2%  MFU 0.412  updates 9600
      step                  113.7s   91.2%
      compile                 6.1s    4.9%
      checkpoint_save         2.4s    1.9%
      data_stall              1.1s    0.9%
      host_idle               1.4s    1.1%
    run: 1 host stream(s)  wall 124.7s  goodput 91.2%  mean MFU 0.412

Usage:
    python scripts/goodput_report.py FILE [FILE ...] [--json]
    python scripts/goodput_report.py FILE [FILE ...] --watch N

``--json`` prints one machine-readable JSON object instead of the table.

``--watch N`` turns the post-mortem report into a **mid-run monitor**:
the report re-renders every N seconds from the growing JSONL bank (the
same parse path — the gauges are cumulative, so the newest complete
line per host is always the run total so far). In watch mode a missing
file or a bank with no goodput data yet is a *waiting* state, not an
error — the run may simply not have flushed — and Ctrl-C exits 0.

Exit codes (one-shot mode): 0 = goodput data found and reported; 1 =
inputs readable but NO goodput metrics anywhere (the plane was off —
nothing to report); 2 = a file was missing/unreadable. A torn or
corrupt LINE (a host killed mid-write — the very post-mortem this
report serves) is skipped with a stderr warning, never fatal.

Stdlib-only, no jax, no package import — runnable anywhere the JSONL
landed (same contract as scripts/check_metrics_schema.py, which
validates the same streams).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

# Sibling import that also works when this script is loaded by file
# path (the test suite's importlib trick) rather than run from scripts/.
_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
from telemetry_jsonl import process_of, scan_jsonl  # noqa: E402


def _extract_goodput(record: dict) -> dict[str, Any] | None:
    """Pull the goodput.* gauges out of one telemetry flush record;
    None when the record carries none (the plane was off at that
    flush)."""
    metrics = record.get("metrics")
    if not isinstance(metrics, list):
        return None
    out: dict[str, Any] = {"buckets": {}}
    found = False
    for m in metrics:
        if not isinstance(m, dict):
            continue
        name = m.get("name")
        if not isinstance(name, str) or not name.startswith("goodput."):
            continue
        value = m.get("value")
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        found = True
        if name == "goodput.bucket_seconds":
            bucket = (m.get("labels") or {}).get("bucket")
            if isinstance(bucket, str) and bucket:
                out["buckets"][bucket] = float(value)
        elif name == "goodput.wall_seconds":
            out["wall_seconds"] = float(value)
        elif name == "goodput.fraction":
            out["goodput_fraction"] = float(value)
        elif name == "goodput.updates":
            out["updates"] = int(value)
        elif name == "goodput.mfu":
            out["mfu"] = float(value)
        elif name == "goodput.mfu_productive":
            out["mfu_productive"] = float(value)
    return out if found else None


def _read_streams(paths: list[str]) -> tuple[dict[int, dict], list[str]]:
    """Last goodput-carrying record per process across all files
    (torn lines warned-and-skipped by the shared scan — see
    telemetry_jsonl.py for the tolerance contract). Returns
    ``(per_process, errors)`` — errors are fatal (exit 2)."""
    per_process: dict[int, dict] = {}
    rows, errors = scan_jsonl(paths, "goodput_report")
    for _path, _lineno, rec in rows:
        gp = _extract_goodput(rec)
        if gp is None:
            continue
        proc = process_of(rec)
        gp["process"] = proc
        gp["time_unix"] = rec.get("time_unix")
        # Later lines supersede earlier ones: the gauges are
        # cumulative run totals, newest flush wins.
        per_process[proc] = gp
    return per_process, errors


def _aggregate(per_process: dict[int, dict]) -> dict[str, Any]:
    hosts = [per_process[p] for p in sorted(per_process)]
    walls = [h.get("wall_seconds", 0.0) for h in hosts]
    steps = [h.get("buckets", {}).get("step", 0.0) for h in hosts]
    mfus = [h["mfu"] for h in hosts if h.get("mfu") is not None]
    total_wall = sum(walls)
    buckets: dict[str, float] = {}
    for h in hosts:
        for name, seconds in h.get("buckets", {}).items():
            buckets[name] = buckets.get(name, 0.0) + seconds
    return {
        "hosts": hosts,
        "host_count": len(hosts),
        "wall_seconds": total_wall,
        "buckets": buckets,
        # Fleet goodput: productive host-seconds over total host-seconds
        # (hosts weighted by their wall, not a plain mean of fractions).
        "goodput_fraction": (
            sum(steps) / total_wall if total_wall > 0 else 0.0
        ),
        "mean_mfu": sum(mfus) / len(mfus) if mfus else None,
        "updates": max(
            (h.get("updates", 0) for h in hosts), default=0
        ),
    }


def _print_host(host: dict) -> None:
    wall = host.get("wall_seconds", 0.0)
    frac = host.get("goodput_fraction", 0.0)
    mfu = host.get("mfu")
    line = (
        f"host {host['process']}: wall {wall:.1f}s  "
        f"goodput {100.0 * frac:.1f}%"
    )
    if mfu is not None:
        line += f"  MFU {mfu:.4f}"
    if host.get("mfu_productive") is not None:
        line += f"  (productive MFU {host['mfu_productive']:.4f})"
    if host.get("updates") is not None:
        line += f"  updates {host.get('updates')}"
    print(line)
    buckets = host.get("buckets", {})
    for name in sorted(buckets, key=lambda n: -buckets[n]):
        seconds = buckets[name]
        share = 100.0 * seconds / wall if wall > 0 else 0.0
        print(f"  {name:<20} {seconds:>9.2f}s  {share:>5.1f}%")


def _report_once(files: list[str], as_json: bool) -> int:
    """One parse-and-render pass (the original one-shot behavior);
    returns the process exit code."""
    per_process, errors = _read_streams(files)
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        return 2
    if not per_process:
        print(
            "goodput_report: no goodput.* metrics in "
            f"{len(files)} file(s) — was the run started with "
            "FLUXMPI_TPU_GOODPUT=1 / init(goodput=True)?",
            file=sys.stderr,
        )
        return 1
    agg = _aggregate(per_process)
    if as_json:
        print(json.dumps(agg))
        return 0
    for host in agg["hosts"]:
        _print_host(host)
    line = (
        f"run: {agg['host_count']} host stream(s)  "
        f"wall {agg['wall_seconds']:.1f}s  "
        f"goodput {100.0 * agg['goodput_fraction']:.1f}%"
    )
    if agg["mean_mfu"] is not None:
        line += f"  mean MFU {agg['mean_mfu']:.4f}"
    print(line)
    return 0


def _watch(files: list[str], interval: float, as_json: bool, count: int) -> int:
    """Re-render every ``interval`` seconds from the growing bank.
    Missing files / no-goodput-yet are waiting states here, not errors —
    the run this monitors may not have flushed its first line yet.
    ``count`` bounds the iterations (0 = until Ctrl-C; tests pass a
    small count)."""
    import time

    iterations = 0
    while True:
        per_process, errors = _read_streams(files)
        if not as_json:
            # Redraw in place (ANSI clear), terminal-top style; JSON
            # mode stays line-oriented for piping.
            sys.stdout.write("\x1b[2J\x1b[H")
        header = (
            f"goodput_report --watch  {time.strftime('%H:%M:%S')}  "
            f"({len(files)} file(s), refresh {interval:g}s)"
        )
        if as_json:
            agg = _aggregate(per_process) if per_process else None
            print(json.dumps({"time": time.time(), "report": agg}), flush=True)
        else:
            print(header)
            for e in errors:
                print(f"  waiting: {e}", file=sys.stderr)
            if not per_process:
                print("  (no goodput data yet — waiting for the first flush)")
            else:
                agg = _aggregate(per_process)
                for host in agg["hosts"]:
                    _print_host(host)
                line = (
                    f"run: {agg['host_count']} host stream(s)  "
                    f"wall {agg['wall_seconds']:.1f}s  "
                    f"goodput {100.0 * agg['goodput_fraction']:.1f}%"
                )
                if agg["mean_mfu"] is not None:
                    line += f"  mean MFU {agg['mean_mfu']:.4f}"
                print(line, flush=True)
        iterations += 1
        if count and iterations >= count:
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        description="Per-run goodput/badput breakdown from telemetry JSONL"
    )
    parser.add_argument("files", nargs="+", help="telemetry JSONL file(s)")
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--watch", type=float, default=None, metavar="N",
        help="re-render every N seconds from the growing bank (mid-run "
        "monitoring; Ctrl-C exits 0)",
    )
    parser.add_argument(
        "--watch-count", type=int, default=0, metavar="K",
        help="stop after K watch renders (0 = until interrupted; "
        "scripting/tests)",
    )
    args = parser.parse_args(argv)
    if args.watch is not None:
        if args.watch <= 0:
            parser.error("--watch interval must be > 0")
        return _watch(args.files, args.watch, args.json, args.watch_count)
    return _report_once(args.files, args.json)


if __name__ == "__main__":
    try:
        raise SystemExit(main(sys.argv[1:]))
    except KeyboardInterrupt:
        raise SystemExit(0)
