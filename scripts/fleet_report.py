#!/usr/bin/env python
"""Straggler-attribution post-mortem from fleet snapshot banks.

Reads the JSONL bank a :class:`fluxmpi_tpu.telemetry.FleetCollector`
appended (``init(fleet="fleet.jsonl")`` / ``FLUXMPI_TPU_FLEET=path`` —
one ``fluxmpi_tpu.fleet/v1`` snapshot line per collection interval),
replays the interval verdicts, and prints the operator view:

    $ python scripts/fleet_report.py fleet.jsonl
    fleet: 12 snapshot(s) from 1 stream(s)  hosts 2 (1 stale)
      host 10.0.0.1:9307  alive  stale 0.2s  updates 9600
      host 10.0.0.2:9307  STALE  last seen 12.3s ago  (status unreachable)
      straggler intervals by cause: data_stall 7, comm_wait 1
      blamed: 10.0.0.2:9307 x8 (data_stall 7, comm_wait 1)
    last verdict: 10.0.0.2:9307  cause data_stall  skew 2.31x  streak 8

Every per-cause total is a **registry twin** of the collector's
cumulative ``fleet.straggler_intervals`` counter (``_REGISTRY_TWINS``
names the pairing), so the bank and the collector host's live
``/metrics`` endpoint can be cross-checked — if the counts disagree,
snapshot lines were lost.

Usage:
    python scripts/fleet_report.py FILE [FILE ...] [--json]

``--json`` prints one machine-readable JSON object instead of the
table. Exit codes: 0 = fleet snapshots found and reported; 1 = inputs
readable but NO ``fluxmpi_tpu.fleet/v1`` snapshots anywhere (the plane
was off, or armed without a bank path); 2 = a file was
missing/unreadable. A torn line (the collector host killed mid-write)
is skipped with a stderr warning, never fatal (the shared
telemetry_jsonl.py tolerance contract).

Stdlib-only, no jax, no package import — runnable anywhere the bank
landed (same contract as scripts/goodput_report.py;
scripts/check_metrics_schema.py validates the same lines).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

# Sibling import that also works when this script is loaded by file
# path (the test suite's importlib trick) rather than run from scripts/.
_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
from telemetry_jsonl import scan_jsonl  # noqa: E402

FLEET_SCHEMA = "fluxmpi_tpu.fleet/v1"

# Bank aggregate → the collector's cumulative registry instrument
# counting the SAME population: the cross-check contract (and the
# fluxlint consumer-rule anchor — every literal must be schema-known).
_REGISTRY_TWINS = {
    "straggler_intervals": "fleet.straggler_intervals",
    "host_count": "fleet.hosts",
    "stale_count": "fleet.hosts_stale",
    "flight_seq_lag": "fleet.flight_seq_lag",
}


def _read_banks(
    paths: list[str],
) -> tuple[list[tuple[str, dict]], list[str]]:
    """All fleet snapshots across all files in bank order, tagged with
    their source path. Returns ``(snapshots, errors)`` — errors are
    fatal (exit 2)."""
    rows, errors = scan_jsonl(paths, "fleet_report")
    snaps = [
        (path, rec)
        for path, _lineno, rec in rows
        if rec.get("schema") == FLEET_SCHEMA
    ]
    return snaps, errors


def _aggregate(snaps: list[tuple[str, dict]]) -> dict[str, Any]:
    last = snaps[-1][1]
    hosts = last.get("hosts") if isinstance(last.get("hosts"), dict) else {}
    # Blame history: which host was named per interval, with what cause
    # — replayed from every snapshot, not just the final totals, so the
    # report can say WHO the per-cause counts convicted.
    blamed: dict[str, dict[str, Any]] = {}
    for _path, snap in snaps:
        attr = snap.get("attribution")
        if not isinstance(attr, dict):
            continue
        host, cause = attr.get("straggler"), attr.get("cause")
        if not isinstance(host, str) or not host:
            continue
        row = blamed.setdefault(host, {"intervals": 0, "causes": {}})
        row["intervals"] += 1
        if isinstance(cause, str):
            row["causes"][cause] = row["causes"].get(cause, 0) + 1
    stale = [t for t, h in hosts.items() if not h.get("alive")]
    totals = last.get("stragglers")
    return {
        "snapshots": len(snaps),
        "stream_count": len({path for path, _ in snaps}),
        "host_count": len(hosts),
        "stale_count": len(stale),
        "hosts": hosts,
        "stragglers": dict(totals) if isinstance(totals, dict) else {},
        "blamed": blamed,
        "attribution": last.get("attribution"),
        "collects": last.get("collects"),
        "time_unix": last.get("time_unix"),
        "registry_twins": dict(_REGISTRY_TWINS),
    }


def _render(agg: dict[str, Any]) -> None:
    print(
        f"fleet: {agg['snapshots']} snapshot(s) from "
        f"{agg['stream_count']} stream(s)  hosts {agg['host_count']} "
        f"({agg['stale_count']} stale)"
    )
    for target in sorted(agg["hosts"]):
        h = agg["hosts"][target]
        stale_s = h.get("stale_seconds")
        if h.get("alive"):
            line = f"  host {target}  alive"
            if isinstance(stale_s, (int, float)):
                line += f"  stale {stale_s:.1f}s"
        else:
            line = f"  host {target}  STALE"
            if isinstance(stale_s, (int, float)):
                line += f"  last seen {stale_s:.1f}s ago"
            else:
                line += "  never seen"
            if h.get("error"):
                line += f"  ({h['error']})"
        if h.get("updates") is not None:
            line += f"  updates {h['updates']:g}"
        print(line)
    totals = agg["stragglers"]
    if totals:
        causes = ", ".join(
            f"{c} {n}" for c, n in sorted(totals.items(), key=lambda e: -e[1])
        )
        print(f"  straggler intervals by cause: {causes}")
    else:
        print("  straggler intervals by cause: none — no straggler named")
    for host in sorted(
        agg["blamed"], key=lambda h: -agg["blamed"][h]["intervals"]
    ):
        row = agg["blamed"][host]
        causes = ", ".join(
            f"{c} {n}"
            for c, n in sorted(row["causes"].items(), key=lambda e: -e[1])
        )
        line = f"  blamed: {host} x{row['intervals']}"
        if causes:
            line += f" ({causes})"
        print(line)
    attr = agg.get("attribution")
    if isinstance(attr, dict) and attr.get("straggler"):
        line = (
            f"last verdict: {attr['straggler']}  cause {attr.get('cause')}"
        )
        if isinstance(attr.get("skew"), (int, float)):
            line += f"  skew {attr['skew']:.2f}x"
        if isinstance(attr.get("streak"), int):
            line += f"  streak {attr['streak']}"
        print(line)
    else:
        print("last verdict: no straggler")


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        description="Straggler-attribution report from fleet snapshot "
        "banks"
    )
    parser.add_argument("files", nargs="+", help="fleet snapshot JSONL file(s)")
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)
    snaps, errors = _read_banks(args.files)
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        return 2
    if not snaps:
        print(
            f"fleet_report: no {FLEET_SCHEMA} snapshots in "
            f"{len(args.files)} file(s) — was the run started with "
            "FLUXMPI_TPU_FLEET=<bank path> / init(fleet='...jsonl')?",
            file=sys.stderr,
        )
        return 1
    agg = _aggregate(snaps)
    if args.json:
        print(json.dumps(agg))
        return 0
    _render(agg)
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main(sys.argv[1:]))
    except KeyboardInterrupt:
        raise SystemExit(0)
