"""One PATIENT process for every TPU measurement: no child kills, ever.

Why this exists (BENCH_NOTES_r05.md, measured three times): killing a
client that holds the axon tunnel lease (SIGKILL / subprocess timeout)
arms a ~1500 s server-side TTL — the NEXT client blocks that long in
backend init. `tpu_session.py` isolates each config in a child with a
timeout; when one big compile overruns (the r5 ResNet-50 pathology), the
timeout kill arms the TTL and every later child burns its budget blocked
in init. This runner is the prescribed recovery mode: ONE long-lived
process that

  1. tolerates a TTL-length init (it just waits — nothing kills it),
  2. runs every measurement INLINE (no subprocesses, nothing to kill),
  3. banks results incrementally as JSON lines (stdout + the --out
     file, default /tmp/patient_session.jsonl), cheapest/likeliest-to-
     succeed first, so a later hang costs nothing already written,
  4. exits cleanly, releasing the lease in seconds for the next client.

Launch it with nohup and NO external timeout; it self-limits by checking
the soft budget BETWEEN stages (a stage once started is allowed to
finish — aborting mid-compile is exactly the kill this design exists to
avoid).

Order: probe, mlp (pipeline warm-up), transformer-LM grid (the r3-proven
workload; VERDICT r5 ask #3), attention kernels, band-kernel probe, then
the ResNet ladder LAST (64 px canary with a separately-timed compile
before any 224 px attempt — the compile pathology is measured, not
suffered blind) with loader-fed on the best config (asks #1/#2).

Usage: nohup python scripts/patient_session.py --budget 9000 &
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = "/tmp/patient_session.jsonl"


def emit(obj: dict) -> None:
    line = json.dumps({"ts": round(time.time(), 1), **obj})
    print(line, flush=True)
    with open(OUT, "a") as f:
        f.write(line + "\n")


def _run_stage(name: str, fn, env: dict | None = None) -> dict | None:
    """Run one measurement inline; bank the result or the error."""
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    t0 = time.monotonic()
    try:
        result = fn()
        emit({"stage": name, "env": env or {},
              "wall_s": round(time.monotonic() - t0, 1), **(result or {})})
        return result
    except Exception as e:  # noqa: BLE001 - bank and continue
        emit({"stage": name, "env": env or {},
              "wall_s": round(time.monotonic() - t0, 1),
              "error": f"{type(e).__name__}: {e}",
              "tb": traceback.format_exc()[-600:]})
        return None
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _resnet_canary(image: int, per_chip: int):
    """Small-image ResNet-50 train step with the compile timed separately
    — the cheap probe that tells slow-compile apart from hung-compile
    before anything commits to the 224 px graph."""
    import jax
    import jax.numpy as jnp
    import optax

    import bench
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import ResNet50
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    devs = bench._visible_devices()
    mesh = fm.init(devices=devs)
    n_dev = fm.total_workers()
    # bf16 emulation on XLA:CPU is pathologically slow — rehearse in f32.
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    model = ResNet50(num_classes=1000, dtype=dtype)
    x = jnp.ones((per_chip * n_dev, image, image, 3), dtype)
    y = jnp.zeros((per_chip * n_dev,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=False)
    optimizer = optax.sgd(0.1, momentum=0.9)
    step = make_train_step(bench._bn_loss(model), optimizer, mesh=mesh,
                           style="auto")
    state = replicate(
        TrainState.create(variables["params"], optimizer,
                          variables.get("batch_stats")), mesh)
    data = shard_batch((x, y), mesh)
    t0 = time.monotonic()
    compiled = step.lower(state, data).compile()  # step is already a jit
    compile_s = round(time.monotonic() - t0, 1)
    rate, _ = bench._steps_per_sec(compiled, state, data, warmup=2, steps=10)
    return {"image": image, "per_chip_batch": per_chip,
            "compile_s": compile_s,
            "images_per_sec_per_chip": round(per_chip * rate, 2)}


def main() -> None:
    global OUT
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=9000.0)
    ap.add_argument("--skip", default="",
                    help="comma list: mlp,lm,attention,band,resnet,loader")
    ap.add_argument("--canary-ceiling", type=float, default=1500.0,
                    help="skip 224px ResNet if the 64px canary compile "
                         "took longer than this (seconds)")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="rehearsal mode: keep JAX_PLATFORMS and don't "
                         "abort on a CPU backend")
    ap.add_argument("--canary-image", type=int, default=64)
    ap.add_argument("--canary-batch", type=int, default=32)
    ap.add_argument("--out", default=OUT,
                    help="JSONL results file (appended)")
    args = ap.parse_args()
    OUT = args.out
    skip = set(s for s in args.skip.split(",") if s)
    t_start = time.monotonic()

    def remaining() -> float:
        return args.budget - (time.monotonic() - t_start)

    # Land on the axon TPU: drop any lingering cpu pin from the
    # CPU-fallback workflow, keep the import path correct.
    if not args.allow_cpu:
        os.environ.pop("JAX_PLATFORMS", None)

    import bench  # noqa: E402  (repo-root bench.py)

    if args.allow_cpu and os.environ.get("JAX_PLATFORMS"):
        # The sitecustomize's force-registered axon platform wins over the
        # env var unless the config is pinned too. Pin BEFORE the cache
        # enabler: its jax.default_backend() check INITIALIZES the backend,
        # and an unpinned axon platform hangs there on a busy/wedged
        # tunnel (bench._child_main pins in this same order).
        import jax as _jax
        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # --- 1. Init + probe: this is where a TTL wait lands; just wait.
    # EVERYTHING that can initialize the backend sits inside the try —
    # including the cache enabler, whose jax.default_backend() check is
    # the first backend touch in the default mode. In the
    # erroring-service mode the init waits the TTL and then raises
    # UNAVAILABLE — bank that (with the measured wait) and exit cleanly
    # (a clean exit does NOT re-arm the TTL; the relaunch loop tries
    # again). ------------------------------------------------------------
    t0 = time.monotonic()
    import jax
    import jax.numpy as jnp

    try:
        bench._enable_compilation_cache()
        devs = jax.devices()
        xm = jnp.ones((256, 256), jnp.bfloat16)
        (xm @ xm).block_until_ready()
    except Exception as e:  # noqa: BLE001
        emit({"stage": "probe", "error": f"{type(e).__name__}: {e}"[:400],
              "init_s": round(time.monotonic() - t0, 1)})
        return
    emit({"stage": "probe", "platform": devs[0].platform,
          "kind": devs[0].device_kind, "n": len(devs),
          "init_s": round(time.monotonic() - t0, 1)})
    if devs[0].platform != "tpu" and not args.allow_cpu:
        emit({"stage": "abort", "reason": "no TPU backend"})
        return

    # --- 2. Pipeline warm-up + a cheap banked number ------------------
    if "mlp" not in skip:
        _run_stage("mlp", bench._bench_mlp)

    # --- 3. Transformer-LM grid (ask #3) -------------------------------
    if "lm" not in skip:
        grid: list[tuple[str, dict]] = [
            ("lm_default", {}),
            ("lm_dense_head", {"FLUXMPI_TPU_LM_FUSED_CE": "0"}),
            ("lm_scan8", {"FLUXMPI_TPU_BENCH_SCAN_STEPS": "8"}),
            ("lm_b16", {"FLUXMPI_TPU_LM_BATCH": "16"}),
            ("lm_b16_scan8", {"FLUXMPI_TPU_LM_BATCH": "16",
                              "FLUXMPI_TPU_BENCH_SCAN_STEPS": "8"}),
            ("lm_b32", {"FLUXMPI_TPU_LM_BATCH": "32"}),
            ("lm_b32_remat_dots", {"FLUXMPI_TPU_LM_BATCH": "32",
                                   "FLUXMPI_TPU_BENCH_REMAT": "dots"}),
            ("lm_blk_512_1024", {"FLUXMPI_TPU_LM_BLOCK_Q": "512",
                                 "FLUXMPI_TPU_LM_BLOCK_K": "1024"}),
            ("lm_blk_256_512", {"FLUXMPI_TPU_LM_BLOCK_Q": "256",
                                "FLUXMPI_TPU_LM_BLOCK_K": "512"}),
        ]
        for name, env in grid:
            if remaining() < 300:
                emit({"stage": name, "skipped": "budget"})
                continue
            _run_stage(name, bench._bench_transformer, env)

    # --- 4. Attention kernels + band-mode compile probe ----------------
    if "attention" not in skip:
        if remaining() > 600:
            _run_stage("attention", bench._bench_attention)
        else:
            emit({"stage": "attention", "skipped": "budget"})
    if "band" not in skip and remaining() <= 300:
        emit({"stage": "band_kernel", "skipped": "budget"})
    elif "band" not in skip:
        def band():
            from fluxmpi_tpu.ops import flash_attention_with_lse as f
            q = jnp.ones((2, 256, 4, 64), jnp.bfloat16)
            o, _ = f(q, q, q, causal=False, window=64,
                     block_q=128, block_k=128)
            g = jax.grad(lambda q: f(q, q, q, causal=False, window=64,
                                     block_q=128, block_k=128)[0]
                         .astype(jnp.float32).sum())(q)
            import numpy as np
            return {"band_kernel": "ok",
                    "finite": bool(np.isfinite(
                        np.asarray(g, np.float32)).all())}
        _run_stage("band_kernel", band)

    # --- 5. ResNet ladder, canary first (asks #1/#2) -------------------
    if "resnet" not in skip:
        if "loader" in skip:
            # The loader-fed re-time is wired into _bench_resnet50
            # (loader_fed=True); neutralize it for operators who need the
            # synthetic number without the loader path.
            bench._loader_fed_rate = lambda **kw: None
        canary = None
        if remaining() > 300:
            canary = _run_stage(
                f"resnet_canary_{args.canary_image}px",
                lambda: _resnet_canary(args.canary_image, args.canary_batch),
            )
        else:
            emit({"stage": "resnet_canary", "skipped": "budget"})
        if canary is None:
            emit({"stage": "resnet224",
                  "skipped": "canary failed or budget-skipped"})
        elif canary["compile_s"] > args.canary_ceiling:
            emit({"stage": "resnet224",
                  "skipped": f"canary compile {canary['compile_s']}s > "
                             f"ceiling {args.canary_ceiling}s"})
        else:
            for name, env in [
                ("resnet224_b128", {}),
                ("resnet224_b256", {"FLUXMPI_TPU_RESNET_BATCH": "256"}),
                ("resnet224_b128_scan8",
                 {"FLUXMPI_TPU_BENCH_SCAN_STEPS": "8"}),
            ]:
                if remaining() < 600:
                    emit({"stage": name, "skipped": "budget"})
                    continue
                _run_stage(name, bench._bench_resnet50, env)

    emit({"stage": "done", "wall_s": round(time.monotonic() - t_start, 1)})


if __name__ == "__main__":
    main()
