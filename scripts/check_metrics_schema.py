#!/usr/bin/env python
"""Validate bench JSON, telemetry JSONL, and trace-plane files against
the documented schemas (fluxmpi_tpu/telemetry/schema.py — the single
source of truth).

Usage:
    python scripts/check_metrics_schema.py [FILE ...]

- ``*.jsonl`` files: every line must be a valid telemetry flush record
  (schema "fluxmpi_tpu.telemetry/v1") — except lines carrying
  ``"schema": "fluxmpi_tpu.request/v1"`` (the serving plane's
  per-request terminal records, ``init(request_log=...)`` /
  ``FLUXMPI_TPU_REQUEST_LOG``), which validate as request records,
  and lines carrying ``"schema": "fluxmpi_tpu.fleet/v1"`` (the
  :class:`FleetCollector`'s per-interval snapshot bank,
  ``init(fleet=...)`` / ``FLUXMPI_TPU_FLEET``), which validate as
  fleet snapshots, and lines carrying
  ``"schema": "fluxmpi_tpu.autotune/v1"`` (layout-autotuner records),
  which validate as autotune records, and lines carrying
  ``"schema": "fluxmpi_tpu.resize/v1"`` (the live-resize badput bank,
  ``init(resize=...)`` / ``FLUXMPI_TPU_RESIZE``), which validate as
  resize records (a number for every ``RESIZE_PHASES`` phase, totals
  that sum; transient handoff half-records pass untouched) — and a
  line carrying a ``bench`` key must also embed a valid bench record. Metric names in the
  framework-owned ``fault.`` / ``checkpoint.`` / ``goodput.`` /
  ``anomaly.`` / ``compile.`` / ``memory.`` namespaces must come from
  ``schema.KNOWN_METRIC_NAMES``
  (``fault.injected``, ``checkpoint.retries``, the run-health plane's
  ``goodput.bucket_seconds``/``goodput.mfu``/``anomaly.triggered``
  family; ``train.resumes`` and the ``train.preemption`` /
  ``anomaly.<rule>`` trace instants are validated the same way) —
  producer drift there fails the check.
- ``*.json`` files carrying ``"schema": "fluxmpi_tpu.trace/v1"``:
  dispatched on ``kind`` — a trace export (``Tracer.export`` /
  ``scripts/merge_traces.py`` output), a flight-recorder dump, or a
  watchdog hang dump. Anomaly diagnostics bundles
  (``fluxmpi_anomaly.<process>.json``, written by the
  :class:`AnomalyDetector` on trigger) and OOM forensics bundles
  (``fluxmpi_oom.<process>.json``, written by ``train_loop`` when an
  XLA ``RESOURCE_EXHAUSTED`` escapes the dispatch loop — live-array
  census + per-device HBM stats + peak watermark) are
  watchdog-dump-kind records with an extra ``anomaly`` / ``oom``
  section and validate through the same path. The device plane's
  ``compile.`` / ``memory.`` metric namespaces are closed like the
  run-health ones — unknown names there fail the check.
- ``*.json`` files carrying ``"schema": "fluxmpi_tpu.manifest/v1"``
  (the ``<step>.manifest.json`` topology sidecar every checkpoint save
  writes): validated against the manifest schema — leaf
  shapes/dtypes/partition specs, mesh axes, loader geometry.
- ``*.json`` files carrying ``"schema": "fluxmpi_tpu.autotune/v1"``
  (the ``FLUXMPI_TPU_AUTOTUNE_BANK`` file or a ``<ckpt>.autotune.json``
  sidecar): validated as layout-autotuner records — candidate table
  consistency (pruned ⇒ no trial, trials count, winner trialed).
- ``*.json`` files carrying ``"schema": "fluxmpi_tpu.resize/v1"``: a
  completed live-resize record saved whole validates like a bank line;
  a pending handoff stamp (``.fluxmpi_resize.json``, ``"handoff":
  true``) passes untouched.
- other ``*.json`` files: a bench record — either bench.py's raw output
  (``{"metric": ...}``) or a driver BENCH_*.json wrapper whose ``tail``
  holds the JSON line bench.py printed.

With no arguments, validates every ``BENCH_*.json`` in the repo root —
the PR-time drift check (wired into tests/test_telemetry.py; the
trace-plane paths are exercised by tests/test_tracing.py).

The schema module is loaded by file path, NOT via ``import fluxmpi_tpu``:
this script must stay runnable in a second without booting jax or any
backend.
"""

from __future__ import annotations

import glob
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_schema():
    # One loader for "the schema module, by file path, without booting
    # jax": fluxmpi_tpu/analysis/context.py owns it (fluxlint checks
    # metric-name and env-var drift against the same source), and this
    # script borrows it instead of keeping a second copy.
    path = os.path.join(_REPO, "fluxmpi_tpu", "analysis", "context.py")
    spec = importlib.util.spec_from_file_location(
        "_fluxmpi_analysis_context", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load_schema_module(_REPO)


def _bench_record_from(data: dict) -> dict | None:
    """Extract the bench record from either bench.py's raw output or a
    driver BENCH_*.json wrapper (record rides as the last JSON line of
    the captured ``tail``). Returns None when the wrapper holds no record
    (e.g. a round where bench.py never ran)."""
    if "metric" in data:
        return data
    tail = data.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
    return None


def check_file(path: str, schema) -> list[str]:
    """Validate one file; returns error strings prefixed with location."""
    errors: list[str] = []
    with open(path, "r", encoding="utf-8") as f:
        content = f.read()
    if path.endswith(".jsonl"):
        for i, line in enumerate(content.splitlines(), 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                errors.append(f"{path}:{i}: not JSON: {exc}")
                continue
            if (
                isinstance(rec, dict)
                and rec.get("schema") == schema.REQUEST_SCHEMA
            ):
                # Per-request terminal record (the serving plane's
                # request log) — a different line schema sharing the
                # JSONL transport.
                for e in schema.validate_request_record(rec):
                    errors.append(f"{path}:{i}: {e}")
                continue
            if (
                isinstance(rec, dict)
                and rec.get("schema") == schema.FLEET_SCHEMA
            ):
                # Fleet snapshot line (the cross-host collector's bank,
                # replayed by scripts/fleet_report.py).
                for e in schema.validate_fleet_snapshot(rec):
                    errors.append(f"{path}:{i}: {e}")
                continue
            if (
                isinstance(rec, dict)
                and rec.get("schema") == schema.AUTOTUNE_SCHEMA
            ):
                # Layout-autotuner record appended to a JSONL stream
                # (e.g. a bank of tunes) — the same shape as the
                # FLUXMPI_TPU_AUTOTUNE_BANK file.
                for e in schema.validate_autotune_record(rec):
                    errors.append(f"{path}:{i}: {e}")
                continue
            if (
                isinstance(rec, dict)
                and rec.get("schema") == schema.RESIZE_SCHEMA
            ):
                # Live-resize event record (the FLUXMPI_TPU_RESIZE
                # bank). Handoff stamps share the schema tag but are
                # half-records by design (the resumed world completes
                # and removes them) — skipped, not failed.
                if not rec.get("handoff"):
                    for e in schema.validate_resize_record(rec):
                        errors.append(f"{path}:{i}: {e}")
                continue
            for e in schema.validate_record(rec):
                errors.append(f"{path}:{i}: {e}")
            if isinstance(rec, dict) and "bench" in rec:
                for e in schema.validate_bench_record(rec["bench"]):
                    errors.append(f"{path}:{i}: bench: {e}")
        return errors
    try:
        data = json.loads(content)
    except json.JSONDecodeError as exc:
        return [f"{path}: not JSON: {exc}"]
    if isinstance(data, dict) and data.get("schema") == schema.TRACE_SCHEMA:
        # Trace-plane file (span export / flight recorder / watchdog
        # dump): validate_trace_file dispatches on its 'kind'.
        return [f"{path}: {e}" for e in schema.validate_trace_file(data)]
    if isinstance(data, dict) and data.get("schema") == schema.MANIFEST_SCHEMA:
        # Checkpoint topology manifest (the elastic-restore sidecar).
        return [f"{path}: {e}" for e in schema.validate_manifest(data)]
    if isinstance(data, dict) and data.get("schema") == schema.FLEET_SCHEMA:
        # A single fleet snapshot saved as .json (FleetCollector
        # .snapshot() dumped whole rather than banked line-by-line).
        return [f"{path}: {e}" for e in schema.validate_fleet_snapshot(data)]
    if isinstance(data, dict) and data.get("schema") == schema.AUTOTUNE_SCHEMA:
        # A layout-autotuner bank file (FLUXMPI_TPU_AUTOTUNE_BANK) or a
        # <ckpt>.autotune.json sidecar: the banked winner + candidate
        # table a later init(parallel="auto") trusts instead of
        # re-running trials.
        return [
            f"{path}: {e}" for e in schema.validate_autotune_record(data)
        ]
    if isinstance(data, dict) and data.get("schema") == schema.RESIZE_SCHEMA:
        # A completed resize record saved whole; pending handoff stamps
        # (.fluxmpi_resize.json, "handoff": true) are transient
        # half-records and pass untouched.
        if data.get("handoff"):
            return errors
        return [f"{path}: {e}" for e in schema.validate_resize_record(data)]
    rec = _bench_record_from(data) if isinstance(data, dict) else None
    if rec is None:
        # A wrapper with no bench line is a bench that never ran — not a
        # schema violation; drift in records that DO exist is the target.
        return errors
    for e in schema.validate_bench_record(rec):
        errors.append(f"{path}: {e}")
    return errors


def main(argv: list[str]) -> int:
    schema = _load_schema()
    paths = argv or sorted(glob.glob(os.path.join(_REPO, "BENCH_*.json")))
    if not paths:
        print("check_metrics_schema: nothing to validate", file=sys.stderr)
        return 0
    errors: list[str] = []
    for path in paths:
        if not os.path.exists(path):
            errors.append(f"{path}: no such file")
            continue
        errors.extend(check_file(path, schema))
    for e in errors:
        print(e, file=sys.stderr)
    print(
        f"check_metrics_schema: {len(paths)} file(s), "
        f"{len(errors)} error(s)"
    )
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
