#!/usr/bin/env python
"""Per-request latency/SLO/reject post-mortem from serving request logs.

Reads the per-host request-log JSONL a serving run emitted
(``init(request_log=...)`` / ``FLUXMPI_TPU_REQUEST_LOG`` — one
``fluxmpi_tpu.request/v1`` line per terminal request), aggregates the
fleet's request population, and prints the operator view:

    $ python scripts/serving_report.py requests.*.jsonl
    host 0: 48 request(s)  finished 44  rejected 4  slo_ok 91.7%
    fleet: 48 request(s) from 1 stream(s)
      finished 44  rejected 4 (queue_full 3, preempted 1)
      tokens: prompt 1203  output 982
      ttft    p50 0.041s  p99 0.512s  (44 samples)
      ...
      slo: 91.7% ok  violations: ttft 3, per_token 1
      worst ttft: #17 0.512s, #9 0.488s, ...

Every aggregate here is a **registry twin**: the same population the
engine's cumulative instruments count (``_REGISTRY_TWINS`` names the
pairing), so the log and the live ``/metrics`` endpoint can be
cross-checked — if ``finished`` here disagrees with
``serving.requests_completed`` there, records were lost.

Usage:
    python scripts/serving_report.py FILE [FILE ...] [--json]
    python scripts/serving_report.py FILE [FILE ...] --watch N

``--json`` prints one machine-readable JSON object; ``--watch N``
re-renders every N seconds from the growing log (mid-run monitoring —
missing files / no records yet are waiting states, Ctrl-C exits 0).

Exit codes (one-shot mode): 0 = request records found and reported;
1 = inputs readable but NO request records anywhere (the plane was
off); 2 = a file was missing/unreadable. A torn line (a host killed
mid-write) is skipped with a stderr warning, never fatal.

Stdlib-only, no jax, no package import — runnable anywhere the JSONL
landed (same contract as scripts/goodput_report.py;
scripts/check_metrics_schema.py validates the same lines).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

# Sibling import that also works when this script is loaded by file
# path (the test suite's importlib trick) rather than run from scripts/.
_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
from telemetry_jsonl import process_of, scan_jsonl  # noqa: E402

REQUEST_SCHEMA = "fluxmpi_tpu.request/v1"

# Log-aggregate → the engine's cumulative registry instrument counting
# the SAME population: the cross-check contract (and the fluxlint
# consumer-rule anchor — every literal must be schema-known).
_REGISTRY_TWINS = {
    "finished": "serving.requests_completed",
    "rejected": "serving.admission_rejects",
    "prompt_tokens": "serving.prompt_tokens",
    "output_tokens": "serving.output_tokens",
    "ttft": "serving.ttft_seconds",
    "per_token": "serving.token_seconds",
    "queue_wait": "serving.queue_wait_seconds",
}


def _read_streams(
    paths: list[str],
) -> tuple[dict[tuple[int, int], dict], list[str]]:
    """All request records across all files, keyed by
    ``(process, request_id)`` (a re-read in watch mode must not double
    count; torn lines warned-and-skipped by the shared scan — see
    telemetry_jsonl.py for the tolerance contract). Returns
    ``(records, errors)`` — errors are fatal (exit 2)."""
    records: dict[tuple[int, int], dict] = {}
    rows, errors = scan_jsonl(paths, "serving_report")
    for _path, _lineno, rec in rows:
        if rec.get("schema") != REQUEST_SCHEMA:
            continue
        proc = process_of(rec)
        rid = rec.get("request_id")
        rid = rid if isinstance(rid, int) else len(records)
        records[(proc, rid)] = rec
    return records, errors


def _percentile(data: list[float], p: float) -> float:
    """Nearest-rank percentile over a sorted sample."""
    return data[min(len(data) - 1, int(p * (len(data) - 1) + 0.5))]


def _latency_summary(samples: list[float]) -> dict[str, Any] | None:
    if not samples:
        return None
    data = sorted(samples)
    return {
        "count": len(data),
        "p50": _percentile(data, 0.50),
        "p99": _percentile(data, 0.99),
        "max": data[-1],
        "mean": sum(data) / len(data),
    }


def _aggregate(records: dict[tuple[int, int], dict]) -> dict[str, Any]:
    recs = [records[k] for k in sorted(records)]
    finished = [r for r in recs if r.get("status") == "finished"]
    rejected = [r for r in recs if r.get("status") == "rejected"]
    reject_reasons: dict[str, int] = {}
    for r in rejected:
        reason = r.get("reason") or "unknown"
        reject_reasons[reason] = reject_reasons.get(reason, 0) + 1
    violations: dict[str, int] = {}
    for r in recs:
        for v in r.get("slo_violations") or []:
            if isinstance(v, str):
                violations[v] = violations.get(v, 0) + 1

    def numbers(key: str) -> list[float]:
        return [
            float(r[key])
            for r in recs
            if isinstance(r.get(key), (int, float))
            and not isinstance(r.get(key), bool)
        ]

    per_process: dict[int, dict[str, int]] = {}
    for r in recs:
        proc = r.get("process") if isinstance(r.get("process"), int) else 0
        row = per_process.setdefault(
            proc, {"requests": 0, "finished": 0, "rejected": 0, "slo_ok": 0}
        )
        row["requests"] += 1
        row["finished"] += int(r.get("status") == "finished")
        row["rejected"] += int(r.get("status") == "rejected")
        row["slo_ok"] += int(bool(r.get("slo_ok")))
    worst = sorted(
        (
            (float(r["ttft_s"]), r.get("request_id"), r.get("process"))
            for r in recs
            if isinstance(r.get("ttft_s"), (int, float))
            and not isinstance(r.get("ttft_s"), bool)
        ),
        reverse=True,
    )[:5]
    slo_ok = sum(1 for r in recs if r.get("slo_ok"))
    return {
        "requests": len(recs),
        "stream_count": len({p for p, _ in records}),
        "finished": len(finished),
        "rejected": len(rejected),
        "reject_reasons": reject_reasons,
        "prompt_tokens": int(sum(numbers("prompt_tokens"))),
        "output_tokens": int(sum(numbers("output_tokens"))),
        "ttft": _latency_summary(numbers("ttft_s")),
        "per_token": _latency_summary(numbers("per_token_s")),
        "queue_wait": _latency_summary(numbers("queue_wait_s")),
        "total": _latency_summary(numbers("total_s")),
        "slo_ok": slo_ok,
        "slo_ok_fraction": slo_ok / len(recs) if recs else 0.0,
        "slo_violations": violations,
        "worst_ttft": [
            {"request_id": rid, "process": proc, "ttft_s": t}
            for t, rid, proc in worst
        ],
        "per_process": {str(p): per_process[p] for p in sorted(per_process)},
        "registry_twins": dict(_REGISTRY_TWINS),
    }


def _render(agg: dict[str, Any]) -> None:
    for proc, row in agg["per_process"].items():
        pct = 100.0 * row["slo_ok"] / row["requests"] if row["requests"] else 0.0
        print(
            f"host {proc}: {row['requests']} request(s)  "
            f"finished {row['finished']}  rejected {row['rejected']}  "
            f"slo_ok {pct:.1f}%"
        )
    print(
        f"fleet: {agg['requests']} request(s) from "
        f"{agg['stream_count']} stream(s)"
    )
    rejects = ", ".join(
        f"{k} {v}"
        for k, v in sorted(agg["reject_reasons"].items(), key=lambda e: -e[1])
    )
    line = f"  finished {agg['finished']}  rejected {agg['rejected']}"
    if rejects:
        line += f" ({rejects})"
    print(line)
    print(
        f"  tokens: prompt {agg['prompt_tokens']}  "
        f"output {agg['output_tokens']}"
    )
    for key in ("ttft", "per_token", "queue_wait", "total"):
        s = agg.get(key)
        if s is None:
            continue
        print(
            f"  {key:<10} p50 {s['p50']:.4f}s  p99 {s['p99']:.4f}s  "
            f"max {s['max']:.4f}s  ({s['count']} samples)"
        )
    vio = ", ".join(
        f"{k} {v}"
        for k, v in sorted(
            agg["slo_violations"].items(), key=lambda e: -e[1]
        )
    )
    line = f"  slo: {100.0 * agg['slo_ok_fraction']:.1f}% ok"
    if vio:
        line += f"  violations: {vio}"
    print(line)
    if agg["worst_ttft"]:
        worst = ", ".join(
            f"#{w['request_id']} {w['ttft_s']:.4f}s"
            for w in agg["worst_ttft"]
        )
        print(f"  worst ttft: {worst}")


def _report_once(files: list[str], as_json: bool) -> int:
    records, errors = _read_streams(files)
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        return 2
    if not records:
        print(
            f"serving_report: no {REQUEST_SCHEMA} records in "
            f"{len(files)} file(s) — was the run started with "
            "FLUXMPI_TPU_REQUEST_LOG / init(request_log=...)?",
            file=sys.stderr,
        )
        return 1
    agg = _aggregate(records)
    if as_json:
        print(json.dumps(agg))
        return 0
    _render(agg)
    return 0


def _watch(files: list[str], interval: float, as_json: bool, count: int) -> int:
    """Re-render every ``interval`` seconds from the growing log.
    Missing files / no records yet are waiting states here, not errors.
    ``count`` bounds the iterations (0 = until Ctrl-C; tests pass a
    small count)."""
    import time

    iterations = 0
    while True:
        records, errors = _read_streams(files)
        if as_json:
            agg = _aggregate(records) if records else None
            print(json.dumps({"time": time.time(), "report": agg}), flush=True)
        else:
            sys.stdout.write("\x1b[2J\x1b[H")
            print(
                f"serving_report --watch  {time.strftime('%H:%M:%S')}  "
                f"({len(files)} file(s), refresh {interval:g}s)"
            )
            for e in errors:
                print(f"  waiting: {e}", file=sys.stderr)
            if not records:
                print("  (no request records yet — waiting for traffic)")
            else:
                _render(_aggregate(records))
            sys.stdout.flush()
        iterations += 1
        if count and iterations >= count:
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        description="Per-request latency/SLO/reject report from serving "
        "request logs"
    )
    parser.add_argument("files", nargs="+", help="request-log JSONL file(s)")
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--watch", type=float, default=None, metavar="N",
        help="re-render every N seconds from the growing log (mid-run "
        "monitoring; Ctrl-C exits 0)",
    )
    parser.add_argument(
        "--watch-count", type=int, default=0, metavar="K",
        help="stop after K watch renders (0 = until interrupted; "
        "scripting/tests)",
    )
    args = parser.parse_args(argv)
    if args.watch is not None:
        if args.watch <= 0:
            parser.error("--watch interval must be > 0")
        return _watch(args.files, args.watch, args.json, args.watch_count)
    return _report_once(args.files, args.json)


if __name__ == "__main__":
    try:
        raise SystemExit(main(sys.argv[1:]))
    except KeyboardInterrupt:
        raise SystemExit(0)
