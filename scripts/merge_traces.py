#!/usr/bin/env python
"""Merge per-host trace exports into one Perfetto-loadable file.

Usage:
    python scripts/merge_traces.py -o merged.json trace.0.json trace.1.json ...
    python scripts/merge_traces.py -o merged.json 'traces/trace.*.json'
    python scripts/merge_traces.py -o merged.json profiles/    # a logdir

Each file input is a ``fluxmpi_tpu.trace/v1`` / kind="trace" export
(what ``Tracer.export(path)`` / ``FLUXMPI_TPU_TRACE=<path>`` writes, one
per host). Span timestamps are wall-clock-anchored microseconds, so
events from different hosts land on one shared timeline without
re-basing — cross-host skew is NTP skew, small enough to read collective
alignment at step granularity. Every host keeps its own pid lane
(relabeled ``host <process>``), so Perfetto renders one process group
per host.

A **directory** input is discovered recursively: every ``*.json`` /
``*.json.gz`` under it, including the per-process ``proc<k>``
subdirectories that ``profile_trace(all_hosts=True)`` and the
anomaly-triggered :class:`~fluxmpi_tpu.utils.profiling.AutoProfiler`
write into a shared logdir — a merged view of an auto-captured profile
no longer needs hand-globbing. Discovered files are handled tolerantly:
our kind="trace" exports merge as usual; a raw Chrome-trace JSON from
profiler tooling (a bare ``{"traceEvents": [...]}`` or event list, the
``.trace.json.gz`` TensorBoard's trace viewer emits) is wrapped with
its process index inferred from the ``proc<k>`` path component; files
that are neither are skipped with a count. Explicitly-named files keep
the strict behavior (an invalid file is an error).

The output is itself a valid kind="trace" record (extra top-level keys
are Chrome-trace metadata, which Perfetto ignores), so
``scripts/check_metrics_schema.py merged.json`` validates it.

Like check_metrics_schema.py, the schema module is loaded by file path —
this script must stay runnable in a second without importing jax.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import importlib.util
import json
import os
import re
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_schema():
    path = os.path.join(_REPO, "fluxmpi_tpu", "telemetry", "schema.py")
    spec = importlib.util.spec_from_file_location("_fluxmpi_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_PROC_DIR_RE = re.compile(r"(?:^|[/\\])proc(\d+)(?:[/\\]|$)")


def _proc_from_path(path: str) -> int:
    """Process index from a ``proc<k>`` path component (the shared-logdir
    layout ``profile_trace(all_hosts=True)`` writes), else 0."""
    m = _PROC_DIR_RE.search(path)
    return int(m.group(1)) if m else 0


def discover(inputs: list[str]) -> list[tuple[str, bool]]:
    """Expand the input list into ``(path, tolerant)`` pairs. Globs
    expand; a directory is walked recursively for ``*.json`` /
    ``*.json.gz`` (the ``proc<k>`` capture layout included) and its
    files are tolerant (non-trace JSON skips instead of erroring);
    explicitly-named files stay strict. A literal missing path is kept
    so the caller errors on it."""
    out: list[tuple[str, bool]] = []
    for pattern in inputs:
        matched = sorted(glob.glob(pattern))
        if not matched:
            out.append((pattern, False))  # missing: error below
            continue
        for path in matched:
            if os.path.isdir(path):
                found = []
                for root, _dirs, names in os.walk(path):
                    for name in names:
                        if name.endswith((".json", ".json.gz")):
                            found.append(os.path.join(root, name))
                out.extend((p, True) for p in sorted(found))
            else:
                out.append((path, False))
    return out


def _load_json(path: str):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        return json.load(f)


def _wrap_raw_chrome_trace(raw: object, path: str, schema) -> dict | None:
    """Wrap a bare Chrome-trace JSON (profiler tooling output: a
    ``{"traceEvents": [...]}`` object or a plain event list) as a
    kind="trace" record, process inferred from the ``proc<k>`` path.
    Returns None when the payload is not a Chrome trace at all."""
    if isinstance(raw, list):
        events = raw
    elif isinstance(raw, dict) and isinstance(raw.get("traceEvents"), list):
        events = raw["traceEvents"]
    else:
        return None
    rec = {
        "schema": schema.TRACE_SCHEMA,
        "kind": "trace",
        "time_unix": os.path.getmtime(path),
        "process": _proc_from_path(path),
        "traceEvents": events,
    }
    return rec if not schema.validate_trace_export(rec) else None


def merge(records: list[dict]) -> dict:
    """Merge kind="trace" records into one. Each host's events are
    re-pidded to its ``process`` index — original pids can collide
    across hosts (containerized SPMD launches everything as pid 1),
    which would silently fold two hosts into one Perfetto lane — and
    process_name metadata is rewritten to ``host <process>`` so the
    merged view is attributable at a glance."""
    events: list[dict] = []
    seen_processes: list[int] = []
    for rec in records:
        process = int(rec.get("process", 0))
        seen_processes.append(process)
        for ev in rec.get("traceEvents", []):
            if "pid" in ev:
                ev = {**ev, "pid": process}
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev = {
                    **ev,
                    "args": {"name": f"host {process}"},
                }
            events.append(ev)
    # Stable render order: metadata first, then by timestamp.
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {
        "schema": _load_schema().TRACE_SCHEMA,
        "kind": "trace",
        "time_unix": time.time(),
        # The merged file spans hosts; 'process' names the lead by
        # convention so the record stays schema-valid.
        "process": min(seen_processes) if seen_processes else 0,
        "merged_from": sorted(seen_processes),
        "displayTimeUnit": "ms",
        "traceEvents": events,
    }


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        description="Merge per-host fluxmpi_tpu trace exports into one "
        "Perfetto-loadable Chrome-trace JSON."
    )
    parser.add_argument(
        "-o", "--output", required=True, help="merged output path"
    )
    parser.add_argument(
        "inputs", nargs="+",
        help="per-host trace JSON files (globs are expanded) and/or "
        "capture directories (walked recursively, proc<k> subdirs "
        "included)",
    )
    args = parser.parse_args(argv)

    schema = _load_schema()
    records: list[dict] = []
    errors: list[str] = []
    skipped = 0
    for path, tolerant in discover(args.inputs):
        if not os.path.exists(path):
            errors.append(f"{path}: no such file")
            continue
        try:
            raw = _load_json(path)
        except (json.JSONDecodeError, OSError, UnicodeDecodeError) as exc:
            if tolerant:
                skipped += 1
            else:
                errors.append(f"{path}: not JSON: {exc}")
            continue
        errs = schema.validate_trace_export(raw)
        if not errs:
            records.append(raw)
            continue
        if tolerant:
            # Discovered under a capture directory: accept a raw
            # Chrome trace (profiler tooling output) by wrapping it;
            # anything else (an xplane sidecar, an unrelated JSON) is
            # counted and skipped, never fatal.
            wrapped = _wrap_raw_chrome_trace(raw, path, schema)
            if wrapped is not None:
                records.append(wrapped)
            else:
                skipped += 1
            continue
        errors.extend(f"{path}: {e}" for e in errs)
    for e in errors:
        print(e, file=sys.stderr)
    if not records:
        print("merge_traces: no valid trace files", file=sys.stderr)
        return 1
    merged = merge(records)
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    print(
        f"merge_traces: {len(records)} host trace(s), "
        f"{len(merged['traceEvents'])} event(s) -> {args.output}"
        + (f" ({skipped} discovered file(s) skipped)" if skipped else "")
        + (f" ({len(errors)} input error(s))" if errors else "")
    )
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
