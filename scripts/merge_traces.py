#!/usr/bin/env python
"""Merge per-host trace exports into one Perfetto-loadable file.

Usage:
    python scripts/merge_traces.py -o merged.json trace.0.json trace.1.json ...
    python scripts/merge_traces.py -o merged.json 'traces/trace.*.json'

Each input is a ``fluxmpi_tpu.trace/v1`` / kind="trace" export (what
``Tracer.export(path)`` / ``FLUXMPI_TPU_TRACE=<path>`` writes, one per
host). Span timestamps are wall-clock-anchored microseconds, so events
from different hosts land on one shared timeline without re-basing —
cross-host skew is NTP skew, small enough to read collective alignment
at step granularity. Every host keeps its own pid lane (relabeled
``host <process>``), so Perfetto renders one process group per host.

The output is itself a valid kind="trace" record (extra top-level keys
are Chrome-trace metadata, which Perfetto ignores), so
``scripts/check_metrics_schema.py merged.json`` validates it.

Like check_metrics_schema.py, the schema module is loaded by file path —
this script must stay runnable in a second without importing jax.
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_schema():
    path = os.path.join(_REPO, "fluxmpi_tpu", "telemetry", "schema.py")
    spec = importlib.util.spec_from_file_location("_fluxmpi_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def merge(records: list[dict]) -> dict:
    """Merge kind="trace" records into one. Each host's events are
    re-pidded to its ``process`` index — original pids can collide
    across hosts (containerized SPMD launches everything as pid 1),
    which would silently fold two hosts into one Perfetto lane — and
    process_name metadata is rewritten to ``host <process>`` so the
    merged view is attributable at a glance."""
    events: list[dict] = []
    seen_processes: list[int] = []
    for rec in records:
        process = int(rec.get("process", 0))
        seen_processes.append(process)
        for ev in rec.get("traceEvents", []):
            if "pid" in ev:
                ev = {**ev, "pid": process}
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev = {
                    **ev,
                    "args": {"name": f"host {process}"},
                }
            events.append(ev)
    # Stable render order: metadata first, then by timestamp.
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {
        "schema": _load_schema().TRACE_SCHEMA,
        "kind": "trace",
        "time_unix": time.time(),
        # The merged file spans hosts; 'process' names the lead by
        # convention so the record stays schema-valid.
        "process": min(seen_processes) if seen_processes else 0,
        "merged_from": sorted(seen_processes),
        "displayTimeUnit": "ms",
        "traceEvents": events,
    }


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        description="Merge per-host fluxmpi_tpu trace exports into one "
        "Perfetto-loadable Chrome-trace JSON."
    )
    parser.add_argument(
        "-o", "--output", required=True, help="merged output path"
    )
    parser.add_argument(
        "inputs", nargs="+",
        help="per-host trace JSON files (globs are expanded)",
    )
    args = parser.parse_args(argv)

    paths: list[str] = []
    for pattern in args.inputs:
        matched = sorted(glob.glob(pattern))
        if matched:
            paths.extend(matched)
        else:
            paths.append(pattern)  # literal path: missing files error below

    schema = _load_schema()
    records: list[dict] = []
    errors: list[str] = []
    for path in paths:
        if not os.path.exists(path):
            errors.append(f"{path}: no such file")
            continue
        with open(path, "r", encoding="utf-8") as f:
            try:
                rec = json.load(f)
            except json.JSONDecodeError as exc:
                errors.append(f"{path}: not JSON: {exc}")
                continue
        errs = schema.validate_trace_export(rec)
        if errs:
            errors.extend(f"{path}: {e}" for e in errs)
            continue
        records.append(rec)
    for e in errors:
        print(e, file=sys.stderr)
    if not records:
        print("merge_traces: no valid trace files", file=sys.stderr)
        return 1
    merged = merge(records)
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    print(
        f"merge_traces: {len(records)} host trace(s), "
        f"{len(merged['traceEvents'])} event(s) -> {args.output}"
        + (f" ({len(errors)} input error(s))" if errors else "")
    )
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
