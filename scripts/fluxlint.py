#!/usr/bin/env python
"""fluxlint CLI — run the repo's AST-based SPMD/hot-path invariant
checker (fluxmpi_tpu/analysis/) over source trees.

Usage:
    python scripts/fluxlint.py [PATH ...] [--json] [--baseline FILE]
                               [--no-baseline]

- PATHs are files or directories, absolute or repo-root-relative;
  default: ``fluxmpi_tpu scripts`` (the tier-1 configuration).
- ``--json`` emits one ``fluxmpi_tpu.fluxlint/v1`` report object on
  stdout instead of text lines.
- ``--baseline FILE`` overrides the default ``.fluxlint-baseline.json``
  at the repo root; ``--no-baseline`` runs raw (every finding active).

Exit codes mirror scripts/check_metrics_schema.py: 0 clean, 1 findings,
2 unreadable input (unparsable file, missing registry source).

The analysis package is loaded **by file path** — not via
``import fluxmpi_tpu`` — so a lint run never imports jax or boots a
backend (the same discipline check_metrics_schema.py applies to the
telemetry schema; in fact that script now borrows this package's
schema loader).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PKG_NAME = "_fluxmpi_analysis"


def load_analysis(repo_root: str = _REPO):
    """Load ``fluxmpi_tpu/analysis`` as a standalone package (no parent
    ``fluxmpi_tpu`` import, hence no jax)."""
    if _PKG_NAME in sys.modules:
        return sys.modules[_PKG_NAME]
    pkg_dir = os.path.join(repo_root, "fluxmpi_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        _PKG_NAME,
        os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[_PKG_NAME] = mod  # registered first so `from .x import`
    try:                          # inside the package resolves
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(_PKG_NAME, None)
        raise
    return mod


def main(argv: list[str]) -> int:
    as_json = False
    baseline_path: str | None = None
    no_baseline = False
    targets: list[str] = []
    it = iter(argv)
    for arg in it:
        if arg == "--json":
            as_json = True
        elif arg == "--baseline":
            baseline_path = next(it, None)
            if baseline_path is None:
                print("--baseline needs a FILE argument", file=sys.stderr)
                return 2
        elif arg == "--no-baseline":
            no_baseline = True
        elif arg in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            targets.append(arg)
    if not targets:
        targets = ["fluxmpi_tpu", "scripts"]
    try:
        analysis = load_analysis()
    except (OSError, SyntaxError) as exc:
        print(f"fluxlint: cannot load analysis package: {exc}", file=sys.stderr)
        return 2
    if no_baseline:
        baseline_path = ""
    try:
        report = analysis.lint_repo(
            _REPO, targets, baseline_path=baseline_path
        )
    except (OSError, ValueError) as exc:
        # Missing/garbled registry sources (schema.py, faults.py, docs
        # table) are unreadable-input failures, not findings.
        print(f"fluxlint: {exc}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(report.to_json(), indent=1, sort_keys=True))
    else:
        print(report.text())
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
