#!/usr/bin/env python
"""Per-layer training-dynamics report from telemetry JSONL streams.

Reads the per-host JSONL files a run emitted (``init(telemetry=...)``
with the model-internals plane on — ``init(model_stats=True)`` /
``FLUXMPI_TPU_MODEL_STATS=1``), takes each process's LAST record
carrying ``model.*`` metrics (the gauges describe the newest flush),
and prints the per-layer view the plane exists for — which layers carry
the gradient signal, whether the update-to-weight ratios are in the
healthy band, where nonfinite gradients first appeared, and the
gradient noise scale (B_simple, McCandlish et al. 2018) with its
critical-batch-size reading:

    $ python scripts/modelstats_report.py run.*.jsonl
    host 0: 3 layer group(s), step data from the last flush
      LAYER                      GRAD NORM   PARAM NORM   UPD/WEIGHT  NONFIN
      params/dense_1               0.412        3.21        2.1e-03       0
      params/dense_0               0.307        2.88        1.8e-03       0
      params/dense_2               0.101        1.09        9.9e-04       0
      noise scale B_simple ~ 1.6e+00  (last flush; ingredients below)
        E|g_rank|^2 19.48  |g_mean|^2 16.67
    run: 1 host stream(s), 3 layer group(s)

The history mode (``--history``) additionally aggregates over EVERY
record in the bank: the mean of the two noise-scale *ingredients*
(``model.grad_sqnorm_{local,global}``) and a B_simple recomputed from
those means — single-flush B_simple estimates are noisy by construction
(and the derived gauge is absent on flushes where the estimators landed
outside their valid region, so a mean of the per-flush values would be
a biased survivor-sample mean-of-ratios); averaging the ingredients
first is the stable reading to size a batch against. Deriving B_simple
from the ingredient means needs the run geometry the bank does not
carry — pass ``--batch`` (global batch size) and ``--workers`` (DP
width) and history mode prints it; without them it prints the mean
ingredients and their ratio. The per-flush estimate history
(last/mean/count) is shown alongside for reference.

Usage:
    python scripts/modelstats_report.py FILE [FILE ...] [--json]
                                        [--top N] [--history]
                                        [--batch N --workers W]

``--top N`` limits the per-layer table to the N largest gradient norms
(default: all). ``--json`` prints one machine-readable JSON object.

Exit codes: 0 = model.* data found and reported; 1 = inputs readable
but NO model metrics anywhere (the plane was off — nothing to report);
2 = a file was missing/unreadable. Torn/corrupt LINES are skipped with
a stderr warning, never fatal (the goodput_report contract).

Stdlib-only, no jax, no package import — runnable anywhere the JSONL
landed (same contract as scripts/check_metrics_schema.py).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any


def _extract_model(record: dict) -> dict[str, Any] | None:
    """Pull the model.* gauges out of one telemetry flush record; None
    when the record carries none (the plane was off at that flush)."""
    metrics = record.get("metrics")
    if not isinstance(metrics, list):
        return None
    layers: dict[str, dict[str, float]] = {}
    scalars: dict[str, float] = {}
    found = False
    for m in metrics:
        if not isinstance(m, dict):
            continue
        name = m.get("name")
        if not isinstance(name, str) or not name.startswith("model."):
            continue
        value = m.get("value")
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        found = True
        layer = (m.get("labels") or {}).get("layer")
        if isinstance(layer, str) and layer:
            slot = layers.setdefault(layer, {})
            if name == "model.layer_grad_norm":
                slot["grad_norm"] = float(value)
            elif name == "model.layer_param_norm":
                slot["param_norm"] = float(value)
            elif name == "model.update_ratio":
                slot["update_ratio"] = float(value)
            elif name == "model.nonfinite":
                slot["nonfinite"] = float(value)
        elif name in (
            "model.grad_sqnorm_local",
            "model.grad_sqnorm_global",
            "model.grad_noise_scale",
        ):
            scalars[name.split(".", 1)[1]] = float(value)
    if not found:
        return None
    return {"layers": layers, "scalars": scalars}


def parse_banks(
    paths: list[str],
) -> tuple[dict[int, dict[str, Any]], dict[int, dict[str, list]], list[str]]:
    """(last model view per process, per-process noise histories —
    ``{"estimates": [...], "local": [...], "global": [...]}`` — fatal
    errors). Torn lines warn to stderr and are skipped."""
    last: dict[int, dict[str, Any]] = {}
    history: dict[int, dict[str, list]] = {}
    errors: list[str] = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                content = f.read()
        except OSError as exc:
            errors.append(f"{path}: {exc}")
            continue
        for i, line in enumerate(content.splitlines(), 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                print(
                    f"warning: {path}:{i}: skipping torn/corrupt line",
                    file=sys.stderr,
                )
                continue
            if not isinstance(rec, dict):
                continue
            view = _extract_model(rec)
            if view is None:
                continue
            proc = rec.get("process")
            proc = proc if isinstance(proc, int) else 0
            view["time_unix"] = rec.get("time_unix")
            last[proc] = view
            hist = history.setdefault(
                proc, {"estimates": [], "local": [], "global": []}
            )
            scalars = view["scalars"]
            ns = scalars.get("grad_noise_scale")
            if ns is not None:
                hist["estimates"].append(ns)
            if (
                "grad_sqnorm_local" in scalars
                and "grad_sqnorm_global" in scalars
            ):
                # The INGREDIENTS are present on every noise-carrying
                # flush — including the ones where the derived estimate
                # was undefined — so their means are the unbiased,
                # uncensored aggregate.
                hist["local"].append(scalars["grad_sqnorm_local"])
                hist["global"].append(scalars["grad_sqnorm_global"])
    return last, history, errors


def _fmt(v: Any, spec: str, dash: str = "-") -> str:
    if v is None:
        return dash
    try:
        return format(v, spec)
    except (TypeError, ValueError):
        return dash


def _b_simple(
    local_sq: float, global_sq: float, batch: int, workers: int
) -> float | None:
    """B_simple from the two gradient sq-norms (the stdlib twin of
    ``fluxmpi_tpu.telemetry.modelstats.noise_scale`` — this script must
    not import the package): tr(Sigma)/|G|^2 via the McCandlish
    two-batch-size estimators."""
    if workers <= 1 or batch <= 0:
        return None
    b_big = float(batch)
    b_small = b_big / float(workers)
    g2 = (b_big * global_sq - b_small * local_sq) / (b_big - b_small)
    trace_sigma = (local_sq - global_sq) / (1.0 / b_small - 1.0 / b_big)
    if g2 <= 0.0 or trace_sigma < 0.0:
        return None
    return trace_sigma / g2


def render(
    last: dict[int, dict[str, Any]],
    history: dict[int, dict[str, list]],
    top: int | None,
    show_history: bool,
    batch: int | None = None,
    workers: int | None = None,
) -> str:
    lines: list[str] = []
    all_layers: set[str] = set()
    for proc in sorted(last):
        view = last[proc]
        layers = view["layers"]
        all_layers.update(layers)
        lines.append(
            f"host {proc}: {len(layers)} layer group(s), "
            f"stats from the last flush"
        )
        lines.append(
            f"  {'LAYER':<28}{'GRAD NORM':>11} {'PARAM NORM':>11} "
            f"{'UPD/WEIGHT':>11} {'NONFIN':>7}"
        )
        ranked = sorted(
            layers.items(),
            key=lambda kv: kv[1].get("grad_norm", 0.0),
            reverse=True,
        )
        if top is not None:
            ranked = ranked[:top]
        for name, st in ranked:
            bad = st.get("nonfinite", 0.0)
            flag = "  <-- NONFINITE" if bad else ""
            lines.append(
                f"  {name:<28}"
                f"{_fmt(st.get('grad_norm'), '>11.4g')} "
                f"{_fmt(st.get('param_norm'), '>11.4g')} "
                f"{_fmt(st.get('update_ratio'), '>11.3g')} "
                f"{_fmt(bad, '>7.0f')}{flag}"
            )
        scalars = view["scalars"]
        ns = scalars.get("grad_noise_scale")
        if ns is not None:
            lines.append(
                f"  noise scale B_simple ~ {ns:.3g}  "
                f"(last flush; single-step estimates are noisy)"
            )
        if "grad_sqnorm_local" in scalars:
            lines.append(
                f"    E|g_rank|^2 {scalars['grad_sqnorm_local']:.4g}  "
                f"|g_mean|^2 {scalars.get('grad_sqnorm_global', 0.0):.4g}"
            )
        if show_history and history.get(proc):
            hist = history[proc]
            if hist["local"]:
                # The unbiased aggregate: INGREDIENT means over every
                # noise-carrying flush (estimate-less flushes included),
                # turned into B_simple when the run geometry is known.
                mean_l = sum(hist["local"]) / len(hist["local"])
                mean_g = sum(hist["global"]) / len(hist["global"])
                line = (
                    f"  ingredient means over {len(hist['local'])} "
                    f"flush(es): E|g_rank|^2 {mean_l:.4g}  "
                    f"|g_mean|^2 {mean_g:.4g}  ratio {mean_l / mean_g:.3g}"
                    if mean_g > 0
                    else f"  ingredient means over {len(hist['local'])} "
                    f"flush(es): E|g_rank|^2 {mean_l:.4g}  |g_mean|^2 0"
                )
                lines.append(line)
                if batch and workers:
                    b_mean = _b_simple(mean_l, mean_g, batch, workers)
                    lines.append(
                        f"  B_simple from ingredient means "
                        f"(batch={batch}, workers={workers}): "
                        + (f"{b_mean:.3g}" if b_mean is not None
                           else "undefined (|G|^2 estimate <= 0)")
                    )
            est = hist["estimates"]
            if est:
                lines.append(
                    f"  per-flush estimate history (None-censored): "
                    f"last {est[-1]:.3g}  mean {sum(est) / len(est):.3g}  "
                    f"n={len(est)}"
                )
    lines.append(
        f"run: {len(last)} host stream(s), {len(all_layers)} layer group(s)"
    )
    return "\n".join(lines)


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        description="Per-layer gradient/update statistics and gradient "
        "noise scale from telemetry JSONL streams (the model-internals "
        "plane, init(model_stats=True))."
    )
    parser.add_argument("files", nargs="+", help="telemetry JSONL file(s)")
    parser.add_argument(
        "--json", action="store_true",
        help="print one machine-readable JSON object instead of the table",
    )
    parser.add_argument(
        "--top", type=int, default=None, metavar="N",
        help="show only the N layers with the largest gradient norms",
    )
    parser.add_argument(
        "--history", action="store_true",
        help="aggregate the noise-scale ingredients over every record "
        "in the bank",
    )
    parser.add_argument(
        "--batch", type=int, default=None, metavar="N",
        help="global batch size of the run — with --workers, history "
        "mode derives B_simple from the ingredient means",
    )
    parser.add_argument(
        "--workers", type=int, default=None, metavar="W",
        help="data-parallel width of the run (see --batch)",
    )
    args = parser.parse_args(argv)
    if args.top is not None and args.top < 1:
        parser.error("--top must be >= 1")
    if bool(args.batch) != bool(args.workers):
        parser.error("--batch and --workers go together")

    last, history, errors = parse_banks(args.files)
    for err in errors:
        print(f"error: {err}", file=sys.stderr)
    if errors:
        return 2
    if not last:
        print(
            "no model.* metrics found — was the model-internals plane on? "
            "(init(model_stats=True) / FLUXMPI_TPU_MODEL_STATS=1)",
            file=sys.stderr,
        )
        return 1
    if args.json:
        out = {
            "hosts": {
                str(proc): last[proc] for proc in sorted(last)
            },
            "noise_history": {
                str(proc): history[proc] for proc in sorted(history)
            },
        }
        print(json.dumps(out))
    else:
        print(
            render(
                last, history, args.top, args.history,
                batch=args.batch, workers=args.workers,
            )
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
