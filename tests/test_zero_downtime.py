"""Zero-downtime fleet ops: async sharded saves (driver pays only the
snapshot), multi-tier retention (local fast tier + durable tier), and
live N→M resize. Fast chaos tests here; the kill-mid-async-write and
real multi-process resize subprocess variants are at the bottom (the
resize one slow-marked, the elastic-test discipline)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.errors import FaultInjectedError
from fluxmpi_tpu.fleet import resize as resize_mod
from fluxmpi_tpu.fleet.resize import ResizeCoordinator, read_handoff
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import goodput as goodput_mod
from fluxmpi_tpu.telemetry import schema as tschema
from fluxmpi_tpu.telemetry.goodput import GoodputTracker
from fluxmpi_tpu.telemetry.watchdog import Watchdog
from fluxmpi_tpu.utils import CheckpointManager

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_planes():
    faults.clear()
    fm.clear_preemption()
    prev_tracker = goodput_mod.set_goodput_tracker(
        GoodputTracker(enabled=False)
    )
    yield
    faults.clear()
    fm.clear_preemption()
    resize_mod.shutdown()
    goodput_mod.set_goodput_tracker(prev_tracker)


def _state():
    return {"w": jnp.arange(4.0), "b": jnp.ones((2,))}


def _leaves_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        ),
        a, b,
    )


def _pieces(world, n=128):
    from fluxmpi_tpu.models import MLP

    model = MLP(features=(16, 1))

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1)))
    )
    ds = ArrayDataset((x, x**2))

    def fresh():
        return replicate(TrainState.create(params, opt), world)

    def loader():
        return DistributedDataLoader(ds, 32, mesh=world, shuffle=True,
                                     seed=7, device_gather=False, prefetch=0)

    return loss_fn, opt, fresh, loader


# ---------------------------------------------------------------------------
# New chaos sites are registered and injectable through the real code paths
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "site",
    ["ckpt.snapshot", "ckpt.async_write", "resize.drain", "resize.reshard"],
)
def test_new_zero_downtime_sites_are_registered(site):
    assert site in faults.KNOWN_SITES


def test_ckpt_snapshot_site_fires_on_driver(world, tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    with faults.scope("ckpt.snapshot@step=1"):
        with pytest.raises(FaultInjectedError, match="ckpt.snapshot"):
            mgr.save(1, _state())
    # The failed snapshot never reached the writer: nothing committed,
    # nothing in flight, and the manager is reusable.
    assert mgr.all_steps() == []
    mgr.save(1, _state())
    mgr.close()
    assert mgr.all_steps() == [1]


def test_ckpt_async_write_failure_is_stored_and_reraised(world, tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    with faults.scope("ckpt.async_write@step=1"):
        mgr.save(1, _state())  # driver returns: the fault fires off-thread
        with pytest.raises(FaultInjectedError, match="ckpt.async_write"):
            mgr.wait_until_finished()
    # The failure was consumed by the re-raise; the next save is clean.
    mgr.save(2, _state())
    mgr.close()
    assert mgr.all_steps() == [2]


def test_resize_drain_site_fires(tmp_path):
    rc = ResizeCoordinator()
    with faults.scope("resize.drain@step=1"):
        with pytest.raises(FaultInjectedError, match="resize.drain"):
            rc.begin(2, from_processes=1)


def test_resize_reshard_site_fires(tmp_path):
    rc = ResizeCoordinator()
    rc.begin(1, from_processes=1)
    rc.note_drained()
    rc.write_handoff(str(tmp_path), step=3, from_processes=1, to_processes=1)
    assert read_handoff(str(tmp_path)) is not None
    with faults.scope("resize.reshard@step=1"):
        with pytest.raises(FaultInjectedError, match="resize.reshard"):
            ResizeCoordinator().maybe_begin_reshard(str(tmp_path))


# ---------------------------------------------------------------------------
# Async saves: bit-identity with sync, driver cost ≈ snapshot, coalescing
# ---------------------------------------------------------------------------


def test_async_save_bit_identical_to_sync(world, tmp_path):
    """A fused-window run checkpointed asynchronously banks byte-for-byte
    the same artifacts as the same run checkpointed synchronously — the
    donation-safe snapshot is a faithful copy of the live state."""
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    states = {}
    for mode, async_save in [("sync", False), ("async", True)]:
        mgr = CheckpointManager(
            str(tmp_path / mode), async_save=async_save
        )
        train_loop(step, fresh(), loader(), steps=6, checkpoint=mgr,
                   save_every=2, flush_every=2)
        # The async run may legitimately coalesce an intermediate save
        # away (a newer request supersedes a queued one); the final
        # boundary is always committed.
        assert mgr.all_steps()[-1] == 6
        # Resume through the loop (0 updates left): the returned state
        # IS the restored banked step-6 payload.
        restored, summary = train_loop(step, fresh(), loader(), steps=6,
                                       checkpoint=mgr, save_every=2,
                                       flush_every=2, resume=True)
        mgr.close()
        assert summary["resumed_from"] == 6 and summary["updates"] == 6
        states[mode] = restored
    _leaves_equal(states["sync"], states["async"])


def test_async_save_driver_pays_snapshot_only_and_watchdog_stays_green(
    world, tmp_path
):
    """With a ``delay=`` stall injected into the background writer, the
    driver-thread ``checkpoint_save`` goodput bucket stays ≈ the snapshot
    cost (far below the stall), the real write cost lands in the
    off-driver ``background`` ledger, and a watchdog watching driver
    progress never trips while the slow save is in flight."""
    delay = 0.6
    tracker = goodput_mod.set_goodput_tracker(GoodputTracker())
    tracker = goodput_mod.get_goodput_tracker()
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    beat = [0]
    wd = Watchdog(deadline=0.25, dump_dir=str(tmp_path),
                  sources=[lambda: beat[0]])
    with faults.scope(f"ckpt.async_write@step=1:delay={delay}"):
        t0 = time.perf_counter()
        mgr.save(1, _state())
        driver_cost = time.perf_counter() - t0
        assert driver_cost < delay / 2  # never blocked on the stall
        # The driver keeps making progress while the writer stalls —
        # the watchdog (and through the same sources, /healthz) stays
        # green for the whole slow save.
        deadline = time.time() + delay
        while time.time() < deadline and mgr.tier_of(1) is None:
            beat[0] += 1
            assert wd.check() is None
            time.sleep(0.02)
        driver_bucket = tracker.bucket_seconds("checkpoint_save")
        assert driver_bucket < delay / 2
        mgr.wait_until_finished()
    mgr.close()
    assert mgr.all_steps() == [1]
    report = tracker.report()
    # The stalled write's wall time is observable — in the background
    # ledger, NOT the driver buckets (which still sum to the wall).
    assert report["background"]["checkpoint_async_write"] >= delay
    assert report["buckets"]["checkpoint_save"] < delay / 2


def test_overlapping_async_saves_coalesce(world, tmp_path):
    """At most one write in flight; a newer request supersedes the one
    queued behind it (its snapshot is dropped and counted)."""
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True,
                            max_to_keep=None)
    with faults.scope("ckpt.async_write@step=1:delay=0.4"):
        mgr.save(1, _state())   # writer stalls on the injected delay
        mgr.save(2, _state())   # parks in the queued slot
        mgr.save(3, _state())   # supersedes step 2
        assert mgr.superseded == 1
        mgr.wait_until_finished()
    mgr.close()
    # Step 2 was coalesced away; 1 and 3 committed under one wait.
    assert mgr.all_steps() == [1, 3]


# ---------------------------------------------------------------------------
# Multi-tier retention
# ---------------------------------------------------------------------------


def test_multi_tier_retention_promotion_and_restore_preference(
    world, tmp_path
):
    durable, local = str(tmp_path / "durable"), str(tmp_path / "local")
    mgr = CheckpointManager(durable, async_save=False, max_to_keep=2,
                            local_dir=local, local_max_to_keep=1)
    saved = {}
    for step in (1, 2, 3):
        saved[step] = _state()
        saved[step]["w"] = saved[step]["w"] + step
        mgr.save(step, saved[step])
    mgr.close()
    # Independent retention: fast tier keeps 1, durable keeps 2; a step
    # present in ANY tier is restorable.
    assert mgr.all_steps() == [2, 3]
    assert mgr.tier_of(3) == "local"     # fastest committed tier wins
    assert mgr.tier_of(2) == "durable"   # evicted locally, promoted copy
    assert mgr.tier_of(1) is None
    step, restored = mgr.restore(_state())
    assert step == 3
    _leaves_equal(restored, saved[3])
    step, restored = mgr.restore(_state(), step=2)
    _leaves_equal(restored, saved[2])
    # Promotion ran for every committed step: the durable tier holds the
    # newest max_to_keep of them on its own, so losing the local disk
    # loses nothing retained.
    mgr2 = CheckpointManager(durable, async_save=False)
    assert mgr2.all_steps() == [2, 3]


def test_env_vars_wire_async_and_local_dir(world, tmp_path, monkeypatch):
    local = str(tmp_path / "fast")
    monkeypatch.setenv("FLUXMPI_TPU_CKPT_ASYNC", "0")
    monkeypatch.setenv("FLUXMPI_TPU_CKPT_LOCAL_DIR", local)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr._async is False
    assert mgr.local_dir == os.path.abspath(local)
    monkeypatch.setenv("FLUXMPI_TPU_CKPT_ASYNC", "1")
    monkeypatch.delenv("FLUXMPI_TPU_CKPT_LOCAL_DIR")
    mgr = CheckpointManager(str(tmp_path / "ck2"))
    assert mgr._async is True and mgr.local_dir is None


# ---------------------------------------------------------------------------
# Resize coordinator: request plumbing, record validation, in-process loop
# ---------------------------------------------------------------------------


def test_resize_configure_env_and_spec_forms(tmp_path, monkeypatch):
    assert not resize_mod.enabled()
    monkeypatch.setenv("FLUXMPI_TPU_RESIZE", "1")
    assert resize_mod.configure() is not None and resize_mod.enabled()
    resize_mod.configure(False)
    assert not resize_mod.enabled()
    bank = str(tmp_path / "resize.jsonl")
    rc = resize_mod.configure(bank)
    assert rc.enabled and rc.log_path == bank
    with pytest.raises(ValueError, match="resize target"):
        resize_mod.request_resize(0)
    resize_mod.request_resize(4, reason="test")
    assert rc.requested_target() == 4
    resize_mod.shutdown()
    # The shutdown no-leak contract: a request must not leak into the
    # next run's first flush boundary.
    assert rc.requested_target() == 0 and not resize_mod.enabled()


def test_resize_record_schema_validation():
    rec = {
        "schema": tschema.RESIZE_SCHEMA,
        "time_unix": 1.0,
        "step": 4,
        "from_processes": 4,
        "to_processes": 2,
        "reason": "api",
        "phases": {"drain": 0.1, "save": 0.5, "reshard": 0.2,
                   "restart": 0.2},
        "badput_seconds": 1.0,
    }
    assert tschema.validate_resize_record(rec) == []
    bad = dict(rec, phases={"drain": 0.1}, badput_seconds=0.1)
    assert tschema.validate_resize_record(bad)
    bad = dict(rec, badput_seconds=2.0)
    assert any("sum" in e for e in tschema.validate_resize_record(bad))


def test_in_process_resize_round_trip_is_sample_exact(world, tmp_path):
    """Single-process end-to-end: request → drain at a flush boundary →
    timed save + handoff stamp → resumed loop resheards, finishes the
    run, and banks one schema-valid badput record."""
    bank = str(tmp_path / "resize_bank.jsonl")
    resize_mod.configure(bank)
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    ckpt_dir = str(tmp_path / "ck")

    # Uninterrupted reference.
    ref_state, ref_summary = train_loop(step, fresh(), loader(), steps=8,
                                        flush_every=2)

    mgr = CheckpointManager(ckpt_dir, async_save=True)
    resize_mod.request_resize(1, reason="test-shrink")
    state, summary = train_loop(step, fresh(), loader(), steps=8,
                                checkpoint=mgr, save_every=100,
                                flush_every=2)
    mgr.close()
    assert summary["resized_to"] == 1
    assert 0 < summary["updates"] < 8  # drained at a window boundary
    stamp = read_handoff(ckpt_dir)
    assert stamp is not None and stamp["handoff"] is True
    assert stamp["step"] == summary["updates"]

    mgr2 = CheckpointManager(ckpt_dir, async_save=True)
    state, summary2 = train_loop(step, fresh(), loader(), steps=8,
                                 checkpoint=mgr2, save_every=100,
                                 flush_every=2, resume=True)
    mgr2.close()
    assert summary2["resumed_from"] == summary["updates"]
    assert summary2["updates"] == 8
    assert summary2["resized_to"] is None
    # The resumed world consumed the stamp: record banked, stamp gone.
    assert read_handoff(ckpt_dir) is None
    with open(bank) as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == 1
    rec = records[0]
    assert tschema.validate_resize_record(rec) == []
    assert rec["from_processes"] == rec["to_processes"] == 1
    assert rec["reason"] == "test-shrink"
    assert set(rec["phases"]) == set(tschema.RESIZE_PHASES)
    assert rec["badput_seconds"] > 0
    # Sample-exact across the handoff: same final state as the
    # uninterrupted run (single process: bit-for-bit).
    _leaves_equal(state.params, ref_state.params)


# ---------------------------------------------------------------------------
# Kill mid-async-write: the previous committed step survives (subprocess)
# ---------------------------------------------------------------------------

_KILL_CHILD = """
import os, sys
ckpt_dir = sys.argv[1]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from fluxmpi_tpu import faults
from fluxmpi_tpu.utils import CheckpointManager

state = {"w": jnp.arange(4.0), "b": jnp.ones((2,))}
mgr = CheckpointManager(ckpt_dir, async_save=True)
mgr.save(1, state)
mgr.wait_until_finished()  # step 1 committed
# Stall the next write inside the commit protocol (payload staged,
# marker not yet written) and hold it there until the kill.
faults.install("ckpt.commit@step=1:delay=120")
mgr.save(2, state)
import time
while mgr.tier_of(2) is None:
    print("INFLIGHT", flush=True)
    time.sleep(0.1)
"""


def test_kill_mid_async_write_previous_step_restorable(world, tmp_path):
    """SIGKILL a process whose background writer is mid-commit: the torn
    step is quarantined at the next startup and the previously committed
    step restores untouched — an async save can never eat the last good
    checkpoint."""
    script = tmp_path / "child.py"
    script.write_text(_KILL_CHILD)
    ckpt_dir = tmp_path / "ck"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, str(script), str(ckpt_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
    )
    try:
        assert proc.stdout.readline().strip() == "INFLIGHT"
        time.sleep(0.3)  # let the writer sit mid-commit
        proc.kill()
        proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    # The torn step-2 artifacts exist but are uncommitted: discovery
    # never lists them, and the next manager quarantines them away.
    with pytest.warns(UserWarning, match="quarantined"):
        mgr = CheckpointManager(str(ckpt_dir), async_save=True)
    assert any("step_00000002" in name for name in mgr.quarantined)
    assert mgr.all_steps() == [1]
    step, restored = mgr.restore(
        {"w": jnp.zeros(4), "b": jnp.zeros(2)}
    )
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored["w"])), np.arange(4.0)
    )
    mgr.close()


# ---------------------------------------------------------------------------
# Real multi-process live resize, 4→2 and 2→4 (slow)
# ---------------------------------------------------------------------------

_RESIZE_CHILD = """
import json, os, sys
coordinator, nprocs, pid, ckpt_dir, log_dir, resize_to = sys.argv[1:7]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import optax
import fluxmpi_tpu as fm
from fluxmpi_tpu.data import (ArrayDataset, DistributedDataContainer,
                              DistributedDataLoader)
from fluxmpi_tpu.fleet import resize as flr
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.utils import CheckpointManager
from fluxmpi_tpu.models import MLP

bank = os.path.join(log_dir, "resize_bank.jsonl")
mesh = fm.init(distributed=True, coordinator_address=coordinator,
               num_processes=int(nprocs), process_id=int(pid),
               preemption=True, resize=bank)

n = 256
rng = np.random.default_rng(0)  # same data on every process
x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
ids = np.arange(n, dtype=np.int32)
ds = ArrayDataset((x, x**2, ids))

log = open(os.path.join(log_dir, f"consumed.{nprocs}.{pid}.jsonl"), "a",
           buffering=1)
seen = [0]

def track(batch):
    log.write(json.dumps(np.asarray(batch[2]).tolist()) + "\\n")
    seen[0] += 1
    if int(resize_to) and seen[0] == 3:
        flr.request_resize(int(resize_to), reason="autoscaler")
    return batch

loader = DistributedDataLoader(
    DistributedDataContainer(ds), 16, mesh=mesh, shuffle=True, seed=5,
    elastic_order=True, prefetch=0, device_gather=False, transform=track,
)

model = MLP(features=(16, 1))

def loss_fn(p, ms, b):
    bx, by, _ = b
    return jnp.mean((model.apply(p, bx) - by) ** 2), ms

opt = optax.adam(1e-3)
params = fm.synchronize(model.init(jax.random.PRNGKey(0), x[:2]))
state = replicate(TrainState.create(params, opt), mesh)
step = make_train_step(loss_fn, opt, mesh=mesh)
mgr = CheckpointManager(ckpt_dir, async_save=False)
print("READY", flush=True)
state, summary = train_loop(step, state, loader, epochs=2,
                            checkpoint=mgr, save_every=100, flush_every=2,
                            resume=True)
print("SUMMARY " + json.dumps(
    {"updates": summary["updates"], "epochs": summary["epochs"],
     "resized_to": summary["resized_to"], "loss": summary["loss"],
     "resumed_from": summary["resumed_from"]}), flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_world(script, nprocs, ckpt_dir, log_dir, resize_to):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(nprocs), str(i),
             str(ckpt_dir), str(log_dir), str(resize_to)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for i in range(nprocs)
    ]


def _drain_world(procs, tag):
    summaries = []
    try:
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=360)
            assert p.returncode == 0, f"{tag} rank {i}:\n{out}"
            line = [ln for ln in out.splitlines()
                    if ln.startswith("SUMMARY ")][-1]
            summaries.append(json.loads(line[len("SUMMARY "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return summaries


def _consumed_ids(log_dir, nprocs):
    out = []
    for i in range(nprocs):
        p = os.path.join(log_dir, f"consumed.{nprocs}.{i}.jsonl")
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                out.extend(json.loads(line))
    return out


@pytest.mark.slow
@pytest.mark.parametrize("n_before,n_after", [(4, 2), (2, 4)])
def test_live_resize_across_topologies_is_sample_exact(
    world, tmp_path, n_before, n_after
):
    """A mid-epoch ``request_resize(M)`` drains an N-process world at a
    window boundary, hands off, and the M-process resume finishes the
    run sample-exact (consumption-log multiset equality against an
    uninterrupted reference) with one schema-valid badput record in the
    bank."""
    script = tmp_path / "child.py"
    script.write_text(_RESIZE_CHILD)

    # Uninterrupted reference at the BEFORE topology (no resize request).
    ref_ckpt, ref_logs = tmp_path / "ref_ck", tmp_path / "ref_logs"
    ref_logs.mkdir()
    ref_summaries = _drain_world(
        _spawn_world(script, n_before, ref_ckpt, ref_logs, 0), "ref"
    )
    ref_ids = sorted(_consumed_ids(str(ref_logs), n_before))
    assert len(ref_ids) == 256 * 2  # 2 epochs, no remainder

    # Resizing run: every process requests M after 3 local batches.
    ckpt, logs = tmp_path / "ck", tmp_path / "logs"
    logs.mkdir()
    pre = _drain_world(
        _spawn_world(script, n_before, ckpt, logs, n_after), "draining"
    )
    assert all(s["resized_to"] == n_after for s in pre)
    banked = pre[0]["updates"]
    assert 0 < banked < 32  # drained mid-run at a window boundary
    stamp = read_handoff(str(ckpt))
    assert stamp is not None and stamp["to_processes"] == n_after

    # Resume at the AFTER topology, same checkpoint directory.
    post = _drain_world(
        _spawn_world(script, n_after, ckpt, logs, 0), "resumed"
    )
    assert all(s["resumed_from"] == banked for s in post)
    assert all(s["epochs"] == 2 for s in post)
    assert all(s["resized_to"] is None for s in post)

    # Sample-exact across the topology change.
    got = sorted(
        _consumed_ids(str(logs), n_before) + _consumed_ids(str(logs),
                                                           n_after)
    )
    assert got == ref_ids
    np.testing.assert_allclose(
        post[0]["loss"], ref_summaries[0]["loss"], rtol=5e-3
    )

    # The badput record: banked once (lead process of the resumed
    # world), schema-valid, with all four phases attributed.
    assert read_handoff(str(ckpt)) is None
    with open(logs / "resize_bank.jsonl") as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == 1
    rec = records[0]
    assert tschema.validate_resize_record(rec) == []
    assert rec["from_processes"] == n_before
    assert rec["to_processes"] == n_after
    assert rec["step"] == banked
    assert rec["reason"] == "autoscaler"
    assert rec["badput_seconds"] > 0
