"""Train-step factory tests: both styles agree with each other and with a
serial single-device update (the end-to-end analogue of the reference's
optimizer equivalence oracle, test/test_optimizer.jl:20-26)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp


def _setup(world):
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState

    model = MLP(features=(8, 8, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
    optimizer = optax.sgd(0.1)
    state = TrainState.create(params, optimizer)

    def loss_fn(p, mstate, batch):
        x, y = batch
        pred = model.apply(p, x)
        return jnp.mean((pred - y) ** 2), mstate

    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 2)).astype(np.float32)
    y = rng.normal(size=(16, 1)).astype(np.float32)
    return model, params, optimizer, state, loss_fn, (x, y)


def test_auto_matches_serial(world):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    step = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    new_state, loss = step(replicate(state), shard_batch(batch))

    # serial oracle on one device
    (sloss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, None, batch
    )
    updates, _ = optimizer.update(grads, optimizer.init(params), params)
    serial_params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        new_state.params,
        serial_params,
    )
    assert int(new_state.step) == 1


def test_shard_map_matches_auto(world):
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    auto = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    explicit = make_train_step(
        loss_fn, optimizer, style="shard_map", grad_reduce="mean", donate=False
    )
    s1, l1 = auto(replicate(state), shard_batch(batch))
    s2, l2 = explicit(replicate(state), shard_batch(batch))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s1.params,
        s2.params,
    )


def test_sum_semantics_with_distributed_optimizer(world, nworkers):
    # reference pattern: DistributedOptimizer sums; loss scaled by 1/workers
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch
    from fluxmpi_tpu.parallel import TrainState

    model, params, optimizer, _, _, batch = _setup(world)

    def scaled_loss(p, mstate, b):
        x, y = b
        pred = model.apply(p, x)
        return jnp.mean((pred - y) ** 2) / nworkers, mstate

    dopt = fm.DistributedOptimizer(optax.sgd(0.1), axis_name="dp")
    state = TrainState.create(params, dopt)
    step = make_train_step(
        scaled_loss, dopt, style="shard_map", grad_reduce=None, donate=False
    )
    s1, _ = step(replicate(state), shard_batch(batch))

    # mean-reduce path with plain optimizer must give the same parameters
    def plain_loss(p, mstate, b):
        x, y = b
        pred = model.apply(p, x)
        return jnp.mean((pred - y) ** 2), mstate

    plain = optax.sgd(0.1)
    state2 = TrainState.create(params, plain)
    step2 = make_train_step(
        plain_loss, plain, style="shard_map", grad_reduce="mean", donate=False
    )
    s2, _ = step2(replicate(state2), shard_batch(batch))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s1.params,
        s2.params,
    )


def test_training_converges(world):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch
    from fluxmpi_tpu.models import MLP

    model = MLP(features=(16, 16, 1))
    params = model.init(jax.random.PRNGKey(1), jnp.ones((1, 1)))
    optimizer = optax.adam(1e-2)

    def loss_fn(p, mstate, b):
        x, y = b
        return jnp.mean((model.apply(p, x) - y) ** 2), mstate

    step = make_train_step(loss_fn, optimizer, style="auto")
    state = replicate(TrainState.create(params, optimizer))
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(64, 1)).astype(np.float32)
    batch = shard_batch((x, (x**2).astype(np.float32)))
    losses = []
    for _ in range(60):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_invalid_style_rejected(world):
    import optax
    from fluxmpi_tpu.parallel import make_train_step

    with pytest.raises(ValueError):
        make_train_step(lambda p, s, b: (0.0, s), optax.sgd(0.1), style="magic")
    with pytest.raises(ValueError):
        make_train_step(
            lambda p, s, b: (0.0, s), optax.sgd(0.1), grad_reduce="median"
        )


def test_remat_matches_plain(world):
    """jax.checkpoint rematerialization must not change the math."""
    import optax as _optax

    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    plain = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    remat = make_train_step(
        loss_fn, optimizer, style="auto", donate=False, remat=True
    )
    s1, l1 = plain(replicate(state), shard_batch(batch))
    s2, l2 = remat(replicate(state), shard_batch(batch))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        s1.params,
        s2.params,
    )


def test_grad_accum_matches_full_batch(world):
    """K accumulation microbatches == one full-batch step (same mean-loss
    semantics, single optimizer update)."""
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    full = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    accum = make_train_step(
        loss_fn, optimizer, style="auto", donate=False, grad_accum_steps=4
    )
    s1, l1 = full(replicate(state), shard_batch(batch))
    s2, l2 = accum(replicate(state), shard_batch(batch))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s1.params,
        s2.params,
    )
    assert int(s2.step) == 1  # one update, not four


def test_scan_steps_match_sequential(world):
    """K scanned updates in one dispatch == K sequential single-step calls
    (same updates in the same order; [K] per-update losses returned)."""
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    K = 3
    rng = np.random.default_rng(7)
    xs = rng.normal(size=(K, 16, 2)).astype(np.float32)
    ys = rng.normal(size=(K, 16, 1)).astype(np.float32)

    single = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    s1 = replicate(state)
    losses_seq = []
    for i in range(K):
        s1, l = single(s1, shard_batch((xs[i], ys[i])))
        losses_seq.append(float(l))

    scanned = make_train_step(
        loss_fn, optimizer, style="auto", donate=False, scan_steps=K
    )
    s2, losses = scanned(
        replicate(state), shard_batch((xs, ys), spec=P(None, "dp"))
    )
    assert losses.shape == (K,)
    np.testing.assert_allclose(np.asarray(losses), losses_seq, rtol=1e-5)
    assert int(s2.step) == K
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s1.params,
        s2.params,
    )


def test_scan_steps_requires_auto(world):
    from fluxmpi_tpu.parallel import make_train_step

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    with pytest.raises(ValueError, match="scan_steps"):
        make_train_step(
            loss_fn, optimizer, style="shard_map", scan_steps=2
        )


def test_grad_accum_divisibility_error(world):
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    step = make_train_step(
        loss_fn, optimizer, style="auto", donate=False, grad_accum_steps=5
    )
    with pytest.raises(ValueError, match="not divisible"):
        step(replicate(state), shard_batch(batch))


def test_eval_step(world):
    from fluxmpi_tpu.parallel import make_eval_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)

    def metric_fn(p, mstate, b):
        x, y = b
        pred = model.apply(p, x)
        return {"mse": jnp.mean((pred - y) ** 2), "mae": jnp.mean(jnp.abs(pred - y))}

    ev = make_eval_step(metric_fn)
    metrics = ev(replicate(state), shard_batch(batch))
    x, y = batch
    pred = model.apply(params, x)
    np.testing.assert_allclose(
        float(metrics["mse"]), float(jnp.mean((pred - y) ** 2)), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(metrics["mae"]), float(jnp.mean(jnp.abs(pred - y))), rtol=1e-5
    )


def test_remat_dots_matches_plain(world):
    """checkpoint_dots policy must not change the math either."""
    import optax as _optax  # noqa: F401

    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, params, optimizer, state, loss_fn, batch = _setup(world)
    plain = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    dots = make_train_step(
        loss_fn, optimizer, style="auto", donate=False, remat="dots"
    )
    s1, l1 = plain(replicate(state), shard_batch(batch))
    s2, l2 = dots(replicate(state), shard_batch(batch))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        s1.params, s2.params,
    )

    with pytest.raises(ValueError, match="remat"):
        make_train_step(loss_fn, optimizer, style="auto", remat="everything")


def test_scan_steps_composes_with_fsdp_sharding(world):
    """scan_steps under an FSDP state layout: the scan carry keeps the
    sharded TrainState layout and the result matches replicated scan."""
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import (
        TrainState, fsdp_rule, make_train_step, shard_tree,
    )
    from fluxmpi_tpu.parallel.train import replicate, shard_batch
    import fluxmpi_tpu as fm

    mesh = fm.global_mesh()
    model = MLP(features=(32, 32, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
    opt = optax.adam(1e-2)

    def loss_fn(p, ms, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2), ms

    K = 2
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(K, 16, 2)).astype(np.float32)
    ys = rng.normal(size=(K, 16, 1)).astype(np.float32)
    batch = shard_batch((xs, ys), spec=P(None, "dp"))

    state0 = TrainState.create(params, opt)
    sharded, shardings = shard_tree(state0, mesh, fsdp_rule(mesh, min_size=8))
    step_fsdp = make_train_step(
        loss_fn, opt, mesh=mesh, donate=False, scan_steps=K,
        state_sharding=shardings,
    )
    s1, l1 = step_fsdp(sharded, batch)

    step_rep = make_train_step(loss_fn, opt, mesh=mesh, donate=False,
                               scan_steps=K)
    s2, l2 = step_rep(replicate(state0), batch)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        jax.device_get(s1.params), jax.device_get(s2.params),
    )


def test_policy_casts_params_entering_loss(world):
    # policy= : the loss sees compute-dtype params, the TrainState keeps
    # f32 masters, gradients/updates run f32, and training still works.
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch
    from fluxmpi_tpu.utils import get_policy

    model, params, optimizer, state, _, batch = _setup(world)
    seen = []

    def loss_fn(p, mstate, b):
        x, y = b
        seen.append(jax.tree_util.tree_leaves(p)[0].dtype)
        pred = model.apply(p, x.astype(jnp.bfloat16))
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2), mstate

    step = make_train_step(loss_fn, optimizer, style="auto", donate=False,
                           policy=get_policy("bf16"))
    st = replicate(state)
    data = shard_batch(batch)
    for _ in range(40):
        st, loss = step(st, data)
    assert seen and all(d == jnp.bfloat16 for d in seen)  # compute dtype
    leaves = jax.tree_util.tree_leaves(st.params)
    assert all(x.dtype == jnp.float32 for x in leaves)  # f32 masters
    assert float(loss) < 1.0  # learns through the cast

    # Eval step gets the same cast.
    from fluxmpi_tpu.parallel.train import make_eval_step

    eval_seen = []

    def metric_fn(p, mstate, b):
        x, y = b
        eval_seen.append(jax.tree_util.tree_leaves(p)[0].dtype)
        pred = model.apply(p, x.astype(jnp.bfloat16))
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    ev = make_eval_step(metric_fn, policy=get_policy("bf16"))
    _ = ev(st, data)
    assert eval_seen and eval_seen[0] == jnp.bfloat16


def test_train_step_not_retraced_across_steps(world):
    # Recompilation guard: the compiled step traces ONCE; repeated calls
    # (including through loader-produced batches, whose sharding object
    # is constant per epoch) hit the jit cache.
    import optax

    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate

    model = MLP(features=(8, 1))

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.sgd(1e-2)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1)))
    )
    step = make_train_step(loss_fn, opt, mesh=world)
    assert step.scan_steps == 1  # loop-driver metadata rides the step
    x = np.linspace(-1, 1, 64, dtype=np.float32)[:, None]
    loader = DistributedDataLoader(ArrayDataset((x, x**2)), 32, mesh=world)
    state = replicate(TrainState.create(params, opt, None), world)
    for _ in range(2):
        for batch in loader:
            state, _ = step(state, batch)
    assert step._cache_size() == 1

    # Instrumented steps expose the same guarantee through the wrapper.
    step_i = make_train_step(loss_fn, opt, mesh=world, metrics=True)
    state = replicate(TrainState.create(params, opt, None), world)
    for batch in loader:
        state, _ = step_i(state, batch)
    assert step_i.__fluxmpi_compiled__._cache_size() == 1
