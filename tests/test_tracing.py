"""Trace-plane tests: span ring + Chrome-trace export, the collective
flight recorder (ring wraparound, sequence monotonicity, cross-"host"
desync diffing), the hang watchdog (fake clock, zero real sleeps), the
schema checker's trace dispatch, and merge_traces.py.

The acceptance story: a simulated stall produces a dump file containing
thread stacks, the last-N collective ring with sequence numbers, and a
schema-valid final registry flush; merged per-host trace exports load as
valid Chrome-trace JSON.
"""

import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from fluxmpi_tpu.telemetry import (
    FlightRecorder,
    JSONLSink,
    MemorySink,
    MetricsRegistry,
    TRACE_SCHEMA,
    Tracer,
    TrainingMonitor,
    Watchdog,
    diff_flight_dumps,
    get_flight_recorder,
    validate_flight_dump,
    validate_record,
    validate_trace_export,
    validate_watchdog_dump,
)
from fluxmpi_tpu.telemetry import tracing, watchdog as watchdog_mod

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHECKER = os.path.join(_REPO, "scripts", "check_metrics_schema.py")
_MERGER = os.path.join(_REPO, "scripts", "merge_traces.py")


def _run_script(script, *args):
    return subprocess.run(
        [sys.executable, script, *args],
        capture_output=True,
        text=True,
        timeout=60,
    )


# ---------------------------------------------------------------------------
# Tracer: spans, nesting, ring bound, export round-trip
# ---------------------------------------------------------------------------


def test_span_nesting_and_export_round_trip(tmp_path):
    tr = Tracer(capacity=128, enabled=True)
    with tr.span("train.step", step=1):
        with tr.span("data.wait"):
            pass
        tr.instant("grad.ready", norm=1.5)
    record = tr.export(str(tmp_path / "trace.json"))
    assert validate_trace_export(record) == []

    # Round-trip: the written file is plain Chrome-trace JSON.
    loaded = json.loads((tmp_path / "trace.json").read_text())
    assert loaded["schema"] == TRACE_SCHEMA and loaded["kind"] == "trace"
    events = [e for e in loaded["traceEvents"] if e["ph"] != "M"]
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"train.step", "data.wait", "grad.ready"}
    # Nesting: the child "X" event lies within the parent's [ts, ts+dur].
    parent, child = by_name["train.step"], by_name["data.wait"]
    assert parent["ts"] <= child["ts"]
    # 2 µs slack: ts values are unix-epoch µs, where float64 rounding is
    # ~0.5 µs per operand.
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 2.0
    assert parent["args"] == {"step": 1}
    assert by_name["grad.ready"]["ph"] == "i"
    # Metadata rows make the Perfetto lanes readable.
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in loaded["traceEvents"])


def test_tracer_ring_is_bounded():
    tr = Tracer(capacity=8, enabled=True)
    for i in range(50):
        tr.instant("tick", i=i)
    assert len(tr) == 8
    record = tr.export()
    ticks = [e for e in record["traceEvents"] if e["name"] == "tick"]
    assert [e["args"]["i"] for e in ticks] == list(range(42, 50))


def test_disabled_tracer_records_nothing_and_reuses_noop():
    tr = Tracer(capacity=8, enabled=False)
    cm1 = tr.span("a")
    cm2 = tr.span("b")
    assert cm1 is cm2  # shared no-op singleton: zero allocation per call
    with cm1:
        with cm2:
            tr.instant("x")
            tr.add_complete_event("y", 0.0, 1.0)
    assert len(tr) == 0
    assert tr.open_spans() == []


def test_open_spans_visible_while_active():
    tr = Tracer(enabled=True)
    with tr.span("outer"):
        with tr.span("inner"):
            stacks = tr.open_spans()
            assert len(stacks) == 1
            assert stacks[0]["thread_id"] == threading.get_ident()
            assert stacks[0]["spans"] == ["outer", "inner"]
    assert tr.open_spans() == []


def test_add_complete_event_lands_on_wall_clock_timeline():
    import time

    tr = Tracer(enabled=True)
    t0 = time.perf_counter()
    t1 = t0 + 0.25
    tr.add_complete_event("comm.allreduce", t0, t1, path="device", nbytes=64)
    ev = [e for e in tr.export()["traceEvents"] if e["ph"] == "X"][0]
    assert ev["dur"] == pytest.approx(0.25e6, rel=1e-3)  # microseconds
    # ts is unix-anchored: within a day of now, not a raw perf_counter.
    assert abs(ev["ts"] / 1e6 - time.time()) < 86400
    assert ev["args"] == {"path": "device", "nbytes": 64}


def test_configure_specs():
    prev = tracing.get_tracer()
    try:
        tr = Tracer(capacity=4)
        assert tracing.configure(tr) is tr
        assert tracing.get_tracer() is tr and tr.enabled
        tracing.configure(False)
        assert not tr.enabled
        tracing.configure(True)
        assert tr.enabled
        with pytest.raises(ValueError, match="trace spec"):
            tracing.configure(3.14)
        # A bad placeholder must fail HERE, not silently at shutdown.
        with pytest.raises(ValueError, match="not formattable"):
            tracing.configure("trace-{rank}.json")
    finally:
        tracing.set_tracer(prev)
        tracing._export_path = None


# ---------------------------------------------------------------------------
# Flight recorder: wraparound, monotonicity, comm wiring, dumps
# ---------------------------------------------------------------------------


def test_flight_ring_wraparound_and_seq_monotonicity():
    fr = FlightRecorder(capacity=8)
    for _ in range(20):
        fr.complete(fr.begin("allreduce", "device", 128))
    assert len(fr) == 8
    dump = fr.dump()
    assert validate_flight_dump(dump) == []
    seqs = [e["seq"] for e in dump["entries"]]
    assert seqs == list(range(13, 21))  # oldest fell off; order preserved
    assert dump["sequence"] == 20 and dump["completed"] == 20
    assert all(e["completed"] for e in dump["entries"])


def test_flight_in_flight_entry_marks_the_hang():
    fr = FlightRecorder(capacity=4)
    fr.complete(fr.begin("allreduce", "device", 64))
    fr.begin("bcast", "device", 256)  # never completes: the "hang"
    dump = fr.dump()
    assert validate_flight_dump(dump) == []
    tail = dump["entries"][-1]
    assert tail["completed"] is False and tail["duration"] is None
    assert tail["op"] == "bcast"
    assert fr.completed_count == 1


def test_comm_collectives_feed_the_flight_recorder(world, nworkers):
    import fluxmpi_tpu as fm

    fr = get_flight_recorder()
    seq0, done0 = fr.sequence, fr.completed_count
    x = np.ones((nworkers, 2), dtype=np.float32)
    fm.allreduce(x)
    fm.bcast(x, root=0)
    fm.host_allgather(np.float32(1.0))
    assert fr.sequence == seq0 + 3
    assert fr.completed_count == done0 + 3
    ops = [e.op for e in fr.entries()[-3:]]
    assert ops == ["allreduce", "bcast", "host_allgather"]
    tail = fr.entries()[-1]
    assert tail.completed and tail.path == "host"


def test_raised_collective_aborts_entry_instead_of_faking_a_hang(world):
    import fluxmpi_tpu as fm

    fr = get_flight_recorder()
    with pytest.raises(ValueError, match="root rank"):
        fm.bcast(np.ones((8, 2), dtype=np.float32), root=99)
    # Root range is validated before _begin_op, so nothing recorded; an
    # error INSIDE the collective call must finalize the entry as
    # aborted, not leave it "in flight" forever. Exercise via abort().
    entry = fr.begin("allreduce", "device", 64)
    fr.abort(entry)
    dump = fr.dump()
    tail = dump["entries"][-1]
    assert tail["completed"] is True and tail["aborted"] is True
    assert validate_flight_dump(dump) == []  # extra key tolerated
    # Aborts are not progress: completed_count untouched.
    assert not any(
        e["seq"] == entry.seq for d in [dump]
        for e in d["entries"] if not e["completed"]
    )


def test_cross_host_desync_diff():
    # Two in-memory "hosts": host 0 completed 10 collectives, host 1
    # hangs inside seq 9 — the diff names the stuck collective.
    h0, h1 = FlightRecorder(capacity=16), FlightRecorder(capacity=16)
    for i in range(10):
        h0.complete(h0.begin("allreduce", "device", 1024))
    for i in range(8):
        h1.complete(h1.begin("allreduce", "device", 1024))
    h1.begin("allreduce", "device", 1024)  # in flight: the hang
    d0, d1 = h0.dump(), h1.dump()
    d1["process"] = 1
    diff = diff_flight_dumps([d0, d1])
    assert diff["max_sequence"] == 10 and diff["min_sequence"] == 9
    assert diff["laggards"] == [1]
    assert diff["hosts"]["1"]["in_flight"]["seq"] == 9
    assert diff["hosts"]["1"]["in_flight"]["op"] == "allreduce"
    assert diff["hosts"]["0"]["in_flight"] is None
    assert diff["first_mismatch"] is None  # lag, not divergence
    assert diff["synchronized"] is False


def test_cross_host_divergence_diff_finds_first_mismatch():
    # Hosts disagree on what collective seq 3 *is* — a divergence bug
    # (mismatched program order), distinct from a mere lag.
    h0, h1 = FlightRecorder(capacity=16), FlightRecorder(capacity=16)
    for op0, op1 in [("allreduce", "allreduce"), ("bcast", "bcast"),
                     ("allreduce", "reduce"), ("barrier", "barrier")]:
        h0.complete(h0.begin(op0, "device", 64))
        h1.complete(h1.begin(op1, "device", 64))
    d0, d1 = h0.dump(), h1.dump()
    d1["process"] = 1
    diff = diff_flight_dumps([d0, d1])
    assert diff["first_mismatch"]["seq"] == 3
    assert diff["first_mismatch"]["entries"]["0"]["op"] == "allreduce"
    assert diff["first_mismatch"]["entries"]["1"]["op"] == "reduce"
    assert diff["synchronized"] is False


def test_healthy_hosts_diff_synchronized():
    h0, h1 = FlightRecorder(), FlightRecorder()
    for _ in range(5):
        h0.complete(h0.begin("allreduce", "device", 64))
        h1.complete(h1.begin("allreduce", "device", 64))
    d0, d1 = h0.dump(), h1.dump()
    d1["process"] = 1
    diff = diff_flight_dumps([d0, d1])
    assert diff["synchronized"] is True
    assert diff["laggards"] == [] and diff["first_mismatch"] is None


def test_diff_rejects_duplicate_process_indices():
    h0, h1 = FlightRecorder(), FlightRecorder()
    h0.complete(h0.begin("allreduce", "device", 64))
    h1.begin("bcast", "device", 64)
    # Both dumps stamp process 0 (pre-init): collapsing them could call
    # a desynced pair synchronized — must refuse instead.
    with pytest.raises(ValueError, match="share process index"):
        diff_flight_dumps([h0.dump(), h1.dump()])


# ---------------------------------------------------------------------------
# Watchdog: fake clock, no real sleeps
# ---------------------------------------------------------------------------


def _fake_watchdog(tmp_path, **kwargs):
    clock = {"t": 0.0}
    progress = {"n": 0}
    wd = Watchdog(
        deadline=30.0,
        dump_dir=str(tmp_path),
        sources=[lambda: progress["n"]],
        clock=lambda: clock["t"],
        **kwargs,
    )
    return wd, clock, progress


def test_watchdog_dumps_on_simulated_stall(tmp_path):
    mem = MemorySink()
    reg = MetricsRegistry(sinks=[mem])
    reg.counter("train.steps").inc(7)
    tr = Tracer(enabled=True)
    fr = FlightRecorder(capacity=8)
    for _ in range(3):
        fr.complete(fr.begin("allreduce", "device", 4096))
    fr.begin("bcast", "device", 128)  # the collective "we" hang in
    wd, clock, progress = _fake_watchdog(tmp_path)
    wd._registry, wd._tracer, wd._recorder = reg, tr, fr

    span_cm = tr.span("train.step")
    span_cm.__enter__()  # a live span when the stall fires
    try:
        assert wd.check() is None  # seeds the baseline at t=0
        clock["t"] = 10.0
        progress["n"] += 1
        assert wd.check() is None  # progress observed: timer resets
        clock["t"] = 35.0
        assert wd.check() is None  # only 25 s since last progress
        clock["t"] = 41.0
        path = wd.check()  # 31 s stalled: dump
        assert path is not None and os.path.exists(path)
        assert wd.check() is None  # one dump per plateau
        dump = json.load(open(path, encoding="utf-8"))
    finally:
        span_cm.__exit__(None, None, None)

    assert validate_watchdog_dump(dump) == []
    assert dump["reason"] == "stall"
    # Thread stacks: this test's own frame is in the dump.
    me = [t for t in dump["threads"]
          if t["thread_id"] == threading.get_ident()]
    assert me and any(
        fr_["function"] == "test_watchdog_dumps_on_simulated_stall"
        for fr_ in me[0]["stack"]
    )
    # Flight-recorder tail with sequence numbers, in-flight op visible:
    entries = dump["flight_recorder"]["entries"]
    assert [e["seq"] for e in entries] == [1, 2, 3, 4]
    assert entries[-1]["op"] == "bcast" and not entries[-1]["completed"]
    # Open span stack:
    assert dump["open_spans"] == [
        {"thread_id": threading.get_ident(), "spans": ["train.step"]}
    ]
    # Final registry flush: schema-valid and actually written to sinks.
    assert validate_record(dump["registry_flush"]) == []
    assert dump["registry_flush"]["watchdog_reason"] == "stall"
    assert len(mem.records) == 1
    # The documented validator accepts the artifact.
    proc = _run_script(_CHECKER, path)
    assert proc.returncode == 0, proc.stderr + proc.stdout


def test_watchdog_redump_after_progress_resumes(tmp_path):
    wd, clock, progress = _fake_watchdog(tmp_path)
    wd._registry = MetricsRegistry()
    assert wd.check() is None
    clock["t"] = 31.0
    assert wd.check() is not None  # first stall
    clock["t"] = 40.0
    progress["n"] += 1
    assert wd.check() is None  # recovery observed
    clock["t"] = 75.0
    assert wd.check() is not None  # a second stall dumps again


def test_watchdog_signal_dump(tmp_path):
    import time

    # The handler must not dump inline (a signal handler taking the
    # registry lock on the main thread can self-deadlock): it sets a
    # flag the armed daemon thread serves on its next sub-tick.
    wd, clock, progress = _fake_watchdog(tmp_path, poll_interval=0.01)
    wd._registry = MetricsRegistry()
    try:
        wd.arm(install_signal=False)
        wd._on_sigusr1(signal.SIGUSR1, None)
        assert wd._signal_requested or wd.last_dump_path  # flag, not dump
        deadline = time.monotonic() + 10.0
        while wd.last_dump_path is None and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        wd.disarm()
    assert wd.last_dump_path is not None
    dump = json.load(open(wd.last_dump_path, encoding="utf-8"))
    assert validate_watchdog_dump(dump) == []
    assert dump["reason"] == "signal"


def test_watchdog_arm_disarm_thread_and_module_wiring(tmp_path):
    wd, clock, progress = _fake_watchdog(tmp_path, poll_interval=0.01)
    wd._registry = MetricsRegistry()
    try:
        armed = watchdog_mod.arm_watchdog(wd)
        assert armed is wd and wd.armed
        assert watchdog_mod.get_watchdog() is wd
        # configure() replay with the same armed instance is a no-op.
        assert watchdog_mod.configure(wd) is wd
    finally:
        watchdog_mod.disarm_watchdog()
    assert not wd.armed and watchdog_mod.get_watchdog() is None


def test_watchdog_configure_specs(tmp_path, monkeypatch):
    monkeypatch.setenv("FLUXMPI_TPU_WATCHDOG_DIR", str(tmp_path))
    try:
        wd = watchdog_mod.configure("45")
        assert wd is not None and wd.deadline == 45.0 and wd.armed
        assert wd.dump_dir == str(tmp_path)
        assert watchdog_mod.configure("45") is wd  # idempotent replay
        with pytest.raises(ValueError, match="watchdog spec"):
            watchdog_mod.configure("not-a-number")
        assert watchdog_mod.configure("0") is None
        assert watchdog_mod.get_watchdog() is None
    finally:
        watchdog_mod.disarm_watchdog()


def test_notify_progress_and_default_sources():
    before = watchdog_mod._progress
    watchdog_mod.notify_progress()
    watchdog_mod.notify_progress(3)
    assert watchdog_mod._progress == before + 4


def test_monitor_progress_shares_heartbeat_truth():
    reg = MetricsRegistry()
    mon = TrainingMonitor(registry=reg, interval=1, cross_host=False)
    assert mon.progress == 0
    g0 = watchdog_mod._progress
    mon.collect()
    mon.collect()
    # One source of truth: progress IS the heartbeat counter...
    assert mon.progress == 2
    assert reg.counter("monitor.heartbeat").value == 2
    # ...and each collect also ticks the armed-watchdog global source.
    assert watchdog_mod._progress == g0 + 2


# ---------------------------------------------------------------------------
# Wiring: train-step spans, runtime kwargs, shutdown export
# ---------------------------------------------------------------------------


def test_train_step_emits_span_and_progress(world):
    import optax

    import jax
    import jax.numpy as jnp
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model = MLP(features=(4, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
    optimizer = optax.sgd(0.1)

    def loss_fn(p, mstate, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2), mstate

    tr = Tracer(enabled=True)
    prev = tracing.set_tracer(tr)
    g0 = watchdog_mod._progress
    try:
        step = make_train_step(
            loss_fn, optimizer, donate=False, metrics=MetricsRegistry()
        )
        st = replicate(TrainState.create(params, optimizer))
        batch = shard_batch((
            np.ones((8, 2), dtype=np.float32),
            np.ones((8, 1), dtype=np.float32),
        ))
        for _ in range(2):
            st, _ = step(st, batch)
    finally:
        tracing.set_tracer(prev)
    steps = [e for e in tr.export()["traceEvents"]
             if e["name"] == "train.step"]
    assert len(steps) == 2 and all(e["dur"] > 0 for e in steps)
    assert watchdog_mod._progress == g0 + 2  # liveness per step


def test_loader_emits_fetch_events(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    tr = Tracer(enabled=True)
    prev = tracing.set_tracer(tr)
    try:
        data = ArrayDataset(np.arange(64, dtype=np.float32).reshape(32, 2))
        batches = list(DistributedDataLoader(data, 8, prefetch=0))
    finally:
        tracing.set_tracer(prev)
    fetches = [e for e in tr.export()["traceEvents"]
               if e["name"] == "data.fetch"]
    assert len(fetches) == len(batches) == 4
    assert [e["args"]["batch"] for e in fetches] == [0, 1, 2, 3]


def test_init_wires_trace_and_watchdog_kwargs(world, tmp_path):
    import fluxmpi_tpu as fm

    prev = tracing.get_tracer()
    prev_enabled = prev.enabled
    try:
        fm.init(trace=True, watchdog=60)
        assert tracing.get_tracer().enabled
        wd = watchdog_mod.get_watchdog()
        assert wd is not None and wd.armed and wd.deadline == 60.0
    finally:
        watchdog_mod.disarm_watchdog()
        prev.enabled = prev_enabled


def test_tracing_shutdown_exports_configured_path(tmp_path):
    prev = tracing.get_tracer()
    tr = Tracer(enabled=True)
    tracing.set_tracer(tr)
    try:
        path = str(tmp_path / "trace.{process}.json")
        tracing.configure(path)
        tr.instant("mark")
        written = tracing.shutdown()
        assert written == str(tmp_path / "trace.0.json")
        loaded = json.load(open(written, encoding="utf-8"))
        assert validate_trace_export(loaded) == []
    finally:
        tracing.set_tracer(prev)
        tracing._export_path = None


# ---------------------------------------------------------------------------
# Scripts: schema checker dispatch + merge_traces
# ---------------------------------------------------------------------------


def test_checker_validates_trace_plane_files(tmp_path):
    tr = Tracer(enabled=True)
    with tr.span("s"):
        pass
    trace_path = tmp_path / "trace.json"
    tr.export(str(trace_path))

    fr = FlightRecorder()
    fr.complete(fr.begin("allreduce", "device", 64))
    flight_path = tmp_path / "flight.json"
    flight_path.write_text(json.dumps(fr.dump()))

    proc = _run_script(_CHECKER, str(trace_path), str(flight_path))
    assert proc.returncode == 0, proc.stderr + proc.stdout

    bad = tmp_path / "bad_trace.json"
    bad.write_text(json.dumps({
        "schema": TRACE_SCHEMA, "kind": "trace", "time_unix": 1.0,
        "process": 0,
        "traceEvents": [{"name": "x", "ph": "X", "ts": 0.0}],  # no dur
    }))
    proc = _run_script(_CHECKER, str(bad))
    assert proc.returncode == 1 and "dur" in proc.stderr


def test_merge_traces_produces_loadable_chrome_trace(tmp_path):
    paths = []
    for process in (0, 1):
        tr = Tracer(enabled=True)
        with tr.span("train.step", host=process):
            pass
        rec = tr.export()
        rec["process"] = process  # simulate per-host exports
        for ev in rec["traceEvents"]:
            if ev.get("name") == "process_name":
                ev["args"] = {"name": f"host {process}"}
        p = tmp_path / f"trace.{process}.json"
        p.write_text(json.dumps(rec))
        paths.append(str(p))

    out = str(tmp_path / "merged.json")
    proc = _run_script(_MERGER, "-o", out, *paths)
    assert proc.returncode == 0, proc.stderr + proc.stdout

    merged = json.load(open(out, encoding="utf-8"))
    # Valid Chrome-trace JSON: a traceEvents list of well-formed events —
    # exactly what Perfetto/chrome://tracing loads — and still valid
    # against our schema (extra keys are Chrome-trace metadata).
    assert validate_trace_export(merged) == []
    assert merged["merged_from"] == [0, 1]
    spans = [e for e in merged["traceEvents"] if e["name"] == "train.step"]
    assert len(spans) == 2
    # Events are re-pidded to the host's process index: the two hosts
    # here share one real pid (same test process), which would
    # otherwise fold both into one Perfetto lane.
    assert sorted(e["pid"] for e in spans) == [0, 1]
    names = {json.dumps(e["args"]) for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {'{"name": "host 0"}', '{"name": "host 1"}'}
    # The merged file validates through the checker too.
    proc = _run_script(_CHECKER, out)
    assert proc.returncode == 0, proc.stderr + proc.stdout


# ---------------------------------------------------------------------------
# Satellites: step_timer sentinel cache, profile_trace flag repair
# ---------------------------------------------------------------------------


def test_step_timer_sentinel_is_cached(world):
    from fluxmpi_tpu.utils import profiling

    holder = {}
    with profiling.step_timer(holder):
        pass  # nothing watched: the sentinel drain path runs
    first = profiling._sentinel_bump
    assert first is not None
    with profiling.step_timer(holder):
        pass
    # Same jitted callable both times — no per-call jit cache entry, so
    # timed no-watch steps stop retracing every call.
    assert profiling._sentinel_bump is first
    assert profiling._bump_fn() is first
    assert holder["seconds"] > 0


def test_profile_trace_lead_only_and_deprecated_flag(world, tmp_path, monkeypatch):
    from fluxmpi_tpu.utils import profiling

    calls = []

    class _FakeTrace:
        def __init__(self, logdir):
            calls.append(logdir)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return None

    import jax

    monkeypatch.setattr(jax.profiler, "trace", _FakeTrace)
    # Default: lead process traces (single-process world: that's us).
    with profiling.profile_trace(str(tmp_path / "a")):
        pass
    assert calls == [str(tmp_path / "a")]
    # all_hosts=True also traces here.
    with profiling.profile_trace(str(tmp_path / "b"), all_hosts=True):
        pass
    assert len(calls) == 2
    # The deprecated spelling keeps each caller's old actual behavior
    # (host_only=True traced everywhere → all_hosts=True) and warns.
    with pytest.warns(DeprecationWarning, match="host_only"):
        with profiling.profile_trace(str(tmp_path / "c"), host_only=True):
            pass
    assert len(calls) == 3
    with pytest.warns(DeprecationWarning, match="host_only"):
        with profiling.profile_trace(str(tmp_path / "d"), host_only=False):
            pass
    assert len(calls) == 4  # lead-only, and we are the lead


def test_merge_traces_discovers_proc_subdirectories(tmp_path):
    """A directory input is walked recursively — including the
    per-process proc<k> subdirectories profile_trace(all_hosts=True)
    and the AutoProfiler write into a shared logdir — with tolerant
    handling: our exports merge as usual, a raw Chrome trace from
    profiler tooling (.trace.json.gz) is wrapped with its process
    inferred from the proc<k> component, junk JSON is skipped."""
    import gzip

    logdir = tmp_path / "captures"
    (logdir / "proc1" / "plugins" / "profile" / "r1").mkdir(parents=True)
    tr = Tracer(enabled=True)
    with tr.span("train.step"):
        pass
    (logdir / "trace.0.json").write_text(json.dumps(tr.export()))
    raw = {"traceEvents": [
        {"name": "xla_op", "ph": "X", "ts": 5.0, "dur": 2.0,
         "pid": 7, "tid": 7},
    ]}
    with gzip.open(
        logdir / "proc1" / "plugins" / "profile" / "r1"
        / "host.trace.json.gz", "wt", encoding="utf-8"
    ) as f:
        json.dump(raw, f)
    (logdir / "proc1" / "notes.json").write_text('{"not": "a trace"}')

    out = str(tmp_path / "merged.json")
    proc = _run_script(_MERGER, "-o", out, str(logdir))
    assert proc.returncode == 0, proc.stderr + proc.stdout
    assert "skipped" in proc.stdout  # the junk file, counted not fatal
    merged = json.load(open(out, encoding="utf-8"))
    assert validate_trace_export(merged) == []
    assert merged["merged_from"] == [0, 1]
    xla = [e for e in merged["traceEvents"] if e["name"] == "xla_op"]
    assert xla and xla[0]["pid"] == 1  # process inferred from proc1/
    spans = [e for e in merged["traceEvents"] if e["name"] == "train.step"]
    assert spans and spans[0]["pid"] == 0
    # An explicitly-named invalid file still errors (strict path kept).
    proc = _run_script(
        _MERGER, "-o", out, str(logdir / "proc1" / "notes.json")
    )
    assert proc.returncode == 1
