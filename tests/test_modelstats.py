"""Model-internals plane tests (telemetry/modelstats.py + the in-jit
collection in parallel/train.py + the train_loop flush wiring):
grouping/stat math against numpy oracles, trajectory invariance
(bit-identical on/off, both drivers), pipelined-vs-fused stat equality,
sharded-param-tree (FSDP) norms against a replicated oracle, the
shard_map gradient noise scale, NaN provenance end to end (event +
instant + bundle + schema CLI), the new anomaly layer rules, the
zero-cost-when-off explode contract, configure/env forms, the /status
MODEL board + fluxmpi_top rendering, and the modelstats_report CLI."""

import importlib.util
import json
import math
import os
import subprocess
import sys
import urllib.request
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from fluxmpi_tpu import telemetry
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.models import MLP
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import (
    AnomalyDetector,
    JSONLSink,
    MetricsRegistry,
    ModelStats,
    anomaly,
    export,
    get_registry,
    modelstats,
)
from fluxmpi_tpu.telemetry import schema as tschema
from fluxmpi_tpu.telemetry.modelstats import (
    compute_stats,
    group_paths,
    noise_scale,
    resolve_step_spec,
    stats_zeros,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHECKER = os.path.join(_REPO, "scripts", "check_metrics_schema.py")
_REPORT = os.path.join(_REPO, "scripts", "modelstats_report.py")
_TOP = os.path.join(_REPO, "scripts", "fluxmpi_top.py")


@pytest.fixture()
def ms_off():
    """Guarantee the model-internals plane (and the anomaly detector it
    feeds) is off around a test, restoring whatever was installed."""
    prev = modelstats.set_model_stats(None)
    prev_det = anomaly.set_anomaly_detector(None)
    try:
        yield
    finally:
        modelstats.set_model_stats(prev)
        anomaly.set_anomaly_detector(prev_det)


def _mlp_pieces(n=256, features=(8, 8, 1), poison_layer=None, poison_from=None):
    """Loss/opt/params/dataset for a small MLP. With ``poison_layer``,
    a custom_vjp injects NaN into EXACTLY that layer's kernel gradient
    once a sentinel batch (x > 100) flows — the loss and every other
    layer's gradient stay finite, which is the provenance scenario (a
    NaN *input* would poison every layer through backprop)."""
    model = MLP(features=features)

    @jax.custom_vjp
    def _poison(x, flag):
        return x

    def _poison_fwd(x, flag):
        return x, flag

    def _poison_bwd(flag, g):
        return (
            jnp.where(flag, jnp.full_like(g, jnp.nan), g),
            None,
        )

    _poison.defvjp(_poison_fwd, _poison_bwd)

    def loss_fn(p, mstate, b):
        bx, by = b
        if poison_layer is not None:
            flag = jnp.any(bx > 100.0)
            inner = dict(p["params"])
            slot = dict(inner[poison_layer])
            slot["kernel"] = _poison(slot["kernel"], flag)
            inner[poison_layer] = slot
            p = {"params": inner}
            # Keep the FORWARD finite even on the sentinel batch: the
            # NaN must exist only in one layer's gradient.
            bx = jnp.where(jnp.abs(bx) > 100.0, 0.0, bx)
        return jnp.mean((model.apply(p, bx) - by) ** 2), mstate

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    y = (x**2).astype(np.float32)
    if poison_from is not None:
        x[poison_from] = 1000.0  # the sentinel the poison flag keys on
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), np.zeros((2, 1), np.float32))
    )
    return loss_fn, opt, params, ArrayDataset((x, y))


# ---------------------------------------------------------------------------
# Grouping + stat math (numpy oracles, no train loop)
# ---------------------------------------------------------------------------


def test_group_paths_depth_controls_granularity():
    tree = {
        "params": {
            "dense_0": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
            "dense_1": {"kernel": jnp.ones((2, 1))},
        }
    }
    depth2 = group_paths(tree, 2)
    assert sorted(depth2) == ["params/dense_0", "params/dense_1"]
    assert len(depth2["params/dense_0"]) == 2  # kernel + bias leaves
    depth1 = group_paths(tree, 1)
    assert sorted(depth1) == ["params"]
    depth9 = group_paths(tree, 9)  # deeper than the tree: one per leaf
    assert len(depth9) == 3
    with pytest.raises(ValueError, match="depth"):
        group_paths(tree, 0)


def test_compute_stats_matches_numpy_oracle():
    rng = np.random.default_rng(3)
    params = {
        "params": {
            "a": {"kernel": rng.normal(size=(3, 4)).astype(np.float32)},
            "b": {"kernel": rng.normal(size=(4, 2)).astype(np.float32)},
        }
    }
    grads = jax.tree_util.tree_map(
        lambda p: np.asarray(rng.normal(size=p.shape), np.float32), params
    )
    updates = jax.tree_util.tree_map(
        lambda p: np.asarray(rng.normal(size=p.shape), np.float32), params
    )
    grads["params"]["b"]["kernel"][0, 0] = np.nan
    grads["params"]["b"]["kernel"][1, 1] = np.inf
    stats = jax.device_get(
        compute_stats(grads, params, updates, depth=2)
    )
    for group, sub in (("params/a", "a"), ("params/b", "b")):
        g = grads["params"][sub]["kernel"]
        assert float(stats["layers"][group]["param_norm"]) == pytest.approx(
            float(np.linalg.norm(params["params"][sub]["kernel"])), rel=1e-6
        )
        assert float(stats["layers"][group]["update_norm"]) == pytest.approx(
            float(np.linalg.norm(updates["params"][sub]["kernel"])), rel=1e-6
        )
        if sub == "a":
            assert float(stats["layers"][group]["grad_norm"]) == pytest.approx(
                float(np.linalg.norm(g)), rel=1e-6
            )
    assert float(stats["layers"]["params/a"]["nonfinite"]) == 0.0
    assert float(stats["layers"]["params/b"]["nonfinite"]) == 2.0
    assert not math.isfinite(float(stats["layers"]["params/b"]["grad_norm"]))
    # The zeros builder mirrors the structure exactly (the fused window
    # carry-init contract).
    zeros = stats_zeros(params, depth=2)
    assert jax.tree_util.tree_structure(zeros) == (
        jax.tree_util.tree_structure(jax.device_get(stats))
    )


def test_noise_scale_algebra_and_degenerate_cases():
    # Hand-checkable: B_small=8, B_big=64, |G|^2=4, tr(Sigma)=160:
    # E|g_small|^2 = 4 + 160/8 = 24 ; |g_big|^2 = 4 + 160/64 = 6.5
    b_simple = noise_scale(24.0, 6.5, batch_examples=64, workers=8)
    assert b_simple == pytest.approx(160.0 / 4.0)
    # Degenerate: one worker (no local/global split), bad batch, |G|^2
    # estimate <= 0 (noise dominated), tr(Sigma) < 0 — all None, never
    # a crash or a garbage negative estimate.
    assert noise_scale(24.0, 6.5, batch_examples=64, workers=1) is None
    assert noise_scale(24.0, 6.5, batch_examples=0, workers=8) is None
    assert noise_scale(100.0, 1.0, batch_examples=64, workers=8) is None
    assert noise_scale(1.0, 2.0, batch_examples=64, workers=8) is None
    assert noise_scale(float("nan"), 1.0, batch_examples=64, workers=8) is None


def test_observe_flush_emits_and_summarizes(ms_off):
    plane = ModelStats(depth=2, top_k=2)
    reg = MetricsRegistry()
    stats = {
        "layers": {
            "params/a": {
                "grad_norm": 1.0, "param_norm": 4.0,
                "update_norm": 0.2, "nonfinite": 0.0,
            },
            "params/b": {
                "grad_norm": 3.0, "param_norm": 2.0,
                "update_norm": 0.1, "nonfinite": 2.0,
            },
            "params/c": {
                "grad_norm": 2.0, "param_norm": 0.0,
                "update_norm": 0.0, "nonfinite": 0.0,
            },
        },
        "noise": {"local_sqnorm": 24.0, "global_sqnorm": 6.5},
    }
    summary = plane.observe_flush(
        stats, step=10, registry=reg, batch_examples=64, workers=8
    )
    assert summary["layers"]["params/b"] == 3.0
    assert summary["update_ratios"]["params/a"] == pytest.approx(0.05)
    assert summary["update_ratios"]["params/c"] == 0.0  # zero-weight guard
    assert summary["nonfinite_layer"] == "params/b"
    assert summary["nonfinite_total"] == 2
    assert summary["noise_scale"] == pytest.approx(40.0)
    assert [name for name, _ in summary["top"]] == ["params/b", "params/c"]
    assert reg.gauge("model.layer_grad_norm", layer="params/b").value == 3.0
    assert reg.gauge("model.update_ratio", layer="params/a").value == (
        pytest.approx(0.05)
    )
    assert reg.gauge("model.nonfinite", layer="params/b").value == 2.0
    assert reg.gauge("model.grad_noise_scale").value == pytest.approx(40.0)
    # Disabled registry: summary still computed, nothing recorded.
    reg2 = MetricsRegistry()
    reg2.enabled = False
    plane.observe_flush(stats, registry=reg2)
    assert not any(
        m["name"].startswith("model.") for m in reg2.snapshot()
    )


def test_resolve_step_spec_forms(ms_off):
    assert resolve_step_spec(None) is None  # plane off
    assert resolve_step_spec(False) is None
    assert resolve_step_spec(True) == modelstats.DEFAULT_DEPTH
    assert resolve_step_spec(3) == 3
    assert resolve_step_spec(ModelStats(depth=4)) == 4
    modelstats.configure(True)
    assert resolve_step_spec(None) == modelstats.DEFAULT_DEPTH
    modelstats.get_model_stats().enabled = False
    assert resolve_step_spec(None) is None
    with pytest.raises(ValueError, match="model_stats"):
        resolve_step_spec("bogus")


def test_configure_forms_idempotency_and_shutdown(ms_off, monkeypatch):
    assert modelstats.configure(False) is None
    plane = modelstats.configure(True)
    assert plane is not None and plane.depth == modelstats.DEFAULT_DEPTH
    assert modelstats.configure(True) is plane  # idempotent replay
    deep = modelstats.configure(3)
    assert deep is not plane and deep.depth == 3
    assert modelstats.configure("3") is deep
    custom = ModelStats(depth=5, top_k=2)
    assert modelstats.configure(custom) is custom
    with pytest.raises(ValueError, match="model_stats"):
        modelstats.configure("bogus")
    # Env route + the warn-and-default knob parsing.
    monkeypatch.setenv("FLUXMPI_TPU_MODEL_STATS", "0")
    assert modelstats.configure() is None
    monkeypatch.setenv("FLUXMPI_TPU_MODEL_STATS", "1")
    monkeypatch.setenv("FLUXMPI_TPU_MODEL_STATS_DEPTH", "junk")
    monkeypatch.setenv("FLUXMPI_TPU_MODEL_STATS_TOPK", "7")
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_MODEL_STATS_DEPTH"):
        env_plane = modelstats.configure()
    assert env_plane.depth == modelstats.DEFAULT_DEPTH
    assert env_plane.top_k == 7
    telemetry.shutdown()
    assert modelstats.get_model_stats() is None


def test_model_namespace_is_closed():
    rec = {
        "schema": tschema.SCHEMA,
        "time_unix": 1.0,
        "process": 0,
        "metrics": [
            {
                "name": "model.not_a_thing",
                "type": "gauge",
                "labels": {},
                "value": 1.0,
            }
        ],
    }
    errs = tschema.validate_record(rec)
    assert any("model.not_a_thing" in e for e in errs)


# ---------------------------------------------------------------------------
# In-jit collection: oracle checks, sharded trees, trajectory invariance
# ---------------------------------------------------------------------------


def test_step_stats_match_numpy_oracle(world, ms_off):
    """A direct (wrapper-driven) instrumented step with model_stats=True
    emits per-layer gauges matching grads/updates recomputed outside."""
    modelstats.configure(True)
    loss_fn, opt, params, ds = _mlp_pieces()
    reg = MetricsRegistry()
    step = make_train_step(
        loss_fn, opt, mesh=world, metrics=reg, model_stats=True, donate=False
    )
    state = replicate(TrainState.create(params, opt, None), world)
    x, y = ds.arrays
    batch = (x[:64], y[:64])
    from fluxmpi_tpu.parallel.train import shard_batch

    step(state, shard_batch(batch, world))
    (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, None, batch
    )
    updates, _ = opt.update(grads, opt.init(params), params)
    for group, slot in (
        ("params/dense_0", "dense_0"),
        ("params/dense_1", "dense_1"),
        ("params/dense_2", "dense_2"),
    ):
        g_leaves = jax.tree_util.tree_leaves(grads["params"][slot])
        oracle_g = math.sqrt(
            sum(float(np.sum(np.square(np.asarray(g)))) for g in g_leaves)
        )
        p_leaves = jax.tree_util.tree_leaves(params["params"][slot])
        oracle_p = math.sqrt(
            sum(float(np.sum(np.square(np.asarray(p)))) for p in p_leaves)
        )
        u_leaves = jax.tree_util.tree_leaves(updates["params"][slot])
        oracle_u = math.sqrt(
            sum(float(np.sum(np.square(np.asarray(u)))) for u in u_leaves)
        )
        assert reg.gauge(
            "model.layer_grad_norm", layer=group
        ).value == pytest.approx(oracle_g, rel=1e-5)
        assert reg.gauge(
            "model.layer_param_norm", layer=group
        ).value == pytest.approx(oracle_p, rel=1e-5)
        assert reg.gauge(
            "model.update_ratio", layer=group
        ).value == pytest.approx(oracle_u / oracle_p, rel=1e-5)
        assert reg.gauge("model.nonfinite", layer=group).value == 0.0


def test_sharded_param_tree_stats_match_replicated_oracle(world, ms_off):
    """Satellite: under an FSDP-style layout the per-layer norms must be
    GLOBAL values (XLA reduces across shards inside the program), equal
    to the replicated run's — asserted against a replicated oracle."""
    from fluxmpi_tpu.parallel import fsdp_rule, shard_tree
    from fluxmpi_tpu.parallel.train import shard_batch

    modelstats.configure(True)
    model = MLP(features=(16, 16, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
    opt = optax.adam(0.05)

    def loss_fn(p, mstate, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2), mstate

    state = TrainState.create(params, opt)
    rule = fsdp_rule(world, min_size=16)
    sharded_state, shardings = shard_tree(state, world, rule)
    reg = MetricsRegistry()
    step = make_train_step(
        loss_fn, opt, mesh=world, state_sharding=shardings,
        metrics=reg, model_stats=True, donate=False,
    )
    rng = np.random.default_rng(1)
    batch = (
        rng.normal(size=(16, 2)).astype(np.float32),
        rng.normal(size=(16, 1)).astype(np.float32),
    )
    step(sharded_state, shard_batch(batch, world))
    # Replicated oracle: full-value grads of the same batch.
    (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, None, batch
    )
    for slot in ("dense_0", "dense_1", "dense_2"):
        leaves = jax.tree_util.tree_leaves(
            jax.device_get(grads["params"][slot])
        )
        oracle = math.sqrt(
            sum(float(np.sum(np.square(np.asarray(g)))) for g in leaves)
        )
        got = reg.gauge(
            "model.layer_grad_norm", layer=f"params/{slot}"
        ).value
        assert got == pytest.approx(oracle, rel=1e-4), slot


def _run_loop(world, *, stats, fuse, metrics=True, scan_steps=1,
              record_flushes=None):
    loss_fn, opt, params, ds = _mlp_pieces()
    if stats:
        plane = modelstats.configure(True)
        if record_flushes is not None:
            orig = ModelStats.observe_flush

            def recording(self, tree, **kw):
                out = orig(self, tree, **kw)
                record_flushes.append(out)
                return out

            plane.observe_flush = recording.__get__(plane)
    else:
        modelstats.set_model_stats(None)
    step = make_train_step(
        loss_fn, opt, mesh=world, metrics=metrics, scan_steps=scan_steps
    )
    state = replicate(TrainState.create(params, opt, None), world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    final, summary = train_loop(
        step, state, loader, epochs=2, flush_every=2, fuse=fuse
    )
    return jax.device_get(final), summary


def test_trajectory_invariance_both_drivers(world, ms_off):
    """Acceptance: model_stats on is bit-identical (assert_array_equal)
    to off, on the pipelined AND the fused-window path — the stats tree
    reads the values the program already computes, never changes them."""
    on_pipe, s1 = _run_loop(world, stats=True, fuse=False)
    off_pipe, _ = _run_loop(world, stats=False, fuse=False)
    on_fused, s3 = _run_loop(world, stats=True, fuse="window")
    off_fused, _ = _run_loop(world, stats=False, fuse="window")
    assert s1["updates"] == s3["updates"] == 8
    assert s3["fused_window"] == 2
    for a, b in ((on_pipe, off_pipe), (on_fused, off_fused),
                 (on_pipe, on_fused)):
        jax.tree_util.tree_map(
            np.testing.assert_array_equal, a.params, b.params
        )
        jax.tree_util.tree_map(
            np.testing.assert_array_equal, a.opt_state, b.opt_state
        )


def test_pipelined_and_fused_emit_equal_stats(world, ms_off):
    """Acceptance: both drivers emit IDENTICAL per-flush stats for the
    same run (the fused window folds the tree into its scan carry; the
    pipelined path reads the last dispatch's — same update, same
    numbers)."""
    pipe_flushes: list = []
    fused_flushes: list = []
    _run_loop(world, stats=True, fuse=False, record_flushes=pipe_flushes)
    _run_loop(world, stats=True, fuse="window", record_flushes=fused_flushes)
    assert len(pipe_flushes) == len(fused_flushes) == 4
    for a, b in zip(pipe_flushes, fused_flushes):
        assert a["layers"] == b["layers"]
        assert a["param_norms"] == b["param_norms"]
        assert a["update_ratios"] == b["update_ratios"]
        assert a["nonfinite_layer"] is None and b["nonfinite_layer"] is None


def test_scan_steps_stats_describe_last_update(world, ms_off):
    """A scan_steps step stacks per-update stats [K]; the flush (and the
    per-step wrapper) must report the NEWEST update's tree — matching a
    k=1 run at the same update count."""
    flushes_k2: list = []
    flushes_k1: list = []
    _run_loop(world, stats=True, fuse=False, scan_steps=2,
              record_flushes=flushes_k2)
    _run_loop(world, stats=True, fuse=False, scan_steps=1,
              record_flushes=flushes_k1)
    assert flushes_k2  # flush_every=2 == one scan dispatch per flush
    assert flushes_k2[0]["layers"] == flushes_k1[0]["layers"]


# ---------------------------------------------------------------------------
# Gradient noise scale (shard_map) end to end
# ---------------------------------------------------------------------------


def test_shard_map_noise_scale_end_to_end(world, ms_off):
    modelstats.configure(True)
    loss_fn, opt, params, ds = _mlp_pieces()
    reg = MetricsRegistry()
    step = make_train_step(
        loss_fn, opt, mesh=world, style="shard_map", metrics=reg,
        model_stats=True,
    )
    state = replicate(TrainState.create(params, opt, None), world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    train_loop(step, state, loader, epochs=1, flush_every=2, fuse=False)
    local = reg.gauge("model.grad_sqnorm_local").value
    glob = reg.gauge("model.grad_sqnorm_global").value
    ns = reg.gauge("model.grad_noise_scale").value
    # E over ranks of |g_rank|^2 >= |mean g|^2 always (Jensen); real
    # per-example noise makes it strictly larger, so B_simple > 0.
    assert local >= glob > 0.0
    assert ns > 0.0 and math.isfinite(ns)
    assert ns == pytest.approx(
        noise_scale(local, glob, batch_examples=64, workers=8)
    )


def test_shard_map_noise_scale_sum_reduce_matches_mean(world, ms_off):
    """grad_reduce='sum' consumes W x the mean gradient; the noise
    ingredients must rescale to the AVERAGE convention, so the recorded
    sq-norms match a grad_reduce='mean' step's."""
    modelstats.configure(True)
    vals = {}
    for reduce in ("mean", "sum"):
        loss_fn, opt, params, ds = _mlp_pieces()
        reg = MetricsRegistry()
        step = make_train_step(
            loss_fn, opt, mesh=world, style="shard_map",
            grad_reduce=reduce, metrics=reg, model_stats=True,
        )
        state = replicate(TrainState.create(params, opt, None), world)
        loader = DistributedDataLoader(ds, 64, mesh=world)
        train_loop(step, state, loader, steps=1, flush_every=1, fuse=False)
        vals[reduce] = (
            reg.gauge("model.grad_sqnorm_local").value,
            reg.gauge("model.grad_sqnorm_global").value,
        )
    assert vals["sum"][0] == pytest.approx(vals["mean"][0], rel=1e-5)
    assert vals["sum"][1] == pytest.approx(vals["mean"][1], rel=1e-5)


def test_auto_style_carries_no_noise_ingredients(world, ms_off):
    """style='auto' never materializes a per-rank gradient — the noise
    gauges must be absent, not zero-filled garbage."""
    modelstats.configure(True)
    loss_fn, opt, params, ds = _mlp_pieces()
    reg = MetricsRegistry()
    step = make_train_step(
        loss_fn, opt, mesh=world, metrics=reg, model_stats=True
    )
    state = replicate(TrainState.create(params, opt, None), world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    train_loop(step, state, loader, steps=2, flush_every=2, fuse=False)
    names = {m["name"] for m in reg.snapshot()}
    assert "model.layer_grad_norm" in names
    assert "model.grad_sqnorm_local" not in names
    assert "model.grad_noise_scale" not in names


# ---------------------------------------------------------------------------
# Anomaly layer rules + NaN provenance
# ---------------------------------------------------------------------------


def test_layer_grad_explosion_rule(ms_off):
    det = AnomalyDetector(warmup=2, layer_explosion_factor=5.0, dump=False)
    base = {"params/a": 1.0, "params/b": 1.0}
    for step in range(3):
        assert det.observe(layer_grad_norms=base, step=step) == []
    events = det.observe(
        layer_grad_norms={"params/a": 50.0, "params/b": 1.0}, step=3
    )
    assert len(events) == 1
    ev = events[0]
    assert ev["rule"] == "layer_grad_explosion"
    assert ev["layer"] == "params/a"
    assert ev["action"] == "warn"  # statistical-rule default policy
    assert ev["value"] == pytest.approx(50.0)
    # The instant carries the layer (fluxmpi_top renders it).
    assert det.triggered[-1]["layer"] == "params/a"


def test_dead_layer_rule_fires_once_and_rearms(ms_off):
    det = AnomalyDetector(
        warmup=1, dead_layer_flushes=3, dump=False
    )
    live = {"params/a": 1.0, "params/b": 0.0}
    fired = []
    for step in range(7):
        fired.extend(det.observe(layer_grad_norms=live, step=step))
    # Streak hits 3 at the third flush; staying dead does NOT re-fire.
    assert [e["rule"] for e in fired] == ["dead_layer"]
    assert fired[0]["layer"] == "params/b"
    # Recovery re-arms: one live flush, then three dead ones fire again.
    det.observe(layer_grad_norms={"params/a": 1.0, "params/b": 1.0}, step=7)
    again = []
    for step in range(8, 11):
        again.extend(det.observe(layer_grad_norms=live, step=step))
    assert [e["rule"] for e in again] == ["dead_layer"]


def test_nan_provenance_end_to_end(world, tmp_path, ms_off):
    """Acceptance: an injected PER-LAYER NaN (loss finite, one layer's
    gradient NaN) halts via nan_grad with the offending layer named in
    the anomaly event, the trace instant, and the diagnostics bundle —
    all schema-valid via check_metrics_schema.py."""
    from fluxmpi_tpu.telemetry import tracing

    jsonl = str(tmp_path / "run.jsonl")
    reg = MetricsRegistry(sinks=[JSONLSink(jsonl)])
    modelstats.configure(True)
    anomaly.set_anomaly_detector(
        AnomalyDetector(dump_dir=str(tmp_path), registry=reg)
    )
    tracer = tracing.Tracer(enabled=True)
    prev_tracer = tracing.set_tracer(tracer)
    # Batch 4 (samples 192..255) carries the sentinel that poisons
    # ONLY dense_1's kernel gradient.
    loss_fn, opt, params, ds = _mlp_pieces(
        poison_layer="dense_1", poison_from=192
    )
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(
        loss_fn, opt, mesh=world, metrics=reg, model_stats=True
    )
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, summary = train_loop(
                step, replicate(TrainState.create(params, opt, None), world),
                loader, epochs=2, flush_every=2, fuse=False,
            )
    finally:
        tracing.set_tracer(prev_tracer)
    assert summary["anomaly"] == "nan_grad"
    assert summary["updates"] == 4  # halted at the flush that saw it
    det = anomaly.get_anomaly_detector()
    ev = next(e for e in det.triggered if e["rule"] == "nan_grad")
    assert ev["layer"] == "params/dense_1"
    # Per-layer nonfinite gauge names the layer in the metrics plane.
    assert reg.gauge(
        "model.nonfinite", layer="params/dense_1"
    ).value > 0.0
    assert reg.gauge("model.nonfinite", layer="params/dense_0").value == 0.0
    # Trace instant carries the layer, schema-valid.
    trace = tracer.export()
    assert tschema.validate_trace_export(trace) == []
    instants = [
        e for e in trace["traceEvents"] if e.get("name") == "anomaly.nan_grad"
    ]
    assert len(instants) == 1
    assert instants[0]["args"]["layer"] == "params/dense_1"
    assert instants[0]["args"]["step"] == 4
    # Bundle on disk, schema-valid, layer inside.
    bundle = json.loads((tmp_path / "fluxmpi_anomaly.0.json").read_text())
    assert tschema.validate_watchdog_dump(bundle) == []
    assert bundle["anomaly"]["layer"] == "params/dense_1"
    # The JSONL stream (model.* included) passes the checker CLI.
    reg.close()
    proc = subprocess.run(
        [sys.executable, _CHECKER, jsonl], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# Zero-cost-when-off
# ---------------------------------------------------------------------------


def test_train_loop_fully_off_computes_no_stats(world, ms_off, monkeypatch):
    """The monkeypatch-explode contract: plane off means NO stats
    computation at build time, no grouping, no observe_flush, on both
    the build and the drive path."""
    assert modelstats.get_model_stats() is None

    def boom(*a, **k):
        raise AssertionError("model-stats plane touched on the off path")

    monkeypatch.setattr(modelstats, "compute_stats", boom)
    monkeypatch.setattr(modelstats, "stats_zeros", boom)
    monkeypatch.setattr(modelstats, "group_paths", boom)
    monkeypatch.setattr(ModelStats, "observe_flush", boom)
    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state = replicate(TrainState.create(params, opt, None), world)
    _, summary = train_loop(step, state, loader, epochs=1, flush_every=2)
    assert summary["updates"] == 4


def test_plane_on_but_statless_step_emits_nothing(world, ms_off):
    """A step compiled while the plane was OFF keeps running after it
    turns on — stats-less (collection is baked at build time), with the
    flush never attempting an observe."""
    loss_fn, opt, params, ds = _mlp_pieces()
    step = make_train_step(loss_fn, opt, mesh=world, metrics=True)
    modelstats.configure(True)  # turned on AFTER the build
    reg = get_registry()
    reg.reset()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    state = replicate(TrainState.create(params, opt, None), world)
    _, summary = train_loop(step, state, loader, epochs=1, flush_every=2)
    assert summary["updates"] == 4
    assert not any(
        m["name"].startswith("model.") for m in reg.snapshot()
    )


# ---------------------------------------------------------------------------
# init() wiring, /status board, fluxmpi_top, report CLI
# ---------------------------------------------------------------------------


def test_init_model_stats_round_trip(world, ms_off):
    import fluxmpi_tpu as fm

    fm.init(model_stats=True)  # idempotent replay applies the spec
    assert modelstats.get_model_stats() is not None
    fm.init(model_stats=False)
    assert modelstats.get_model_stats() is None


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read()


def test_status_model_board_and_fluxmpi_top(world, ms_off):
    from fluxmpi_tpu.telemetry.export import Exporter
    from fluxmpi_tpu.telemetry.schema import validate_status_record

    get_registry().reset()
    modelstats.configure(True)
    exp = Exporter(0, "127.0.0.1", deadline=3600.0)
    export.configure(exp)
    try:
        loss_fn, opt, params, ds = _mlp_pieces()
        loader = DistributedDataLoader(ds, 64, mesh=world)
        step = make_train_step(
            loss_fn, opt, mesh=world, metrics=True, model_stats=True
        )
        state = replicate(TrainState.create(params, opt, None), world)
        train_loop(step, state, loader, epochs=1, flush_every=2, fuse=False)
        code, body = _get(exp.port, "/status")
        assert code == 200
        status = json.loads(body)
        assert validate_status_record(status) == []
        board = status["model"]
        assert board is not None
        assert board["nonfinite_layer"] is None
        top_layers = [t["layer"] for t in board["top"]]
        assert "params/dense_0" in top_layers or "params/dense_1" in (
            top_layers
        )
        # fluxmpi_top renders the MODEL block from the same snapshot.
        proc = subprocess.run(
            [sys.executable, _TOP, f"127.0.0.1:{exp.port}", "--once"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "MODEL" in proc.stdout
        assert "params/dense_" in proc.stdout
    finally:
        export.shutdown()


def test_fluxmpi_top_anomaly_ticker_renders_labels():
    """Satellite: the ticker names the triggering event's layer /
    function instead of the bare rule id (render_frame unit — the
    script is imported by file path, the goodput_report test trick)."""
    spec = importlib.util.spec_from_file_location("_fm_top", _TOP)
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    statuses = {
        "host-a": {
            "run_id": "r1",
            "train": {"updates": 10, "phase": "running"},
            "anomaly": {
                "rule": "steady_state_retrace",
                "function": "train_loop.step",
                "value_repr": "3",
                "step": 10,
            },
        },
        "host-b": {
            "run_id": "r1",
            "train": {"updates": 10},
            "anomaly": {
                "rule": "nan_grad",
                "layer": "params/dense_1",
                "value_repr": "nan",
                "step": 10,
            },
            "model": {
                "noise_scale": 123.4,
                "nonfinite_layer": "params/dense_1",
                "top": [{"layer": "params/dense_1", "grad_norm": 3.2}],
                "step": 10,
            },
        },
    }
    frame = top.render_frame(statuses, {})
    assert "function=train_loop.step" in frame
    assert "layer=params/dense_1" in frame
    assert "MODEL" in frame
    assert "123" in frame  # noise-scale readout
    assert "NONFINITE gradients in params/dense_1" in frame


def test_modelstats_report_cli(world, tmp_path, ms_off):
    jsonl = str(tmp_path / "run.jsonl")
    reg = MetricsRegistry(sinks=[JSONLSink(jsonl)])
    modelstats.configure(True)
    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(
        loss_fn, opt, mesh=world, style="shard_map", metrics=reg,
        model_stats=True,
    )
    state = replicate(TrainState.create(params, opt, None), world)
    train_loop(step, state, loader, epochs=1, flush_every=2, fuse=False)
    reg.close()
    proc = subprocess.run(
        [sys.executable, _REPORT, jsonl, "--history",
         "--batch", "64", "--workers", "8"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "params/dense_1" in proc.stdout
    assert "noise scale" in proc.stdout
    # History mode aggregates the INGREDIENT means (unbiased — present
    # even on flushes whose derived estimate was censored) and, with
    # the run geometry given, derives B_simple from them.
    assert "ingredient means" in proc.stdout
    assert "B_simple from ingredient means" in proc.stdout
    # --json round-trips.
    proc = subprocess.run(
        [sys.executable, _REPORT, jsonl, "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    data = json.loads(proc.stdout)
    assert "params/dense_1" in data["hosts"]["0"]["layers"]
    assert data["hosts"]["0"]["scalars"]["grad_noise_scale"] > 0
    # A bank without model metrics exits 1 (plane was off).
    empty = tmp_path / "empty.jsonl"
    empty.write_text(
        json.dumps(
            {
                "schema": tschema.SCHEMA,
                "time_unix": 1.0,
                "process": 0,
                "metrics": [],
            }
        )
        + "\n"
    )
    proc = subprocess.run(
        [sys.executable, _REPORT, str(empty)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    # A missing file exits 2.
    proc = subprocess.run(
        [sys.executable, _REPORT, str(tmp_path / "nope.jsonl")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2
