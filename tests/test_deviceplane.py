"""Device-plane tests (PR 9): compile/retrace telemetry from
jax.monitoring, the steady_state_retrace anomaly rule (polymorphic step
fires it after warmup, the PR 4 stable step does not), HBM gauges /
census / the monitor fold, OOM forensics bundles from train_loop,
anomaly-triggered auto-profiling, and the zero-cost-when-off contract
(monkeypatch-explode)."""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import fluxmpi_tpu as fm
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import (
    AnomalyDetector,
    CompileMonitor,
    GoodputTracker,
    MetricsRegistry,
    TrainingMonitor,
    anomaly,
    compileplane,
    goodput,
    memory,
)
from fluxmpi_tpu.telemetry import schema as tschema
from fluxmpi_tpu.utils import AutoProfiler, profiling

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHECKER = os.path.join(_REPO, "scripts", "check_metrics_schema.py")


@pytest.fixture()
def device_plane_off():
    """Guarantee the device + run-health planes are fully off around a
    test and restore whatever was installed before."""
    prev_cp = compileplane.set_compile_monitor(None)
    prev_det = anomaly.set_anomaly_detector(None)
    prev_gp = goodput.set_goodput_tracker(GoodputTracker(enabled=False))
    prev_ap = profiling.set_auto_profiler(None)
    was_mem = memory.enabled()
    memory.shutdown()
    try:
        yield
    finally:
        compileplane.set_compile_monitor(prev_cp)
        anomaly.set_anomaly_detector(prev_det)
        goodput.set_goodput_tracker(prev_gp)
        ap = profiling.set_auto_profiler(prev_ap)
        if ap is not None and ap is not prev_ap:
            ap.wait(timeout=90.0)
        memory.shutdown()
        if was_mem:
            memory.configure(True)


@pytest.fixture()
def fake_xplane(monkeypatch):
    """Stub jax.profiler's trace session for the unit tests: the real
    backend's first session of a process pays a multi-second cold start
    (budgeted once, in the e2e acceptance test). start_trace drops a
    marker file so directory-walk assertions still mean something."""
    def start(logdir, *a, **k):
        os.makedirs(logdir, exist_ok=True)
        with open(os.path.join(logdir, "fake.xplane.pb"), "a") as f:
            f.write("x")
    monkeypatch.setattr(jax.profiler, "start_trace", start)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)


def _mlp_pieces(n=256):
    from fluxmpi_tpu.models import MLP

    model = MLP(features=(16, 16, 1))

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1)))
    )
    return loss_fn, opt, params, ArrayDataset((x, x**2))


def _fresh_state(params, opt, world):
    return replicate(TrainState.create(params, opt, None), world)


# ---------------------------------------------------------------------------
# CompileMonitor
# ---------------------------------------------------------------------------


def test_compile_monitor_counts_and_attributes(device_plane_off):
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)
    compileplane.set_compile_monitor(mon)

    f = jax.jit(lambda x: x * 2 + 1)
    mon.track("f", f)
    f(jnp.ones(4)).block_until_ready()
    info = mon.observe_flush(reg)
    # Warmup flush: the first-dispatch compile is counted and attributed
    # but NOT steady-state.
    assert info["steady"] is False
    assert info["events"] >= 1
    assert "f" in info["functions"]
    assert reg.counter("compile.events").value >= 1
    assert reg.counter("compile.seconds", phase="compile").value > 0
    assert (
        reg.counter("compile.function_seconds", function="f").value > 0
    )
    # No retrace counter during warmup.
    assert reg.counter("compile.retraces", function="f").value == 0

    # Shape change: a steady-state retrace, named.
    f(jnp.ones(16)).block_until_ready()
    info = mon.observe_flush(reg)
    assert info["steady"] is True
    assert info["events"] >= 1
    assert info["functions"] == ["f"]
    assert reg.counter("compile.retraces", function="f").value == 1
    assert mon.retraces and mon.retraces[-1]["functions"] == ["f"]

    # A quiet interval reports nothing.
    f(jnp.ones(16)).block_until_ready()
    info = mon.observe_flush(reg)
    assert info["events"] == 0
    assert info["functions"] == []


def test_compile_monitor_untracked_compiles_labeled(device_plane_off):
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)
    compileplane.set_compile_monitor(mon)
    mon.observe_flush(reg)  # warmup boundary, nothing tracked

    g = jax.jit(lambda x: x - 3)  # never track()ed
    g(jnp.ones(7)).block_until_ready()
    info = mon.observe_flush(reg)
    assert info["steady"] is True
    assert info["events"] >= 1
    assert info["functions"] == [compileplane.UNTRACKED]
    assert (
        reg.counter(
            "compile.retraces", function=compileplane.UNTRACKED
        ).value
        >= 1
    )


def test_compile_monitor_goodput_crosscheck_gauge(device_plane_off):
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)

    class _FakeTracker:
        enabled = True

        def bucket_seconds(self, name):
            assert name == "compile"
            return 0.05

    # Forced totals: XLA reported 0.30s of compile work, the goodput
    # plane only booked 0.05s as compile — 0.25s is hiding in other
    # buckets (the silent-retrace signature).
    with mon._lock:
        mon._seconds = {"trace": 0.08, "lower": 0.02, "compile": 0.20}
        mon._events = 2
    mon.observe_flush(reg, goodput_tracker=_FakeTracker())
    assert reg.gauge("compile.unattributed_seconds").value == pytest.approx(
        0.25
    )


def test_compile_monitor_reset_run_reopens_warmup(device_plane_off):
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)
    mon.observe_flush(reg)
    assert mon.steady
    # A new run window: warmup re-opens, so run 2's first-dispatch
    # compiles are NOT steady-state retraces of run 1.
    mon.reset_run()
    assert not mon.steady
    with mon._lock:
        mon._events += 1
        mon._seconds["compile"] += 0.1
    info = mon.observe_flush(reg)
    assert info["steady"] is False
    assert mon.retraces == []


def test_compile_monitor_crosscheck_is_per_run(device_plane_off):
    """Pre-run compile seconds (model init, a previous loop) must not
    count against the CURRENT run's goodput compile bucket."""
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)

    class _FakeTracker:
        enabled = True

        def bucket_seconds(self, name):
            return 0.05

    with mon._lock:
        mon._seconds = {"trace": 0.0, "lower": 0.0, "compile": 10.0}
    mon.reset_run()  # train_loop start: 10s of pre-run compiles re-based
    with mon._lock:
        mon._seconds["compile"] += 0.30  # this run's compiles
    mon.observe_flush(reg, goodput_tracker=_FakeTracker())
    assert reg.gauge("compile.unattributed_seconds").value == pytest.approx(
        0.25
    )


def test_second_train_loop_run_does_not_false_alarm(world, device_plane_off):
    """Two sequential train_loop runs in one process: run 2's fresh step
    compiles at ITS warmup, which must not fire steady_state_retrace."""
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)
    compileplane.set_compile_monitor(mon)
    det = AnomalyDetector(registry=reg, dump=False)
    anomaly.set_anomaly_detector(det)
    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    for _ in range(2):
        step = make_train_step(loss_fn, opt, mesh=world)  # fresh jit
        train_loop(
            step, _fresh_state(params, opt, world), loader,
            epochs=1, flush_every=2, metrics=reg,
        )
    assert det.triggered == []
    assert mon.retraces == []


def test_compileplane_configure_env_forms(device_plane_off, monkeypatch):
    monkeypatch.delenv("FLUXMPI_TPU_COMPILEPLANE", raising=False)
    assert compileplane.configure(None) is None
    monkeypatch.setenv("FLUXMPI_TPU_COMPILEPLANE", "1")
    mon = compileplane.configure(None)
    assert isinstance(mon, CompileMonitor)
    assert compileplane.configure(None) is mon  # idempotent replay
    monkeypatch.setenv("FLUXMPI_TPU_COMPILEPLANE", "0")
    assert compileplane.configure(None) is None
    assert compileplane.get_compile_monitor() is None
    with pytest.raises(ValueError):
        compileplane.configure("sideways")


def test_compileplane_off_never_subscribes(device_plane_off, monkeypatch):
    """The no-subscribe half of the zero-cost contract: while the plane
    is off, configure() touches jax.monitoring not at all; installing a
    monitor registers the listener exactly then."""
    import jax.monitoring

    calls = []
    monkeypatch.setattr(compileplane, "_listener_registered", False)
    monkeypatch.setattr(
        jax.monitoring,
        "register_event_duration_secs_listener",
        lambda cb: calls.append(cb),
    )
    monkeypatch.delenv("FLUXMPI_TPU_COMPILEPLANE", raising=False)
    compileplane.configure(None)
    compileplane.configure(False)
    assert calls == []
    mon = compileplane.configure(True)
    assert calls == [compileplane._on_duration]
    compileplane.set_compile_monitor(None)
    assert mon is not None


# ---------------------------------------------------------------------------
# steady_state_retrace anomaly rule + auto-profile trigger
# ---------------------------------------------------------------------------


def test_retrace_rule_event_and_bundle(device_plane_off, tmp_path):
    from fluxmpi_tpu.telemetry import tracing

    tracer = tracing.Tracer(enabled=True)
    prev = tracing.set_tracer(tracer)
    try:
        det = AnomalyDetector(dump_dir=str(tmp_path))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            events = det.observe(
                retraces=3, retraced="train_loop.step", step=40
            )
        assert len(events) == 1
        ev = events[0]
        assert ev["rule"] == "steady_state_retrace"
        assert ev["action"] == "warn"  # per-host signal: never halt
        assert ev["value"] == 3.0
        assert ev["function"] == "train_loop.step"
        export = tracer.export()
        assert tschema.validate_trace_export(export) == []
        instants = [
            e
            for e in export["traceEvents"]
            if e.get("name") == "anomaly.steady_state_retrace"
        ]
        assert len(instants) == 1
        assert instants[0]["args"]["function"] == "train_loop.step"
        # The bundle carries the function too, and validates.
        bundle = json.loads(
            (tmp_path / "fluxmpi_anomaly.0.json").read_text()
        )
        assert tschema.validate_watchdog_dump(bundle) == []
        assert bundle["anomaly"]["function"] == "train_loop.step"
    finally:
        tracing.set_tracer(prev)


def test_retrace_trigger_fires_auto_profiler(
    device_plane_off, fake_xplane, tmp_path
):
    ap = AutoProfiler(str(tmp_path / "prof"), seconds=0.05, limit=1)
    profiling.set_auto_profiler(ap)
    det = AnomalyDetector(dump=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        det.observe(retraces=1, retraced="f", step=10)
    assert ap.last_reason == "anomaly:steady_state_retrace"
    ap.wait(timeout=90.0)
    captured = [
        os.path.join(r, f)
        for r, _, fs in os.walk(ap.last_capture_path)
        for f in fs
    ]
    assert captured, "no XPlane files written by the capture window"
    # Rate limit: a second trigger in the same run is a no-op.
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        det.observe(retraces=1, retraced="f", step=20)
    assert ap.captures == 1


def test_step_time_regression_triggers_auto_profiler(
    device_plane_off, fake_xplane, tmp_path
):
    ap = AutoProfiler(str(tmp_path / "prof"), seconds=0.05, limit=1)
    profiling.set_auto_profiler(ap)
    det = AnomalyDetector(dump=False, warmup=1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        det.observe(step_seconds=0.1, step=1)
        det.observe(step_seconds=0.1, step=2)
        events = det.observe(step_seconds=10.0, step=3)
    assert [e["rule"] for e in events] == ["step_time_regression"]
    assert ap.last_reason == "anomaly:step_time_regression"
    ap.wait(timeout=90.0)


def test_non_performance_rule_does_not_profile(
    device_plane_off, fake_xplane, tmp_path
):
    ap = AutoProfiler(str(tmp_path / "prof"), seconds=0.05, limit=1)
    profiling.set_auto_profiler(ap)
    det = AnomalyDetector(dump=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        det.observe(loss=float("nan"), step=1)
    assert ap.captures == 0
    assert ap.last_reason is None


# ---------------------------------------------------------------------------
# Memory plane
# ---------------------------------------------------------------------------


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_memory_stats_normalization():
    stats = memory.device_memory_stats(
        _FakeDevice(
            {
                "bytes_in_use": 5,
                "peak_bytes_in_use": 7,
                "bytes_limit": 10,
                "num_allocs": 3,  # not a gauge key: dropped
            }
        )
    )
    assert stats == {
        "bytes_in_use": 5.0,
        "peak_bytes_in_use": 7.0,
        "bytes_limit": 10.0,
    }
    assert memory.device_memory_stats(_FakeDevice(None)) == {}

    class _Broken:
        def memory_stats(self):
            raise RuntimeError("no stats on this backend")

    assert memory.device_memory_stats(_Broken()) == {}


def test_record_hbm_gauges_and_watermark(device_plane_off, monkeypatch):
    devs = [
        _FakeDevice({"bytes_in_use": 5, "peak_bytes_in_use": 70}),
        _FakeDevice({"bytes_in_use": 6, "peak_bytes_in_use": 90}),
    ]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    reg = MetricsRegistry()
    snap = memory.record_hbm(reg)
    assert snap["local_peak_bytes"] == 90.0
    assert snap["watermark_bytes"] == 90.0
    assert reg.gauge("memory.bytes_in_use", device="1").value == 6.0
    assert reg.gauge("memory.peak_bytes_in_use", device="0").value == 70.0
    assert reg.gauge("memory.peak_watermark_bytes").value == 90.0
    # Watermark is monotonic: a later, lower peak never lowers it.
    devs[1]._stats["peak_bytes_in_use"] = 40
    snap = memory.record_hbm(reg)
    assert snap["local_peak_bytes"] == 70.0
    assert snap["watermark_bytes"] == 90.0
    assert memory.peak_watermark_bytes() == 90.0


def test_census_top_n_ordering():
    big = jnp.ones((256, 64))
    small = jnp.ones((4,))
    c = memory.census(top_n=2)
    assert c["count"] >= 2
    assert c["total_bytes"] >= int(big.nbytes) + int(small.nbytes)
    assert len(c["arrays"]) == 2
    sizes = [a["nbytes"] for a in c["arrays"]]
    assert sizes == sorted(sizes, reverse=True)
    assert sizes[0] >= int(big.nbytes)
    top = c["arrays"][0]
    assert isinstance(top["shape"], list)
    assert isinstance(top["dtype"], str)
    assert isinstance(top["sharding"], str)
    del big, small


def test_memory_configure_env_forms(device_plane_off, monkeypatch):
    monkeypatch.delenv("FLUXMPI_TPU_MEMORY", raising=False)
    assert memory.configure(None) is False
    monkeypatch.setenv("FLUXMPI_TPU_MEMORY", "1")
    assert memory.configure(None) is True
    assert memory.enabled()
    monkeypatch.setenv("FLUXMPI_TPU_MEMORY", "0")
    assert memory.configure(None) is False
    with pytest.raises(ValueError):
        memory.configure("sideways")


# ---------------------------------------------------------------------------
# TrainingMonitor: dedupe + the HBM fold
# ---------------------------------------------------------------------------


def test_monitor_device_memory_routes_through_helper(
    device_plane_off, monkeypatch
):
    """Satellite: the monitor's device.memory.* series reads through the
    ONE normalization helper in telemetry/memory.py."""
    monkeypatch.setattr(
        memory,
        "device_memory_stats",
        lambda d: {"bytes_in_use": 42.0},
    )
    reg = MetricsRegistry()
    mon = TrainingMonitor(registry=reg, interval=1, cross_host=False)
    mon.collect()
    assert (
        reg.gauge("device.memory.bytes_in_use", device="0").value == 42.0
    )


def test_monitor_folds_hbm_peak_when_plane_on(device_plane_off, monkeypatch):
    devs = [_FakeDevice({"bytes_in_use": 10, "peak_bytes_in_use": 77})]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    memory.configure(True)
    reg = MetricsRegistry()
    mon = TrainingMonitor(registry=reg, interval=1, cross_host=False)
    summary = mon.observe_step(0.1)
    assert summary["hbm_peak_bytes_max"] == 77.0
    assert summary["hbm_peak_bytes_min"] == 77.0
    assert reg.gauge("monitor.hbm_peak_bytes_mean").value == 77.0
    # The one device walk also feeds the legacy device.memory.* series.
    assert reg.gauge("device.memory.peak_bytes_in_use", device="0").value == 77.0


def test_monitor_no_hbm_fold_when_plane_off(device_plane_off):
    reg = MetricsRegistry()
    mon = TrainingMonitor(registry=reg, interval=1, cross_host=False)
    summary = mon.observe_step(0.1)
    assert "hbm_peak_bytes_max" not in summary
    assert all(
        m["name"] != "monitor.hbm_peak_bytes_mean" for m in reg.snapshot()
    )


# ---------------------------------------------------------------------------
# OOM forensics in train_loop
# ---------------------------------------------------------------------------


def _oom_step_pieces(fail_at=3, message=None):
    message = message or (
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes."
    )
    calls = {"n": 0}

    def step(state, batch):
        calls["n"] += 1
        if calls["n"] >= fail_at:
            raise RuntimeError(message)
        return state, jnp.zeros(())

    batches = [
        (np.zeros((8, 1), np.float32), np.zeros((8, 1), np.float32))
        for _ in range(6)
    ]
    return step, jnp.zeros(()), batches


def test_train_loop_oom_writes_census_bundle(
    device_plane_off, tmp_path, monkeypatch
):
    monkeypatch.setenv("FLUXMPI_TPU_OOM_DIR", str(tmp_path))
    step, state, batches = _oom_step_pieces()
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            train_loop(step, state, batches, epochs=1)
    path = tmp_path / "fluxmpi_oom.0.json"
    assert path.exists()
    rec = json.loads(path.read_text())
    assert tschema.validate_watchdog_dump(rec) == []
    assert rec["kind"] == "watchdog_dump"
    assert rec["reason"] == "oom"
    assert "RESOURCE_EXHAUSTED" in rec["oom"]["error"]
    assert rec["oom"]["census"]["count"] >= 1
    assert isinstance(rec["oom"]["devices"], dict)
    # The repo checker validates it like every other artifact.
    proc = subprocess.run(
        [sys.executable, _CHECKER, str(path)],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout


def test_train_loop_non_oom_error_writes_no_bundle(
    device_plane_off, tmp_path, monkeypatch
):
    monkeypatch.setenv("FLUXMPI_TPU_OOM_DIR", str(tmp_path))
    step, state, batches = _oom_step_pieces(message="some unrelated crash")
    with pytest.raises(RuntimeError, match="unrelated"):
        train_loop(step, state, batches, epochs=1)
    assert not (tmp_path / "fluxmpi_oom.0.json").exists()


def test_is_oom_error_matching():
    assert memory.is_oom_error(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 8 bytes")
    )
    assert memory.is_oom_error(RuntimeError("Allocator ran Out of Memory"))
    assert not memory.is_oom_error(ValueError("shape mismatch"))


# ---------------------------------------------------------------------------
# Auto-profiler + profile_trace collision fix
# ---------------------------------------------------------------------------


def test_auto_profiler_rate_limit_and_force(
    device_plane_off, fake_xplane, tmp_path
):
    ap = AutoProfiler(str(tmp_path), seconds=0.05, limit=1)
    # An early SIGUSR2 capture must NOT spend the automatic budget — the
    # one auto capture exists for a later anomaly's evidence.
    forced = ap.maybe_capture("human", force=True)
    assert forced is not None
    ap.wait(timeout=90.0)
    first = ap.maybe_capture("one")
    assert first is not None
    ap.wait(timeout=90.0)
    assert ap.maybe_capture("two") is None  # auto budget spent
    assert ap.captures == 2
    forced = ap.maybe_capture("human-again", force=True)  # still allowed
    assert forced is not None
    ap.wait(timeout=90.0)
    assert ap.captures == 3
    ap.reset()
    assert ap.maybe_capture("fresh-run") is not None
    ap.wait(timeout=90.0)


def test_auto_profiler_refunds_budget_when_start_fails(
    device_plane_off, tmp_path, monkeypatch
):
    """A capture that collides with a live profiler session (start_trace
    raises) must refund the budget — the armed profiler exists to
    guarantee one capture of XPlane evidence."""
    attempts = []

    def flaky_start(logdir, *a, **k):
        attempts.append(logdir)
        if len(attempts) == 1:
            raise RuntimeError("Only one profile may be run at a time.")
        os.makedirs(logdir, exist_ok=True)
        with open(os.path.join(logdir, "fake.xplane.pb"), "a") as f:
            f.write("x")

    monkeypatch.setattr(jax.profiler, "start_trace", flaky_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    ap = AutoProfiler(str(tmp_path), seconds=0.05, limit=1)
    assert ap.maybe_capture("one") is not None  # collides, refunded
    ap.wait(timeout=90.0)
    assert ap.captures == 0
    assert ap.maybe_capture("two") is not None  # budget still available
    ap.wait(timeout=90.0)
    assert ap.captures == 1
    assert len(attempts) == 2


def test_train_loop_resets_auto_capture_budget_per_run(
    world, device_plane_off, fake_xplane, tmp_path
):
    """The 'once per run' budget is per train_loop run: a capture spent
    in run 1 must not leave run 2's regression evidence-less."""
    ap = AutoProfiler(str(tmp_path), seconds=0.05, limit=1)
    profiling.set_auto_profiler(ap)
    assert ap.maybe_capture("run1-anomaly") is not None
    ap.wait(timeout=90.0)
    assert ap.maybe_capture("still-run1") is None  # budget spent
    anomaly.set_anomaly_detector(AnomalyDetector(dump=False))
    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    train_loop(step, _fresh_state(params, opt, world), loader, epochs=1)
    assert ap.maybe_capture("run2-anomaly") is not None  # budget re-opened
    ap.wait(timeout=90.0)


def test_auto_profiler_configure_idempotent_keeps_budget(
    device_plane_off, fake_xplane, tmp_path, monkeypatch
):
    monkeypatch.setenv("FLUXMPI_TPU_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("FLUXMPI_TPU_PROFILE_SECONDS", "0.1")
    monkeypatch.setenv("FLUXMPI_TPU_PROFILE_LIMIT", "1")
    ap = profiling.configure_auto_profiler(None)
    assert isinstance(ap, AutoProfiler)
    assert ap.seconds == 0.1
    ap.maybe_capture("x")
    ap.wait(timeout=90.0)
    # init() replay with the same spec keeps the instance AND its spent
    # budget — a replay must not grant a fresh capture.
    assert profiling.configure_auto_profiler(None) is ap
    assert ap.captures == 1
    profiling.configure_auto_profiler("0")
    assert profiling.get_auto_profiler() is None


def test_profile_trace_all_hosts_gets_proc_subdir(monkeypatch):
    """Satellite: profile_trace(all_hosts=True) writes each process into
    <logdir>/proc<k> instead of documenting the collision away."""
    captured = []

    class _FakeTrace:
        def __init__(self, logdir):
            captured.append(logdir)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return None

    monkeypatch.setattr(jax.profiler, "trace", _FakeTrace)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(jax, "process_index", lambda: 2)
    with profiling.profile_trace("/logs/run1", all_hosts=True):
        pass
    assert captured == [os.path.join("/logs/run1", "proc2")]
    # Non-lead process without all_hosts: no trace at all.
    with profiling.profile_trace("/logs/run1"):
        pass
    assert len(captured) == 1
    # Single-process all_hosts keeps the plain logdir (no nesting).
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    with profiling.profile_trace("/logs/run1", all_hosts=True):
        pass
    assert captured[-1] == "/logs/run1"


# ---------------------------------------------------------------------------
# Schema: closed compile./memory. namespaces
# ---------------------------------------------------------------------------


def test_compile_memory_namespaces_are_closed():
    ok = {
        "name": "memory.bytes_in_use",
        "type": "gauge",
        "labels": {"device": "0"},
        "value": 1.0,
    }
    assert tschema.validate_metric(ok) == []
    for bogus in ("compile.bogus", "memory.bogus"):
        bad = {"name": bogus, "type": "gauge", "labels": {}, "value": 1.0}
        assert any(
            "framework-owned" in e for e in tschema.validate_metric(bad)
        )


# ---------------------------------------------------------------------------
# End-to-end: retrace detection + stable-step negative + zero-cost
# ---------------------------------------------------------------------------


def _polymorphic_batches(n_stable=8, n_poly=4):
    """Batches whose shape changes mid-run — the silent retrace: batch
    size 64 for the first n_stable dispatches, then 80."""
    rng = np.random.default_rng(0)
    for i in range(n_stable + n_poly):
        n = 64 if i < n_stable else 80
        x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
        yield (x, (x**2).astype(np.float32))


def test_retrace_end_to_end(world, device_plane_off, tmp_path, monkeypatch):
    """Acceptance: an injected mid-run retrace emits compile.* metrics,
    fires steady_state_retrace naming the recompiled function, and drops
    a profile capture in FLUXMPI_TPU_PROFILE_DIR."""
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)
    compileplane.set_compile_monitor(mon)
    det = AnomalyDetector(
        registry=reg, dump_dir=str(tmp_path / "bundles")
    )
    anomaly.set_anomaly_detector(det)
    prof_dir = tmp_path / "profiles"
    monkeypatch.setenv("FLUXMPI_TPU_PROFILE_DIR", str(prof_dir))
    monkeypatch.setenv("FLUXMPI_TPU_PROFILE_SECONDS", "0.2")
    ap = profiling.configure_auto_profiler(None)

    loss_fn, opt, params, _ = _mlp_pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        state, summary = train_loop(
            step,
            _fresh_state(params, opt, world),
            _polymorphic_batches(),
            flush_every=4,
            metrics=reg,
        )
    assert summary["updates"] == 12
    # compile.* metrics: the warmup compile AND the retrace are counted;
    # the retrace is attributed to the loop's tagged hot step.
    assert reg.counter("compile.events").value >= 2
    assert (
        reg.counter(
            "compile.retraces", function="train_loop.step"
        ).value
        >= 1
    )
    # The rule fired, naming the function...
    rules = [ev["rule"] for ev in det.triggered]
    assert "steady_state_retrace" in rules
    ev = next(
        e for e in det.triggered if e["rule"] == "steady_state_retrace"
    )
    assert "train_loop.step" in ev["function"]
    assert ev["action"] == "warn"  # and the run completed
    # ...the diagnostics bundle is on disk and valid...
    bundle = json.loads(
        (tmp_path / "bundles" / "fluxmpi_anomaly.0.json").read_text()
    )
    assert tschema.validate_watchdog_dump(bundle) == []
    # ...and the auto-profiler dropped an XPlane capture.
    assert ap.captures == 1
    ap.wait(timeout=90.0)
    captured = [
        os.path.join(r, f)
        for r, _, fs in os.walk(str(prof_dir))
        for f in fs
    ]
    assert captured, "no profile capture landed in FLUXMPI_TPU_PROFILE_DIR"


def test_stable_step_never_fires_retrace(world, device_plane_off):
    """The PR 4 stable loop (loader-fed, fixed shapes, multi-epoch) must
    stay silent: its only compiles are warmup."""
    reg = MetricsRegistry()
    mon = CompileMonitor(registry=reg)
    compileplane.set_compile_monitor(mon)
    det = AnomalyDetector(registry=reg, dump=False)
    anomaly.set_anomaly_detector(det)

    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state, summary = train_loop(
        step,
        _fresh_state(params, opt, world),
        loader,
        epochs=2,
        flush_every=2,
        metrics=reg,
    )
    assert summary["updates"] == 8
    assert det.triggered == []
    assert mon.retraces == []
    assert (
        reg.counter("compile.retraces", function="train_loop.step").value
        == 0
    )


def test_train_loop_fully_off_device_plane_costs_nothing(
    world, device_plane_off, monkeypatch
):
    """The PR 4 monkeypatch-explode contract extended to the device
    plane: with no compile monitor, memory plane off, and no
    auto-profiler, the train loop performs no monitoring subscriptions,
    no compile-cache polls, no HBM stat reads, and no census walks."""
    assert compileplane.get_compile_monitor() is None
    assert not memory.enabled()
    assert profiling.get_auto_profiler() is None

    def boom(*a, **k):
        raise AssertionError("device plane touched on the off path")

    monkeypatch.setattr(CompileMonitor, "track", boom)
    monkeypatch.setattr(CompileMonitor, "observe_flush", boom)
    monkeypatch.setattr(compileplane, "_ensure_listener", boom)
    monkeypatch.setattr(memory, "record_hbm", boom)
    monkeypatch.setattr(memory, "census", boom)
    monkeypatch.setattr(memory, "write_oom_bundle", boom)
    monkeypatch.setattr(memory, "is_oom_error", boom)
    monkeypatch.setattr(AutoProfiler, "maybe_capture", boom)
    monkeypatch.setattr(AutoProfiler, "reset", boom)
    monkeypatch.setattr(profiling, "maybe_auto_capture", boom)

    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, epochs=1
    )
    assert summary["updates"] == 4
    assert summary["anomaly"] is None
