"""Framework-agnosticism tests (reference design principle: "not tied to
any framework — works with anything Optimisers.jl-compatible",
docs/src/index.md:30-36). Here: anything whose state is a pytree works —
flax (used throughout the suite), dm-haiku, and raw-dict models."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp


def test_haiku_model_end_to_end(world):
    hk = pytest.importorskip("haiku")

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    def net_fn(x):
        return hk.nets.MLP([16, 16, 1])(x)

    net = hk.without_apply_rng(hk.transform(net_fn))
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(32, 1)).astype(np.float32)
    y = (x**2).astype(np.float32)

    params = net.init(jax.random.PRNGKey(fm.local_rank()), jnp.asarray(x[:2]))
    params = fm.synchronize(params)  # haiku params are a plain dict pytree

    optimizer = optax.adam(1e-2)

    def loss_fn(p, ms, batch):
        bx, by = batch
        return jnp.mean((net.apply(p, bx) - by) ** 2), ms

    step = make_train_step(loss_fn, optimizer, donate=False)
    state = replicate(TrainState.create(params, optimizer))
    batch = shard_batch((jnp.asarray(x), jnp.asarray(y)))
    losses = []
    for _ in range(40):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5


def test_raw_pytree_model(world):
    # no framework at all: params as a plain dict, apply as a function
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    params = {
        "w1": jnp.zeros((1, 8)),
        "b1": jnp.zeros((8,)),
        "w2": jnp.zeros((8, 1)),
    }
    params = fm.synchronize(params)

    def apply(p, x):
        return jnp.tanh(x @ p["w1"] + p["b1"]) @ p["w2"]

    def loss_fn(p, ms, batch):
        x, y = batch
        return jnp.mean((apply(p, x) - y) ** 2), ms

    optimizer = fm.DistributedOptimizer(optax.sgd(0.1))
    step = make_train_step(
        loss_fn, optimizer, style="shard_map", grad_reduce=None, donate=False
    )
    x = np.linspace(-1, 1, 32).reshape(32, 1).astype(np.float32)
    batch = shard_batch((jnp.asarray(x), jnp.asarray(2 * x)))
    state = replicate(TrainState.create(params, optimizer))
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))
