"""Runtime + logging tests (reference: test/test_common.jl)."""

import pytest


def test_not_initialized_error():
    import fluxmpi_tpu as fm
    from fluxmpi_tpu import runtime

    was_init = runtime.is_initialized()
    saved_mesh = runtime._state.mesh
    try:
        runtime.shutdown()
        assert not fm.is_initialized()
        with pytest.raises(fm.FluxMPINotInitializedError):
            fm.local_rank()
        with pytest.raises(fm.FluxMPINotInitializedError):
            fm.total_workers()
    finally:
        if was_init:
            runtime._state.initialized = True
            runtime._state.mesh = saved_mesh


def test_init_idempotent(world):
    import fluxmpi_tpu as fm

    mesh1 = fm.init()
    mesh2 = fm.init(verbose=True)
    assert mesh1 is mesh2
    assert fm.is_initialized()
    assert fm.Initialized()


def test_world_identity(world):
    # reference: test/test_common.jl asserts local_rank() < total_workers()
    import fluxmpi_tpu as fm

    assert fm.total_workers() == 8
    assert 0 <= fm.local_rank() < fm.total_workers()
    assert fm.process_count() == 1
    assert fm.device_count() == 8
    assert fm.local_device_count() == 8


def test_mesh_shape(world):
    import fluxmpi_tpu as fm

    mesh = fm.global_mesh()
    assert mesh.shape == {fm.dp_axis_name(): 8}


def test_custom_mesh_shape_inference():
    import fluxmpi_tpu as fm
    from fluxmpi_tpu import runtime

    saved = (runtime._state.initialized, runtime._state.mesh)
    try:
        runtime.shutdown()
        mesh = fm.init(mesh_shape={"dp": -1, "sp": 2})
        assert mesh.shape == {"dp": 4, "sp": 2}
    finally:
        runtime._state.initialized, runtime._state.mesh = saved


def test_print_functions(world, capsys):
    # reference: test/test_common.jl:6-13 — print fns run without error
    import fluxmpi_tpu as fm

    fm.fluxmpi_println("hello", "world")
    fm.fluxmpi_print("partial")
    out = capsys.readouterr().out
    assert "hello" in out and "partial" in out


def test_print_before_init(capsys):
    from fluxmpi_tpu import runtime
    from fluxmpi_tpu.logging import fluxmpi_println

    saved = (runtime._state.initialized, runtime._state.mesh)
    try:
        runtime.shutdown()
        fluxmpi_println("pre-init message")
        out = capsys.readouterr().out
        assert "pre-init message" in out
        # timestamp prefix present pre-init (reference: src/common.jl:76-79)
        assert out[:4].isdigit()
    finally:
        runtime._state.initialized, runtime._state.mesh = saved
