"""Pallas flash attention vs dense oracle (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _dense(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(b=2, s=64, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        for _ in range(3)
    )


def test_flash_matches_dense(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv()
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v)), atol=2e-5
    )


def test_flash_causal(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=1)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v, causal=True)), atol=2e-5
    )


def test_flash_single_block(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=16, seed=2)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v)), atol=2e-5
    )


def test_flash_bf16(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(seed=3))
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    expected = _dense(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(expected), atol=0.05
    )


def test_flash_bad_blocks_rejected(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=48, seed=4)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=32, block_k=32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_matches_dense(world, causal):
    # The Pallas backward kernels (dq + dk/dv) against autodiff through the
    # dense oracle (VERDICT r1 next #3).
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=causal,
                                               block_q=32, block_k=32)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense(q, k, v, causal=causal)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_lse_and_its_gradient(world):
    # flash_attention_with_lse: the lse output matches dense logsumexp and
    # its cotangent is honored (the merge key ring attention relies on).
    from fluxmpi_tpu.ops import flash_attention_with_lse

    q, k, v = _qkv(seed=6)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    lse_dense = jax.scipy.special.logsumexp(s, axis=-1)  # [b, h, q]

    out, lse = flash_attention_with_lse(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(lse_dense), atol=1e-5
    )

    def loss_flash(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, block_q=32, block_k=32)
        return jnp.sum(jnp.cos(lse)) + jnp.sum(out**2)

    def loss_dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        return jnp.sum(jnp.cos(lse)) + jnp.sum(_dense(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_transformer_trains_through_flash_attention(world):
    # A TransformerLM whose attention is the Pallas kernel end-to-end: the
    # compiled DP train step runs and the flash model's gradients match the
    # dense-attention model's (same params, same batch).
    import optax

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import TransformerLM
    from fluxmpi_tpu.ops import flash_attention_fn
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    mesh = fm.global_mesh()
    kwargs = dict(vocab_size=64, max_len=32, num_layers=1, d_model=32,
                  num_heads=2, d_ff=64)
    flash_model = TransformerLM(
        attention_fn=flash_attention_fn(causal=True), **kwargs
    )
    dense_model = TransformerLM(**kwargs)

    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, 64, size=(16, 32)).astype(np.int32))
    params = dense_model.init(jax.random.PRNGKey(0), tokens[:2], train=False)

    def make_loss(model):
        def loss_fn(p, mstate, batch):
            logits = model.apply(p, batch, train=True)
            targets = jnp.roll(batch, -1, axis=1)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], targets[:, :-1]
            ).mean()
            return loss, mstate

        return loss_fn

    gf = jax.grad(lambda p: make_loss(flash_model)(p, None, tokens)[0])(params)
    gd = jax.grad(lambda p: make_loss(dense_model)(p, None, tokens)[0])(params)
    flat_f = jax.tree_util.tree_leaves(gf)
    flat_d = jax.tree_util.tree_leaves(gd)
    for a, b in zip(flat_f, flat_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    step = make_train_step(
        make_loss(flash_model), optax.adam(1e-3), mesh=mesh, style="auto"
    )
    state = replicate(TrainState.create(params, optax.adam(1e-3)), mesh)
    data = shard_batch(tokens, mesh)
    state, loss0 = step(state, data)
    state, loss1 = step(state, data)
    assert np.isfinite(float(loss0)) and float(loss1) < float(loss0)


# ---- segment-id / padding masking (VERDICT r2 next #5) ----


from _oracles import dense_seg_attention as _dense_seg  # noqa: E402


def _packed_segments(b=2, s=64):
    seg = np.zeros((b, s), np.int32)
    seg[0, :16] = 1
    seg[0, 16:48] = 2
    seg[0, 48:] = 3
    seg[1, :40] = 1
    seg[1, 40:] = 2
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segments_packed(world, causal):
    # Packed-sequence masking: documents attend only within themselves.
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=10)
    seg = _packed_segments()
    out = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                          block_q=16, block_k=16)
    expected = _dense_seg(q, k, v, seg, seg, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5
    )


def test_flash_segments_padding_rows_zero(world):
    # Pad tokens (segment id 0) attend nothing and output exactly zero;
    # valid rows are unaffected by the padding.
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=11)
    seg = np.ones((2, 64), np.int32)
    seg[0, 48:] = 0
    seg[1, 56:] = 0
    seg = jnp.asarray(seg)
    out = flash_attention(q, k, v, segment_ids=seg, block_q=16, block_k=16)
    expected = _dense_seg(q, k, v, seg, seg)
    valid = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[valid], np.asarray(expected)[valid], atol=2e-5
    )
    assert np.all(np.asarray(out)[~valid] == 0.0)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segments_grad_matches_dense(world, causal):
    # Backward kernels under segment masking, padding included: grads match
    # autodiff through the dense oracle when the loss reads valid rows only
    # (the dense oracle's pad rows are garbage by construction).
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=12)
    seg = _packed_segments()
    seg = seg.at[0, 56:].set(0)  # add a pad tail too
    row_w = (seg != 0).astype(jnp.float32)[:, :, None, None]

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                              block_q=16, block_k=16)
        return jnp.sum(jnp.sin(out) * row_w)

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_seg(q, k, v, seg, seg, causal)) * row_w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_fn_accepts_flax_padding_mask(world):
    # flash_attention_fn honors nn.make_attention_mask-style padding masks
    # (VERDICT r2 next #5: "accepting flax's padding mask instead of
    # raising").
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=13)
    valid = np.ones((2, 64), bool)
    valid[0, 40:] = False
    valid[1, 60:] = False
    valid = jnp.asarray(valid)
    mask = nn.make_attention_mask(valid, valid)  # [b, 1, sq, sk]

    out = flash_attention_fn(block_q=16, block_k=16)(q, k, v, mask=mask)
    seg = valid.astype(jnp.int32)
    expected = _dense_seg(q, k, v, seg, seg)
    ok = np.asarray(valid)
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_flash_fn_combined_causal_padding_mask(world):
    # ADVICE r2 #1: causal=True with a combined causal∧padding mask must
    # honor the padding component, not silently drop it.
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=14)
    valid = np.ones((2, 64), bool)
    valid[0, 32:] = False
    valid = jnp.asarray(valid)
    mask = nn.combine_masks(
        nn.make_causal_mask(jnp.zeros((2, 64))),
        nn.make_attention_mask(valid, valid),
    )

    out = flash_attention_fn(causal=True, block_q=16, block_k=16)(
        q, k, v, mask=mask
    )
    seg = valid.astype(jnp.int32)
    expected = _dense_seg(q, k, v, seg, seg, causal=True)
    ok = np.asarray(valid)
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_flash_fn_rejects_bias(world):
    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=15)
    with pytest.raises(ValueError, match="bias"):
        flash_attention_fn()(q, k, v, bias=jnp.zeros((2, 2, 64, 64)))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fn_packed_sequence_mask(world, causal):
    # Code-review r3: the flax packed-sequence idiom
    # nn.make_attention_mask(seg, seg, jnp.equal) (block-diagonal) must be
    # recovered EXACTLY — tokens must not attend across document
    # boundaries.
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=16)
    seg = _packed_segments()  # contiguous docs, no padding
    mask = nn.make_attention_mask(seg, seg, jnp.equal)
    if causal:
        mask = nn.combine_masks(mask, nn.make_causal_mask(jnp.zeros((2, 64))))

    out = flash_attention_fn(causal=causal, block_q=16, block_k=16)(
        q, k, v, mask=mask
    )
    expected = _dense_seg(q, k, v, seg, seg, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5
    )


def test_flash_fn_packed_plus_padding_mask(world):
    # Packing AND a trailing pad, combined with causal — the full flax
    # combine_masks stack.
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=17)
    seg = np.zeros((2, 64), np.int32)
    seg[0, :24] = 1
    seg[0, 24:48] = 2  # then pad tail (0)
    seg[1, :64] = 1
    seg = jnp.asarray(seg)
    valid = seg != 0
    mask = nn.combine_masks(
        nn.make_attention_mask(seg, seg, jnp.equal),
        nn.make_attention_mask(valid, valid),
        nn.make_causal_mask(jnp.zeros((2, 64))),
    )
    out = flash_attention_fn(causal=True, block_q=16, block_k=16)(
        q, k, v, mask=mask
    )
    expected = _dense_seg(q, k, v, seg, seg, causal=True)
    ok = np.asarray(valid)
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_flash_fn_decode_prefix_mask_skips_garbage_tiles(world):
    # The serving decode shape (ISSUE 19): ONE query position against a
    # gathered paged cache, masked by flax's cache-index prefix mask
    # ([b, 1, 1, sk]). The masked tail holds garbage (the paged pool's
    # trash-block rows), planted to discriminate the two masking
    # mechanisms: LARGE-FINITE garbage in the partially-masked tile
    # (where-masked: p -> 0, and 0 x finite = 0 contributes nothing)
    # and NaN in the fully-masked tiles — if those tiles were computed
    # at all, 0 x NaN = NaN would poison the contraction, so a finite
    # output PROVES the @pl.when tile skip, not just the where mask.
    from fluxmpi_tpu.ops import flash_attention_fn

    block_k = 16
    b, sk, h, d = 2, 64, 2, 8
    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)).astype(np.float32))
    k = rng.normal(size=(b, sk, h, d)).astype(np.float32)
    v = rng.normal(size=(b, sk, h, d)).astype(np.float32)
    lengths = (9, 40)
    for i, n in enumerate(lengths):
        tile_end = -(-n // block_k) * block_k  # end of the partial tile
        k[i, n:tile_end] = 1e6
        v[i, n:tile_end] = 1e6
        k[i, tile_end:] = np.nan
        v[i, tile_end:] = np.nan
    k, v = jnp.asarray(k), jnp.asarray(v)
    mask = (
        jnp.arange(sk)[None, None, None, :]
        < jnp.asarray(lengths)[:, None, None, None]
    )

    # mask_check=False mirrors the decode path (models/transformer.py):
    # the prefix mask is representable by construction there.
    out = flash_attention_fn(mask_check=False, block_k=block_k)(
        q, k, v, mask=mask
    )
    assert np.isfinite(np.asarray(out)).all(), "fully-masked tile was computed"
    scale = 1.0 / np.sqrt(d)
    for i, n in enumerate(lengths):
        s = jnp.einsum("qhd,khd->hqk", q[i], k[i, :n]) * scale
        w = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("hqk,khd->qhd", w, v[i, :n])
        np.testing.assert_allclose(
            np.asarray(out[i]), np.asarray(ref), atol=2e-5
        )


def test_flash_fn_rejects_unrepresentable_concrete_mask(world):
    # VERDICT r3 next #10: an unrepresentable CONCRETE mask (e.g. a causal
    # mask passed with causal=False) must be a Python ValueError at call
    # time — not a mid-training NaN.
    import pytest
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=18)
    causal_mask = nn.make_causal_mask(jnp.zeros((2, 64)))
    with pytest.raises(ValueError, match="not representable"):
        flash_attention_fn(block_q=16, block_k=16)(q, k, v, mask=causal_mask)

    # …and a representable mask on the same path works.
    valid = jnp.asarray(np.ones((2, 64), bool))
    pad_mask = nn.make_attention_mask(valid, valid)
    out = flash_attention_fn(block_q=16, block_k=16)(q, k, v, mask=pad_mask)
    assert not np.any(np.isnan(np.asarray(out, dtype=np.float32)))


def test_flash_fn_poisons_unrepresentable_traced_mask(world):
    # Genuinely dynamic (traced) masks can only be checked on-device: the
    # NaN-poison remains the last resort there — loud failure, never
    # silently-wrong attention.
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=18)
    causal_mask = nn.make_causal_mask(jnp.zeros((2, 64)))

    @jax.jit
    def run(q, k, v, mask):
        return flash_attention_fn(block_q=16, block_k=16)(q, k, v, mask=mask)

    out = run(q, k, v, causal_mask)
    assert np.all(np.isnan(np.asarray(out, dtype=np.float32)))

    # mask_check=False skips the runtime check (validated-pipeline mode):
    # same call, no poison — the mask degrades to its segment projection.
    @jax.jit
    def run_unchecked(q, k, v, mask):
        return flash_attention_fn(block_q=16, block_k=16, mask_check=False)(
            q, k, v, mask=mask
        )

    out = run_unchecked(q, k, v, causal_mask)
    assert not np.any(np.isnan(np.asarray(out, dtype=np.float32)))


def test_flash_fn_head_varying_mask_rejected(world):
    # Per-head masks are unrepresentable by per-batch segment ids; the
    # any-over-heads reduction used to let them through silently.
    import pytest

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=19)
    m = np.ones((2, 4, 64, 64), bool)
    m[:, 0] = False  # head 0 attends nothing; other heads attend all
    with pytest.raises(ValueError, match="not representable"):
        flash_attention_fn(block_q=16, block_k=16)(q, k, v, mask=jnp.asarray(m))


def test_flash_fn_dropout_dense_fallback(world):
    # VERDICT r3 next #9: dropout_rate > 0 in training mode transparently
    # takes the dense fallback with flax-exact semantics — no user-visible
    # branching, and it matches flax's own dot_product_attention under the
    # same rng.
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    q, k, v = _qkv(seed=20)
    rng = jax.random.PRNGKey(7)
    out = flash_attention_fn(causal=True)(
        q, k, v,
        dropout_rng=rng, dropout_rate=0.3, deterministic=False,
        broadcast_dropout=True,
    )
    mask = nn.make_causal_mask(jnp.zeros((2, 64)))
    expected = nn.dot_product_attention(
        q, k, v, mask=mask,
        dropout_rng=rng, dropout_rate=0.3, deterministic=False,
        broadcast_dropout=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5
    )
    # deterministic=True ignores dropout and stays on the flash path.
    out_det = flash_attention_fn(causal=True)(
        q, k, v, dropout_rate=0.3, deterministic=True
    )
    no_drop = flash_attention_fn(causal=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_det), np.asarray(no_drop))


def test_flash_fn_dropout_module_trains(world):
    # A flax attention module with dropout trains through the adapter end
    # to end (grads finite), with no user-visible branching.
    import flax.linen as nn
    import optax

    from fluxmpi_tpu.ops import flash_attention_fn

    attn = nn.MultiHeadDotProductAttention(
        num_heads=4, qkv_features=32, dropout_rate=0.2,
        attention_fn=flash_attention_fn(causal=True),
    )
    x = jnp.asarray(
        np.random.default_rng(21).normal(size=(2, 16, 32)).astype(np.float32)
    )
    params = attn.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, x, deterministic=False,
    )

    def loss_fn(p, rng):
        y = attn.apply(
            p, x, x, deterministic=False, rngs={"dropout": rng}
        )
        return jnp.mean(y**2)

    g = jax.jit(jax.grad(loss_fn))(params, jax.random.PRNGKey(2))
    assert all(
        np.all(np.isfinite(np.asarray(leaf)))
        for leaf in jax.tree_util.tree_leaves(g)
    )
    # and an optimizer step applies cleanly
    opt = optax.adam(1e-3)
    state = opt.init(params)
    updates, _ = opt.update(g, state, params)
    optax.apply_updates(params, updates)


def _dense_window(q, k, v, window):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    sq, sk = q.shape[1], k.shape[1]
    qpos = jnp.arange(sq)[:, None]
    kpos = jnp.arange(sk)[None, :]
    mask = (qpos >= kpos) & (qpos - kpos < window)
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("window", [16, 40])
def test_flash_sliding_window(world, window):
    # Mistral-style local attention: position i attends (i-window, i].
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=128, seed=30)
    out = flash_attention(q, k, v, causal=True, window=window,
                          block_q=16, block_k=16)
    expected = _dense_window(q, k, v, window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_flash_sliding_window_grads(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=64, seed=31)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=True, window=24, block_q=16, block_k=16)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_window(q, k, v, 24)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_window_requires_causal(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=32)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, window=16)


def test_flash_window_composes_with_segments(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=64, seed=33)
    seg = np.ones((2, 64), np.int32)
    seg[0, 48:] = 0  # pad tail
    seg = jnp.asarray(seg)
    out = flash_attention(q, k, v, causal=True, window=24, segment_ids=seg,
                          block_q=16, block_k=16)
    # dense oracle: window ∧ causal ∧ segments
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    qpos = jnp.arange(64)[:, None]
    kpos = jnp.arange(64)[None, :]
    mask = (qpos >= kpos) & (qpos - kpos < 24)
    mask = mask[None] & (seg[:, :, None] == seg[:, None, :]) & (
        seg[:, None, :] != 0
    )
    s = jnp.where(mask[:, None], s, -1e30)
    expected = jnp.einsum(
        "bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v
    )
    ok = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_flash_cross_attention(world):
    # sq != sk (encoder-decoder cross attention): separate q/kv lengths and
    # a (q_seg, kv_seg) pair.
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(40)
    q = jnp.asarray(rng.normal(size=(2, 32, 2, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v)), atol=2e-5
    )

    # With validity segments on both sides.
    q_valid = np.ones((2, 32), bool); q_valid[0, 24:] = False
    kv_valid = np.ones((2, 64), bool); kv_valid[1, 48:] = False
    qseg = jnp.asarray(q_valid.astype(np.int32))
    kseg = jnp.asarray(kv_valid.astype(np.int32))
    out = flash_attention(q, k, v, segment_ids=(qseg, kseg),
                          block_q=16, block_k=16)
    expected = _dense_seg(q, k, v, qseg, kseg)
    ok = q_valid
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_flash_cross_attention_grads(world):
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(41)
    q = jnp.asarray(rng.normal(size=(2, 32, 2, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))

    gf = jax.grad(
        lambda q, k, v: jnp.sum(jnp.sin(flash_attention(
            q, k, v, block_q=16, block_k=16))),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(jnp.sin(_dense(q, k, v))), argnums=(0, 1, 2)
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


# ---- grouped-query attention (GQA/MQA) ----


def _repeat_kv(t, group):
    b, s, h_kv, d = t.shape
    return jnp.repeat(t, group, axis=2)


@pytest.mark.parametrize("h_kv", [1, 2])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_dense(world, causal, h_kv):
    # k/v with fewer heads: each query head attends its group's kv head —
    # identical to dense attention over group-repeated k/v.
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(50)
    h = 4
    q = jnp.asarray(rng.normal(size=(2, 64, h, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 64, h_kv, 32)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 64, h_kv, 32)).astype(np.float32))
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    group = h // h_kv
    expected = _dense(q, _repeat_kv(k, group), _repeat_kv(v, group),
                      causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5
    )


def test_flash_gqa_grads_match_dense(world):
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(51)
    h, h_kv = 4, 2
    group = h // h_kv
    q = jnp.asarray(rng.normal(size=(2, 32, h, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 32, h_kv, 32)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 32, h_kv, 32)).astype(np.float32))

    gf = jax.grad(
        lambda q, k, v: jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16))),
        argnums=(0, 1, 2),
    )(q, k, v)

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense(
            q, _repeat_kv(k, group), _repeat_kv(v, group), causal=True)))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_gqa_rejects_indivisible_heads(world):
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(52)
    q = jnp.asarray(rng.normal(size=(2, 32, 4, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 32, 3, 32)).astype(np.float32))
    with pytest.raises(ValueError, match="multiple of the kv head"):
        flash_attention(q, k, k)


def test_flash_gqa_with_segments(world):
    # GQA × segment masking: the kv-head-major dkv grid decodes batch as
    # g0 // h_kv while q operands use the folded q-row map — this pins the
    # two decodings together (fwd + bwd).
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(53)
    h, h_kv = 4, 2
    group = h // h_kv
    q = jnp.asarray(rng.normal(size=(2, 64, h, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 64, h_kv, 32)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 64, h_kv, 32)).astype(np.float32))
    seg = _packed_segments()
    seg = seg.at[1, 56:].set(0)  # pad tail on row 1
    row_w = (seg != 0).astype(jnp.float32)[:, :, None, None]

    out = flash_attention(q, k, v, segment_ids=seg, block_q=16, block_k=16)
    expected = _dense_seg(q, _repeat_kv(k, group), _repeat_kv(v, group),
                          seg, seg)
    ok = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, segment_ids=seg, block_q=16, block_k=16)
        return jnp.sum(jnp.sin(o) * row_w)

    def loss_dense(q, k, v):
        o = _dense_seg(q, _repeat_kv(k, group), _repeat_kv(v, group), seg, seg)
        return jnp.sum(jnp.sin(o) * row_w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("seed", range(6))
def test_flash_property_sweep(world, seed):
    # Randomized config sweep: one dense-oracle comparison per seed across
    # the kernel's whole feature cross-product (GQA ratio x causal x
    # window x segments x block sizes x dtype) — breadth the individual
    # feature tests don't cover pairwise.
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(100 + seed)
    b = int(rng.integers(1, 3))
    sq = int(rng.choice([16, 32, 48]))
    h_kv = int(rng.choice([1, 2]))
    h = h_kv * int(rng.choice([1, 2, 4]))
    d = int(rng.choice([8, 16]))
    causal = bool(rng.integers(0, 2))
    window = int(rng.choice([4, 8])) if causal and rng.integers(0, 2) else None
    use_seg = bool(rng.integers(0, 2))
    block = int(rng.choice([8, 16]))
    dtype = jnp.bfloat16 if rng.integers(0, 2) else jnp.float32
    atol = 0.06 if dtype == jnp.bfloat16 else 3e-5
    drop = float(rng.choice([0.0, 0.3]))

    q = jnp.asarray(rng.normal(size=(b, sq, h, d)).astype(np.float32)).astype(dtype)
    k = jnp.asarray(rng.normal(size=(b, sq, h_kv, d)).astype(np.float32)).astype(dtype)
    v = jnp.asarray(rng.normal(size=(b, sq, h_kv, d)).astype(np.float32)).astype(dtype)

    seg = None
    valid = np.ones((b, sq), bool)
    if use_seg:
        seg_np = np.ones((b, sq), np.int32)
        for row in range(b):
            cut = int(rng.integers(1, sq))
            seg_np[row, cut:] = 2
            if rng.integers(0, 2):
                pad = int(rng.integers(1, sq // 4 + 1))
                seg_np[row, -pad:] = 0
        seg = jnp.asarray(seg_np)
        valid = seg_np != 0

    out = flash_attention(
        q, k, v, causal=causal, window=window, segment_ids=seg,
        block_q=block, block_k=block,
        dropout_rate=drop, dropout_seed=seed if drop else None,
    )

    # Dense oracle with identical semantics (f32 math; bf16 inputs upcast).
    kf = jnp.repeat(k, h // h_kv, axis=2).astype(jnp.float32)
    vf = jnp.repeat(v, h // h_kv, axis=2).astype(jnp.float32)
    q = q.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kf) / np.sqrt(d)
    mask = np.ones((b, 1, sq, sq), bool)
    if causal:
        pos = np.arange(sq)[:, None] >= np.arange(sq)[None, :]
        if window is not None:
            pos = pos & (np.arange(sq)[:, None] - np.arange(sq)[None, :] < window)
        mask = mask & pos[None, None]
    if seg is not None:
        sm = (np.asarray(seg)[:, :, None] == np.asarray(seg)[:, None, :]) & (
            np.asarray(seg)[:, None, :] != 0
        )
        mask = mask & sm[:, None]
    s = jnp.where(jnp.asarray(mask), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if drop:
        from fluxmpi_tpu.ops.flash_attention import _dropout_keep

        p = jnp.where(jnp.asarray(mask), p, 0.0)
        q_pos = jnp.broadcast_to(jnp.arange(sq)[:, None], (sq, sq))
        k_pos = jnp.broadcast_to(jnp.arange(sq)[None, :], (sq, sq))
        keep = jax.vmap(
            lambda bh: _dropout_keep(
                jnp.uint32(seed), bh, q_pos, k_pos, 1.0 - drop
            )
        )(jnp.arange(b * h, dtype=jnp.uint32)).reshape(b, h, sq, sq)
        p = jnp.where(keep, p / (1.0 - drop), 0.0)
    expected = jnp.einsum("bhqk,bkhd->bqhd", p, vf)

    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32)[valid],
        np.asarray(expected)[valid], atol=atol,
        err_msg=f"config: b={b} sq={sq} h={h} h_kv={h_kv} causal={causal} "
                f"window={window} seg={use_seg} block={block} dtype={dtype} "
                f"drop={drop}",
    )


# ---- in-kernel dropout (counter-based position hash) ----


def _kernel_dropout_oracle(q, k, v, seed, rate, causal=False):
    """Dense attention applying the EXACT mask the kernels generate: the
    same murmur-hash keep decision per (bh, q_pos, k_pos), post-softmax,
    1/keep_prob scaled."""
    from fluxmpi_tpu.ops.flash_attention import _dropout_keep

    b, s, h, d = q.shape
    kp = 1.0 - rate
    scale = 1.0 / np.sqrt(d)
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        pos = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        sc = jnp.where(pos[None, None], sc, -1e30)
    w = jax.nn.softmax(sc, axis=-1)
    q_pos = jnp.broadcast_to(jnp.arange(s)[:, None], (s, s))
    k_pos = jnp.broadcast_to(jnp.arange(s)[None, :], (s, s))
    keep = jax.vmap(
        lambda bh: _dropout_keep(jnp.uint32(seed), bh, q_pos, k_pos, kp)
    )(jnp.arange(b * h, dtype=jnp.uint32))
    keep = keep.reshape(b, h, s, s)
    w = jnp.where(keep, w / kp, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_dropout_matches_oracle(world, causal):
    # The in-kernel dropout is a deterministic function of (seed, head,
    # positions) — rebuild the identical mask at the JAX level and the
    # outputs must agree to float tolerance.
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=32, seed=60)
    out = flash_attention(
        q, k, v, causal=causal, dropout_rate=0.3, dropout_seed=1234,
        block_q=16, block_k=16,
    )
    expected = _kernel_dropout_oracle(q, k, v, 1234, 0.3, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5
    )


def test_flash_kernel_dropout_grads_match_oracle(world):
    # All three kernels regenerate the same mask: grads through the flash
    # path equal autodiff through the dense oracle holding the mask fixed.
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=32, seed=61)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=True, dropout_rate=0.25, dropout_seed=7,
            block_q=16, block_k=16,
        )))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(
            _kernel_dropout_oracle(q, k, v, 7, 0.25, causal=True)
        ))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_kernel_dropout_statistics(world):
    # Keep fraction ≈ keep_prob; mean output ≈ undropped output (unbiased);
    # different seeds give different masks, same seed reproduces.
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=64, seed=62)
    kwargs = dict(block_q=16, block_k=16, dropout_rate=0.5)
    a1 = np.asarray(flash_attention(q, k, v, dropout_seed=1, **kwargs))
    a1b = np.asarray(flash_attention(q, k, v, dropout_seed=1, **kwargs))
    a2 = np.asarray(flash_attention(q, k, v, dropout_seed=2, **kwargs))
    np.testing.assert_array_equal(a1, a1b)  # deterministic per seed
    assert np.abs(a1 - a2).max() > 1e-3  # seed changes the mask

    # Unbiasedness: averaging over many seeds approaches the clean output.
    clean = np.asarray(flash_attention(q, k, v, block_q=16, block_k=16))
    acc = np.zeros_like(clean)
    n = 24
    for s in range(n):
        acc += np.asarray(flash_attention(q, k, v, dropout_seed=100 + s,
                                          **kwargs))
    np.testing.assert_allclose(acc / n, clean, atol=0.25)


def test_flash_kernel_dropout_gqa_and_segments(world):
    # Dropout composes with GQA (dkv kernel rebuilds the query-head index
    # from its kv-head-major grid) and segment masking.
    from fluxmpi_tpu.ops import flash_attention

    rng = np.random.default_rng(63)
    b, s, h, h_kv, d = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, h_kv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, h_kv, d)).astype(np.float32))
    seg = np.ones((b, s), np.int32)
    seg[0, 20:] = 2
    seg[1, 24:] = 0
    seg = jnp.asarray(seg)

    def loss(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=True, segment_ids=seg,
            dropout_rate=0.2, dropout_seed=9, block_q=16, block_k=16,
        )))

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert all(np.all(np.isfinite(np.asarray(t))) for t in g)

    # Oracle: repeated-KV dense with segment mask + the kernel's hash mask
    # (keyed by the QUERY head index — exactly what the dkv kernel must
    # reconstruct from its kv-head-major grid).
    from fluxmpi_tpu.ops.flash_attention import _dropout_keep

    kf = jnp.repeat(k, 2, axis=2)
    vf = jnp.repeat(v, 2, axis=2)
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, kf) / np.sqrt(d)
    segm = (np.asarray(seg)[:, :, None] == np.asarray(seg)[:, None, :]) & (
        np.asarray(seg)[:, None, :] != 0
    )
    pos = np.arange(s)[:, None] >= np.arange(s)[None, :]
    mask = jnp.asarray(segm[:, None] & pos[None, None])
    sc = jnp.where(mask, sc, -1e30)
    w = jax.nn.softmax(sc, axis=-1)
    w = jnp.where(mask, w, 0.0)  # fully-masked rows: uniform → zero
    q_pos = jnp.broadcast_to(jnp.arange(s)[:, None], (s, s))
    k_pos = jnp.broadcast_to(jnp.arange(s)[None, :], (s, s))
    keep = jax.vmap(
        lambda bh: _dropout_keep(jnp.uint32(9), bh, q_pos, k_pos, 0.8)
    )(jnp.arange(b * h, dtype=jnp.uint32)).reshape(b, h, s, s)
    w = jnp.where(keep, w / 0.8, 0.0)
    expected = jnp.einsum("bhqk,bkhd->bqhd", w, vf)
    out = flash_attention(
        q, k, v, causal=True, segment_ids=seg,
        dropout_rate=0.2, dropout_seed=9, block_q=16, block_k=16,
    )
    valid = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[valid], np.asarray(expected)[valid], atol=2e-5
    )

    # Backward oracle too: autodiff through the same dense hash-masked
    # formulation — a wrong bh_q reconstruction in the dkv kernel would
    # pass the forward check and finite-grad check but fail here.
    def oracle_loss(q, k, v):
        kf = jnp.repeat(k, 2, axis=2)
        vf = jnp.repeat(v, 2, axis=2)
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, kf) / np.sqrt(d)
        sc = jnp.where(mask, sc, -1e30)
        w = jax.nn.softmax(sc, axis=-1)
        w = jnp.where(mask, w, 0.0)
        w = jnp.where(keep, w / 0.8, 0.0)
        o = jnp.einsum("bhqk,bkhd->bqhd", w, vf)
        vmask = jnp.asarray(valid)[:, :, None, None]
        return jnp.sum(jnp.where(vmask, jnp.sin(o), 0.0))

    def flash_loss(q, k, v):
        o = flash_attention(
            q, k, v, causal=True, segment_ids=seg,
            dropout_rate=0.2, dropout_seed=9, block_q=16, block_k=16,
        )
        vmask = jnp.asarray(valid)[:, :, None, None]
        return jnp.sum(jnp.where(vmask, jnp.sin(o), 0.0))

    gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_fn_kernel_dropout_path(world):
    # dropout_impl="kernel" on the adapter: stays on the flash path, trains
    # through a flax module, deterministic under a fixed rng.
    import flax.linen as nn

    from fluxmpi_tpu.ops import flash_attention_fn

    attn = nn.MultiHeadDotProductAttention(
        num_heads=4, qkv_features=32, dropout_rate=0.2,
        attention_fn=flash_attention_fn(causal=True, dropout_impl="kernel"),
    )
    x = jnp.asarray(
        np.random.default_rng(64).normal(size=(2, 16, 32)).astype(np.float32)
    )
    params = attn.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, x, deterministic=False,
    )

    def loss_fn(p, rng):
        y = attn.apply(p, x, x, deterministic=False, rngs={"dropout": rng})
        return jnp.mean(y**2)

    g1 = jax.jit(jax.grad(loss_fn))(params, jax.random.PRNGKey(2))
    g2 = jax.jit(jax.grad(loss_fn))(params, jax.random.PRNGKey(2))
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert all(np.all(np.isfinite(np.asarray(t)))
               for t in jax.tree_util.tree_leaves(g1))

    with pytest.raises(ValueError, match="dropout_impl"):
        flash_attention_fn(dropout_impl="bogus")


# ---- chunked fused unembed + cross-entropy (round-5 perf surface) ----


def _ce_oracle(h, W, targets):
    logits = (h.astype(jnp.float32) @ W.astype(jnp.float32).T)
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(logits, targets)


@pytest.mark.parametrize("chunk", [7, 16, 64, 100])
def test_unembed_ce_matches_dense(world, chunk):
    # chunk=7 and 100: the trailing vocab tile is zero-padded and masked
    # (64 % 7 != 0; 100 > 64 clamps to one full tile) — the tile size is
    # never silently shrunk.
    from fluxmpi_tpu.ops import unembed_cross_entropy

    rng = np.random.default_rng(0)
    b, s, d, v = 2, 8, 16, 64
    h = jnp.asarray(rng.normal(size=(b, s, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32) * 0.3)
    t = jnp.asarray(rng.integers(0, v, size=(b, s)).astype(np.int32))
    out = unembed_cross_entropy(h, W, t, chunk=chunk)
    expected = _ce_oracle(h.reshape(-1, d), W, t.reshape(-1)).reshape(b, s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5, rtol=1e-5)


def test_unembed_ce_grads_match_dense(world):
    from fluxmpi_tpu.ops import unembed_cross_entropy

    rng = np.random.default_rng(1)
    n, d, v = 24, 16, 48
    h = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32) * 0.3)
    t = jnp.asarray(rng.integers(0, v, size=(n,)).astype(np.int32))
    # Non-uniform per-token cotangents through a weighted mean.
    wgt = jnp.asarray(rng.uniform(0.5, 1.5, size=(n,)).astype(np.float32))

    def loss_fused(h, W):
        return jnp.sum(unembed_cross_entropy(h, W, t, chunk=16) * wgt)

    def loss_dense(h, W):
        return jnp.sum(_ce_oracle(h, W, t) * wgt)

    gf = jax.grad(loss_fused, argnums=(0, 1))(h, W)
    gd = jax.grad(loss_dense, argnums=(0, 1))(h, W)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gd[0]),
                               atol=5e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gd[1]),
                               atol=5e-5, rtol=1e-4)


def test_unembed_ce_bf16_operands(world):
    # bf16 h/W with f32 accumulation: close to the f32 oracle at bf16
    # tolerance, and gradients come back in the operand dtypes.
    from fluxmpi_tpu.ops import unembed_cross_entropy

    rng = np.random.default_rng(2)
    n, d, v = 16, 32, 64
    h32 = rng.normal(size=(n, d)).astype(np.float32)
    W32 = (rng.normal(size=(v, d)) * 0.3).astype(np.float32)
    t = jnp.asarray(rng.integers(0, v, size=(n,)).astype(np.int32))
    h = jnp.asarray(h32, jnp.bfloat16)
    W = jnp.asarray(W32, jnp.bfloat16)
    out = unembed_cross_entropy(h, W, t, chunk=16)
    assert out.dtype == jnp.float32
    expected = _ce_oracle(
        jnp.asarray(h32, jnp.bfloat16), jnp.asarray(W32, jnp.bfloat16), t
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=5e-2, rtol=5e-2)
    gh, gW = jax.grad(
        lambda h, W: jnp.mean(unembed_cross_entropy(h, W, t, chunk=16)),
        argnums=(0, 1),
    )(h, W)
    assert gh.dtype == jnp.bfloat16 and gW.dtype == jnp.bfloat16

    # Mixed: bf16 hidden states against an f32 table (the weight-tied
    # model layout) — the table's gradient comes back f32, un-quantized.
    gh, gW = jax.grad(
        lambda h, W: jnp.mean(unembed_cross_entropy(h, W, t, chunk=16)),
        argnums=(0, 1),
    )(h, jnp.asarray(W32))
    assert gh.dtype == jnp.bfloat16 and gW.dtype == jnp.float32


def test_unembed_ce_shape_errors(world):
    from fluxmpi_tpu.ops import unembed_cross_entropy

    h = jnp.ones((2, 4, 8))
    W = jnp.ones((16, 8))
    with pytest.raises(ValueError, match="targets shape"):
        unembed_cross_entropy(h, W, jnp.zeros((2, 3), jnp.int32))
    with pytest.raises(ValueError, match="hidden dim"):
        unembed_cross_entropy(h, jnp.ones((16, 9)), jnp.zeros((2, 4), jnp.int32))


def test_tp_unembed_ce_matches_dense(world):
    # Megatron-style vocab-sharded CE over a tp axis: exact global loss
    # and gradients from shard-local tables + three tiny collectives.
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.ops import tp_unembed_cross_entropy

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))
    rng = np.random.default_rng(3)
    b, s, d, v = 2, 8, 16, 64
    h = jnp.asarray(rng.normal(size=(b, s, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32) * 0.3)
    t = jnp.asarray(rng.integers(0, v, size=(b, s)).astype(np.int32))
    W_sharded = jax.device_put(W, NamedSharding(mesh, P("tp", None)))

    out = jax.jit(
        lambda h, W, t: tp_unembed_cross_entropy(
            h, W, t, mesh=mesh, axis_name="tp", chunk=4
        )
    )(h, W_sharded, t)
    expected = _ce_oracle(h.reshape(-1, d), W, t.reshape(-1)).reshape(b, s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5, rtol=1e-5)

    # Gradients: dh and the vocab-sharded dW both match the dense oracle.
    def loss_tp(h, W):
        return jnp.mean(tp_unembed_cross_entropy(
            h, W, t, mesh=mesh, axis_name="tp", chunk=4))

    def loss_dense(h, W):
        return jnp.mean(_ce_oracle(h.reshape(-1, d), W, t.reshape(-1)))

    gf = jax.jit(jax.grad(loss_tp, argnums=(0, 1)))(h, W_sharded)
    gd = jax.grad(loss_dense, argnums=(0, 1))(h, W)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gd[0]),
                               atol=5e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gd[1]),
                               atol=5e-5, rtol=1e-4)


def test_tp_unembed_ce_validation(world):
    from jax.sharding import Mesh

    from fluxmpi_tpu.ops import tp_unembed_cross_entropy

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))
    h = jnp.ones((2, 4, 8))
    with pytest.raises(ValueError, match="divide evenly"):
        tp_unembed_cross_entropy(
            h, jnp.ones((60, 8)), jnp.zeros((2, 4), jnp.int32),
            mesh=mesh, axis_name="tp",
        )
    with pytest.raises(ValueError, match="no axis"):
        tp_unembed_cross_entropy(
            h, jnp.ones((64, 8)), jnp.zeros((2, 4), jnp.int32),
            mesh=mesh, axis_name="model",
        )


def test_tp_unembed_ce_with_batch_sharding(world):
    # dp×tp mesh, token dim sharded over dp: every device works on its
    # own token slice; the table gradient psums over dp. Exact vs dense.
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.ops import tp_unembed_cross_entropy

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    rng = np.random.default_rng(4)
    n, d, v = 16, 8, 32
    h = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32) * 0.3)
    t = jnp.asarray(rng.integers(0, v, size=(n,)).astype(np.int32))
    hs = jax.device_put(h, NamedSharding(mesh, P("dp", None)))
    Ws = jax.device_put(W, NamedSharding(mesh, P("tp", None)))

    def loss_tp(h, W):
        return jnp.mean(tp_unembed_cross_entropy(
            h, W, t, mesh=mesh, axis_name="tp", batch_axis_name="dp",
            chunk=8))

    def loss_dense(h, W):
        return jnp.mean(_ce_oracle(h, W, t))

    lf = jax.jit(loss_tp)(hs, Ws)
    np.testing.assert_allclose(float(lf), float(loss_dense(h, W)), rtol=1e-5)
    gf = jax.jit(jax.grad(loss_tp, argnums=(0, 1)))(hs, Ws)
    gd = jax.grad(loss_dense, argnums=(0, 1))(h, W)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gd[0]),
                               atol=5e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gd[1]),
                               atol=5e-5, rtol=1e-4)

    with pytest.raises(ValueError, match="cannot include the tp axis"):
        tp_unembed_cross_entropy(
            h, W, t, mesh=mesh, axis_name="tp", batch_axis_name="tp")
    with pytest.raises(ValueError, match="chunk"):
        tp_unembed_cross_entropy(
            h, W, t, mesh=mesh, axis_name="tp", chunk=0)


def test_unembed_ce_composes_with_sequence_sharding(world):
    # SP composition: hidden states sharded over the sequence axis, the
    # fused CE computed per shard inside shard_map (table replicated) —
    # per-token losses equal the dense full-sequence oracle.
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.ops import unembed_cross_entropy

    from fluxmpi_tpu.parallel._compat import shard_map_unchecked

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("sp",))
    rng = np.random.default_rng(5)
    b, s, d, v = 2, 32, 8, 32
    h = jnp.asarray(rng.normal(size=(b, s, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32) * 0.3)
    t = jnp.asarray(rng.integers(0, v, size=(b, s)).astype(np.int32))
    hs = jax.device_put(h, NamedSharding(mesh, P(None, "sp", None)))
    ts = jax.device_put(t, NamedSharding(mesh, P(None, "sp")))

    mapped = shard_map_unchecked(
        lambda h, W, t: unembed_cross_entropy(h, W, t, chunk=8),
        mesh=mesh,
        in_specs=(P(None, "sp", None), P(), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(hs, W, ts)
    expected = _ce_oracle(h.reshape(-1, d), W, t.reshape(-1)).reshape(b, s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5, rtol=1e-5)


def test_unembed_ce_label_smoothing_matches_dense(world):
    # Smoothed target distribution (1-eps)*onehot + eps/V: values AND
    # both gradients vs optax's soft-label CE, including a padded tile.
    import optax

    from fluxmpi_tpu.ops import unembed_cross_entropy

    rng = np.random.default_rng(6)
    n, d, v, eps = 12, 8, 20, 0.1
    h = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32) * 0.3)
    t = jnp.asarray(rng.integers(0, v, size=(n,)).astype(np.int32))

    def dense(h, W):
        logits = h @ W.T
        soft = (1 - eps) * jax.nn.one_hot(t, v) + eps / v
        return optax.softmax_cross_entropy(logits, soft)

    def fused(h, W):
        return unembed_cross_entropy(h, W, t, chunk=8, label_smoothing=eps)

    np.testing.assert_allclose(np.asarray(fused(h, W)),
                               np.asarray(dense(h, W)),
                               atol=2e-5, rtol=1e-5)
    gf = jax.grad(lambda h, W: jnp.mean(fused(h, W)), argnums=(0, 1))(h, W)
    gd = jax.grad(lambda h, W: jnp.mean(dense(h, W)), argnums=(0, 1))(h, W)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-4)

    with pytest.raises(ValueError, match="label_smoothing"):
        unembed_cross_entropy(h, W, t, label_smoothing=1.0)


def test_tp_unembed_ce_label_smoothing_matches_dense(world):
    import optax
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.ops import tp_unembed_cross_entropy

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))
    rng = np.random.default_rng(7)
    n, d, v, eps = 8, 8, 32, 0.2
    h = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32) * 0.3)
    t = jnp.asarray(rng.integers(0, v, size=(n,)).astype(np.int32))
    Ws = jax.device_put(W, NamedSharding(mesh, P("tp", None)))

    def dense(h, W):
        soft = (1 - eps) * jax.nn.one_hot(t, v) + eps / v
        return optax.softmax_cross_entropy(h @ W.T, soft)

    def fused(h, W):
        return tp_unembed_cross_entropy(
            h, W, t, mesh=mesh, axis_name="tp", chunk=4,
            label_smoothing=eps)

    np.testing.assert_allclose(np.asarray(jax.jit(fused)(h, Ws)),
                               np.asarray(dense(h, W)),
                               atol=2e-5, rtol=1e-5)
    gf = jax.jit(jax.grad(lambda h, W: jnp.mean(fused(h, W)),
                          argnums=(0, 1)))(h, Ws)
    gd = jax.grad(lambda h, W: jnp.mean(dense(h, W)), argnums=(0, 1))(h, W)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-4)
