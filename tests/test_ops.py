"""Pallas flash attention vs dense oracle (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _dense(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(b=2, s=64, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        for _ in range(3)
    )


def test_flash_matches_dense(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv()
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v)), atol=2e-5
    )


def test_flash_causal(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=1)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v, causal=True)), atol=2e-5
    )


def test_flash_single_block(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=16, seed=2)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v)), atol=2e-5
    )


def test_flash_bf16(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(seed=3))
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    expected = _dense(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(expected), atol=0.05
    )


def test_flash_bad_blocks_rejected(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=48, seed=4)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=32, block_k=32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_matches_dense(world, causal):
    # The Pallas backward kernels (dq + dk/dv) against autodiff through the
    # dense oracle (VERDICT r1 next #3).
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=causal,
                                               block_q=32, block_k=32)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense(q, k, v, causal=causal)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_lse_and_its_gradient(world):
    # flash_attention_with_lse: the lse output matches dense logsumexp and
    # its cotangent is honored (the merge key ring attention relies on).
    from fluxmpi_tpu.ops import flash_attention_with_lse

    q, k, v = _qkv(seed=6)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    lse_dense = jax.scipy.special.logsumexp(s, axis=-1)  # [b, h, q]

    out, lse = flash_attention_with_lse(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(lse_dense), atol=1e-5
    )

    def loss_flash(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, block_q=32, block_k=32)
        return jnp.sum(jnp.cos(lse)) + jnp.sum(out**2)

    def loss_dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        return jnp.sum(jnp.cos(lse)) + jnp.sum(_dense(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_transformer_trains_through_flash_attention(world):
    # A TransformerLM whose attention is the Pallas kernel end-to-end: the
    # compiled DP train step runs and the flash model's gradients match the
    # dense-attention model's (same params, same batch).
    import optax

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import TransformerLM
    from fluxmpi_tpu.ops import flash_attention_fn
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    mesh = fm.global_mesh()
    kwargs = dict(vocab_size=64, max_len=32, num_layers=1, d_model=32,
                  num_heads=2, d_ff=64)
    flash_model = TransformerLM(
        attention_fn=flash_attention_fn(causal=True), **kwargs
    )
    dense_model = TransformerLM(**kwargs)

    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, 64, size=(16, 32)).astype(np.int32))
    params = dense_model.init(jax.random.PRNGKey(0), tokens[:2], train=False)

    def make_loss(model):
        def loss_fn(p, mstate, batch):
            logits = model.apply(p, batch, train=True)
            targets = jnp.roll(batch, -1, axis=1)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], targets[:, :-1]
            ).mean()
            return loss, mstate

        return loss_fn

    gf = jax.grad(lambda p: make_loss(flash_model)(p, None, tokens)[0])(params)
    gd = jax.grad(lambda p: make_loss(dense_model)(p, None, tokens)[0])(params)
    flat_f = jax.tree_util.tree_leaves(gf)
    flat_d = jax.tree_util.tree_leaves(gd)
    for a, b in zip(flat_f, flat_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    step = make_train_step(
        make_loss(flash_model), optax.adam(1e-3), mesh=mesh, style="auto"
    )
    state = replicate(TrainState.create(params, optax.adam(1e-3)), mesh)
    data = shard_batch(tokens, mesh)
    state, loss0 = step(state, data)
    state, loss1 = step(state, data)
    assert np.isfinite(float(loss0)) and float(loss1) < float(loss0)
