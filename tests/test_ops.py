"""Pallas flash attention vs dense oracle (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _dense(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(b=2, s=64, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        for _ in range(3)
    )


def test_flash_matches_dense(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv()
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v)), atol=2e-5
    )


def test_flash_causal(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(seed=1)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v, causal=True)), atol=2e-5
    )


def test_flash_single_block(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=16, seed=2)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense(q, k, v)), atol=2e-5
    )


def test_flash_bf16(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(seed=3))
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    expected = _dense(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(expected), atol=0.05
    )


def test_flash_bad_blocks_rejected(world):
    from fluxmpi_tpu.ops import flash_attention

    q, k, v = _qkv(s=48, seed=4)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=32, block_k=32)
