"""Run-health plane tests (PR 7): goodput/badput accounting, live MFU
from the shared FLOPs helpers, anomaly detection with warn/halt
policies + diagnostics bundles, the zero-cost-when-off contract in
train_loop, the monitor's heartbeat staleness + goodput fold, the
goodput.*/anomaly.* schema namespaces, and the goodput_report CLI."""

import json
import math
import os
import subprocess
import sys
import threading
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import fluxmpi_tpu as fm
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import (
    AnomalyDetector,
    GoodputTracker,
    JSONLSink,
    MetricsRegistry,
    anomaly,
    goodput,
)
from fluxmpi_tpu.telemetry import schema as tschema
from fluxmpi_tpu.utils import flops as flops_util

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPORT = os.path.join(_REPO, "scripts", "goodput_report.py")
_CHECKER = os.path.join(_REPO, "scripts", "check_metrics_schema.py")


def _fake_clock(*ticks):
    """Deterministic clock: yields the given stamps in order (the
    watchdog's injectable-clock test discipline — no real sleeps)."""
    it = iter(ticks)
    return lambda: next(it)


@pytest.fixture()
def plane_off():
    """Guarantee the run-health plane is fully off around a test and
    restore whatever tracker/detector was installed before."""
    prev_tracker = goodput.set_goodput_tracker(GoodputTracker(enabled=False))
    prev_detector = anomaly.set_anomaly_detector(None)
    try:
        yield
    finally:
        goodput.set_goodput_tracker(prev_tracker)
        anomaly.set_anomaly_detector(prev_detector)


# ---------------------------------------------------------------------------
# Shared FLOPs/MFU helpers (promoted out of bench.py)
# ---------------------------------------------------------------------------


def test_flops_helpers_match_bench_delegates():
    import bench

    # One implementation: the bench module delegates to utils.flops.
    assert bench._chip_peak_flops("TPU v5 lite") == flops_util.chip_peak_flops(
        "TPU v5 lite"
    )
    assert bench._mfu(1e12, 98.5, 1, "TPU v5 lite") == flops_util.mfu(
        1e12, 98.5, 1, "TPU v5 lite"
    )


def test_mfu_raw_returns_impossible_values_for_caller_decision():
    # The shared helper reports the raw number; discarding is the
    # caller's policy (bench records mfu_discarded, see test_bench).
    raw = flops_util.mfu(1e12, 1000.0, 1, "TPU v5 lite")
    assert raw is not None and raw > 1.0
    assert flops_util.mfu(None, 10.0, 1, "TPU v5 lite") is None
    assert flops_util.mfu(1e12, 10.0, 1, "cpu") is None
    # peak= override bypasses the device-kind table (live-tracker hook).
    assert flops_util.mfu(1e12, 98.5, 1, peak=197e12) == 0.5
    assert flops_util.mfu(1e12, 98.5, 1, None) is None


def test_bench_record_carries_mfu_discarded_flag():
    rec = {
        "metric": "m",
        "value": 1.0,
        "unit": "x",
        "vs_baseline": 1.0,
        "mfu_discarded": True,
    }
    assert tschema.validate_bench_record(rec) == []
    rec["mfu_discarded"] = "yes"  # wrong type: drift fails the check
    assert any("mfu_discarded" in e for e in tschema.validate_bench_record(rec))


# ---------------------------------------------------------------------------
# GoodputTracker
# ---------------------------------------------------------------------------


def test_tracker_buckets_sum_to_wall_with_idle_remainder():
    clock = _fake_clock(0.0, 0.0, 1.0, 2.0, 3.0, 10.0)
    t = GoodputTracker(clock=clock)
    t.start_run()  # 0.0
    with t.segment("step"):  # 0.0 -> 1.0
        pass
    with t.segment("checkpoint_save"):  # 2.0 -> 3.0
        pass
    rep = t.report()  # wall = 10.0
    assert rep["wall_seconds"] == 10.0
    assert rep["buckets"]["step"] == 1.0
    assert rep["buckets"]["checkpoint_save"] == 1.0
    assert rep["buckets"]["host_idle"] == pytest.approx(8.0)
    assert sum(rep["buckets"].values()) == pytest.approx(rep["wall_seconds"])
    assert rep["goodput_fraction"] == pytest.approx(0.1)


def test_tracker_nested_segments_count_once():
    # resume wrapping checkpoint_restore must not double-book the wall:
    # only the outermost segment records.
    clock = _fake_clock(0.0, 0.0, 1.0, 2.0, 5.0, 5.0)
    t = GoodputTracker(clock=clock)
    t.start_run()
    with t.segment("resume"):  # 0.0 -> 5.0
        with t.segment("checkpoint_restore"):  # 1.0 -> 2.0, swallowed
            pass
    rep = t.report()
    assert rep["buckets"]["resume"] == 5.0
    assert "checkpoint_restore" not in rep["buckets"]


def test_tracker_ignores_other_threads():
    # A background async-checkpoint thread overlaps the driver's wall
    # clock — booking it would sum buckets past the wall.
    t = GoodputTracker()
    t.start_run()
    t.add("step", 1.0)

    def background():
        with t.segment("checkpoint_save"):
            pass
        t.add("checkpoint_save", 99.0)

    th = threading.Thread(target=background)
    th.start()
    th.join()
    assert t.bucket_seconds("checkpoint_save") == 0.0
    assert t.bucket_seconds("step") == 1.0


def test_tracker_disabled_reads_no_clock():
    def boom():
        raise AssertionError("clock read on the disabled path")

    t = GoodputTracker(clock=boom, enabled=False)
    assert t.segment("step") is t.segment("other")  # shared no-op
    with t.segment("step"):
        pass
    t.add("step", 1.0)
    assert t.bucket_seconds("step") == 0.0


def test_tracker_mfu_uses_shared_helper():
    # Live MFU == bench.py's for the same FLOPs/rate — both go through
    # utils.flops.mfu, so the numbers are identical by construction.
    clock = _fake_clock(0.0, 0.0, 2.0, 10.0)
    t = GoodputTracker(clock=clock, peak_flops_per_chip=197e12, n_chips=8)
    t.start_run()
    with t.segment("step"):  # 2.0s productive
        pass
    t.note_updates(50)
    t.set_flops_per_update(1e12)
    rep = t.report()  # wall = 10.0
    assert rep["mfu_productive"] == flops_util.mfu(
        1e12, 50 / 2.0, 8, "TPU v5 lite"
    )
    assert rep["mfu"] == flops_util.mfu(1e12, 50 / 10.0, 8, "TPU v5 lite")
    assert rep["mfu"] < rep["mfu_productive"]  # badput drags wall MFU


def test_tracker_record_flushes_schema_valid_goodput_metrics():
    reg = MetricsRegistry()
    clock = _fake_clock(0.0, 0.0, 1.0, 4.0, 4.0)
    t = GoodputTracker(registry=reg, clock=clock)
    t.start_run()
    with t.segment("step"):
        pass
    t.note_updates(10)
    t.record()
    assert reg.gauge("goodput.bucket_seconds", bucket="step").value == 1.0
    assert reg.gauge("goodput.fraction").value == pytest.approx(0.25)
    assert reg.gauge("goodput.updates").value == 10.0
    record = reg.flush()
    assert tschema.validate_record(record) == []
    # Disabled registry: record() is a no-op (zero-cost contract).
    reg.enabled = False
    try:
        before = reg.gauge("goodput.updates").value
        t.note_updates(5)
        t.record()
        assert reg.gauge("goodput.updates").value == before
    finally:
        reg.enabled = True


def test_goodput_configure_env_and_shutdown(monkeypatch, plane_off):
    tr = goodput.get_goodput_tracker()
    monkeypatch.delenv("FLUXMPI_TPU_GOODPUT", raising=False)
    assert goodput.configure() is tr and not tr.enabled
    monkeypatch.setenv("FLUXMPI_TPU_GOODPUT", "1")
    assert goodput.configure().enabled
    monkeypatch.setenv("FLUXMPI_TPU_GOODPUT", "0")
    assert not goodput.configure().enabled
    custom = GoodputTracker(enabled=False)
    assert goodput.configure(custom) is custom and custom.enabled
    assert goodput.get_goodput_tracker() is custom
    with pytest.raises(ValueError, match="goodput spec"):
        goodput.configure("bogus")
    custom.add("step", 1.0)
    goodput.shutdown()
    assert not custom.enabled
    assert custom.bucket_seconds("step") == 0.0  # run state dropped


# ---------------------------------------------------------------------------
# AnomalyDetector
# ---------------------------------------------------------------------------


def test_anomaly_nan_halts_and_writes_bundle(tmp_path):
    reg = MetricsRegistry()
    det = AnomalyDetector(registry=reg, dump_dir=str(tmp_path))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        events = det.observe(loss=float("nan"), step=7)
    assert [e["rule"] for e in events] == ["nan_loss"]
    assert events[0]["action"] == "halt"
    assert events[0]["step"] == 7
    assert reg.counter("anomaly.triggered", rule="nan_loss").value == 1.0
    bundle_path = det.last_dump_path
    assert bundle_path is not None and os.path.exists(bundle_path)
    with open(bundle_path) as f:
        text = f.read()
    # STRICT JSON: the NaN trigger value must serialize as null +
    # value_repr, never as the bare `NaN` token Perfetto/jq reject.
    def _no_constants(name):
        raise AssertionError(f"non-strict JSON constant {name!r} in bundle")

    bundle = json.loads(text, parse_constant=_no_constants)
    assert bundle["anomaly"]["value"] is None
    assert bundle["anomaly"]["value_repr"] == "nan"
    # The bundle IS a watchdog_dump record (thread stacks, flight tail,
    # registry flush) + the anomaly section — one validator covers both.
    assert tschema.validate_watchdog_dump(bundle) == []
    assert bundle["anomaly"]["rule"] == "nan_loss"
    assert bundle["reason"] == "anomaly:nan_loss"


def test_anomaly_nan_grad_and_policy_override(tmp_path):
    det = AnomalyDetector(
        policies={"nan_grad": "warn", "nan_loss": "off"},
        dump_dir=str(tmp_path),
        dump=False,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        events = det.observe(loss=float("inf"), grad_norm=float("nan"))
    # nan_loss is off; nan_grad downgraded to warn.
    assert [(e["rule"], e["action"]) for e in events] == [("nan_grad", "warn")]
    with pytest.raises(ValueError, match="unknown anomaly rule"):
        AnomalyDetector(policies={"bogus": "warn"})
    with pytest.raises(ValueError, match="policy"):
        AnomalyDetector(policies={"nan_loss": "explode"})


def test_anomaly_loss_spike_zscore_after_warmup():
    det = AnomalyDetector(
        warmup=5, spike_zscore=4.0, ewma_alpha=0.5, dump=False
    )
    rng = np.random.default_rng(0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in range(20):  # stable baseline, no triggers
            assert det.observe(loss=1.0 + 0.01 * rng.standard_normal()) == []
        events = det.observe(loss=50.0, step=21)
    assert [e["rule"] for e in events] == ["loss_spike"]
    assert events[0]["value"] > 4.0  # the z-score rides the event


def test_anomaly_spike_quiet_during_warmup():
    det = AnomalyDetector(warmup=5, dump=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert det.observe(loss=1.0) == []
        assert det.observe(loss=1000.0) == []  # within warmup: armed later


def test_anomaly_step_time_regression_and_data_stall():
    det = AnomalyDetector(
        warmup=3, step_time_factor=2.0, data_stall_factor=1.0, dump=False
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in range(5):
            assert det.observe(step_seconds=0.1) == []
        events = det.observe(step_seconds=0.5, step=6)
        assert [e["rule"] for e in events] == ["step_time_regression"]
        # The loader wait is PART of the wall step time, so the rule
        # judges it against the compute remainder: 0.06s wait vs 0.04s
        # compute = input-bound, 0.02s wait vs 0.08s compute = healthy.
        events = det.observe(
            step_seconds=0.1, fetch_seconds=0.06, step=7
        )
        assert "data_stall" in [e["rule"] for e in events]
        events = det.observe(
            step_seconds=0.1, fetch_seconds=0.02, step=8
        )
        assert "data_stall" not in [e["rule"] for e in events]
        # All-wait interval (compute remainder 0) triggers too.
        events = det.observe(
            step_seconds=0.1, fetch_seconds=0.1, step=9
        )
    assert "data_stall" in [e["rule"] for e in events]
    assert all(math.isfinite(e["value"]) for e in events)


def test_anomaly_instant_rides_trace_and_validates(tmp_path):
    from fluxmpi_tpu.telemetry import tracing

    tracer = tracing.Tracer(enabled=True)
    prev = tracing.set_tracer(tracer)
    try:
        det = AnomalyDetector(dump_dir=str(tmp_path), dump=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            det.observe(loss=float("nan"), step=3)
        export = tracer.export()
        assert tschema.validate_trace_export(export) == []
        instants = [
            ev
            for ev in export["traceEvents"]
            if ev.get("name") == "anomaly.nan_loss"
        ]
        assert len(instants) == 1
        assert instants[0]["ph"] in ("i", "I")
        assert instants[0]["args"]["step"] == 3
        assert instants[0]["args"]["rule"] == "nan_loss"
    finally:
        tracing.set_tracer(prev)


def test_anomaly_event_schema_rejects_wrong_phase():
    ev = {"name": "anomaly.nan_loss", "ph": "X", "ts": 1.0, "dur": 2.0,
          "pid": 1, "tid": 1, "args": {"step": 3, "rule": "nan_loss"}}
    assert any("instant" in e for e in tschema.validate_trace_event(ev))
    ev = {"name": "anomaly.nan_loss", "ph": "i", "ts": 1.0, "pid": 1,
          "tid": 1, "args": {"rule": "nan_loss"}}
    assert any("args.step" in e for e in tschema.validate_trace_event(ev))


def test_goodput_namespace_is_closed():
    m = {"name": "goodput.bogus", "type": "gauge", "labels": {}, "value": 1.0}
    assert any(
        "framework-owned" in e for e in tschema.validate_metric(m)
    )
    m = {"name": "anomaly.triggered", "type": "counter",
         "labels": {"rule": "nan_loss"}, "value": 1.0}
    assert tschema.validate_metric(m) == []


def test_anomaly_configure_forms(plane_off):
    assert anomaly.configure() is None  # env unset: plane stays off
    det = anomaly.configure(True)
    assert det is not None and anomaly.get_anomaly_detector() is det
    assert anomaly.configure(True) is det  # idempotent replay keeps state
    warn_det = anomaly.configure("warn")
    assert all(p in ("warn", "off") for p in warn_det.policies.values())
    # configure(True) after "warn" must deliver True's documented
    # defaults (NaN halts) — not silently keep the observe-only one.
    halting = anomaly.configure(True)
    assert halting is not warn_det
    assert halting.policies["nan_loss"] == "halt"
    assert anomaly.configure(False) is None
    assert anomaly.get_anomaly_detector() is None
    with pytest.raises(ValueError, match="anomaly spec"):
        anomaly.configure("bogus")
    anomaly.configure(True)
    anomaly.shutdown()
    assert anomaly.get_anomaly_detector() is None


# ---------------------------------------------------------------------------
# TrainingMonitor: heartbeat staleness + goodput fold
# ---------------------------------------------------------------------------


def test_monitor_heartbeat_age_with_injected_clock(world):
    from fluxmpi_tpu.telemetry import TrainingMonitor

    reg = MetricsRegistry()
    mon = TrainingMonitor(reg, interval=1, cross_host=False,
                          clock=_fake_clock(100.0, 107.5, 109.0))
    mon.collect()
    assert reg.gauge("monitor.heartbeat_age_seconds").value == 0.0
    assert reg.gauge("monitor.heartbeat_unix").value == 100.0
    mon.collect()
    assert reg.gauge("monitor.heartbeat_age_seconds").value == 7.5
    mon.collect()
    assert reg.gauge("monitor.heartbeat_age_seconds").value == 1.5


def test_monitor_folds_goodput_fraction(world, plane_off):
    from fluxmpi_tpu.telemetry import TrainingMonitor

    tracker = GoodputTracker(clock=_fake_clock(0.0, 0.0, 3.0, 4.0))
    tracker.start_run()
    with tracker.segment("step"):  # 3s productive of 4s wall
        pass
    goodput.set_goodput_tracker(tracker)
    reg = MetricsRegistry()
    mon = TrainingMonitor(reg, interval=1, cross_host=False)
    summary = mon.observe_step(0.01)  # interval=1: collects immediately
    assert reg.gauge("monitor.goodput_fraction_mean").value == pytest.approx(
        0.75
    )
    assert summary["goodput_fraction_min"] == pytest.approx(0.75)
    # Plane off: no goodput gauges ride the collect.
    goodput.set_goodput_tracker(GoodputTracker(enabled=False))
    reg2 = MetricsRegistry()
    mon2 = TrainingMonitor(reg2, interval=1, cross_host=False)
    summary2 = mon2.observe_step(0.01)
    assert "goodput_fraction_min" not in summary2
    assert all(
        m["name"] != "monitor.goodput_fraction_mean"
        for m in reg2.snapshot()
    )


# ---------------------------------------------------------------------------
# train_loop wiring
# ---------------------------------------------------------------------------


def _mlp_pieces(n=256, nan_from=None):
    from fluxmpi_tpu.models import MLP

    model = MLP(features=(16, 16, 1))

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    y = (x**2).astype(np.float32)
    if nan_from is not None:
        y[nan_from:] = np.nan
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1)))
    )
    return loss_fn, opt, params, ArrayDataset((x, y))


def test_train_loop_fully_off_plane_costs_nothing(world, plane_off):
    """The PR 4 monkeypatch-explode contract extended to the run-health
    plane: with goodput disabled and no detector installed, the hot loop
    performs no tracker clock reads, no segment/bucket work, and no
    anomaly observes."""
    tracker = goodput.get_goodput_tracker()
    assert not tracker.enabled
    assert anomaly.get_anomaly_detector() is None

    def boom(*a, **k):
        raise AssertionError("run-health plane touched on the off path")

    tracker._clock = boom
    tracker.segment = boom
    tracker.add = boom
    tracker.note_updates = boom
    tracker.record = boom
    orig_observe = AnomalyDetector.observe
    AnomalyDetector.observe = boom
    try:
        loss_fn, opt, params, ds = _mlp_pieces()
        loader = DistributedDataLoader(ds, 64, mesh=world)
        step = make_train_step(loss_fn, opt, mesh=world)
        state, summary = train_loop(
            step, replicate(TrainState.create(params, opt, None), world),
            loader, epochs=1,
        )
    finally:
        AnomalyDetector.observe = orig_observe
    assert summary["updates"] == 4
    assert summary["anomaly"] is None
    assert "goodput" not in summary


def test_train_loop_goodput_accounting(world, plane_off):
    tracker = GoodputTracker()
    goodput.set_goodput_tracker(tracker)
    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    reg = MetricsRegistry()
    state, summary = train_loop(
        step, replicate(TrainState.create(params, opt, None), world),
        loader, epochs=2, flush_every=3, metrics=reg,
    )
    rep = summary["goodput"]
    assert rep["updates"] == summary["updates"] == 8
    # Compile, productive dispatch, and loader waits were all observed.
    assert rep["buckets"]["compile"] > 0
    assert rep["buckets"]["step"] > 0
    assert rep["buckets"]["data_stall"] > 0
    # Measured buckets can never exceed the wall; with the computed
    # host_idle remainder they sum to it exactly.
    measured = sum(
        v for k, v in rep["buckets"].items() if k != "host_idle"
    )
    assert measured <= rep["wall_seconds"] + 1e-6
    assert sum(rep["buckets"].values()) == pytest.approx(
        rep["wall_seconds"], rel=1e-6
    )
    assert 0.0 <= rep["goodput_fraction"] <= 1.0
    # goodput.* gauges landed in the loop's registry at flush time.
    assert reg.gauge("goodput.updates").value == 8.0
    assert (
        reg.gauge("goodput.bucket_seconds", bucket="step").value
        == pytest.approx(rep["buckets"]["step"], rel=1e-3)
    )
    # FLOPs came from the shared cost-model helper -> live MFU inputs
    # are the ones bench.py would use for this step function.
    assert rep["flops_per_update"] is None or rep["flops_per_update"] > 0


def test_train_loop_resets_tracker_window_per_run(world, plane_off):
    # A second train_loop in the same process gets a FRESH goodput
    # window: no inherited buckets, no inter-run gap booked as
    # host_idle, no MFU computed from the first run's FLOPs.
    tracker = GoodputTracker()
    goodput.set_goodput_tracker(tracker)
    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    _, s1 = train_loop(
        step, replicate(TrainState.create(params, opt, None), world),
        loader, epochs=1,
    )
    tracker.add("checkpoint_save", 1e6)  # inter-run noise to shed
    _, s2 = train_loop(
        step, replicate(TrainState.create(params, opt, None), world),
        loader, epochs=1,
    )
    assert s2["goodput"]["updates"] == 4  # not cumulative 8
    assert s2["goodput"]["buckets"].get("checkpoint_save", 0.0) == 0.0
    assert s2["goodput"]["wall_seconds"] < s1["goodput"]["wall_seconds"] + 60


def test_train_loop_live_mfu_matches_bench_formula(world, plane_off):
    # Acceptance: live MFU == bench.py's for the same step function.
    # Both sides read FLOPs from utils.flops.cost_analysis_flops and
    # feed utils.flops.mfu; with the same measured rate the numbers are
    # identical. (CPU has no peak-FLOPs entry, so the tracker gets the
    # v5e peak injected — the formula, not the table, is under test.)
    tracker = GoodputTracker(peak_flops_per_chip=197e12, n_chips=8)
    goodput.set_goodput_tracker(tracker)
    loss_fn, opt, params, ds = _mlp_pieces()
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state, summary = train_loop(
        step, replicate(TrainState.create(params, opt, None), world),
        loader, epochs=1,
    )
    rep = summary["goodput"]
    if rep["flops_per_update"] is None:
        pytest.skip("XLA cost analysis unavailable on this backend")
    step_s = rep["buckets"]["step"]
    bench_style = flops_util.mfu(
        rep["flops_per_update"],
        rep["updates"] / step_s,
        8,
        "TPU v5 lite",  # same 197e12 peak the tracker was given
    )
    assert rep["mfu_productive"] == bench_style


def test_train_loop_nan_halts_cleanly_with_bundle(world, tmp_path, plane_off):
    """End-to-end acceptance: goodput+anomaly on, a checkpoint save, a
    synthetic NaN — the loop halts deterministically at the flush that
    sees it, the JSONL passes the schema checker, the bundle lands on
    disk, and the buckets account for the wall."""
    from fluxmpi_tpu.telemetry import tracing

    jsonl = str(tmp_path / "run.jsonl")
    reg = MetricsRegistry(sinks=[JSONLSink(jsonl)])
    goodput.set_goodput_tracker(GoodputTracker())
    anomaly.set_anomaly_detector(
        AnomalyDetector(dump_dir=str(tmp_path), registry=reg)
    )
    tracer = tracing.Tracer(enabled=True)
    prev_tracer = tracing.set_tracer(tracer)
    from fluxmpi_tpu.utils import CheckpointManager

    # Batches 1-3 finite, batch 4 NaN (shuffle off): flush_every=2 sees
    # a finite interval at update 2 (where save_every=2 banks a good
    # checkpoint) and the NaN at update 4 -> halt, no further saves.
    loss_fn, opt, params, ds = _mlp_pieces(nan_from=192)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, summary = train_loop(
                step, replicate(TrainState.create(params, opt, None), world),
                loader, epochs=4, flush_every=2, metrics=reg,
                checkpoint=mgr, save_every=2,
            )
    finally:
        tracing.set_tracer(prev_tracer)
    # Deterministic halt at the first NaN flush — not after 4 epochs.
    assert summary["anomaly"] == "nan_loss"
    assert summary["updates"] == 4
    assert math.isnan(summary["loss"])
    # The save at the halting boundary was skipped: only the known-good
    # step-2 checkpoint exists.
    assert mgr.all_steps() == [2]
    # Diagnostics bundle on disk, schema-valid, naming the rule.
    bundle_file = tmp_path / "fluxmpi_anomaly.0.json"
    assert bundle_file.exists()
    bundle = json.loads(bundle_file.read_text())
    assert tschema.validate_watchdog_dump(bundle) == []
    assert bundle["anomaly"]["rule"] == "nan_loss"
    # anomaly.triggered rode the metrics plane.
    assert reg.counter("anomaly.triggered", rule="nan_loss").value >= 1.0
    # ...and the anomaly.nan_loss instant rode the trace timeline, at
    # the halting update count, in a schema-valid export.
    export = tracer.export()
    assert tschema.validate_trace_export(export) == []
    instants = [
        ev for ev in export["traceEvents"]
        if ev.get("name") == "anomaly.nan_loss"
    ]
    assert len(instants) == 1
    assert instants[0]["args"]["step"] == 4
    # Goodput accounting: checkpoint save time attributed, buckets sum
    # to wall within tolerance.
    rep = summary["goodput"]
    assert rep["buckets"]["checkpoint_save"] > 0
    assert sum(rep["buckets"].values()) == pytest.approx(
        rep["wall_seconds"], rel=1e-6
    )
    # The emitted JSONL (goodput.* + anomaly.* + train.*) validates.
    reg.close()
    proc = subprocess.run(
        [sys.executable, _CHECKER, jsonl], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    # And the report CLI reads it back with matching totals.
    proc = subprocess.run(
        [sys.executable, _REPORT, jsonl, "--json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    agg = json.loads(proc.stdout)
    assert agg["host_count"] == 1
    assert agg["updates"] == 4
    assert agg["buckets"]["checkpoint_save"] > 0


def test_train_loop_warn_policy_does_not_halt(world, plane_off):
    anomaly.set_anomaly_detector(
        AnomalyDetector(
            policies={"nan_loss": "warn", "nan_grad": "warn"}, dump=False
        )
    )
    loss_fn, opt, params, ds = _mlp_pieces(nan_from=0)  # NaN from step 1
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        state, summary = train_loop(
            step, replicate(TrainState.create(params, opt, None), world),
            loader, epochs=2, flush_every=2,
        )
    assert summary["anomaly"] is None  # warned, never halted
    assert summary["updates"] == 8  # full budget ran
    det = anomaly.get_anomaly_detector()
    assert any(e["rule"] == "nan_loss" for e in det.triggered)


def test_train_loop_preemption_with_halt_skips_emergency_save(
    world, tmp_path, plane_off
):
    # A preemption coinciding with a halt-policy anomaly must NOT bank
    # the diverged state as the newest restorable checkpoint — the
    # emergency save is gated like the periodic ones.
    from fluxmpi_tpu.utils import CheckpointManager

    anomaly.set_anomaly_detector(AnomalyDetector(dump=False))
    loss_fn, opt, params, ds = _mlp_pieces(nan_from=0)  # NaN from step 1
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    fm.request_preemption()
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, summary = train_loop(
                step, replicate(TrainState.create(params, opt, None), world),
                loader, epochs=2, flush_every=1, checkpoint=mgr,
            )
    finally:
        fm.clear_preemption()
    assert summary["preempted"] is True
    assert summary["anomaly"] == "nan_loss"
    assert mgr.all_steps() == []  # no NaN checkpoint banked


# ---------------------------------------------------------------------------
# goodput_report.py CLI
# ---------------------------------------------------------------------------


def test_goodput_report_smoke(tmp_path):
    jsonl = tmp_path / "hosts.jsonl"
    reg = MetricsRegistry(sinks=[JSONLSink(str(jsonl))])
    clock = _fake_clock(0.0, 0.0, 8.0, 9.0, 10.0, 10.0)
    t = GoodputTracker(registry=reg, clock=clock)
    t.start_run()
    with t.segment("step"):  # 8s
        pass
    with t.segment("checkpoint_save"):  # 1s
        pass
    t.note_updates(100)
    t.record()
    reg.flush()
    reg.close(flush=False)
    proc = subprocess.run(
        [sys.executable, _REPORT, str(jsonl)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "goodput 80.0%" in proc.stdout
    assert "checkpoint_save" in proc.stdout
    assert "updates 100" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _REPORT, str(jsonl), "--json"],
        capture_output=True, text=True,
    )
    agg = json.loads(proc.stdout)
    assert agg["wall_seconds"] == pytest.approx(10.0)
    assert agg["goodput_fraction"] == pytest.approx(0.8)
    assert agg["buckets"]["step"] == pytest.approx(8.0)


def test_goodput_report_tolerates_torn_line(tmp_path):
    # A host killed mid-write leaves a truncated final line — the very
    # post-mortem this report serves must not refuse the fleet's data.
    jsonl = tmp_path / "torn.jsonl"
    reg = MetricsRegistry(sinks=[JSONLSink(str(jsonl))])
    t = GoodputTracker(registry=reg, clock=_fake_clock(0.0, 0.0, 4.0, 5.0))
    t.start_run()
    with t.segment("step"):
        pass
    t.record()
    reg.flush()
    reg.close(flush=False)
    with open(jsonl, "a", encoding="utf-8") as f:
        f.write('{"schema": "fluxmpi_tpu.telemetry/v1", "time_un')  # torn
    proc = subprocess.run(
        [sys.executable, _REPORT, str(jsonl)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "skipping" in proc.stderr
    assert "goodput 80.0%" in proc.stdout


def test_goodput_report_exit_codes(tmp_path):
    # No goodput metrics anywhere -> exit 1 with a pointed message.
    plain = tmp_path / "plain.jsonl"
    reg = MetricsRegistry(sinks=[JSONLSink(str(plain))])
    reg.counter("train.steps").inc()
    reg.flush()
    reg.close(flush=False)
    proc = subprocess.run(
        [sys.executable, _REPORT, str(plain)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "FLUXMPI_TPU_GOODPUT" in proc.stderr
    # Unreadable input -> exit 2.
    proc = subprocess.run(
        [sys.executable, _REPORT, str(tmp_path / "missing.jsonl")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2


def test_goodput_report_watch_rerenders_midrun(tmp_path):
    """--watch N: the report becomes a mid-run monitor — it re-renders
    from the growing bank on an interval (same parse path), and a bank
    that has no data YET is a waiting state, not an error."""
    jsonl = tmp_path / "live.jsonl"
    reg = MetricsRegistry(sinks=[JSONLSink(str(jsonl))])
    t = GoodputTracker(registry=reg, clock=_fake_clock(0.0, 0.0, 8.0, 10.0))
    t.start_run()
    with t.segment("step"):  # 8s of a 10s wall
        pass
    t.record()
    reg.flush()
    reg.close(flush=False)
    proc = subprocess.run(
        [sys.executable, _REPORT, str(jsonl),
         "--watch", "0.05", "--watch-count", "2"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.count("run: 1 host stream(s)") == 2  # re-rendered
    assert "goodput 80.0%" in proc.stdout
    # Missing file: the run may simply not have flushed yet — waiting,
    # exit 0 (one-shot mode keeps its hard exit 2 for the post-mortem).
    proc = subprocess.run(
        [sys.executable, _REPORT, str(tmp_path / "nonexistent.jsonl"),
         "--watch", "0.05", "--watch-count", "1"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "waiting" in proc.stderr
