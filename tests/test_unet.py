"""UNet/DDPM family: forward shapes, schedule invariants, a DP train step
on the 8-device mesh, the compiled DDIM sampler, and the attention_fn
hook parity with the zoo's transformers."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp


def _tiny_unet(**kw):
    from fluxmpi_tpu.models import UNet

    cfg = dict(out_channels=3, base_channels=8, channel_mults=(1, 2),
               blocks_per_stage=1, attn_resolutions=(8,), num_heads=2,
               groups=4)
    cfg.update(kw)
    return UNet(**cfg)


def test_unet_forward_shape(world):
    model = _tiny_unet()
    x = jnp.ones((2, 16, 16, 3))
    t = jnp.array([0, 9], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, t)
    out = model.apply(params, x, t)
    assert out.shape == x.shape
    assert out.dtype == jnp.float32
    # Zero-init output head: the untrained model predicts exactly zero.
    np.testing.assert_array_equal(np.asarray(out), 0.0)


def test_unet_rejects_non_nhwc(world):
    model = _tiny_unet()
    with pytest.raises(ValueError, match="NHWC"):
        model.init(jax.random.PRNGKey(0), jnp.ones((4, 16, 16)),
                   jnp.zeros((4,), jnp.int32))


def test_timestep_embedding_distinguishes_large_t(world):
    from fluxmpi_tpu.models.unet import timestep_embedding

    t = jnp.array([998, 999], jnp.int32)
    emb = timestep_embedding(t, 64)
    assert emb.dtype == jnp.float32
    assert not np.allclose(np.asarray(emb[0]), np.asarray(emb[1]))


def test_cosine_schedule_invariants(world):
    from fluxmpi_tpu.models import cosine_beta_schedule
    from fluxmpi_tpu.models.unet import _alpha_bars

    betas = cosine_beta_schedule(100)
    assert betas.shape == (100,)
    # 0.999 in f32 is 0.99900001...: compare with an epsilon.
    assert float(betas.min()) >= 0.0
    assert float(betas.max()) <= 0.999 + 1e-6
    ab = _alpha_bars(betas)
    # alpha_bar decreases monotonically from ~1 toward 0.
    assert float(ab[0]) > 0.99
    assert float(ab[-1]) < 0.01
    assert np.all(np.diff(np.asarray(ab)) <= 0)


def test_ddpm_loss_at_zero_head_is_unit_mse(world):
    """Zero-init head predicts eps=0, so the loss starts at E[eps^2] = 1."""
    from fluxmpi_tpu.models import cosine_beta_schedule, ddpm_loss

    model = _tiny_unet()
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 16, 3))
    betas = cosine_beta_schedule(50)
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.zeros((4,), jnp.int32))
    loss = ddpm_loss(model, params, x, jax.random.PRNGKey(2), betas)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - 1.0) < 0.15


def test_ddpm_loss_v_prediction(world):
    """v-target at the zero-init head: E[v^2] = ab·E[eps^2] +
    (1-ab)·E[x0^2] = 1 exactly for unit-normal data — same unit starting
    loss as eps mode, but via both schedule ends."""
    from fluxmpi_tpu.models import cosine_beta_schedule, ddpm_loss

    model = _tiny_unet()
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 16, 3))
    betas = cosine_beta_schedule(50)
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.zeros((4,), jnp.int32))
    loss = ddpm_loss(model, params, x, jax.random.PRNGKey(2), betas,
                     pred_type="v")
    assert np.isfinite(float(loss))
    assert abs(float(loss) - 1.0) < 0.2

    with pytest.raises(ValueError, match="pred_type"):
        ddpm_loss(model, params, x, jax.random.PRNGKey(2), betas,
                  pred_type="x0")


def test_ddim_sample_v_mode_closed_form(world):
    """Zero-output model in v mode: eps_hat = sqrt(1-a_t)·x, so the
    eta=0 unclipped update is x·(sqrt(a_p·a_t) + sqrt((1-a_p)(1-a_t))) —
    a scalar recurrence the test replays exactly."""
    from fluxmpi_tpu.models import cosine_beta_schedule, ddim_sample
    from fluxmpi_tpu.models.unet import _alpha_bars

    model = _tiny_unet()
    betas = cosine_beta_schedule(20)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((2, 16, 16, 3)),
                        jnp.zeros((2,), jnp.int32))
    out = ddim_sample(model, params, jax.random.PRNGKey(3),
                      shape=(2, 16, 16, 3), betas=betas, num_steps=5,
                      clip_x0=None, pred_type="v")

    ab = np.asarray(_alpha_bars(betas))
    ts = np.asarray(
        jnp.linspace(19, 0, 5).round().astype(jnp.int32))
    ab_t = ab[ts]
    ab_prev = np.concatenate([ab[ts[1:]], [1.0]])
    scale = 1.0
    for a_t, a_p in zip(ab_t, ab_prev):
        scale *= np.sqrt(a_p * a_t) + np.sqrt((1 - a_p) * (1 - a_t))
    x_rng = jax.random.split(jax.random.PRNGKey(3))[1]
    x0 = np.asarray(jax.random.normal(x_rng, (2, 16, 16, 3), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), x0 * scale,
                               rtol=2e-4, atol=2e-4)

    with pytest.raises(ValueError, match="pred_type"):
        ddim_sample(model, params, jax.random.PRNGKey(3),
                    shape=(2, 16, 16, 3), betas=betas, num_steps=5,
                    pred_type="score")


def test_unet_bf16_forward(world):
    """bf16 interior threads through (GroupNorm stats and head stay f32)."""
    model = _tiny_unet(dtype=jnp.bfloat16)
    x = jnp.ones((1, 16, 16, 3), jnp.bfloat16)
    t = jnp.zeros((1,), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, t)
    out = model.apply(params, x, t)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_unet_dp_train_step_descends(world):
    """The family trains under make_train_step on the 8-device mesh, with
    the per-step rng folded in data-parallel-deterministically."""
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import cosine_beta_schedule, ddpm_loss
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    mesh = fm.init()
    model = _tiny_unet()
    betas = cosine_beta_schedule(50)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x[:2],
                        jnp.zeros((2,), jnp.int32))

    def loss_fn(p, ms, batch):
        imgs, step_idx = batch
        rng = jax.random.fold_in(jax.random.PRNGKey(7), step_idx[0])
        return ddpm_loss(model, p, imgs, rng, betas), ms

    tx = optax.adam(2e-3)
    step = make_train_step(loss_fn, tx, mesh=mesh, style="auto")
    state = replicate(TrainState.create(params, tx, None), mesh)

    losses = []
    for i in range(8):
        batch = shard_batch(
            (x, jnp.full((8,), i, jnp.int32)), mesh)
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_unet_train_step_with_remat_dots(world):
    """The conv family composes with the checkpoint_dots remat policy
    under make_train_step (the TPU HBM-pressure configuration)."""
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import cosine_beta_schedule, ddpm_loss
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    mesh = fm.init()
    model = _tiny_unet()
    betas = cosine_beta_schedule(20)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x[:2],
                        jnp.zeros((2,), jnp.int32))

    def loss_fn(p, ms, batch):
        imgs, idx = batch
        rng = jax.random.fold_in(jax.random.PRNGKey(7), idx[0])
        return ddpm_loss(model, p, imgs, rng, betas), ms

    tx = optax.adam(1e-3)
    step = make_train_step(loss_fn, tx, mesh=mesh, remat="dots")
    state = replicate(TrainState.create(params, tx, None), mesh)
    batch = shard_batch((x, jnp.zeros((8,), jnp.int32)), mesh)
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))


def test_ddim_sample_shapes_and_finiteness(world):
    from fluxmpi_tpu.models import cosine_beta_schedule, ddim_sample

    model = _tiny_unet()
    betas = cosine_beta_schedule(20)
    x = jnp.ones((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.zeros((2,), jnp.int32))
    out = jax.jit(
        lambda p, r: ddim_sample(model, p, r, shape=(2, 16, 16, 3),
                                 betas=betas, num_steps=5, clip_x0=None)
    )(params, jax.random.PRNGKey(3))
    assert out.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(out)).all()
    # Zero-eps model + eta=0 + no clip: x_{t-1} = sqrt(ab_prev/ab_t) x_t,
    # telescoping to x / sqrt(ab_T) — the sampler output is a deterministic
    # rescale of its own initial noise. Verifies the trajectory arithmetic
    # end to end.
    from fluxmpi_tpu.models.unet import _alpha_bars

    x_rng = jax.random.split(jax.random.PRNGKey(3))[1]
    x0 = jax.random.normal(x_rng, (2, 16, 16, 3), jnp.float32)
    ab = _alpha_bars(betas)
    expected = x0 / jnp.sqrt(ab[-1])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ddim_sample_clip_bounds_output(world):
    """With the default clip, the final sample is within the data range
    (the last step returns ~x0, which is clamped)."""
    from fluxmpi_tpu.models import cosine_beta_schedule, ddim_sample

    model = _tiny_unet()
    betas = cosine_beta_schedule(20)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 16, 16, 3)),
                        jnp.zeros((1,), jnp.int32))
    out = ddim_sample(model, params, jax.random.PRNGKey(5),
                      shape=(2, 16, 16, 3), betas=betas, num_steps=10)
    assert np.isfinite(np.asarray(out)).all()
    assert float(jnp.abs(out).max()) <= 1.0 + 1e-5


def test_ddim_sample_validates_num_steps(world):
    from fluxmpi_tpu.models import cosine_beta_schedule, ddim_sample

    model = _tiny_unet()
    betas = cosine_beta_schedule(10)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 16, 16, 3)),
                        jnp.zeros((1,), jnp.int32))
    with pytest.raises(ValueError, match="num_steps"):
        ddim_sample(model, params, jax.random.PRNGKey(0),
                    shape=(1, 16, 16, 3), betas=betas, num_steps=11)


def test_unet_attention_fn_hook(world):
    """A custom attention_fn must be called and change nothing when it is
    the dense reference implementation."""
    import flax.linen as nn

    calls = []

    def spy_attention(q, k, v, **kw):
        calls.append(q.shape)
        return nn.dot_product_attention(q, k, v, **kw)

    model_a = _tiny_unet()
    model_b = _tiny_unet(attention_fn=spy_attention)
    x = jnp.ones((2, 16, 16, 3))
    t = jnp.zeros((2,), jnp.int32)
    params = model_a.init(jax.random.PRNGKey(0), x, t)
    out_a = model_a.apply(params, x, t)
    out_b = model_b.apply(params, x, t)
    assert calls, "attention_fn hook was never invoked"
    # 8x8 attn resolution -> 64 tokens.
    assert all(s[1] == 64 for s in calls)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-5, atol=1e-5)
