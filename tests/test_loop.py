"""Tests for the pipelined steady-state driver (parallel/loop.py):
budget semantics, the scan-stacking adapter, flush-boundary telemetry
(records per interval, not per step), and pipeline-window edge cases."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import fluxmpi_tpu as fm
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import MetricsRegistry


def _mlp_pieces(world, features=(16, 16, 1), n=256):
    from fluxmpi_tpu.models import MLP

    model = MLP(features=features)

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    # Host copies: the compiled steps donate state buffers, and replicate()
    # may alias device-resident inputs — a second TrainState built from
    # consumed params would hit deleted arrays.
    params = jax.device_get(model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1))))
    return loss_fn, opt, params, ArrayDataset((x, x**2))


def _fresh_state(params, opt, world):
    return replicate(TrainState.create(params, opt, None), world)


def test_train_loop_epochs_budget(world):
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, epochs=2
    )
    assert summary["epochs"] == 2
    assert summary["updates"] == 2 * len(loader)
    assert int(np.asarray(state.step)) == summary["updates"]
    assert np.isfinite(summary["loss"])
    assert summary["examples"] == 2 * len(loader) * 64


def test_train_loop_steps_budget_spans_epochs(world):
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)  # 4 batches/epoch
    step = make_train_step(loss_fn, opt, mesh=world)
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, steps=10
    )
    # 10 updates need 3 passes over a 4-batch loader (re-iterated).
    assert summary["updates"] == 10
    assert int(np.asarray(state.step)) == 10


def test_train_loop_scan_adapter_feeds_multi_step(world):
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)  # 4 batches/epoch
    step = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    assert step.scan_steps == 2  # factory tags the width
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, epochs=1
    )
    # scan_batches stacks pairs: 4 batches -> 2 dispatches -> 4 updates.
    assert summary["updates"] == 4
    assert int(np.asarray(state.step)) == 4


def test_train_loop_counts_epoch_completed_on_exact_steps_budget(world):
    # steps landing exactly on the last dispatch of a sized source IS a
    # full pass — summary["epochs"] must say so (checkpoint/resume logic
    # keys off it).
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)  # 4 batches/epoch
    step = make_train_step(loss_fn, opt, mesh=world)
    _, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, steps=4
    )
    assert summary["updates"] == 4
    assert summary["epochs"] == 1
    _, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, steps=3
    )
    assert summary["epochs"] == 0  # partial pass stays partial


def test_train_loop_inherits_step_metrics_spec(world):
    # metrics=None honors the spec the step was built with — unwrapping
    # the per-step instrumentation must not silently drop its registry.
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    reg = MetricsRegistry()
    step = make_train_step(loss_fn, opt, mesh=world, metrics=reg)
    _, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, epochs=1
    )
    assert reg.counter("train.steps").value == summary["updates"]
    # metrics=False forces recording off even for an instrumented step.
    reg2 = MetricsRegistry()
    step2 = make_train_step(loss_fn, opt, mesh=world, metrics=reg2)
    train_loop(
        step2, _fresh_state(params, opt, world), loader, epochs=1,
        metrics=False,
    )
    assert reg2.counter("train.steps").value == 0


def test_train_loop_scan_steps_rounds_up_to_dispatch(world):
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, steps=3
    )
    # Whole dispatches only: 3 updates round up to 2 dispatches = 4.
    assert summary["updates"] == 4


def test_train_loop_matches_sequential_loss(world):
    # Pipelining must not change the math: same batches, same update
    # count -> same final loss as the plain sequential loop.
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)

    state_seq = _fresh_state(params, opt, world)
    for _ in range(2):
        for batch in loader:
            state_seq, loss_seq = step(state_seq, batch)
    loader2 = DistributedDataLoader(ds, 64, mesh=world)
    step2 = make_train_step(loss_fn, opt, mesh=world)
    state_pipe, summary = train_loop(
        step2, _fresh_state(params, opt, world), loader2, epochs=2,
        in_flight=3,
    )
    np.testing.assert_allclose(
        np.asarray(loss_seq), summary["loss"], rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(state_seq.step), np.asarray(state_pipe.step)
    )


def test_train_loop_flush_boundary_metrics(world):
    # Telemetry lands per flush interval, not per step: histogram count
    # equals the number of flushes, while counters carry the full totals.
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)  # 4 batches/epoch
    step = make_train_step(loss_fn, opt, mesh=world)
    reg = MetricsRegistry()
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, epochs=3,
        flush_every=5, metrics=reg,
    )
    assert summary["updates"] == 12
    assert reg.counter("train.steps").value == 12
    assert reg.counter("train.examples").value == 12 * 64
    hist = reg.histogram("train.step_seconds")
    # 12 updates at flush_every=5: flushes after 5, 10, and the final
    # drain -> 3 interval observations.
    assert hist.count == 3
    assert reg.gauge("train.loss").value == pytest.approx(summary["loss"])


def test_train_loop_instrumented_step_reports_grad_norm(world):
    # An instrumented step is unwrapped for the hot loop (no per-step
    # blocking) but its in-jit grad norm still reaches the registry.
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    reg = MetricsRegistry()
    step = make_train_step(loss_fn, opt, mesh=world, metrics=True)
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, epochs=1,
        metrics=reg,
    )
    assert reg.gauge("train.grad_norm").value > 0.0
    assert reg.counter("train.steps").value == summary["updates"]


def test_train_loop_metrics_hook_receives_interval_records(world):
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    records = []
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, epochs=2,
        flush_every=3, metrics=records.append,
    )
    assert sum(r["steps"] for r in records) == summary["updates"]
    assert all(r["step_seconds"] > 0 for r in records)


def test_train_loop_zero_in_flight_and_validation(world):
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state, summary = train_loop(
        step, _fresh_state(params, opt, world), loader, steps=2, in_flight=0
    )
    assert summary["updates"] == 2
    with pytest.raises(ValueError, match="in_flight"):
        train_loop(step, state, loader, in_flight=-1)
    with pytest.raises(ValueError, match="flush_every"):
        train_loop(step, state, loader, flush_every=0)
    with pytest.raises(ValueError, match="steps"):
        train_loop(step, state, loader, steps=0)


def test_train_loop_exhausted_generator_raises(world):
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    one_pass = iter(list(loader))  # a generator: single pass only
    with pytest.raises(ValueError, match="ran dry"):
        train_loop(step, _fresh_state(params, opt, world), one_pass,
                   steps=100)


def test_train_loop_watchdog_progress_at_flush(world):
    from fluxmpi_tpu.telemetry import watchdog

    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    before = watchdog._progress_value()
    train_loop(step, _fresh_state(params, opt, world), loader, epochs=1)
    # Loader batches tick per fetch; the loop ticks per flush — progress
    # must have advanced by at least the update count.
    assert watchdog._progress_value() >= before + 4
