"""SPMD worker script for the multi-process integration test.

The analogue of one MPI rank in the reference's self-spawning test harness
(reference: test/runtests.jl:11-16 runs each test file under
``mpiexec -n N``): the parent test spawns N copies of this script, each
joins the jax.distributed world over a localhost coordinator with one CPU
device, and the script exercises the true cross-process paths — rank
identity, host collectives, synchronize root-wins, eager fused gradient
allreduce, data-shard lockstep — asserting the same oracles as the
reference's inner test files. Exit code 0 == pass.
"""

import os
import sys

coordinator = sys.argv[1]
num_processes = int(sys.argv[2])
process_id = int(sys.argv[3])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import fluxmpi_tpu as fm

mesh = fm.init(
    distributed=True,
    coordinator_address=coordinator,
    num_processes=num_processes,
    process_id=process_id,
    verbose=True,
)

# --- identity (reference: test/test_common.jl) ---
assert fm.process_count() == num_processes
assert fm.local_rank() == process_id
assert 0 <= fm.local_rank() < fm.total_workers()
fm.fluxmpi_println(f"hello from rank {fm.local_rank()}")

# --- host collectives across processes ---
summed = fm.host_allreduce(np.full((3,), float(process_id + 1)))
expected = sum(range(1, num_processes + 1))
np.testing.assert_allclose(summed, expected)

rooted = fm.host_bcast(np.full((2,), float(process_id)), root=0)
np.testing.assert_allclose(rooted, 0.0)

# --- synchronize: rank-divergent tree, root wins
#     (reference: test/test_synchronize.jl:5-25) ---
import jax.numpy as jnp

tree = {
    "w": jnp.full((4, 2), float(process_id)),
    "scalar": float(process_id),
    "noop": "keep",
}
synced = fm.synchronize(tree)
np.testing.assert_allclose(np.asarray(synced["w"]), 0.0)
assert synced["scalar"] == 0.0
assert synced["noop"] == "keep"

# --- eager fused gradient allreduce (reference: test/test_optimizer.jl:29-36) ---
grads = {"a": np.full((5,), 1.0, np.float32), "b": {"c": np.full((2, 2), 2.0, np.float32)}}
reduced = fm.allreduce_gradients(grads)
np.testing.assert_allclose(reduced["a"], num_processes * 1.0)
np.testing.assert_allclose(reduced["b"]["c"], num_processes * 2.0)

# --- data sharding lockstep (reference: test/test_data.jl) ---
data = list(range(10))
ddc = fm.DistributedDataContainer(data)
local_sum = np.asarray(float(sum(ddc)))
total = fm.host_allreduce(local_sum)
np.testing.assert_allclose(total, sum(data))

loader = fm.DistributedDataLoader(ddc, global_batch_size=num_processes * 2)
count = 0
for batch in loader:
    assert batch.shape[0] == num_processes * 2  # global batch
    count += 1
assert count == len(loader)

fm.barrier("final")
print(f"WORKER_{process_id}_OK")
