"""SPMD worker script for the multi-process integration test.

The analogue of one MPI rank in the reference's self-spawning test harness
(reference: test/runtests.jl:11-16 runs each test file under
``mpiexec -n N``): the parent test spawns N copies of this script, each
joins the jax.distributed world over a localhost coordinator with one CPU
device, and the script exercises the true cross-process paths — rank
identity, host collectives, synchronize root-wins, eager fused gradient
allreduce, data-shard lockstep — asserting the same oracles as the
reference's inner test files. Exit code 0 == pass.
"""

import os
import sys

coordinator = sys.argv[1]
num_processes = int(sys.argv[2])
process_id = int(sys.argv[3])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import fluxmpi_tpu as fm

mesh = fm.init(
    distributed=True,
    coordinator_address=coordinator,
    num_processes=num_processes,
    process_id=process_id,
    verbose=True,
)

# --- identity (reference: test/test_common.jl) ---
assert fm.process_count() == num_processes
assert fm.local_rank() == process_id
assert 0 <= fm.local_rank() < fm.total_workers()
fm.fluxmpi_println(f"hello from rank {fm.local_rank()}")

# --- host collectives across processes ---
summed = fm.host_allreduce(np.full((3,), float(process_id + 1)))
expected = sum(range(1, num_processes + 1))
np.testing.assert_allclose(summed, expected)

rooted = fm.host_bcast(np.full((2,), float(process_id)), root=0)
np.testing.assert_allclose(rooted, 0.0)

# --- synchronize: rank-divergent tree, root wins
#     (reference: test/test_synchronize.jl:5-25) ---
import jax.numpy as jnp

tree = {
    "w": jnp.full((4, 2), float(process_id)),
    "scalar": float(process_id),
    "noop": "keep",
}
synced = fm.synchronize(tree)
np.testing.assert_allclose(np.asarray(synced["w"]), 0.0)
assert synced["scalar"] == 0.0
assert synced["noop"] == "keep"

# --- eager fused gradient allreduce (reference: test/test_optimizer.jl:29-36) ---
grads = {"a": np.full((5,), 1.0, np.float32), "b": {"c": np.full((2, 2), 2.0, np.float32)}}
reduced = fm.allreduce_gradients(grads)
np.testing.assert_allclose(reduced["a"], num_processes * 1.0)
np.testing.assert_allclose(reduced["b"]["c"], num_processes * 2.0)

# --- data sharding lockstep (reference: test/test_data.jl) ---
# Scale with the world: a fixed 10-sample set leaves ranks >= 5 shard-less
# at 8 processes (the loud by-design IndexError).
data = list(range(max(10, num_processes * 2)))
ddc = fm.DistributedDataContainer(data)
local_sum = np.asarray(float(sum(ddc)))
total = fm.host_allreduce(local_sum)
np.testing.assert_allclose(total, sum(data))

loader = fm.DistributedDataLoader(ddc, global_batch_size=num_processes * 2)
count = 0
for batch in loader:
    assert batch.shape[0] == num_processes * 2  # global batch
    count += 1
assert count == len(loader)

# --- println serialization ordering (reference: src/common.jl:86-92) ---
# Each rank prints to a shared append-only file at its barrier-gated turn;
# the parent asserts the lines land in strict rank order.
ordering_path = os.environ.get("FLUXMPI_TEST_ORDER_FILE")
if ordering_path:
    with open(ordering_path, "a", buffering=1) as f:
        fm.fluxmpi_println(f"ORDER rank={process_id}", file=f)

# --- compiled train step over the process-spanning mesh ---
import optax

from fluxmpi_tpu.models import MLP
from fluxmpi_tpu.parallel import TrainState, make_train_step
from fluxmpi_tpu.parallel.train import replicate

model = MLP(features=(16, 16, 1))
rng = np.random.default_rng(0)  # same seed → same data on every process
xs_all = rng.uniform(-2, 2, size=(64, 1)).astype(np.float32)
ys_all = xs_all**2

params = fm.synchronize(model.init(jax.random.PRNGKey(process_id), xs_all[:2]))


def loss_fn(p, mstate, batch):
    bx, by = batch
    return jnp.mean((model.apply(p, bx) - by) ** 2), mstate


optimizer = optax.adam(1e-2)
step = make_train_step(loss_fn, optimizer, mesh=mesh, style="auto")
state = replicate(TrainState.create(params, optimizer), mesh)

train_data = fm.ArrayDataset((xs_all, ys_all))
train_container = fm.DistributedDataContainer(train_data)
train_loader = fm.DistributedDataLoader(
    train_container, global_batch_size=num_processes * 8, mesh=mesh
)
losses = []
for _ in range(3):
    for batch in train_loader:
        state, loss = step(state, batch)
        losses.append(float(loss))
assert all(np.isfinite(losses)), losses
assert losses[-1] < losses[0], losses
# The replicated loss and updated params must agree bit-for-bit across
# processes (max == min over the world).
spread = fm.host_allreduce(np.asarray(losses[-1]), op="max") - fm.host_allreduce(
    np.asarray(losses[-1]), op="min"
)
assert float(spread) == 0.0, spread
w0 = np.asarray(jax.device_get(jax.tree_util.tree_leaves(state.params)[0]))
w_spread = fm.host_allreduce(w0, op="max") - fm.host_allreduce(w0, op="min")
np.testing.assert_allclose(w_spread, 0.0)

# --- checkpoint save/restore across processes ---
ckpt_dir = os.environ.get("FLUXMPI_TEST_CKPT_DIR")
if ckpt_dir:
    from fluxmpi_tpu.utils import restore_checkpoint, save_checkpoint

    # Replicated state: lead process writes, restore root-broadcasts.
    rep_path = os.path.join(ckpt_dir, "replicated")
    save_checkpoint(rep_path, state)
    zeros = jax.tree_util.tree_map(
        lambda x: jnp.zeros_like(x) if isinstance(x, jax.Array) else x, state
    )
    zeros = replicate(zeros, mesh)
    restored = restore_checkpoint(rep_path, zeros)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(restored.params)[0])),
        w0,
    )

    # Sharded (FSDP) state: every process writes/reads only its own shards.
    from fluxmpi_tpu.parallel import fsdp_rule, shard_tree

    big_params = {
        "w": jnp.arange(16 * num_processes, dtype=jnp.float32).reshape(
            num_processes * 4, 4
        )
    }
    sharded_state, shardings = shard_tree(
        TrainState.create(big_params, optimizer),
        mesh,
        fsdp_rule(mesh, min_size=8),
    )
    assert not sharded_state.params["w"].is_fully_replicated
    shard_path = os.path.join(ckpt_dir, "sharded")
    save_checkpoint(shard_path, sharded_state)
    fresh = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(jnp.zeros_like(x), s)
        if isinstance(x, jax.Array)
        else x,
        sharded_state,
        shardings,
    )
    restored_sharded = restore_checkpoint(shard_path, fresh)
    assert (
        restored_sharded.params["w"].sharding
        == sharded_state.params["w"].sharding
    )
    local_ok = np.allclose(
        np.asarray(
            [np.asarray(s.data) for s in restored_sharded.params["w"].addressable_shards]
        ),
        np.asarray(
            [np.asarray(s.data) for s in sharded_state.params["w"].addressable_shards]
        ),
    )
    assert bool(fm.host_allreduce(np.asarray(float(local_ok)), op="min")), (
        "sharded restore mismatch on some process"
    )

    # --- CheckpointManager: mid-epoch resume across processes (VERDICT r2
    # next #7). Train 2 steps checkpointing each, "crash", resume from the
    # latest step on every process, train 1 more — the resumed world must
    # agree bitwise with the uninterrupted one. ---
    from fluxmpi_tpu.utils import CheckpointManager

    mgr = CheckpointManager(
        os.path.join(ckpt_dir, "manager"), max_to_keep=2, async_save=True
    )
    mstate = state
    for i in range(2):
        mstate, _ = step(mstate, batch)
        mgr.save(i + 1, mstate)
    mgr.wait_until_finished()
    assert mgr.latest_step() == 2
    # Template BEFORE the continuation step: the compiled step donates its
    # input state, so mstate's buffers die inside it.
    fresh_like = replicate(
        jax.tree_util.tree_map(
            lambda x: jnp.zeros_like(x) if isinstance(x, jax.Array) else x,
            jax.device_get(mstate),
        ),
        mesh,
    )
    cont_state, cont_loss = step(mstate, batch)  # uninterrupted continuation
    last, resumed = mgr.restore(fresh_like)
    assert last == 2
    resumed_state, resumed_loss = step(resumed, batch)
    assert float(resumed_loss) == float(cont_loss), (
        resumed_loss, cont_loss,
    )
    rspread = fm.host_allreduce(
        np.asarray(float(resumed_loss)), op="max"
    ) - fm.host_allreduce(np.asarray(float(resumed_loss)), op="min")
    assert float(rspread) == 0.0, rspread
    mgr.close()

# --- ragged-shard loader lockstep ---
# 14 samples over N procs: ceil partition gives the last rank a smaller
# (or empty-padded) shard; every process must still yield the same number
# of global batches or assembly deadlocks (this very loop hanging would
# fail the parent's timeout).
ragged_n = num_processes * 4 - 2
ragged = fm.DistributedDataContainer(
    fm.ArrayDataset((np.arange(ragged_n, dtype=np.float32).reshape(-1, 1),))
)
ragged_loader = fm.DistributedDataLoader(
    ragged, global_batch_size=num_processes, mesh=mesh
)
n_batches = sum(1 for _ in ragged_loader)
assert n_batches == len(ragged_loader)
counts_equal = (
    float(fm.host_allreduce(np.asarray(float(n_batches)), op="max"))
    == float(fm.host_allreduce(np.asarray(float(n_batches)), op="min"))
)
assert counts_equal

# --- sequence parallelism across the process-spanning mesh ---
# Ring attention with K/V blocks rotating over REAL cross-process
# ppermute hops (the multi-host long-context path), GQA (h_kv=1) and a
# packed+padded batch via segment ids; every process holds the same
# global inputs (shared seed) and checks its own output shards against
# the dense oracle.
from _oracles import dense_seg_attention  # single-source segment oracle

from fluxmpi_tpu.parallel.ring import make_ring_attention


def _dense_seg_gqa(q, k, v, qseg, kseg, causal):
    h = q.shape[2]
    k = np.repeat(k, h // k.shape[2], axis=2)
    v = np.repeat(v, h // v.shape[2], axis=2)
    return np.asarray(dense_seg_attention(q, k, v, qseg, kseg, causal=causal))


seq_sp = num_processes * 4
rng_sp = np.random.default_rng(11)  # shared seed: same globals everywhere
q_sp = rng_sp.normal(size=(2, seq_sp, 2, 8)).astype(np.float32)
k_sp = rng_sp.normal(size=(2, seq_sp, 1, 8)).astype(np.float32)
v_sp = rng_sp.normal(size=(2, seq_sp, 1, 8)).astype(np.float32)
seg_sp = np.ones((2, seq_sp), np.int32)
seg_sp[0, seq_sp // 2:] = 2          # packed row
seg_sp[1, -max(seq_sp // 4, 1):] = 0  # padded row

ring_fn = make_ring_attention(mesh, axis_name="dp", causal=True)
out_sp = ring_fn(q_sp, k_sp, v_sp, segment_ids=seg_sp)
expected_sp = _dense_seg_gqa(q_sp, k_sp, v_sp, seg_sp, seg_sp, causal=True)
valid_sp = seg_sp != 0
local_ok = True
for shard in out_sp.addressable_shards:
    got = np.asarray(shard.data)
    want = expected_sp[shard.index]
    ok_rows = valid_sp[shard.index[:2]]
    local_ok &= bool(
        np.allclose(got[ok_rows], want[ok_rows], atol=2e-4)
    )
assert bool(
    fm.host_allreduce(np.asarray(float(local_ok)), op="min")
), "cross-process ring attention mismatch on some process"

# Ulysses: heads resharded by a REAL cross-process all_to_all.
from fluxmpi_tpu.parallel import make_ulysses_attention

h_u = num_processes
q_u = rng_sp.normal(size=(2, seq_sp, h_u, 8)).astype(np.float32)
uly_fn = make_ulysses_attention(mesh, axis_name="dp", causal=True)
out_u = uly_fn(q_u, q_u, q_u)
ones_u = np.ones((2, seq_sp), np.int32)  # all-valid → pure causal mask
expected_u = _dense_seg_gqa(q_u, q_u, q_u, ones_u, ones_u, causal=True)
local_ok_u = all(
    np.allclose(
        np.asarray(s.data), expected_u[s.index], atol=2e-4
    )
    for s in out_u.addressable_shards
)
assert bool(
    fm.host_allreduce(np.asarray(float(local_ok_u)), op="min")
), "cross-process ulysses attention mismatch on some process"

fm.barrier("final")
print(f"WORKER_{process_id}_OK")
