"""fluxlint tests: per-rule positive/negative fixtures, the guard-
deletion and rank-wrap mutation checks (the acceptance contract: these
edits to real hot-path files MUST fail the lint), suppression + baseline
round trips, JSON output, CLI exit codes, and the tier-1 repo-clean
assertion. The analyzer is pure stdlib — no jax needed beyond what the
package import pulls in — so everything here is fast."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from fluxmpi_tpu.analysis import (
    Baseline,
    ProjectContext,
    default_rules,
    lint_repo,
    lint_source,
)
from fluxmpi_tpu.analysis.rules import (
    HandBuiltMesh,
    JaxCompatDrift,
    SpmdDivergentCollective,
    UndocumentedEnvVar,
    UnguardedHotPathInstrumentation,
    UnknownMetricName,
    UnregisteredFaultSite,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLI = os.path.join(_REPO, "scripts", "fluxlint.py")


def _ctx(**kw):
    """Small synthetic project context for fixture snippets."""
    defaults = dict(
        known_metric_names=frozenset({"train.loss", "fault.injected"}),
        closed_namespaces=("fault.",),
        known_fault_sites=frozenset({"ckpt.write", "data.fetch"}),
        documented_env_vars={"FLUXMPI_TPU_DOCUMENTED": 10},
        tests_corpus="scope('ckpt.write') scope('data.fetch')",
    )
    defaults.update(kw)
    return ProjectContext(**defaults)


def _keys(report, rule):
    return [f.key for f in report.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# Rule 1: spmd-divergent-collective
# ---------------------------------------------------------------------------


def test_spmd_flags_collective_under_rank_branch():
    src = textwrap.dedent(
        """
        import jax
        from . import comm
        def f(x):
            if jax.process_index() == 0:
                comm.allreduce(x)
        """
    )
    r = lint_source(src, "pkg/a.py", _ctx(), rules=[SpmdDivergentCollective()])
    assert _keys(r, "spmd-divergent-collective") == ["f:allreduce:branch"]


def test_spmd_flags_collective_after_rank_early_exit_via_local_bool():
    src = textwrap.dedent(
        """
        import jax
        from . import comm
        def f(x):
            lead = jax.process_index() == 0
            if not lead:
                return
            comm.barrier()
        """
    )
    r = lint_source(src, "pkg/a.py", _ctx(), rules=[SpmdDivergentCollective()])
    assert _keys(r, "spmd-divergent-collective") == ["f:barrier:after-exit"]


def test_spmd_quiet_on_spmd_consistent_twins():
    # All-ranks collective with lead-only *side effects*, and a
    # world-size condition: both fine.
    src = textwrap.dedent(
        """
        import jax
        from . import comm
        def f(x):
            out = comm.allreduce(x)
            if jax.process_index() == 0:
                print(out)
            if jax.process_count() > 1:
                comm.barrier()
            return out
        """
    )
    r = lint_source(src, "pkg/a.py", _ctx(), rules=[SpmdDivergentCollective()])
    assert r.findings == []


def test_spmd_mutation_of_train_loop_fails_the_lint():
    # The acceptance check: wrapping a collective in a
    # process_index()==0 branch in the real dispatch loop must produce a
    # finding (here: the coordination host_allreduce in train_loop).
    path = os.path.join(_REPO, "fluxmpi_tpu", "parallel", "loop.py")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    target = "if coordinate and at_flush and bool("
    assert target in src
    mutated = src.replace(
        target, "if jax.process_index() == 0 and coordinate and bool("
    )
    ctx = ProjectContext.load(_REPO)
    clean = lint_source(
        src, "fluxmpi_tpu/parallel/loop.py", ctx,
        rules=[SpmdDivergentCollective()],
    )
    assert clean.findings == []
    bad = lint_source(
        mutated, "fluxmpi_tpu/parallel/loop.py", ctx,
        rules=[SpmdDivergentCollective()],
    )
    # The coordination collective lives in train_loop's _post_dispatch
    # closure (the shared pipelined/fused boundary hook) — the key names
    # the innermost function, the prefix anchors it to train_loop.
    assert "train_loop._post_dispatch:host_allreduce:shortcircuit" in _keys(
        bad, "spmd-divergent-collective"
    )


# ---------------------------------------------------------------------------
# Rule 2: unguarded-hot-path-instrumentation
# ---------------------------------------------------------------------------

_HOT = (("pkg/hot.py", "hot", "function"),)


def test_hot_path_flags_unguarded_timing_and_handles():
    src = textwrap.dedent(
        """
        import time
        def hot(reg, x):
            t0 = time.perf_counter()
            reg.histogram("train.step_seconds").observe(time.time() - t0)
            return x
        """
    )
    r = lint_source(
        src, "pkg/hot.py", _ctx(),
        rules=[UnguardedHotPathInstrumentation(_HOT)],
    )
    keys = set(_keys(r, "unguarded-hot-path-instrumentation"))
    assert "hot:time.perf_counter" in keys
    assert "hot:histogram" in keys


def test_hot_path_quiet_on_guarded_twin():
    # Both guard idioms: enclosing `if guard:` and the early
    # `if not guard: return` fast path; IfExp guards too.
    src = textwrap.dedent(
        """
        import time
        def hot(reg, tracer, x):
            enabled = reg.enabled or tracer.enabled
            t0 = time.perf_counter() if enabled else 0.0
            if not enabled:
                return x
            reg.histogram("train.step_seconds").observe(
                time.perf_counter() - t0
            )
            return x
        """
    )
    r = lint_source(
        src, "pkg/hot.py", _ctx(),
        rules=[UnguardedHotPathInstrumentation(_HOT)],
    )
    assert r.findings == []


def test_hot_path_guard_polarity_of_negated_local():
    # `off = not reg.enabled` is truthy when instrumentation is OFF:
    # code under `if off:` is the exact contract violation, and an
    # `if off: return` early exit DOES guard what follows.
    src = textwrap.dedent(
        """
        import time
        def hot(reg, x):
            off = not reg.enabled
            if off:
                t0 = time.perf_counter()
            if off:
                return x
            return time.perf_counter()
        """
    )
    r = lint_source(
        src, "pkg/hot.py", _ctx(),
        rules=[UnguardedHotPathInstrumentation(_HOT)],
    )
    flagged = [f for f in r.findings
               if f.rule == "unguarded-hot-path-instrumentation"]
    assert len(flagged) == 1 and flagged[0].line == 6  # only the OFF-path call


def test_hot_path_loops_scope_keeps_guard_context_in_nested_loops():
    hot = (("pkg/hot.py", "drive", "loops"),)
    src = textwrap.dedent(
        """
        import time
        def drive(reg, batches):
            enabled = reg.enabled
            t_start = time.perf_counter()
            for batch in batches:
                if enabled:
                    for part in batch:
                        reg.histogram("train.step_seconds").observe(1.0)
                for part in batch:
                    t = time.perf_counter()
        """
    )
    r = lint_source(
        src, "pkg/hot.py", _ctx(),
        rules=[UnguardedHotPathInstrumentation(hot)],
    )
    # t_start (function level) is out of scope; the guarded nested loop
    # is quiet; the unguarded nested call is reported exactly once.
    flagged = [f for f in r.findings
               if f.rule == "unguarded-hot-path-instrumentation"]
    assert [(f.key, f.line) for f in flagged] == [
        ("drive:time.perf_counter", 11)
    ]


def test_hot_path_ignores_functions_outside_the_hot_set():
    src = "import time\ndef cold():\n    return time.perf_counter()\n"
    r = lint_source(
        src, "pkg/hot.py", _ctx(),
        rules=[UnguardedHotPathInstrumentation(_HOT)],
    )
    assert r.findings == []


def test_hot_path_guard_deletion_in_comm_fails_the_lint():
    # The acceptance check: deleting the _instrumentation_on() guard in
    # comm.py (resolving the fast-guard to a plain True) must produce
    # findings in _run_collective; the committed source must not.
    path = os.path.join(_REPO, "fluxmpi_tpu", "comm.py")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    assert "instrumented = _instrumentation_on()" in src
    mutated = src.replace(
        "instrumented = _instrumentation_on()", "instrumented = True"
    )
    ctx = ProjectContext.load(_REPO)
    rule = [UnguardedHotPathInstrumentation()]
    clean = lint_source(src, "fluxmpi_tpu/comm.py", ctx, rules=rule)
    assert clean.findings == []
    bad = lint_source(mutated, "fluxmpi_tpu/comm.py", ctx, rules=rule)
    keys = set(_keys(bad, "unguarded-hot-path-instrumentation"))
    assert "_run_collective:time.perf_counter" in keys
    assert "_run_collective:_begin_op" in keys


# ---------------------------------------------------------------------------
# Rule 3: unknown-metric-name
# ---------------------------------------------------------------------------


def test_metric_rule_flags_typo_and_suggests():
    src = 'def f(reg):\n    reg.counter("train.losss").inc()\n'
    r = lint_source(src, "pkg/m.py", _ctx(), rules=[UnknownMetricName()])
    (f,) = r.findings
    assert f.key == "train.losss"
    assert "train.loss" in f.message  # nearest-known hint


def test_metric_rule_quiet_on_known_names_and_open_dynamic():
    src = textwrap.dedent(
        """
        def f(reg, key):
            reg.gauge("train.loss").set(1.0)
            reg.gauge(f"device.memory.{key}").set(0.0)
        """
    )
    r = lint_source(src, "pkg/m.py", _ctx(), rules=[UnknownMetricName()])
    assert r.findings == []


def test_metric_rule_flags_closed_namespace_dynamic_prefix():
    src = 'def f(reg, x):\n    reg.counter("fault.bogus_" + x).inc()\n'
    r = lint_source(src, "pkg/m.py", _ctx(), rules=[UnknownMetricName()])
    assert _keys(r, "unknown-metric-name") == ["prefix:fault.bogus_"]


def test_metric_rule_checks_instant_names():
    ctx = _ctx()
    bad = 'def f(t):\n    t.instant("train.explosion", step=1)\n'
    r = lint_source(bad, "pkg/m.py", ctx, rules=[UnknownMetricName()])
    assert _keys(r, "unknown-metric-name") == ["train.explosion"]
    ok = textwrap.dedent(
        """
        def f(t, rule):
            t.instant("train.preemption", step=1)
            t.instant("anomaly." + rule, step=1)
            t.instant("fault.injected", site="x")
        """
    )
    r = lint_source(ok, "pkg/m.py", ctx, rules=[UnknownMetricName()])
    assert r.findings == []


def test_metric_rule_flags_consumer_literal_drift_in_scripts():
    """The consumer half: dashboards under scripts/ read metric keys as
    PLAIN string literals — a drifted key must fail the lint, not fail
    as a silently blank panel at runtime."""
    src = textwrap.dedent(
        """
        def f(flat):
            return flat.get("train.losss")
        """
    )
    r = lint_source(src, "scripts/top.py", _ctx(), rules=[UnknownMetricName()])
    (f,) = r.findings
    assert f.key == "train.losss"
    assert "train.loss" in f.message  # nearest-known hint
    # The SAME literal outside scripts/ is not a consumer read (package
    # producers go through the instrument-call check instead).
    r = lint_source(src, "pkg/m.py", _ctx(), rules=[UnknownMetricName()])
    assert r.findings == []


def test_metric_rule_consumer_scan_allows_known_shapes():
    src = textwrap.dedent(
        '''
        """Docstring naming train.losss is prose, not a read."""

        def f(flat, name):
            a = flat.get("train.loss")           # schema-known
            b = name.startswith("fault.")        # family-prefix idiom
            c = flat.get("not.a.metric.family")  # foreign dotted string
            d = open("some.file.json")           # ditto
            e = flat.get("train.preemption")     # the instant constant
            return a, b, c, d, e
        '''
    )
    r = lint_source(src, "scripts/top.py", _ctx(), rules=[UnknownMetricName()])
    assert r.findings == []


def test_metric_rule_consumer_scan_flags_dead_family_prefix():
    # A dangling "<family>." prefix read matching NOTHING known under it
    # is drift too (ctx has no metric under "train." besides
    # train.loss, so "fault.zzz_" style reads flag via the family).
    src = 'def f(flat):\n    return flat.get("fault.zzz")\n'
    r = lint_source(src, "scripts/top.py", _ctx(), rules=[UnknownMetricName()])
    assert _keys(r, "unknown-metric-name") == ["fault.zzz"]
    # ...including the trailing-dot form: a startswith("train.loss.")
    # read (sub-namespace typo) matches nothing known and must flag,
    # while a live family prefix stays quiet.
    src = textwrap.dedent(
        """
        def f(name):
            a = name.startswith("train.loss.")
            b = name.startswith("fault.injected")
            return a, b
        """
    )
    r = lint_source(src, "scripts/top.py", _ctx(), rules=[UnknownMetricName()])
    assert _keys(r, "unknown-metric-name") == ["prefix:train.loss."]


def test_metric_rule_covers_serving_report_consumer_literals():
    """scripts/serving_report.py names registry twins for its JSONL
    aggregates as plain metric literals — the consumer rule must keep
    them schema-true: the committed file lints clean, a drifted twin
    fails."""
    path = os.path.join(_REPO, "scripts", "serving_report.py")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    target = '"serving.requests_completed"'
    assert target in src
    ctx = ProjectContext.load(_REPO)
    clean = lint_source(
        src, "scripts/serving_report.py", ctx, rules=[UnknownMetricName()]
    )
    assert clean.findings == []
    bad = lint_source(
        src.replace(target, '"serving.requests_completedd"'),
        "scripts/serving_report.py", ctx, rules=[UnknownMetricName()],
    )
    keys = _keys(bad, "unknown-metric-name")
    assert "serving.requests_completedd" in keys
    (f,) = [x for x in bad.findings if x.key == "serving.requests_completedd"]
    assert "serving.requests_completed" in f.message  # nearest-known hint


# ---------------------------------------------------------------------------
# Rule 4: unregistered-fault-site
# ---------------------------------------------------------------------------


def test_fault_site_rule_flags_unregistered_literal_with_nearest():
    src = (
        "from . import faults as _faults\n"
        'def f():\n    _faults.check("ckpt.wrte")\n'
    )
    r = lint_source(src, "pkg/f.py", _ctx(), rules=[UnregisteredFaultSite()])
    found = [f for f in r.findings if f.key == "ckpt.wrte"]
    assert len(found) == 1 and "ckpt.write" in found[0].message


def test_fault_site_rule_quiet_on_registered_and_known_prefix():
    src = textwrap.dedent(
        """
        from . import faults as _faults
        def f(kind):
            _faults.check("ckpt.write")
            _faults.check("data." + kind)
        """
    )
    r = lint_source(src, "pkg/f.py", _ctx(), rules=[UnregisteredFaultSite()])
    assert r.findings == []


def test_fault_site_rule_demands_test_coverage():
    ctx = _ctx(
        known_fault_sites=frozenset({"ckpt.write", "ghost.site"}),
        tests_corpus="only ckpt.write is exercised here",
    )
    r = lint_source("x = 1\n", "pkg/f.py", ctx, rules=[UnregisteredFaultSite()])
    assert _keys(r, "unregistered-fault-site") == ["untested:ghost.site"]


# ---------------------------------------------------------------------------
# Rule 5: hand-built-mesh
# ---------------------------------------------------------------------------


def test_hand_built_mesh_flags_mesh_and_axis_literals():
    src = textwrap.dedent(
        """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        def bad(devs, q):
            mesh = Mesh(devs, ("dp", "tp"))
            spec = P("dp", None)
            composed = P(("dp", "fsdp"))
            g = jax.lax.psum(q, "tp")
            h = attend(q, axis_name="sp")
            return mesh, spec, composed, g, h
        """
    )
    ctx = _ctx(axis_name_literals=frozenset({"dp", "fsdp", "tp", "sp"}))
    r = lint_source(
        src, "fluxmpi_tpu/parallel/ring.py", ctx, rules=[HandBuiltMesh()]
    )
    keys = _keys(r, "hand-built-mesh")
    assert "mesh" in keys
    assert keys.count("axis:dp") == 2
    assert "axis:fsdp" in keys and "axis:tp" in keys and "axis:sp" in keys


def test_hand_built_mesh_quiet_on_plan_runtime_and_constants():
    ctx = _ctx(axis_name_literals=frozenset({"dp", "tp"}))
    src = textwrap.dedent(
        """
        from jax.sharding import Mesh, PartitionSpec as P
        def build(devs):
            return Mesh(devs, ("dp",)), P("dp")
        """
    )
    # The plan engine and the runtime ARE where meshes come from.
    for path in ("fluxmpi_tpu/parallel/plan.py", "fluxmpi_tpu/runtime.py"):
        assert not lint_source(src, path, ctx, rules=[HandBuiltMesh()]).findings
    # Outside fluxmpi_tpu/ (scripts, tests, examples) the rule is silent.
    assert not lint_source(
        src, "scripts/demo.py", ctx, rules=[HandBuiltMesh()]
    ).findings
    # The canonical spellings don't trip it.
    good = textwrap.dedent(
        """
        from jax.sharding import PartitionSpec as P
        from fluxmpi_tpu import config
        from fluxmpi_tpu.parallel.plan import plan_axis_name
        def fine(q):
            spec = P(config.DP_AXIS_NAME)
            name = plan_axis_name("sp")
            label = {"axis": "dp"}  # a dict literal is not a spec arg
            return spec, name, label
        """
    )
    r = lint_source(
        good, "fluxmpi_tpu/parallel/ring.py", ctx, rules=[HandBuiltMesh()]
    )
    assert not r.findings


def test_hand_built_mesh_clean_on_repo_and_loaded_registry():
    # The merged tree is clean under the rule, and the axis registry
    # loads from config.py (single-sourced, no copy to drift).
    ctx = ProjectContext.load(_REPO)
    assert {"dp", "fsdp", "tp", "pp", "sp", "ep"} <= set(
        ctx.axis_name_literals
    )
    report = lint_repo(_REPO, ["fluxmpi_tpu"], context=ctx)
    assert not [
        f for f in report.findings if f.rule == "hand-built-mesh"
    ], report.text()


# ---------------------------------------------------------------------------
# Rule 6: undocumented-env-var
# ---------------------------------------------------------------------------


def test_env_rule_flags_both_directions():
    src = 'import os\nv = os.environ.get("FLUXMPI_TPU_MYSTERY_KNOB")\n'
    # faults_path == the scanned file marks the scan as "full", enabling
    # the reverse (documented-but-unread) direction.
    r = lint_source(
        src, "pkg/e.py", _ctx(faults_path="pkg/e.py"),
        rules=[UndocumentedEnvVar()],
    )
    keys = _keys(r, "undocumented-env-var")
    # Read-but-undocumented AND documented-but-unread both fire.
    assert "FLUXMPI_TPU_MYSTERY_KNOB" in keys
    assert "unread:FLUXMPI_TPU_DOCUMENTED" in keys


def test_env_rule_quiet_when_table_matches_and_skips_docstrings():
    src = textwrap.dedent(
        '''
        """Docstring mentioning FLUXMPI_TPU_NOT_A_READ is not a read."""
        import os
        v = os.environ.get("FLUXMPI_TPU_DOCUMENTED")
        '''
    )
    r = lint_source(
        src, "pkg/e.py", _ctx(faults_path="pkg/e.py"),
        rules=[UndocumentedEnvVar()],
    )
    assert r.findings == []


def test_env_rule_extra_roots_cover_bench_only_vars():
    ctx = _ctx(
        documented_env_vars={"FLUXMPI_TPU_DOCUMENTED": 10,
                             "FLUXMPI_TPU_BENCH_ONLY": 11},
        extra_env_vars={"FLUXMPI_TPU_BENCH_ONLY"},
        faults_path="pkg/e.py",
    )
    src = 'import os\nv = os.environ.get("FLUXMPI_TPU_DOCUMENTED")\n'
    r = lint_source(src, "pkg/e.py", ctx, rules=[UndocumentedEnvVar()])
    assert r.findings == []


# ---------------------------------------------------------------------------
# Suppressions and baseline
# ---------------------------------------------------------------------------


def test_inline_suppression_trailing_and_own_line():
    src = textwrap.dedent(
        """
        def f(reg):
            reg.counter("bad.one").inc()  # fluxlint: disable=unknown-metric-name
            # fluxlint: disable=unknown-metric-name
            reg.counter("bad.two").inc()
            reg.counter("bad.three").inc()
        """
    )
    r = lint_source(src, "pkg/s.py", _ctx(), rules=[UnknownMetricName()])
    assert r.suppressed == 2
    assert _keys(r, "unknown-metric-name") == ["bad.three"]


def test_own_line_suppression_skips_justification_comments():
    # The documented workflow: directive, then a why-comment, then the
    # statement — the suppression must reach the statement.
    src = textwrap.dedent(
        """
        def f(reg):
            # fluxlint: disable=unknown-metric-name
            # legacy dashboard pins this name, keep until Q4
            reg.counter("bad.metric").inc()
        """
    )
    r = lint_source(src, "pkg/s.py", _ctx(), rules=[UnknownMetricName()])
    assert r.suppressed == 1 and r.findings == []


def test_directive_inside_string_literal_does_not_suppress():
    src = (
        "def f(reg):\n"
        '    msg = "# fluxlint: disable=unknown-metric-name"\n'
        '    reg.counter("bad.metric").inc(); x = msg\n'
        "    return x\n"
    )
    r = lint_source(src, "pkg/s.py", _ctx(), rules=[UnknownMetricName()])
    assert r.suppressed == 0
    assert _keys(r, "unknown-metric-name") == ["bad.metric"]


def test_baseline_round_trip_and_hygiene(tmp_path):
    src = 'def f(reg):\n    reg.counter("bad.metric").inc()\n'
    rule = [UnknownMetricName()]
    ctx = _ctx()

    # Justified entry: finding moves to `baselined`, lint goes clean.
    good = Baseline(
        [{"rule": "unknown-metric-name", "path": "pkg/b.py",
          "key": "bad.metric", "justification": "legacy dashboard name"}]
    )
    r = lint_source(src, "pkg/b.py", ctx, rules=rule, baseline=good)
    assert r.findings == [] and len(r.baselined) == 1
    assert r.exit_code == 0

    # Unjustified entry: the baseline itself is the finding.
    bare = Baseline(
        [{"rule": "unknown-metric-name", "path": "pkg/b.py",
          "key": "bad.metric", "justification": ""}]
    )
    r = lint_source(src, "pkg/b.py", ctx, rules=rule, baseline=bare)
    assert [f.rule for f in r.findings] == ["fluxlint-baseline"]
    assert "justification" in r.findings[0].message

    # Stale entry (matches nothing): flagged so the baseline cannot rot.
    stale = Baseline(
        [{"rule": "unknown-metric-name", "path": "pkg/b.py",
          "key": "gone.metric", "justification": "was real once"}]
    )
    r = lint_source("x = 1\n", "pkg/b.py", ctx, rules=rule, baseline=stale)
    assert [f.key for f in r.findings] == [
        "stale:unknown-metric-name:gone.metric"
    ]

    # File round trip through Baseline.load.
    payload = {"entries": good.entries}
    p = tmp_path / "base.json"
    p.write_text(json.dumps(payload))
    loaded = Baseline.load(str(p))
    r = lint_source(src, "pkg/b.py", ctx, rules=rule, baseline=loaded)
    assert r.findings == [] and len(r.baselined) == 1


# ---------------------------------------------------------------------------
# CLI: JSON schema, exit codes, no-jax loading
# ---------------------------------------------------------------------------


def _run_cli(*args, cwd=_REPO):
    return subprocess.run(
        [sys.executable, _CLI, *args],
        capture_output=True, text=True, cwd=cwd,
    )


def test_cli_repo_clean_and_json_schema():
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["schema"] == "fluxmpi_tpu.fluxlint/v1"
    assert data["findings"] == [] and data["exit_code"] == 0
    assert data["files"] > 50
    for key in ("baselined", "suppressed", "unreadable"):
        assert key in data


def test_cli_exit_codes_findings_and_unreadable(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from . import faults as _faults\n"
        'def f():\n    _faults.check("no.such.site")\n'
    )
    proc = _run_cli(str(bad), "--json")
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert any(
        f["rule"] == "unregistered-fault-site" for f in data["findings"]
    )

    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    proc = _run_cli(str(broken))
    assert proc.returncode == 2


def test_cli_loads_without_importing_jax():
    # The lint must stay runnable in a second without booting a backend:
    # the CLI loads the analysis package by file path, never the parent
    # fluxmpi_tpu package.
    code = (
        "import sys; sys.path.insert(0, 'scripts'); import fluxlint; "
        "a = fluxlint.load_analysis(); "
        "r = a.lint_repo('.', ['fluxmpi_tpu/analysis']); "
        "assert 'jax' not in sys.modules, 'lint imported jax'; "
        "assert 'fluxmpi_tpu' not in sys.modules, 'lint imported the package'; "
        "print(r.exit_code)"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "0"


# ---------------------------------------------------------------------------
# Rule 7: jax-compat-drift
# ---------------------------------------------------------------------------


def test_compat_drift_flags_axis_size_spellings():
    src = textwrap.dedent(
        """
        import jax
        from jax import lax

        def f():
            n = jax.lax.axis_size("dp")
            m = lax.axis_size("tp")
            return n, m
        """
    )
    r = lint_source(src, "fluxmpi_tpu/parallel/ring.py", _ctx(),
                    rules=[JaxCompatDrift()])
    assert _keys(r, "jax-compat-drift") == ["axis_size", "axis_size"]

    imported = "from jax.lax import axis_size\n"
    r = lint_source(imported, "fluxmpi_tpu/ops/x.py", _ctx(),
                    rules=[JaxCompatDrift()])
    assert _keys(r, "jax-compat-drift") == ["axis_size"]


def test_compat_drift_flags_compiler_params_spellings():
    src = textwrap.dedent(
        """
        from jax.experimental.pallas import tpu as pltpu

        old = pltpu.TPUCompilerParams(dimension_semantics=("parallel",))
        new = pltpu.CompilerParams(dimension_semantics=("parallel",))
        """
    )
    r = lint_source(src, "fluxmpi_tpu/ops/k.py", _ctx(),
                    rules=[JaxCompatDrift()])
    assert _keys(r, "jax-compat-drift") == [
        "compiler_params", "compiler_params",
    ]

    imported = "from jax.experimental.pallas.tpu import TPUCompilerParams\n"
    r = lint_source(imported, "scripts/k.py", _ctx(), rules=[JaxCompatDrift()])
    assert _keys(r, "jax-compat-drift") == ["compiler_params"]


def test_compat_drift_flags_shard_map_validation_kwargs():
    src = textwrap.dedent(
        """
        from jax.experimental.shard_map import shard_map

        def f(body, mesh, spec):
            a = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec,
                          check_vma=False)
            b = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec,
                          check_rep=False)
            return a, b
        """
    )
    r = lint_source(src, "fluxmpi_tpu/parallel/p.py", _ctx(),
                    rules=[JaxCompatDrift()])
    assert _keys(r, "jax-compat-drift") == [
        "shard_map:check_vma", "shard_map:check_rep",
    ]


def test_compat_drift_quiet_on_seam_and_wrappers():
    # The seam itself owns the probes — exempt.
    drifted = 'import jax\nn = jax.lax.axis_size("dp")\n'
    r = lint_source(drifted, "fluxmpi_tpu/parallel/_compat.py", _ctx(),
                    rules=[JaxCompatDrift()])
    assert r.findings == []

    # Consuming the wrappers is the blessed spelling.
    good = textwrap.dedent(
        """
        from fluxmpi_tpu.parallel._compat import (
            axis_size,
            pallas_tpu_compiler_params,
            shard_map_unchecked,
        )

        def f(body, mesh, spec, name):
            n = axis_size(name)
            params = pallas_tpu_compiler_params(
                dimension_semantics=("parallel",)
            )
            mapped = shard_map_unchecked(
                body, mesh, in_specs=(spec,), out_specs=spec
            )
            return n, params, mapped
        """
    )
    r = lint_source(good, "fluxmpi_tpu/parallel/ring.py", _ctx(),
                    rules=[JaxCompatDrift()])
    assert r.findings == []

    # A bare shard_map call WITHOUT the drifted kwarg is fine too (the
    # compat module re-exports it for spec-checked call sites).
    bare = textwrap.dedent(
        """
        from fluxmpi_tpu.parallel._compat import shard_map

        def f(body, mesh, spec):
            return shard_map(body, mesh=mesh, in_specs=(spec,),
                             out_specs=spec)
        """
    )
    r = lint_source(bare, "fluxmpi_tpu/comm.py", _ctx(),
                    rules=[JaxCompatDrift()])
    assert r.findings == []


def test_compat_drift_in_default_rules():
    assert any(r.id == "jax-compat-drift" for r in default_rules())


# ---------------------------------------------------------------------------
# The tier-1 contract: the repo itself lints clean (modulo the baseline)
# ---------------------------------------------------------------------------


def test_repo_is_fluxlint_clean():
    report = lint_repo(_REPO, ["fluxmpi_tpu", "scripts"])
    assert report.unreadable == []
    assert report.findings == [], "\n" + report.text()
