"""Telemetry subsystem tests: registry semantics, sink round-trips, comm
instrumentation over the 8-device CPU mesh, the train-step metrics hook,
the TrainingMonitor, and the JSONL/bench schema checker.

The acceptance loop at the bottom is the PR's contract: a CPU-only
training loop with the metrics hook enabled must produce a JSONL stream
carrying step time, examples/sec, loss, grad-norm, per-collective
byte/call counters, and memory stats — validated by
scripts/check_metrics_schema.py.
"""

import json
import os
import subprocess
import sys

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from fluxmpi_tpu.telemetry import (
    ConsoleSink,
    JSONLSink,
    MemorySink,
    MetricsRegistry,
    NullSink,
    SCHEMA,
    TrainingMonitor,
    configure,
    get_registry,
    validate_bench_record,
    validate_record,
)
from fluxmpi_tpu.telemetry import schema

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHECKER = os.path.join(_REPO, "scripts", "check_metrics_schema.py")


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("t.calls")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("t.depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0

    h = reg.histogram("t.lat")
    for v in (0.5, 1.5, 1.0):
        h.observe(v)
    assert h.count == 3
    assert h.sum == pytest.approx(3.0)
    assert h.min == 0.5 and h.max == 1.5 and h.last == 1.0
    assert h.mean == pytest.approx(1.0)
    # No schema-declared edges for this name: bucket-free summary.
    assert h.bins is None
    assert "buckets" not in h.snapshot()


def test_histogram_schema_declared_buckets():
    """Names with edges in schema.HISTOGRAM_BUCKET_EDGES bin into
    cumulative Prometheus-shaped buckets; the snapshot validates and an
    over-the-top observation counts only toward the implicit +Inf."""
    from fluxmpi_tpu.telemetry.schema import HISTOGRAM_BUCKET_EDGES

    reg = MetricsRegistry()
    h = reg.histogram("train.step_seconds")
    edges = HISTOGRAM_BUCKET_EDGES["train.step_seconds"]
    assert tuple(h.edges) == edges
    h.observe(0.003)   # lands in the le=0.005 bin
    h.observe(0.003)
    h.observe(0.3)     # le=0.5
    h.observe(1e9)     # beyond the last edge: +Inf only
    snap = h.snapshot()
    buckets = snap["buckets"]
    assert buckets["edges"] == list(edges)
    cum = dict(zip(buckets["edges"], buckets["counts"]))
    assert cum[0.0025] == 0
    assert cum[0.005] == 2
    assert cum[0.25] == 2
    assert cum[0.5] == 3
    assert cum[edges[-1]] == 3  # the 1e9 sample is only in count (+Inf)
    assert snap["count"] == 4
    # Cumulative counts are non-decreasing and the metric validates.
    assert buckets["counts"] == sorted(buckets["counts"])
    assert schema.validate_metric(snap) == []
    # A flush record carrying buckets stays schema-clean end to end.
    assert schema.validate_record(reg.flush()) == []
    # Corrupt bucket shapes are rejected.
    bad = dict(snap)
    bad["buckets"] = {"edges": [2.0, 1.0], "counts": [1, 0]}
    errs = schema.validate_metric(bad)
    assert any("strictly increasing" in e for e in errs)
    assert any("cumulative" in e for e in errs)


def test_labels_key_identity_and_separation():
    reg = MetricsRegistry()
    a = reg.counter("c.bytes", op="allreduce", path="device")
    # Same name+labels (any kwarg order, any stringable value) → same object.
    assert reg.counter("c.bytes", path="device", op="allreduce") is a
    b = reg.counter("c.bytes", op="bcast", path="device")
    assert b is not a
    a.inc(10)
    assert b.value == 0


def test_kind_conflict_and_empty_name_rejected():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")
    # One name, one kind holds ACROSS label sets too — otherwise a flush
    # line could carry the same name as two instrument types.
    with pytest.raises(ValueError, match="already registered"):
        reg.histogram("x", op="other")
    with pytest.raises(ValueError, match="non-empty"):
        reg.counter("")


def test_snapshot_shapes_validate_against_schema():
    reg = MetricsRegistry()
    reg.counter("a", op="x").inc()
    reg.gauge("b").set(1.0)
    reg.histogram("c").observe(0.1)
    reg.histogram("d")  # empty histogram: count 0, no stats keys
    record = reg.flush()
    assert record["schema"] == SCHEMA
    assert validate_record(record) == []
    empty = [m for m in record["metrics"] if m["name"] == "d"][0]
    assert empty["count"] == 0 and "mean" not in empty


# ---------------------------------------------------------------------------
# Sinks
# ---------------------------------------------------------------------------


def test_jsonl_sink_round_trip(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    reg = MetricsRegistry(sinks=[JSONLSink(path)])
    reg.counter("io.calls", op="allreduce").inc(3)
    reg.histogram("io.lat").observe(0.25)
    reg.flush()
    reg.gauge("io.depth").set(2)
    reg.flush(extra_field="ok")

    lines = [
        json.loads(ln)
        for ln in open(path, encoding="utf-8").read().splitlines()
    ]
    assert len(lines) == 2
    for rec in lines:
        assert validate_record(rec) == []
    by_name = {m["name"]: m for m in lines[1]["metrics"]}
    assert by_name["io.calls"]["value"] == 3
    assert by_name["io.calls"]["labels"] == {"op": "allreduce"}
    assert by_name["io.lat"]["count"] == 1
    assert by_name["io.depth"]["value"] == 2.0
    assert lines[1]["extra_field"] == "ok"


def test_jsonl_sink_private_stream_keeps_fast_path(tmp_path):
    # Default (non-shared) sink: persistent handle, no .lock sidecar.
    path = str(tmp_path / "private.jsonl")
    sink = JSONLSink(path)
    sink.write({"a": 1})
    sink.write({"a": 2})
    assert not os.path.exists(path + ".lock")
    sink.close()
    assert [json.loads(l)["a"] for l in open(path)] == [1, 2]


def test_jsonl_sink_shared_survives_merge_by_rename(tmp_path):
    # shared=True reopens per line: a merge-by-rename writer swapping the
    # inode between writes must not strand the sink on the unlinked file.
    path = str(tmp_path / "bank.jsonl")
    sink = JSONLSink(path, shared=True)
    sink.write({"a": 1})
    os.rename(path, path + ".merged")  # simulate bench's replace
    sink.write({"a": 2})
    assert [json.loads(l)["a"] for l in open(path)] == [2]
    assert os.path.exists(path + ".lock")
    sink.close()


def test_configure_marks_bench_bank_path_shared(tmp_path, monkeypatch):
    bank = str(tmp_path / "bank.jsonl")
    other = str(tmp_path / "other.jsonl")
    monkeypatch.setenv("FLUXMPI_TPU_BENCH_JSONL", bank)
    try:
        configure(bank)
        configure(other)
        by_path = {
            s.path: s for s in get_registry().sinks if isinstance(s, JSONLSink)
        }
        assert by_path[bank].shared is True
        assert by_path[other].shared is False
    finally:
        for s in list(get_registry().sinks):
            if isinstance(s, JSONLSink) and s.path in (bank, other):
                get_registry().remove_sink(s)


def test_memory_and_null_sinks_and_close():
    mem = MemorySink()
    reg = MetricsRegistry(sinks=[mem, NullSink()])
    reg.counter("m").inc()
    reg.flush()
    assert len(mem.records) == 1
    reg.close()  # flushes once more, then detaches
    assert len(mem.records) == 2
    assert reg.sinks == ()


def test_close_without_flush_writes_no_extra_line():
    mem = MemorySink()
    reg = MetricsRegistry(sinks=[mem])
    reg.counter("m").inc()
    reg.flush()
    reg.close(flush=False)
    assert len(mem.records) == 1
    assert reg.sinks == ()


def test_console_sink_prints_on_lead(capsys):
    reg = MetricsRegistry(sinks=[ConsoleSink()])
    reg.gauge("loss").set(0.125)
    reg.histogram("lat").observe(0.5)
    reg.flush()
    out = capsys.readouterr().out
    assert "telemetry:" in out and "loss=0.125" in out and "lat" in out


def test_configure_is_idempotent(tmp_path):
    path = str(tmp_path / "cfg.jsonl")
    before = len(get_registry().sinks)
    try:
        configure(path)
        configure(path)  # same path again — idempotent init() replay
        assert len(get_registry().sinks) == before + 1
    finally:
        for s in list(get_registry().sinks):
            if isinstance(s, JSONLSink) and s.path == path:
                get_registry().remove_sink(s)


# ---------------------------------------------------------------------------
# Comm instrumentation (real XLA collectives over the 8-device CPU mesh)
# ---------------------------------------------------------------------------


def _comm_metric(name, op, path="device"):
    reg = get_registry()
    if name == "comm.block_seconds":
        return reg.histogram(name, op=op, path=path)
    return reg.counter(name, op=op, path=path)


def test_allreduce_records_calls_bytes_and_time(world, nworkers):
    import fluxmpi_tpu as fm

    x = np.arange(nworkers * 4, dtype=np.float32).reshape(nworkers, 4)
    calls0 = _comm_metric("comm.calls", "allreduce").value
    bytes0 = _comm_metric("comm.bytes", "allreduce").value
    n0 = _comm_metric("comm.block_seconds", "allreduce").count

    out = fm.allreduce(x, op="sum")
    np.testing.assert_allclose(
        np.asarray(out), np.broadcast_to(x.sum(0), x.shape)
    )
    assert _comm_metric("comm.calls", "allreduce").value == calls0 + 1
    assert _comm_metric("comm.bytes", "allreduce").value == bytes0 + x.nbytes
    hist = _comm_metric("comm.block_seconds", "allreduce")
    assert hist.count == n0 + 1 and hist.last >= 0


def test_bcast_and_host_collectives_record(world, nworkers):
    import fluxmpi_tpu as fm

    # float32: a float64 host input stages to f32 (x64 disabled), and the
    # recorded bytes are the staged payload that actually moved.
    x = np.ones((nworkers, 2), dtype=np.float32)
    calls0 = _comm_metric("comm.calls", "bcast").value
    bytes0 = _comm_metric("comm.bytes", "bcast").value
    fm.bcast(x, root=1)
    assert _comm_metric("comm.calls", "bcast").value == calls0 + 1
    assert _comm_metric("comm.bytes", "bcast").value == bytes0 + x.nbytes

    h0 = _comm_metric("comm.calls", "host_allreduce", "host").value
    fm.host_allreduce(np.float32(2.0))
    assert _comm_metric("comm.calls", "host_allreduce", "host").value == h0 + 1

    g0 = _comm_metric("comm.calls", "host_allgather", "host").value
    gathered = fm.host_allgather(np.float32(3.0))
    assert gathered.shape == (1,) and gathered[0] == 3.0
    assert _comm_metric("comm.calls", "host_allgather", "host").value == g0 + 1


# ---------------------------------------------------------------------------
# Train-step metrics hook
# ---------------------------------------------------------------------------


def _mlp_problem():
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState

    model = MLP(features=(8, 8, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
    optimizer = optax.sgd(0.1)
    state = TrainState.create(params, optimizer)

    def loss_fn(p, mstate, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2), mstate

    rng = np.random.default_rng(0)
    batch = (
        rng.normal(size=(16, 2)).astype(np.float32),
        rng.normal(size=(16, 1)).astype(np.float32),
    )
    return loss_fn, optimizer, state, batch


@pytest.mark.parametrize("style", ["auto", "shard_map"])
def test_train_step_metrics_hook(world, style):
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    loss_fn, optimizer, state, batch = _mlp_problem()
    reg = MetricsRegistry()
    step = make_train_step(
        loss_fn, optimizer, style=style, donate=False, metrics=reg
    )
    st = replicate(state)
    data = shard_batch(batch)
    for _ in range(3):
        st, loss = step(st, data)
    assert np.isfinite(float(loss))

    assert reg.counter("train.steps").value == 3
    assert reg.counter("train.examples").value == 3 * 16
    assert reg.histogram("train.step_seconds").count == 3
    assert reg.histogram("train.step_seconds").min > 0
    assert np.isfinite(reg.gauge("train.loss").value)
    assert np.isfinite(reg.gauge("train.grad_norm").value)
    assert reg.gauge("train.grad_norm").value > 0
    assert reg.gauge("train.examples_per_sec").value > 0
    assert int(st.step) == 3  # public signature unchanged


def test_train_step_metrics_callable_hook(world):
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    loss_fn, optimizer, state, batch = _mlp_problem()
    records = []
    step = make_train_step(
        loss_fn, optimizer, donate=False, metrics=records.append
    )
    st, loss = step(replicate(state), shard_batch(batch))
    assert len(records) == 1
    rec = records[0]
    assert set(rec) == {
        "step_seconds", "loss", "grad_norm", "examples",
        "examples_per_sec", "steps",
    }
    assert rec["examples"] == 16 and rec["steps"] == 1
    assert rec["loss"] == pytest.approx(float(loss))
    assert np.isfinite(rec["grad_norm"]) and rec["step_seconds"] > 0


def test_train_step_metrics_with_scan_steps(world):
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch
    from fluxmpi_tpu import config as fm_config
    from jax.sharding import PartitionSpec as P

    loss_fn, optimizer, state, batch = _mlp_problem()
    reg = MetricsRegistry()
    k = 2
    step = make_train_step(
        loss_fn, optimizer, donate=False, scan_steps=k, metrics=reg
    )
    stacked = jax.tree_util.tree_map(
        lambda a: np.broadcast_to(a, (k, *a.shape)), batch
    )
    data = shard_batch(stacked, spec=P(None, fm_config.DP_AXIS_NAME))
    st, losses = step(replicate(state), data)
    assert losses.shape == (k,)
    assert reg.counter("train.steps").value == k
    assert reg.counter("train.examples").value == k * 16
    assert np.isfinite(reg.gauge("train.grad_norm").value)


def test_train_step_rejects_bad_metrics_spec(world):
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    loss_fn, optimizer, state, batch = _mlp_problem()
    with pytest.raises(ValueError, match="metrics"):
        make_train_step(loss_fn, optimizer, metrics=123)
    # False is off, same as None — a bool toggle flag must just work.
    step = make_train_step(loss_fn, optimizer, donate=False, metrics=False)
    st, loss = step(replicate(state), shard_batch(batch))
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# TrainingMonitor
# ---------------------------------------------------------------------------


def test_monitor_collects_on_interval_and_flags_stragglers(world):
    mem = MemorySink()
    reg = MetricsRegistry(sinks=[mem])
    mon = TrainingMonitor(registry=reg, interval=3, cross_host=False)
    assert mon.observe_step(0.01) is None
    assert mon.observe_step(0.01) is None
    summary = mon.observe_step(0.01)
    assert summary is not None
    assert summary["step_seconds_mean"] == pytest.approx(0.01)
    assert summary["straggler"] is False
    assert len(mem.records) == 1
    names = {m["name"] for m in mem.records[0]["metrics"]}
    assert "monitor.heartbeat" in names
    assert "monitor.step_seconds_mean" in names
    assert "host.memory.peak_rss_bytes" in names
    assert validate_record(mem.records[0]) == []
    # Single-host: max == mean, so straggler can never flag here; the
    # threshold math is pure python — exercise it directly.
    assert reg.gauge("monitor.straggler").value == 0.0


def test_monitor_heartbeat_advances_per_collect(world):
    reg = MetricsRegistry()
    mon = TrainingMonitor(registry=reg, interval=1, cross_host=False)
    mon.collect()
    t1 = reg.gauge("monitor.heartbeat_unix").value
    mon.collect()
    assert reg.counter("monitor.heartbeat").value == 2
    assert reg.gauge("monitor.heartbeat_unix").value >= t1


# ---------------------------------------------------------------------------
# Data loader instrumentation + transform_with_rng
# ---------------------------------------------------------------------------


def test_loader_records_fetch_latency_and_depth(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    reg = get_registry()
    n0 = reg.histogram("data.batch_fetch_seconds").count
    data = ArrayDataset(np.arange(64, dtype=np.float32).reshape(32, 2))
    loader = DistributedDataLoader(data, 8, prefetch=2)
    batches = list(loader)
    assert len(batches) == 4
    assert reg.histogram("data.batch_fetch_seconds").count == n0 + 4
    assert reg.gauge("data.prefetch_depth").value >= 0


def test_transform_with_rng_explicit_override(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    data = ArrayDataset(np.ones((16, 2), dtype=np.float32))
    seen = []

    def aug(batch, rng=None):  # 1 required positional → inspected as 1-arg
        seen.append(rng)
        return batch

    list(DistributedDataLoader(data, 8, transform=aug, prefetch=0))
    assert all(r is None for r in seen)

    seen.clear()
    list(
        DistributedDataLoader(
            data, 8, transform=aug, transform_with_rng=True, prefetch=0
        )
    )
    assert all(isinstance(r, np.random.Generator) for r in seen)


def test_transform_with_rng_attribute_flag(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    data = ArrayDataset(np.ones((16, 2), dtype=np.float32))
    seen = []

    def aug(batch, rng=None):
        seen.append(rng)
        return batch

    aug.transform_with_rng = True
    list(DistributedDataLoader(data, 8, transform=aug, prefetch=0))
    assert all(isinstance(r, np.random.Generator) for r in seen)


def test_uninspectable_transform_warns(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    data = ArrayDataset(np.ones((8, 2), dtype=np.float32))
    # inspect.signature(dict) raises ValueError — the un-inspectable case.
    with pytest.warns(UserWarning, match="not inspectable"):
        DistributedDataLoader(data, 8, transform=dict)
    # Explicit declaration silences it.
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        DistributedDataLoader(data, 8, transform=dict, transform_with_rng=False)


def test_transform_with_rng_without_transform_rejected(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    data = ArrayDataset(np.ones((8, 2), dtype=np.float32))
    with pytest.raises(ValueError, match="without transform"):
        DistributedDataLoader(data, 8, transform_with_rng=True)


# ---------------------------------------------------------------------------
# Schema checker script + bench schema
# ---------------------------------------------------------------------------


def _run_checker(*args):
    return subprocess.run(
        [sys.executable, _CHECKER, *args],
        capture_output=True,
        text=True,
        timeout=60,
    )


def test_checker_passes_repo_bench_files():
    proc = _run_checker()  # no args → BENCH_*.json in the repo root
    assert proc.returncode == 0, proc.stderr + proc.stdout


def test_checker_validates_jsonl(tmp_path):
    good = tmp_path / "good.jsonl"
    reg = MetricsRegistry(sinks=[JSONLSink(str(good))])
    reg.counter("ok").inc()
    reg.flush()
    assert _run_checker(str(good)).returncode == 0

    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps({"schema": "wrong", "metrics": "nope"}) + "\nnot json\n"
    )
    proc = _run_checker(str(bad))
    assert proc.returncode == 1
    assert "schema" in proc.stderr and "not JSON" in proc.stderr


def test_bench_record_schema():
    ok = {
        "metric": "mlp_quickstart_samples_per_sec_per_chip",
        "value": 84080.6,
        "unit": "samples/sec/chip",
        "vs_baseline": 1.0,
        "platform": "cpu",
        "device_kind": "cpu",
        "n_chips": 1,
        "mfu": 0.5,
        "probe": {"attempts": []},
        "future_key": object(),  # unknown keys must pass
    }
    assert validate_bench_record(ok) == []
    assert validate_bench_record({"value": "x"})  # missing/mistyped keys
    assert any(
        "mfu" in e for e in validate_bench_record({**ok, "mfu": 6.33})
    )
    assert any(
        "n_chips" in e for e in validate_bench_record({**ok, "n_chips": "8"})
    )


def test_bench_emit_telemetry_writes_valid_line(tmp_path, monkeypatch):
    import bench

    path = str(tmp_path / "bench.jsonl")
    monkeypatch.setenv("FLUXMPI_TPU_BENCH_JSONL", path)
    result = {
        "metric": "mlp_quickstart_samples_per_sec_per_chip",
        "value": 100.0,
        "unit": "samples/sec/chip",
        "vs_baseline": 1.0,
        "platform": "cpu",
        "device_kind": "cpu",
        "n_chips": 1,
        "scaling": {"scaling_efficiency": 0.9},
    }
    bench._emit_telemetry(result)
    lines = open(path, encoding="utf-8").read().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert validate_record(rec) == []
    assert validate_bench_record(rec["bench"]) == []
    names = {m["name"]: m for m in rec["metrics"]}
    assert names["bench." + result["metric"]]["value"] == 100.0
    assert names["bench.scaling_efficiency"]["value"] == 0.9
    assert _run_checker(path).returncode == 0


# ---------------------------------------------------------------------------
# Acceptance: CPU training loop → JSONL stream with everything, validated
# ---------------------------------------------------------------------------


def test_training_loop_jsonl_stream_end_to_end(world, nworkers, tmp_path):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    path = str(tmp_path / "train_metrics.jsonl")
    sink = JSONLSink(path)
    reg = get_registry()  # comm.* records here — share the stream
    reg.add_sink(sink)
    try:
        loss_fn, optimizer, state, batch = _mlp_problem()
        mon = TrainingMonitor(registry=reg, interval=2, cross_host=False)
        step = make_train_step(
            loss_fn, optimizer, donate=False, metrics=mon
        )
        st = replicate(state)
        data = shard_batch(batch)
        # An eager collective on the loop path (the cross-host loss
        # average a real loop would do) so comm.* counters are live.
        for _ in range(4):
            st, loss = step(st, data)
            fm.host_allreduce(np.asarray(float(loss)), op="mean")
    finally:
        reg.remove_sink(sink)
        sink.close()

    lines = [
        json.loads(ln)
        for ln in open(path, encoding="utf-8").read().splitlines()
    ]
    assert len(lines) == 2  # 4 steps / interval 2
    for rec in lines:
        assert validate_record(rec) == [], rec
    names = {m["name"]: m for m in lines[-1]["metrics"]}
    # Step time, examples/sec, loss, grad-norm:
    assert names["train.step_seconds"]["count"] >= 4
    assert names["train.examples_per_sec"]["value"] > 0
    assert np.isfinite(names["train.loss"]["value"])
    assert np.isfinite(names["train.grad_norm"]["value"])
    # Per-collective byte/call counters:
    # The final flush fires inside step 4's monitor tick, before that
    # iteration's host_allreduce — so the last line carries 3 of the 4.
    comm_calls = [
        m for m in lines[-1]["metrics"]
        if m["name"] == "comm.calls"
        and m["labels"].get("op") == "host_allreduce"
    ]
    assert comm_calls and comm_calls[0]["value"] >= 3
    comm_bytes = [
        m for m in lines[-1]["metrics"]
        if m["name"] == "comm.bytes"
        and m["labels"].get("op") == "host_allreduce"
    ]
    assert comm_bytes and comm_bytes[0]["value"] > 0
    # Memory stats (device.* where the backend reports them; host RSS
    # everywhere) + liveness:
    assert any(
        n.startswith(("device.memory.", "host.memory.")) for n in names
    )
    assert names["monitor.heartbeat"]["value"] == 2
    # The documented validator accepts the stream.
    assert _run_checker(path).returncode == 0


# ---------------------------------------------------------------------------
# hf_gpt2 dropout carry-over (satellite)
# ---------------------------------------------------------------------------


def test_lm_from_gpt2_carries_resid_pdrop(world):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from fluxmpi_tpu.models import lm_from_gpt2

    def tiny(**pdrops):
        cfg = transformers.GPT2Config(
            vocab_size=96, n_positions=32, n_embd=48, n_layer=2, n_head=4,
            **pdrops,
        )
        torch.manual_seed(0)
        hf = transformers.GPT2LMHeadModel(cfg)
        hf.eval()
        return hf

    # Matching nonzero pdrops: carried, no warning.
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        model, _ = lm_from_gpt2(
            tiny(resid_pdrop=0.1, embd_pdrop=0.1, attn_pdrop=0.1)
        )
    assert model.dropout == pytest.approx(0.1)

    # Divergent pdrops: resid carried, loud warning names the rest.
    with pytest.warns(UserWarning, match="attn_pdrop"):
        model, _ = lm_from_gpt2(
            tiny(resid_pdrop=0.1, embd_pdrop=0.1, attn_pdrop=0.3)
        )
    assert model.dropout == pytest.approx(0.1)

    # All-zero (the parity-test configuration): unchanged, silent.
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        model, _ = lm_from_gpt2(
            tiny(resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
        )
    assert model.dropout == 0.0
