"""Collective tests (reference: test/test_mpi_extensions.jl).

Per-worker values are arrays with leading axis == world size, one slice per
device — the mesh analogue of each MPI rank's local buffer. Oracles are the
reference's: sum-allreduce scales by world size, prod-allreduce of ones is
identity, bcast propagates the root pattern, reduce updates only root.
"""

import numpy as np
import pytest


def _rank_values(nworkers, shape=(4,), root_val=1.0, other_val=0.0, root=0):
    """Rank-dependent fixture: root slice = ones, others = zeros
    (reference: test/test_synchronize.jl:5-11)."""
    x = np.full((nworkers, *shape), other_val, dtype=np.float32)
    x[root] = root_val
    return x


def test_allreduce_sum(world, nworkers):
    # reference: test/test_mpi_extensions.jl — allreduce(+) == x * nworkers
    import fluxmpi_tpu as fm

    x = np.ones((nworkers, 4), dtype=np.float32)
    out = fm.unshard_ranks(fm.allreduce(x, "+"))
    np.testing.assert_allclose(out, np.full((nworkers, 4), nworkers))


def test_allreduce_sum_distinct_ranks(world, nworkers):
    x = np.arange(nworkers * 3, dtype=np.float32).reshape(nworkers, 3)
    out = fm_unshard(fm_allreduce(x, "sum"))
    expected = np.broadcast_to(x.sum(axis=0), (nworkers, 3))
    np.testing.assert_allclose(out, expected)


def test_allreduce_prod_identity(world, nworkers):
    # reference: Iallreduce! with * on ones → identity
    import fluxmpi_tpu as fm

    x = np.ones((nworkers, 5), dtype=np.float32)
    out = fm.unshard_ranks(fm.allreduce(x, "*"))
    np.testing.assert_allclose(out, np.ones((nworkers, 5)))


def test_allreduce_min_max(world, nworkers):
    import fluxmpi_tpu as fm

    x = np.arange(nworkers, dtype=np.float32).reshape(nworkers, 1)
    np.testing.assert_allclose(fm.unshard_ranks(fm.allreduce(x, "min")), 0.0)
    np.testing.assert_allclose(
        fm.unshard_ranks(fm.allreduce(x, "max")), float(nworkers - 1)
    )


def test_allreduce_mean(world, nworkers):
    import fluxmpi_tpu as fm

    x = np.arange(nworkers, dtype=np.float32).reshape(nworkers, 1)
    np.testing.assert_allclose(
        fm.unshard_ranks(fm.allreduce(x, "mean")),
        np.full((nworkers, 1), x.mean()),
    )


def test_bcast_root_pattern(world, nworkers):
    # reference: test/test_mpi_extensions.jl:25-32 — root ones propagate
    import fluxmpi_tpu as fm

    for root in (0, nworkers - 1):
        x = _rank_values(nworkers, root_val=1.0, other_val=0.0, root=root)
        out = fm.unshard_ranks(fm.bcast(x, root))
        np.testing.assert_allclose(out, np.ones((nworkers, 4)))


def test_reduce_root_only(world, nworkers):
    # reference: test/test_mpi_extensions.jl:34-62 — root gets the sum,
    # non-root slices keep their input
    import fluxmpi_tpu as fm

    x = np.ones((nworkers, 4), dtype=np.float32)
    out = fm.unshard_ranks(fm.reduce(x, "+", 0))
    np.testing.assert_allclose(out[0], np.full(4, nworkers))
    np.testing.assert_allclose(out[1:], np.ones((nworkers - 1, 4)))


def test_nonblocking_wrappers(world, nworkers):
    # reference: Iallreduce!/Ibcast! return (buffer, request); wait completes
    import fluxmpi_tpu as fm

    x = np.ones((nworkers, 2), dtype=np.float32)
    out, req = fm.iallreduce(x, "+")
    val = req.wait()
    np.testing.assert_allclose(np.asarray(val), np.full((nworkers, 2), nworkers))

    y = _rank_values(nworkers, shape=(2,))
    out, req = fm.ibcast(y, 0)
    fm.Request.wait_all([req])
    np.testing.assert_allclose(np.asarray(out), np.ones((nworkers, 2)))


def test_bad_op_rejected(world):
    import fluxmpi_tpu as fm

    with pytest.raises(ValueError):
        fm.allreduce(np.ones((8, 2)), "xor")


def test_bad_shape_rejected(world):
    import fluxmpi_tpu as fm

    with pytest.raises(ValueError):
        fm.allreduce(np.ones((3, 2)), "+")


def test_cpu_device_helpers(world):
    import jax.numpy as jnp

    import fluxmpi_tpu as fm

    x = jnp.ones((4,))
    h = fm.cpu(x)
    assert isinstance(h, np.ndarray)
    d = fm.device(h)
    assert hasattr(d, "sharding")
    # identity on non-arrays (reference: src/mpi_extensions.jl:5-8)
    assert fm.cpu("hello") == "hello"
    assert fm.device(None) is None


def test_barrier_noop(world):
    import fluxmpi_tpu as fm

    fm.barrier()


def test_host_collectives_single_process(world):
    import fluxmpi_tpu as fm

    x = np.arange(4.0)
    np.testing.assert_allclose(fm.host_allreduce(x), x)
    np.testing.assert_allclose(fm.host_bcast(x), x)


# Helpers so a couple of tests read tighter.
def fm_allreduce(x, op):
    import fluxmpi_tpu as fm

    return fm.allreduce(x, op)


def fm_unshard(x):
    import fluxmpi_tpu as fm

    return fm.unshard_ranks(x)


def test_bcast_bool_dtype(world, nworkers):
    # Bool per-worker values ride the masked-psum broadcast through int32.
    import jax.numpy as jnp

    import fluxmpi_tpu as fm

    x = np.zeros((nworkers, 4), dtype=bool)
    x[2] = True
    out = fm.unshard_ranks(fm.bcast(x, root=2))
    assert out.dtype == bool
    np.testing.assert_array_equal(out, np.ones((nworkers, 4), dtype=bool))


def test_bcast_lowers_without_allgather(world, nworkers):
    # VERDICT r1 weak #3: bcast/reduce must be O(bytes), not
    # O(world × bytes) — the lowered HLO must contain no all-gather.
    import jax

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.comm import _collective_fn

    mesh = fm.global_mesh()
    x = fm.shard_ranks(np.ones((nworkers, 8), np.float32), mesh)
    for kind in ("bcast", "reduce"):
        fn = _collective_fn(mesh, "dp", kind, "sum", 0, False)
        hlo = jax.jit(fn).lower(x).compile().as_text()
        assert "all-gather" not in hlo, f"{kind} still lowers to all-gather"


def test_pallreduce_prod(world, nworkers):
    # In-jit prod parity with the eager layer (reference
    # test/test_mpi_extensions.jl:9-23: allreduce with *).
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel.collectives import pallreduce

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

    mesh = fm.global_mesh()
    x = jnp.arange(1, nworkers + 1, dtype=jnp.float32).reshape(nworkers, 1)

    def body(v):
        return pallreduce(v, "prod", "dp")

    out = jax.jit(
        sm(body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))
    )(x)
    import math

    expected = float(math.factorial(nworkers))
    np.testing.assert_allclose(
        np.asarray(out), np.full((nworkers, 1), expected)
    )


def test_pbroadcast_masked_psum(world, nworkers):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel.collectives import pbroadcast

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

    mesh = fm.global_mesh()
    x = jnp.arange(float(nworkers)).reshape(nworkers, 1) + 1.0

    def body(v):
        return pbroadcast(v, root=3, axis_name="dp")

    jitted = jax.jit(
        sm(body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))
    )
    out = jitted(x)
    np.testing.assert_allclose(np.asarray(out), np.full((nworkers, 1), 4.0))
    hlo = jitted.lower(x).compile().as_text()
    assert "all-gather" not in hlo


def test_allreduce_donation_in_place(world, nworkers):
    # VERDICT r3 next #8: eager collectives must reuse the caller's buffer
    # instead of allocating a second output copy — parity with the
    # reference's in-place allreduce! (src/mpi_extensions.jl:97-111).
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.comm import _collective_fn

    mesh = fm.global_mesh()
    x = fm.shard_ranks(np.ones((nworkers, 16), np.float32), mesh)

    # Compiled memory analysis: with donation the input buffer is aliased to
    # the output (alias bytes > 0) and no fresh output allocation remains.
    donating = _collective_fn(mesh, "dp", "allreduce", "sum", 0, True)
    plain = _collective_fn(mesh, "dp", "allreduce", "sum", 0, False)
    mem_d = donating.lower(x).compile().memory_analysis()
    mem_p = plain.lower(x).compile().memory_analysis()
    assert mem_d.alias_size_in_bytes > 0
    assert mem_d.alias_size_in_bytes > mem_p.alias_size_in_bytes

    # Semantics: donate=True consumes an already-sharded input...
    out = fm.allreduce(x, "+", donate=True)
    np.testing.assert_allclose(
        fm.unshard_ranks(out), np.full((nworkers, 16), nworkers)
    )
    assert x.is_deleted()

    # ...donate=False (default) leaves it usable.
    y = fm.shard_ranks(np.ones((nworkers, 16), np.float32), mesh)
    out2 = fm.allreduce(y, "+")
    np.testing.assert_allclose(np.asarray(y), np.ones((nworkers, 16)))
    assert not y.is_deleted()
    np.testing.assert_allclose(
        fm.unshard_ranks(out2), np.full((nworkers, 16), nworkers)
    )

    # Host inputs ride a private staged buffer that is always donated;
    # the caller's numpy array is untouched.
    h = np.ones((nworkers, 4), np.float32)
    out3 = fm.allreduce(h, "+")
    np.testing.assert_allclose(h, 1.0)
    np.testing.assert_allclose(
        fm.unshard_ranks(out3), np.full((nworkers, 4), nworkers)
    )


# ---------------------------------------------------------------------------
# Steady-state hot path (PR 4): recompilation guards and the
# zero-cost-when-off instrumentation fast-guard.
# ---------------------------------------------------------------------------


def test_collective_fn_cache_hits_on_repeated_shapes(world, nworkers):
    # Repeated same-shape collectives must reuse ONE compiled program:
    # the lru_cache hit count advances, the miss count does not.
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.comm import _collective_fn

    x = np.ones((nworkers, 8), dtype=np.float32)
    fm.allreduce(x, "+")  # prime the cache for this (mesh, op) key
    info0 = _collective_fn.cache_info()
    for _ in range(3):
        fm.allreduce(x, "+")
    info1 = _collective_fn.cache_info()
    assert info1.misses == info0.misses
    assert info1.hits == info0.hits + 3


def test_shard_ranks_skips_restage_when_already_sharded(world, nworkers):
    # A per-worker value already carrying the target layout is returned
    # as-is — no per-call device_put, and the collective's donate check
    # sees the caller's own array.
    import fluxmpi_tpu as fm

    x = fm.shard_ranks(np.ones((nworkers, 4), np.float32))
    assert fm.shard_ranks(x) is x
    x2 = fm.shard_ranks(np.ones((nworkers, 2, 2), np.float32))
    assert fm.shard_ranks(x2) is x2


def test_comm_handle_cache_tracks_registry_swaps_and_resets(world, nworkers):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.telemetry import MetricsRegistry, get_registry, set_registry

    x = np.ones((nworkers, 4), dtype=np.float32)
    fresh = MetricsRegistry()
    prev = set_registry(fresh)
    try:
        fm.allreduce(x, "+")
        assert fresh.counter(
            "comm.calls", op="allreduce", path="device"
        ).value == 1.0
        # reset() orphans the instruments; the cached handles must
        # re-resolve instead of recording into the dead objects.
        fresh.reset()
        fm.allreduce(x, "+")
        assert fresh.counter(
            "comm.calls", op="allreduce", path="device"
        ).value == 1.0
    finally:
        set_registry(prev)
    # After swapping back, records land in the restored registry again.
    before = prev.counter("comm.calls", op="allreduce", path="device").value
    fm.allreduce(x, "+")
    assert prev.counter(
        "comm.calls", op="allreduce", path="device"
    ).value == before + 1


def test_collective_fully_off_does_no_instrumentation_work(world, nworkers):
    """Acceptance guard: with telemetry, tracing, and the flight recorder
    all disabled, a collective performs no perf_counter reads and no
    labeled-handle lookups."""
    import fluxmpi_tpu as fm
    from fluxmpi_tpu import comm
    from fluxmpi_tpu.telemetry import (
        get_flight_recorder,
        get_registry,
        tracing,
    )

    reg = get_registry()
    rec = get_flight_recorder()
    assert not tracing.trace_enabled()  # default-off in the test world
    x = np.ones((nworkers, 4), dtype=np.float32)
    fm.allreduce(x, "+")  # prime compile caches outside the counted call

    pc_reads = []
    real_pc = comm.time.perf_counter
    lookups = []
    real_get = type(reg)._get

    def counting_pc():
        pc_reads.append(1)
        return real_pc()

    def counting_get(self, *a, **k):
        lookups.append(1)
        return real_get(self, *a, **k)

    seq0 = rec.sequence
    reg.enabled = False
    rec.enabled = False
    comm.time.perf_counter = counting_pc
    type(reg)._get = counting_get
    try:
        out = fm.allreduce(x, "+")
    finally:
        comm.time.perf_counter = real_pc
        type(reg)._get = real_get
        reg.enabled = True
        rec.enabled = True
    np.testing.assert_allclose(
        fm.unshard_ranks(out), np.full((nworkers, 4), nworkers)
    )
    assert pc_reads == []  # no timing on the fully-off path
    assert lookups == []  # no labeled-handle lookups either
    assert rec.sequence == seq0  # and no flight entries


def test_flight_recorder_disabled_records_nothing(world, nworkers):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.telemetry import get_flight_recorder, get_registry

    rec = get_flight_recorder()
    x = np.ones((nworkers, 4), dtype=np.float32)
    rec.enabled = False
    try:
        seq0 = rec.sequence
        fm.allreduce(x, "+")
        assert rec.sequence == seq0
        # Metrics still record: the registry is independently enabled.
        assert get_registry().counter(
            "comm.calls", op="allreduce", path="device"
        ).value > 0
    finally:
        rec.enabled = True
    fm.allreduce(x, "+")
    assert rec.sequence > seq0  # re-enabled recorder records again
