"""Layout-autotuner tests (parallel/autotune.py): the four-stage search
on the 8-virtual-device CPU mesh — enumerate, static prune (memory model
oracle + AOT cost ranking), fused-window trials with zero steady-state
retraces, and the bank contract (same model+topology → zero trials;
topology change → re-tune) — plus the plan spec-cache memoization and
the ``parallel="auto"`` wiring through init/make_train_step."""

import contextlib
import json
import sys

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# The package re-exports the autotune FUNCTION from parallel/__init__,
# which shadows the submodule on attribute access — bind the module
# object itself.
import fluxmpi_tpu.parallel.autotune  # noqa: F401

at = sys.modules["fluxmpi_tpu.parallel.autotune"]


@contextlib.contextmanager
def _fresh_runtime():
    """Swap the runtime out so a test can init() its own auto/plan world
    and hand the session fixture's world back untouched (the test_plan
    pattern, extended with the auto_parallel slot)."""
    from fluxmpi_tpu import runtime

    saved = (
        runtime._state.initialized,
        runtime._state.mesh,
        runtime._state.plan,
        runtime._state.auto_parallel,
    )
    runtime._state.initialized = False
    runtime._state.mesh = None
    runtime._state.plan = None
    runtime._state.auto_parallel = False
    try:
        yield
    finally:
        (
            runtime._state.initialized,
            runtime._state.mesh,
            runtime._state.plan,
            runtime._state.auto_parallel,
        ) = saved


# A transformer-shaped parameter tree (q/k/v/o + ff kernels) so the
# Megatron tp rules and the ZeRO fsdp rule both have leaves to claim.
_D, _FF, _VOCAB = 32, 64, 64


def _tiny_params():
    rng = np.random.default_rng(0)

    def mk(*shape):
        return jnp.asarray(rng.normal(size=shape, scale=0.02), jnp.float32)

    return {
        "embed": {"embedding": mk(_VOCAB, _D)},
        "layer0": {
            "attn": {
                "q": {"kernel": mk(_D, _D)},
                "k": {"kernel": mk(_D, _D)},
                "v": {"kernel": mk(_D, _D)},
                "o": {"kernel": mk(_D, _D)},
            },
            "ff1": {"kernel": mk(_D, _FF)},
            "ff2": {"kernel": mk(_FF, _D)},
        },
    }


def _loss_fn(p, mstate, batch):
    x = p["embed"]["embedding"][batch["x"]]
    a = p["layer0"]
    h = x @ a["attn"]["q"]["kernel"] @ a["attn"]["o"]["kernel"].T
    h = jax.nn.relu(h @ a["ff1"]["kernel"]) @ a["ff2"]["kernel"]
    logits = h @ p["embed"]["embedding"].T
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["y"]
    ).mean()
    return loss, mstate


def _batch(gbs=16, seq=8):
    rng = np.random.default_rng(1)
    return {
        "x": np.asarray(rng.integers(0, _VOCAB, (gbs, seq)), np.int32),
        "y": np.asarray(rng.integers(0, _VOCAB, (gbs, seq)), np.int32),
    }


def _fake_trial(eps_fn):
    """A deterministic _run_trial stand-in: throughput is a pure function
    of the candidate's axes — no compile, no execution."""

    def fake(loss_fn, optimizer, host_params, model_state, sample_batch,
             plan, *, window, epochs, seed):
        # plan.sizes omits size-1 axes — normalize for the eps function.
        axes = {a: plan.sizes.get(a, 1) for a in ("dp", "fsdp", "tp")}
        return {
            "examples_per_sec": float(eps_fn(axes)),
            "updates": window * epochs,
            "compile_seconds": 0.01,
            "steady_compiles": 0,
            "retraces": 0,
            "seconds": 0.02,
        }

    return fake


# ---------------------------------------------------------------------------
# Stage 1: enumeration rides the strict plan path
# ---------------------------------------------------------------------------


def test_enumerate_candidates_covers_factorizations(world):
    cands = at.enumerate_candidates(
        _tiny_params(), jax.devices(), fsdp_min_size=256
    )
    axes = [tuple(c.axes[a] for a in ("dp", "fsdp", "tp")) for c in cands]
    # 8 devices → 10 ordered dp×fsdp×tp factorizations, dp descending;
    # every one is valid for this model (tp divides every matched dim,
    # fsdp has leaves ≥ 256 elements to claim).
    assert len(axes) == 10
    assert axes[0] == (8, 1, 1)  # pure dp first
    assert all(d * f * t == 8 for d, f, t in axes)
    assert len(set(axes)) == 10


def test_enumerate_drops_tp_that_cannot_divide(world):
    # d_model=6: tp=4 cannot divide any matched dim, so every tp=4
    # layout must be dropped (the strict rule engine warns → invalid).
    rng = np.random.default_rng(0)
    params = {
        "attn": {
            "q": {"kernel": jnp.asarray(rng.normal(size=(6, 6)), jnp.float32)},
            "o": {"kernel": jnp.asarray(rng.normal(size=(6, 6)), jnp.float32)},
        },
    }
    cands = at.enumerate_candidates(params, jax.devices(), fsdp_min_size=1)
    assert cands, "some layout must survive"
    assert all(c.axes["tp"] not in (4, 8) for c in cands)


# ---------------------------------------------------------------------------
# Stage 2: the static memory model (oracle) and the prune verdict
# ---------------------------------------------------------------------------


def test_tree_bytes_per_device_oracle(world):
    from fluxmpi_tpu import ParallelConfig

    plan = ParallelConfig(dp=1, fsdp=8, fsdp_min_size=1).resolve(
        jax.devices()
    )
    leaf = jnp.zeros((8, 16), jnp.float32)  # 512 bytes
    assert at._tree_bytes_per_device(
        {"w": leaf}, {"w": P("fsdp", None)}, plan.mesh
    ) == 512 // 8
    assert at._tree_bytes_per_device(
        {"w": leaf}, {"w": P(None, None)}, plan.mesh
    ) == 512
    # Non-divisible shard: ceil, never undercount.
    odd = jnp.zeros((9,), jnp.float32)  # 36 bytes over 8 shards → ceil 5
    assert at._tree_bytes_per_device(
        {"w": odd}, {"w": P("fsdp")}, plan.mesh
    ) == 5


def test_layout_bytes_adamw_oracle(world):
    """Hand-computed floor: params + adamw mu/nu + gradient, fsdp=8 vs
    replicated — the fsdp layout's floor is ~1/8th (small replicated
    scalars like the step counter aside)."""
    from fluxmpi_tpu import ParallelConfig

    params = {"dense": {"kernel": jnp.zeros((64, 64), jnp.float32)}}
    kbytes = 64 * 64 * 4
    template = at.state_template(params, optax.adamw(1e-3))
    flat = ParallelConfig(dp=8).resolve(jax.devices())
    sharded = ParallelConfig(dp=1, fsdp=8, fsdp_min_size=1).resolve(
        jax.devices()
    )
    b_flat = at.layout_bytes(template, flat)
    b_shard = at.layout_bytes(template, sharded)
    # Replicated: kernel + mu + nu + gradient = 4 copies, plus O(bytes)
    # of scalar counters.
    assert b_flat >= 4 * kbytes
    assert b_flat < 4 * kbytes + 1024
    # fsdp=8 shards all four big trees 8-ways.
    assert b_shard >= 4 * kbytes // 8
    assert b_shard < 4 * kbytes // 8 + 1024
    assert b_shard < b_flat // 4


def test_prune_dominated_keeps_pure_dp(world):
    """Without a memory budget the static score alone ranks — and the
    pure-dp baseline survives even when it is ranked dead last."""
    cands = at.enumerate_candidates(
        _tiny_params(), jax.devices(), fsdp_min_size=256
    )
    for c in cands:
        c.mem_bytes_per_device = 1024
        # Synthetic score: favour heavy sharding so pure-dp would be
        # ranked LAST — the forced-inclusion rule must still keep it.
        c.score = float(c.axes["dp"])
    survivors = at._prune(cands, bytes_limit=None, max_trials=3)
    assert len(survivors) == 3
    assert sum(1 for c in cands if c.pruned == "dominated") == len(cands) - 3
    assert any(
        c.axes == {"dp": 8, "fsdp": 1, "tp": 1} for c in survivors
    )


def test_prune_memory_kills_infeasible_even_pure_dp(world):
    """The real memory model makes fully-replicated pure-dp the biggest
    layout; a budget below it prunes it ``"memory"`` — forced inclusion
    never resurrects an infeasible baseline."""
    cands = at.enumerate_candidates(
        _tiny_params(), jax.devices(), fsdp_min_size=256
    )
    template = at.state_template(_tiny_params(), optax.adamw(1e-3))
    for c in cands:
        c.mem_bytes_per_device = at.layout_bytes(template, c.plan)
        c.score = 1.0
    mems = sorted(c.mem_bytes_per_device for c in cands)
    pure = next(c for c in cands if c.axes == {"dp": 8, "fsdp": 1, "tp": 1})
    assert pure.mem_bytes_per_device == mems[-1]  # replicated = biggest
    limit = mems[-2]
    survivors = at._prune(cands, bytes_limit=limit, max_trials=3)
    assert pure.pruned == "memory"
    assert pure not in survivors
    assert all(c.mem_bytes_per_device <= limit for c in survivors)
    assert sum(1 for c in cands if c.pruned == "memory") >= 1


# ---------------------------------------------------------------------------
# Kernel-plane cost term (ISSUE 19): pallas calls are opaque to XLA's
# cost model; the analytic jaxpr walk restores their FLOPs/bytes.
# ---------------------------------------------------------------------------


def test_pallas_kernel_cost_counts_flash_dots(world):
    from fluxmpi_tpu.ops import flash_attention
    from fluxmpi_tpu.utils.flops import pallas_kernel_cost

    b, s, h, d = 2, 64, 2, 16
    q = jnp.zeros((b, s, h, d), jnp.float32)

    cost = pallas_kernel_cost(
        jax.make_jaxpr(lambda q: flash_attention(q, q, q).sum())(q)
    )
    assert cost is not None and cost["calls"] == 1
    # The kernel body's QK^T and PV dots, per grid point x grid size:
    # 2 dots x 2·b·h·s·s·d = 4·b·h·s²·d total.
    assert cost["flops"] == pytest.approx(4.0 * b * h * s * s * d)
    assert cost["bytes_accessed"] > 0

    # grad adds the backward kernels (dq and dkv passes).
    gcost = pallas_kernel_cost(
        jax.make_jaxpr(jax.grad(lambda q: flash_attention(q, q, q).sum()))(q)
    )
    assert gcost is not None and gcost["calls"] >= 2
    assert gcost["flops"] > cost["flops"]

    # No pallas calls -> None, so callers can tell "no kernels" from 0.
    assert pallas_kernel_cost(
        jax.make_jaxpr(lambda a: a @ a)(jnp.zeros((4, 4)))
    ) is None


def test_static_cost_folds_pallas_kernel_work(world):
    """Two candidate scorings differing ONLY by a flash-attention call:
    XLA prices the pallas custom call at zero FLOPs, so without the
    analytic fold the kernel-heavy loss would look computation-free;
    with it, its static cost strictly exceeds the dense-free twin's."""
    from fluxmpi_tpu import ParallelConfig
    from fluxmpi_tpu.ops import flash_attention

    plan = ParallelConfig(dp=8).resolve(jax.devices())
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(16, 16), scale=0.1),
                               jnp.float32)}
    batch = {"x": np.asarray(rng.normal(size=(8, 32, 2, 16)), np.float32)}
    opt = optax.adamw(1e-3)
    template = at.state_template(params, opt)

    def loss_base(p, mstate, b_):
        q = b_["x"] @ p["w"]
        return (q ** 2).mean(), mstate

    def loss_flash(p, mstate, b_):
        q = b_["x"] @ p["w"]
        return (flash_attention(q, q, q) ** 2).mean(), mstate

    base = at._static_cost(loss_base, opt, template, batch, plan)
    flash = at._static_cost(loss_flash, opt, template, batch, plan)
    assert base is not None and flash is not None
    assert flash["flops"] > base["flops"]
    assert flash["bytes_accessed"] > base["bytes_accessed"]


# ---------------------------------------------------------------------------
# The full search, end to end on the real train_loop (slow-ish: real
# fused-window trials) — plus the bank contract in the same process.
# ---------------------------------------------------------------------------


def test_autotune_e2e_and_bank_hit(world):
    from fluxmpi_tpu import runtime
    from fluxmpi_tpu.parallel import make_train_step
    from fluxmpi_tpu.telemetry import get_registry
    from fluxmpi_tpu.telemetry.schema import validate_autotune_record

    at.clear_bank()
    with _fresh_runtime():
        import fluxmpi_tpu as fm

        fm.init(parallel="auto", compileplane=True)
        assert runtime.auto_parallel()
        res = at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(),
            fsdp_min_size=256, window=2, trial_epochs=1, seed=0,
        )
        rec = res.record
        assert not res.from_bank
        assert validate_autotune_record(rec) == []
        cands = rec["candidates"]
        assert len(cands) == 10
        pruned = [c for c in cands if c["pruned"]]
        trialed = [c for c in cands if c["trial"]]
        # ≥50% die statically; at most the default 4 budget run trials.
        assert len(pruned) >= len(cands) // 2
        assert 1 <= len(trialed) <= 4
        assert rec["trials"] == len(trialed)
        # Pure dp is always among the trials (the baseline to beat).
        assert any(
            c["axes"] == {"dp": 8, "fsdp": 1, "tp": 1} for c in trialed
        )
        # Steady state is a pure window-cache hit for every trial.
        for c in trialed:
            assert c["trial"]["steady_compiles"] == 0
            assert c["trial"]["retraces"] == 0
            assert c["trial"]["compile_seconds"] > 0
        # The winner is the measured-throughput argmax.
        best = max(trialed, key=lambda c: c["trial"]["examples_per_sec"])
        assert rec["winner"]["axes"] == best["axes"]
        # The winning plan is installed: make_train_step(parallel="auto")
        # resolves it with no further wiring.
        assert runtime.global_plan() is res.plan
        assert res.plan.autotune_fingerprint == rec["model_fingerprint"]
        make_train_step(_loss_fn, optax.adamw(1e-3), parallel="auto")
        # autotune.* observability landed.
        reg = get_registry()
        assert reg.gauge("autotune.candidates_total").value == 10
        assert reg.gauge("autotune.trials").value == len(trialed)

        # Bank contract: same model + topology → zero trials. Explode on
        # trial entry to prove none runs.
        def boom(*a, **k):  # pragma: no cover - must not run
            raise AssertionError("a trial ran on a bank hit")

        orig = at._run_trial
        at._run_trial = boom
        try:
            res2 = at.autotune(
                _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(),
                fsdp_min_size=256, window=2, trial_epochs=1, seed=0,
            )
        finally:
            at._run_trial = orig
        assert res2.from_bank
        assert res2.record["winner"]["axes"] == rec["winner"]["axes"]
        assert reg.counter("autotune.bank_hits").value >= 1
    at.clear_bank()


def test_autotune_deterministic_pick_and_sidecar(world, tmp_path):
    """With a deterministic trial stub the pick is a pure function of
    the candidate table: two forced runs agree, and the winner is the
    stub's argmax over the trialed set. Also proves the checkpoint
    sidecar contract."""
    from fluxmpi_tpu import runtime
    from fluxmpi_tpu.telemetry.schema import validate_autotune_record

    at.clear_bank()
    # fsdp buys the most fake throughput; tp second.
    stub = _fake_trial(
        lambda axes: 100.0 * axes["fsdp"] + 10.0 * axes["tp"] + axes["dp"]
    )
    orig = at._run_trial
    at._run_trial = stub
    try:
        with _fresh_runtime():
            import fluxmpi_tpu as fm

            fm.init(parallel="auto")
            kw = dict(fsdp_min_size=256, window=2, trial_epochs=1, seed=0)
            r1 = at.autotune(
                _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(),
                force=True, **kw,
            )
            r2 = at.autotune(
                _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(),
                force=True, **kw,
            )
            assert r1.record["winner"]["axes"] == r2.record["winner"]["axes"]
            trialed = [
                c for c in r1.record["candidates"] if c["trial"]
            ]
            best = max(
                trialed, key=lambda c: c["trial"]["examples_per_sec"]
            )
            assert r1.record["winner"]["axes"] == best["axes"]
            # Candidate tables are identical across the two forced runs.
            assert json.dumps(
                r1.record["candidates"], sort_keys=True
            ) == json.dumps(r2.record["candidates"], sort_keys=True)

            # Sidecar: written when the installed plan IS the winner…
            target = str(tmp_path / "ckpt_step10")
            assert at.write_bank_sidecar(target)
            with open(target + ".autotune.json") as f:
                side = json.load(f)
            assert validate_autotune_record(side) == []
            assert side["winner"]["axes"] == r1.record["winner"]["axes"]
        # …and refused once the runtime's plan is no longer that tune's
        # winner (the fixture world has a plain plan or none).
        assert not at.write_bank_sidecar(str(tmp_path / "other"))
    finally:
        at._run_trial = orig
        at.clear_bank()


def test_autotune_topology_change_retunes(world):
    """The elastic-resume contract: a different device set misses the
    bank and re-tunes; returning to the original topology hits it."""
    at.clear_bank()
    calls = []

    def counting(loss_fn, optimizer, host_params, model_state,
                 sample_batch, plan, *, window, epochs, seed):
        calls.append(dict(plan.sizes))
        return _fake_trial(lambda axes: float(axes["dp"]))(
            loss_fn, optimizer, host_params, model_state, sample_batch,
            plan, window=window, epochs=epochs, seed=seed,
        )

    orig = at._run_trial
    at._run_trial = counting
    try:
        kw = dict(fsdp_min_size=256, window=2, trial_epochs=1)
        r8 = at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(16),
            devices=jax.devices(), **kw,
        )
        n8 = len(calls)
        assert n8 >= 1 and not r8.from_bank
        assert r8.record["topology"]["n_devices"] == 8

        r4 = at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(16),
            devices=jax.devices()[:4], **kw,
        )
        assert not r4.from_bank, "topology change must re-tune"
        assert len(calls) > n8
        assert r4.record["topology"]["n_devices"] == 4
        n_after4 = len(calls)

        back = at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(16),
            devices=jax.devices(), **kw,
        )
        assert back.from_bank and len(calls) == n_after4
        assert back.record["winner"]["axes"] == r8.record["winner"]["axes"]
    finally:
        at._run_trial = orig
        at.clear_bank()


def test_autotune_file_bank_roundtrip(world, tmp_path):
    """FLUXMPI_TPU_AUTOTUNE_BANK: the winner survives a process's
    in-memory bank being dropped (simulated via clear_bank) and is
    validated before it is trusted."""
    at.clear_bank()
    bank = str(tmp_path / "bank.json")
    orig = at._run_trial
    at._run_trial = _fake_trial(lambda axes: float(axes["dp"]))
    try:
        kw = dict(fsdp_min_size=256, window=2, trial_epochs=1, bank=bank)
        r1 = at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(16), **kw
        )
        assert not r1.from_bank

        at.clear_bank()  # a "new process": only the file remains

        def boom(*a, **k):  # pragma: no cover - must not run
            raise AssertionError("trial ran despite a valid file bank")

        at._run_trial = boom
        r2 = at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(16), **kw
        )
        assert r2.from_bank
        assert r2.record["winner"]["axes"] == r1.record["winner"]["axes"]

        # A corrupt bank file is ignored (re-tunes instead of crashing).
        at.clear_bank()
        with open(bank, "w") as f:
            f.write("{not json")
        at._run_trial = _fake_trial(lambda axes: float(axes["dp"]))
        r3 = at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(16), **kw
        )
        assert not r3.from_bank
    finally:
        at._run_trial = orig
        at.clear_bank()


def test_autotune_rejects_indivisible_batch(world):
    with pytest.raises(ValueError, match="leading dim"):
        at.autotune(
            _loss_fn, optax.adamw(1e-3), _tiny_params(), _batch(gbs=12),
            devices=jax.devices(),
        )


def test_autotune_memory_limit_prunes_and_raises(world):
    """Explicit bytes_limit drives the memory prune; an impossible limit
    is a loud error, not a silent OOM-to-be."""
    params = _tiny_params()
    template = at.state_template(params, optax.adamw(1e-3))
    cands = at.enumerate_candidates(params, jax.devices(), fsdp_min_size=256)
    mems = sorted(
        at.layout_bytes(template, c.plan) for c in cands
    )
    orig = at._run_trial
    at._run_trial = _fake_trial(lambda axes: float(axes["dp"]))
    try:
        limit = mems[len(mems) // 2]  # median: some layouts must die
        res = at.autotune(
            _loss_fn, optax.adamw(1e-3), params, _batch(16),
            devices=jax.devices(), fsdp_min_size=256, bytes_limit=limit,
            force=True,
        )
        rec = res.record
        assert any(c["pruned"] == "memory" for c in rec["candidates"])
        for c in rec["candidates"]:
            if c["trial"]:
                assert c["mem_bytes_per_device"] <= limit
        with pytest.raises(RuntimeError, match="does not fit"):
            at.autotune(
                _loss_fn, optax.adamw(1e-3), params, _batch(16),
                devices=jax.devices(), fsdp_min_size=256,
                bytes_limit=1, force=True,
            )
    finally:
        at._run_trial = orig
        at.clear_bank()


# ---------------------------------------------------------------------------
# parallel="auto" wiring: init, env var, make_train_step
# ---------------------------------------------------------------------------


def test_init_parallel_auto_arms_runtime(world):
    from fluxmpi_tpu import runtime

    with _fresh_runtime():
        import fluxmpi_tpu as fm

        fm.init(parallel="auto")
        assert runtime.auto_parallel()
        assert runtime.global_plan() is None  # armed, not yet tuned
    with _fresh_runtime():
        import fluxmpi_tpu as fm

        fm.init()
        assert not runtime.auto_parallel()


def test_init_env_var_arms_auto(world, monkeypatch):
    from fluxmpi_tpu import runtime

    monkeypatch.setenv("FLUXMPI_TPU_PARALLEL", "auto")
    with _fresh_runtime():
        import fluxmpi_tpu as fm

        fm.init()
        assert runtime.auto_parallel()


def test_init_rejects_unknown_parallel_string(world):
    with _fresh_runtime():
        import fluxmpi_tpu as fm

        with pytest.raises(ValueError, match="auto"):
            fm.init(parallel="fastest")


def test_make_train_step_auto_requires_installed_plan(world):
    from fluxmpi_tpu.parallel import make_train_step

    with _fresh_runtime():
        import fluxmpi_tpu as fm

        fm.init(parallel="auto")
        with pytest.raises(ValueError, match="autotune"):
            make_train_step(_loss_fn, optax.adamw(1e-3), parallel="auto")
        with pytest.raises(ValueError, match="auto"):
            make_train_step(
                _loss_fn, optax.adamw(1e-3), parallel="fastest"
            )


# ---------------------------------------------------------------------------
# Satellite: plan partition-spec memoization
# ---------------------------------------------------------------------------


def test_partition_specs_memoized(world):
    from fluxmpi_tpu import ParallelConfig

    plan = ParallelConfig(dp=4, fsdp=2, fsdp_min_size=256).resolve(
        jax.devices()
    )
    params = _tiny_params()
    specs1 = plan.partition_specs(params)
    assert plan.spec_cache_misses == 1
    assert plan.spec_cache_hits == 0
    hits1 = dict(plan.rule_hits)
    specs2 = plan.partition_specs(params)
    assert plan.spec_cache_hits == 1
    assert plan.spec_cache_misses == 1
    assert specs2 is specs1
    assert plan.rule_hits == hits1  # hit path restores the hit counts
    # A different tree shape is a different key → a fresh miss.
    plan.partition_specs({"solo": jnp.zeros((512,), jnp.float32)})
    assert plan.spec_cache_misses == 2
    # Same params again: still cached from the first walk.
    plan.partition_specs(params)
    assert plan.spec_cache_hits == 2


# ---------------------------------------------------------------------------
# Schema: the autotune/v1 validator
# ---------------------------------------------------------------------------


def _minimal_record():
    return {
        "schema": "fluxmpi_tpu.autotune/v1",
        "time_unix": 1.7e9,
        "model_fingerprint": "abc123",
        "topology": {
            "n_devices": 8, "device_kind": "cpu", "process_count": 1,
        },
        "fsdp_min_size": 256,
        "winner": {"axes": {"dp": 8}, "axis_names": {"dp": "dp"}},
        "trials": 1,
        "candidates": [
            {
                "axes": {"dp": 8},
                "mem_bytes_per_device": 1024,
                "score": 10.0,
                "pruned": None,
                "trial": {
                    "examples_per_sec": 100.0,
                    "compile_seconds": 0.5,
                    "steady_compiles": 0,
                    "seconds": 1.0,
                },
            },
            {
                "axes": {"dp": 4, "tp": 2},
                "mem_bytes_per_device": 512,
                "score": None,
                "pruned": "dominated",
                "trial": None,
            },
        ],
    }


def test_validate_autotune_record_accepts_minimal(world):
    from fluxmpi_tpu.telemetry.schema import validate_autotune_record

    assert validate_autotune_record(_minimal_record()) == []


@pytest.mark.parametrize(
    "mutate, needle",
    [
        (lambda r: r.update(schema="nope/v0"), "schema"),
        (lambda r: r.update(trials=2), "trials"),
        (lambda r: r["winner"].update(axes={"dp": 2}), "winner"),
        (lambda r: r["candidates"][1].update(pruned="vibes"), "pruned"),
        (
            lambda r: r["candidates"][1].update(
                trial={"examples_per_sec": 1.0, "compile_seconds": 0.0,
                       "steady_compiles": 0, "seconds": 0.1}
            ),
            "pruned",
        ),
        (
            lambda r: r["candidates"][0]["trial"].update(
                steady_compiles=-1
            ),
            "steady_compiles",
        ),
        (lambda r: r.update(candidates=[]), "candidates"),
        (lambda r: r["topology"].update(n_devices=0), "n_devices"),
    ],
)
def test_validate_autotune_record_rejects(world, mutate, needle):
    from fluxmpi_tpu.telemetry.schema import validate_autotune_record

    rec = _minimal_record()
    mutate(rec)
    errors = validate_autotune_record(rec)
    assert errors, "mutation must be caught"
    assert any(needle in e for e in errors), errors
