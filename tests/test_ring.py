"""Ring attention vs dense attention oracle on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _dense_attention(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        scores = jnp.where(mask[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(batch=2, seq=32, heads=4, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, dim)
    return tuple(
        jnp.asarray(rng.normal(size=shape).astype(np.float32)) for _ in range(3)
    )


@pytest.fixture()
def sp_mesh(world):
    """A mesh with an sp axis for sequence parallelism."""
    import jax
    from jax.sharding import Mesh

    import numpy as np

    return Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("sp",))


def test_ring_matches_dense(sp_mesh):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv()
    fn = make_ring_attention(sp_mesh, axis_name="sp")
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_matches_dense_causal(sp_mesh):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seed=1)
    fn = make_ring_attention(sp_mesh, axis_name="sp", causal=True)
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_bf16(sp_mesh):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(seed=2))
    fn = make_ring_attention(sp_mesh, axis_name="sp")
    out = fn(q, k, v)
    assert out.dtype == jnp.bfloat16
    expected = _dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(expected), atol=0.05
    )


def test_ring_composes_with_dp(world):
    # 2-D mesh: batch over dp, sequence over sp — the composition the
    # long-context design requires.
    from jax.sharding import Mesh

    from fluxmpi_tpu.parallel.ring import make_ring_attention

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(batch=4, seq=16, seed=3)
    fn = make_ring_attention(mesh, axis_name="sp", batch_axis_name="dp")
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_grad_finite(sp_mesh):
    # differentiable end-to-end (ppermute has a transpose rule)
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _qkv(seq=16, seed=4)

    def loss(q, k, v):
        out = ring_attention(q, k, v, axis_name="sp")
        return jnp.sum(out**2)

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

    def per_device(q, k, v):
        l = loss(q, k, v)
        return jax.lax.psum(l, "sp")

    mapped = sm(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
        check_vma=False,
    )
    g = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v)))(q, k, v)
    assert np.all(np.isfinite(np.asarray(g)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(sp_mesh, causal):
    # Ring attention with the Pallas flash kernel as the local block attend
    # (VERDICT r1 next #3: the kernel wired into the ring).
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seq=64, seed=6)
    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=causal, use_flash=True
    )
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_flash_grad_matches_dense(sp_mesh):
    # The full ring+flash composition differentiates exactly: the lse
    # cotangent of each block flows through the plain-JAX merge into the
    # Pallas backward kernels.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

    q, k, v = _qkv(seq=32, seed=7)

    def per_device(q, k, v):
        out = ring_attention(q, k, v, axis_name="sp", causal=True,
                             use_flash=True)
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = sm(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
        check_vma=False,
    )
    gf = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v), argnums=(0, 1, 2)))(
        q, k, v
    )

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_attention(q, k, v, causal=True)))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_transformer_with_ring_attention(sp_mesh):
    # End-to-end sequence parallelism: a TransformerEncoder whose attention
    # runs on the ring matches the same encoder with dense attention.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.models import TransformerEncoder
    from fluxmpi_tpu.parallel.ring import ring_attention_fn

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

    d_model, seq = 32, 32
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(2, seq, d_model)).astype(np.float32)
    )
    dense_model = TransformerEncoder(
        num_layers=2, d_model=d_model, num_heads=4, d_ff=64
    )
    variables = dense_model.init(jax.random.PRNGKey(0), x, train=False)
    expected = dense_model.apply(variables, x, train=False)

    ring_model = TransformerEncoder(
        num_layers=2,
        d_model=d_model,
        num_heads=4,
        d_ff=64,
        attention_fn=ring_attention_fn(axis_name="sp"),
    )

    def apply_local(v, xx):
        return ring_model.apply(v, xx, train=False)

    mapped = sm(
        apply_local,
        mesh=sp_mesh,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    )
    out = jax.jit(mapped)(variables, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=3e-5
    )
