"""Ring attention vs dense attention oracle on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _dense_attention(q, k, v, causal=False, window=None):
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        mask = qpos >= kpos
        if window is not None:
            mask = mask & (qpos - kpos < window)
        scores = jnp.where(mask[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(batch=2, seq=32, heads=4, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, dim)
    return tuple(
        jnp.asarray(rng.normal(size=shape).astype(np.float32)) for _ in range(3)
    )


@pytest.fixture()
def sp_mesh(world):
    """A mesh with an sp axis for sequence parallelism."""
    import jax
    from jax.sharding import Mesh

    import numpy as np

    return Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("sp",))


def test_ring_matches_dense(sp_mesh):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv()
    fn = make_ring_attention(sp_mesh, axis_name="sp")
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_matches_dense_causal(sp_mesh):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seed=1)
    fn = make_ring_attention(sp_mesh, axis_name="sp", causal=True)
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_bf16(sp_mesh):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(seed=2))
    fn = make_ring_attention(sp_mesh, axis_name="sp")
    out = fn(q, k, v)
    assert out.dtype == jnp.bfloat16
    expected = _dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(expected), atol=0.05
    )


def test_ring_composes_with_dp(world):
    # 2-D mesh: batch over dp, sequence over sp — the composition the
    # long-context design requires.
    from jax.sharding import Mesh

    from fluxmpi_tpu.parallel.ring import make_ring_attention

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(batch=4, seq=16, seed=3)
    fn = make_ring_attention(mesh, axis_name="sp", batch_axis_name="dp")
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_grad_finite(sp_mesh):
    # differentiable end-to-end (ppermute has a transpose rule)
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _qkv(seq=16, seed=4)

    def loss(q, k, v):
        out = ring_attention(q, k, v, axis_name="sp")
        return jnp.sum(out**2)

    def per_device(q, k, v):
        l = loss(q, k, v)
        return jax.lax.psum(l, "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )
    g = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v)))(q, k, v)
    assert np.all(np.isfinite(np.asarray(g)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(sp_mesh, causal):
    # Ring attention with the Pallas flash kernel as the local block attend
    # (VERDICT r1 next #3: the kernel wired into the ring).
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seq=64, seed=6)
    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=causal, use_flash=True
    )
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_flash_grad_matches_dense(sp_mesh):
    # The full ring+flash composition differentiates exactly: the lse
    # cotangent of each block flows through the plain-JAX merge into the
    # Pallas backward kernels.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _qkv(seq=32, seed=7)

    def per_device(q, k, v):
        out = ring_attention(q, k, v, axis_name="sp", causal=True,
                             use_flash=True)
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )
    gf = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v), argnums=(0, 1, 2)))(
        q, k, v
    )

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_attention(q, k, v, causal=True)))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_transformer_with_ring_attention(sp_mesh):
    # End-to-end sequence parallelism: a TransformerEncoder whose attention
    # runs on the ring matches the same encoder with dense attention.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.models import TransformerEncoder
    from fluxmpi_tpu.parallel.ring import ring_attention_fn

    d_model, seq = 32, 32
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(2, seq, d_model)).astype(np.float32)
    )
    dense_model = TransformerEncoder(
        num_layers=2, d_model=d_model, num_heads=4, d_ff=64
    )
    variables = dense_model.init(jax.random.PRNGKey(0), x, train=False)
    expected = dense_model.apply(variables, x, train=False)

    ring_model = TransformerEncoder(
        num_layers=2,
        d_model=d_model,
        num_heads=4,
        d_ff=64,
        attention_fn=ring_attention_fn(axis_name="sp"),
    )

    def apply_local(v, xx):
        return ring_model.apply(v, xx, train=False)

    mapped = shard_map_unchecked(
        apply_local,
        mesh=sp_mesh,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(variables, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=3e-5
    )


# ---- segment masking + block threading + zigzag (VERDICT r2 next #5/#6) ----


from _oracles import dense_seg_attention as _dense_seg_attention  # noqa: E402

from fluxmpi_tpu.parallel._compat import shard_map_unchecked  # noqa: E402



@pytest.mark.parametrize("use_flash", [False, True])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_segments_match_dense(sp_mesh, causal, use_flash):
    # Packed/padded batches on the ring: segment ids rotate with the K/V
    # blocks; valid rows match the dense masked oracle.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _qkv(seq=64, seed=8)
    seg = np.ones((2, 64), np.int32)
    seg[0, :24] = 1
    seg[0, 24:56] = 2
    seg[0, 56:] = 0  # pad tail
    seg[1, :40] = 3
    seg[1, 40:] = 4
    seg = jnp.asarray(seg)

    def per_device(q, k, v, seg):
        return ring_attention(
            q, k, v, axis_name="sp", causal=causal,
            segment_ids=seg, use_flash=use_flash, block_q=8, block_k=8,
        )

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(q, k, v, seg)
    expected = _dense_seg_attention(q, k, v, seg, seg, causal=causal)
    ok = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_ring_flash_block_threading(world):
    # ADVICE r2 #2: local shards not divisible by the old fixed 128 blocks
    # used to fail at trace time with no tunable. Now block sizes are
    # auto-picked (a legal divisor of the shard), AND remain overridable via
    # block_q/block_k on the public API.
    from jax.sharding import Mesh

    from fluxmpi_tpu.parallel.ring import make_ring_attention

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("sp",))
    q, k, v = _qkv(seq=384, seed=9)  # local shard 192: not divisible by 128
    expected = _dense_attention(q, k, v)
    fn_auto = make_ring_attention(mesh, axis_name="sp", use_flash=True)
    out = fn_auto(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)
    fn = make_ring_attention(
        mesh, axis_name="sp", use_flash=True, block_q=64, block_k=64
    )
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("use_flash", [False, True])
def test_zigzag_matches_dense_causal(sp_mesh, use_flash):
    # The balanced causal schedule end-to-end (permute in → zigzag ring →
    # inverse permute out) against the plain dense causal oracle.
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seq=64, seed=10)
    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=use_flash,
        schedule="zigzag", block_q=4, block_k=4,
    )
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_zigzag_grad_matches_dense(sp_mesh):
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import zigzag_indices, zigzag_ring_attention

    q, k, v = _qkv(seq=64, seed=11)
    idxs = zigzag_indices(64, 8)
    inv = np.argsort(idxs)

    def per_device(q, k, v):
        out = zigzag_ring_attention(q, k, v, axis_name="sp")
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )

    def loss_zigzag(q, k, v):
        return mapped(q[:, idxs], k[:, idxs], v[:, idxs])

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_attention(q, k, v, causal=True)))

    gf = jax.jit(jax.grad(loss_zigzag, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_zigzag_schedule_balanced(world):
    # VERDICT r2 next #6 "test asserting per-tick compute balance": audit
    # the schedule spec the implementation mirrors — every device performs
    # identical FLOP weight on every tick (full = 1, diag = 1/2), and the
    # total equals the causal ideal (half the non-causal ring's work).
    from fluxmpi_tpu.parallel.ring import zigzag_tick_work

    cost = {"full": 1.0, "diag": 0.5}
    for n in (2, 4, 8):
        per_tick = {
            (i, s): sum(cost[kind] for _, _, kind in zigzag_tick_work(i, s, n))
            for i in range(n)
            for s in range(n)
        }
        assert len(set(per_tick.values())) == 1  # same work everywhere
        # chunk-sized attends: total per device = 2n half-chunk units; the
        # contiguous causal ring costs n full-block units = 4n halves on its
        # worst device.
        total = sum(v for (i, s), v in per_tick.items() if i == 0)
        assert total == 2 * n


def test_zigzag_indices_roundtrip(world):
    from fluxmpi_tpu.parallel.ring import zigzag_indices

    idxs = zigzag_indices(32, 4)
    assert sorted(idxs.tolist()) == list(range(32))
    x = np.arange(32)
    np.testing.assert_array_equal(x[idxs][np.argsort(idxs)], x)
    with pytest.raises(ValueError, match="divisible"):
        zigzag_indices(30, 4)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_model_inits_outside_shard_map(world, use_flash):
    # VERDICT r2 weak #6: module.init on a ring-attention model OUTSIDE the
    # shard_map must work (unbound axis → exact n=1 ring), not raise
    # NameError with a "dense twin" workaround.
    from fluxmpi_tpu.models import TransformerEncoder
    from fluxmpi_tpu.parallel.ring import ring_attention_fn

    model = TransformerEncoder(
        num_layers=1, d_model=32, num_heads=4, d_ff=64,
        attention_fn=ring_attention_fn(axis_name="sp", use_flash=use_flash),
    )
    x = jnp.asarray(
        np.random.default_rng(13).normal(size=(2, 32, 32)).astype(np.float32)
    )
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)  # n=1 ring == dense
    dense = TransformerEncoder(num_layers=1, d_model=32, num_heads=4, d_ff=64)
    expected = dense.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=3e-5
    )


def test_zigzag_unbound_axis_fallback(world):
    from fluxmpi_tpu.parallel.ring import zigzag_ring_attention

    q, k, v = _qkv(seq=32, seed=14)
    out = zigzag_ring_attention(q, k, v, axis_name="sp")
    expected = _dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


# ---- Ulysses (all-to-all) sequence parallelism ----


@pytest.mark.parametrize("use_flash", [False, True])
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(sp_mesh, causal, use_flash):
    from fluxmpi_tpu.parallel import make_ulysses_attention

    q, k, v = _qkv(seq=64, heads=8, seed=20)  # heads divisible by sp=8
    fn = make_ulysses_attention(
        sp_mesh, axis_name="sp", causal=causal, use_flash=use_flash
    )
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ulysses_segments_match_dense(sp_mesh):
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel import ulysses_attention

    q, k, v = _qkv(seq=64, heads=8, seed=21)
    seg = np.ones((2, 64), np.int32)
    seg[0, :24] = 1
    seg[0, 24:] = 2
    seg[1, 48:] = 0  # pad tail
    seg = jnp.asarray(seg)

    def per_device(q, k, v, seg):
        return ulysses_attention(
            q, k, v, axis_name="sp", segment_ids=seg
        )

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(q, k, v, seg)
    expected = _dense_seg_attention(q, k, v, seg, seg)
    ok = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_ulysses_grad_matches_dense(sp_mesh):
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel import ulysses_attention

    q, k, v = _qkv(seq=32, heads=8, seed=22)

    def per_device(q, k, v):
        out = ulysses_attention(q, k, v, axis_name="sp", causal=True)
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )
    gf = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v), argnums=(0, 1, 2)))(
        q, k, v
    )

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_attention(q, k, v, causal=True)))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    from fluxmpi_tpu.parallel import make_ulysses_attention

    q, k, v = _qkv(seq=64, heads=4, seed=23)  # 4 heads on sp=8
    fn = make_ulysses_attention(sp_mesh, axis_name="sp")
    with pytest.raises(ValueError, match="head count"):
        fn(q, k, v)


# ---- grouped-query attention through the SP layers (VERDICT r3 next #5) ----


def _gqa_qkv(batch=2, seq=64, heads=8, kv_heads=2, dim=16, seed=30):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(batch, seq, heads, dim)).astype(np.float32))
    k = jnp.asarray(
        rng.normal(size=(batch, seq, kv_heads, dim)).astype(np.float32)
    )
    v = jnp.asarray(
        rng.normal(size=(batch, seq, kv_heads, dim)).astype(np.float32)
    )
    return q, k, v


def _repeat_kv(t, h):
    return jnp.repeat(t, h // t.shape[2], axis=2)


@pytest.mark.parametrize("use_flash", [False, True])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_gqa_matches_dense(sp_mesh, causal, use_flash):
    # GQA on the contiguous ring: the rotating K/V blocks keep their h_kv
    # heads (the ICI saving is the point); output matches dense attention
    # on repeated KV heads.
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _gqa_qkv(seed=30)
    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=causal, use_flash=use_flash,
        block_q=8, block_k=8,
    )
    out = fn(q, k, v)
    expected = _dense_attention(
        q, _repeat_kv(k, 8), _repeat_kv(v, 8), causal=causal
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("use_flash", [False, True])
def test_zigzag_gqa_matches_dense(sp_mesh, use_flash):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _gqa_qkv(seed=31)
    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=use_flash,
        schedule="zigzag", block_q=4, block_k=4,
    )
    out = fn(q, k, v)
    expected = _dense_attention(
        q, _repeat_kv(k, 8), _repeat_kv(v, 8), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_gqa_grad_matches_dense(sp_mesh):
    # dK/dV must arrive group-summed, exactly as differentiating the
    # repeated-KV dense formulation produces.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _gqa_qkv(seq=32, seed=32)

    def per_device(q, k, v):
        out = ring_attention(q, k, v, axis_name="sp", causal=True,
                             use_flash=True, block_q=4, block_k=4)
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )
    gf = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v), argnums=(0, 1, 2)))(
        q, k, v
    )

    def loss_dense(q, k, v):
        out = _dense_attention(q, _repeat_kv(k, 8), _repeat_kv(v, 8),
                               causal=True)
        return jnp.sum(jnp.sin(out))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ulysses_gqa_matches_dense(sp_mesh, use_flash):
    # Ulysses GQA: each tensor's own head axis is all-to-all'd (8 q heads
    # and 8 kv heads won't both fit sp=8 with h_kv=2 — use a 2-device
    # submesh so h=8, h_kv=2 both divide).
    from jax.sharding import Mesh

    from fluxmpi_tpu.parallel import make_ulysses_attention

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("sp",))
    q, k, v = _gqa_qkv(seed=33)
    fn = make_ulysses_attention(
        mesh, axis_name="sp", causal=True, use_flash=use_flash
    )
    out = fn(q, k, v)
    expected = _dense_attention(
        q, _repeat_kv(k, 8), _repeat_kv(v, 8), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ulysses_gqa_grad_matches_dense(world):
    from jax.sharding import Mesh, PartitionSpec as P

    from fluxmpi_tpu.parallel import ulysses_attention

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("sp",))
    q, k, v = _gqa_qkv(seq=32, seed=34)

    def per_device(q, k, v):
        out = ulysses_attention(q, k, v, axis_name="sp", causal=True)
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )
    gf = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v), argnums=(0, 1, 2)))(
        q, k, v
    )

    def loss_dense(q, k, v):
        out = _dense_attention(q, _repeat_kv(k, 8), _repeat_kv(v, 8),
                               causal=True)
        return jnp.sum(jnp.sin(out))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ulysses_rejects_indivisible_kv_heads(sp_mesh):
    # ADVICE r3: GQA inputs whose kv head count doesn't divide the axis
    # used to die deep inside all_to_all with an opaque shape error.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel import ulysses_attention

    q, k, v = _gqa_qkv(seed=35)  # h=8 divides sp=8; h_kv=2 does not

    def per_device(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    with pytest.raises(ValueError, match="kv head count"):
        jax.jit(mapped)(q, k, v)


# ---- zigzag segment ids (VERDICT r3 next #4) ----


@pytest.mark.parametrize("use_flash", [False, True])
def test_zigzag_segments_match_dense(sp_mesh, use_flash):
    # Packed + padded batch through the balanced causal schedule: segment
    # ids ride the zigzag permutation with their tokens and rotate with
    # the K/V blocks. Valid rows match the dense masked causal oracle.
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seq=64, seed=36)
    seg = np.ones((2, 64), np.int32)
    seg[0, :24] = 1
    seg[0, 24:56] = 2
    seg[0, 56:] = 0  # pad tail
    seg[1, :40] = 3
    seg[1, 40:] = 4
    seg = jnp.asarray(seg)

    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=use_flash,
        schedule="zigzag", block_q=4, block_k=4,
    )
    out = fn(q, k, v, segment_ids=seg)
    expected = _dense_seg_attention(q, k, v, seg, seg, causal=True)
    ok = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_zigzag_segments_grad_matches_dense(sp_mesh):
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import zigzag_indices, zigzag_ring_attention

    q, k, v = _qkv(seq=64, seed=37)
    seg = np.ones((2, 64), np.int32)
    seg[0, 32:] = 2
    seg[1, 48:] = 0  # pad tail
    seg = jnp.asarray(seg)
    idxs = zigzag_indices(64, 8)

    def per_device(q, k, v, seg):
        out = zigzag_ring_attention(
            q, k, v, axis_name="sp", segment_ids=seg, block_q=4, block_k=4
        )
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )

    def loss_zigzag(q, k, v):
        return mapped(q[:, idxs], k[:, idxs], v[:, idxs], seg[:, idxs])

    def loss_dense(q, k, v):
        out = _dense_seg_attention(q, k, v, seg, seg, causal=True)
        # Padded rows produce garbage in the dense oracle (uniform
        # softmax); exclude them from the loss so grads compare cleanly.
        valid = (np.asarray(seg) != 0)[:, :, None, None]
        return jnp.sum(jnp.where(valid, jnp.sin(out), 0.0))

    gf = jax.jit(jax.grad(loss_zigzag, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ulysses_unbound_axis_fallback(world):
    from fluxmpi_tpu.parallel import ulysses_attention

    q, k, v = _qkv(seq=32, heads=8, seed=24)
    out = ulysses_attention(q, k, v, axis_name="sp", causal=True)
    expected = _dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


# ---- sliding-window attention through the SP layers ----


def test_ring_window_matches_dense(sp_mesh):
    # Windowed causal attention on the dense ring: global-position masks
    # span block boundaries (window 12 > local shard 8 reaches into the
    # previous device's block).
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seed=50)
    fn = make_ring_attention(sp_mesh, axis_name="sp", causal=True, window=12)
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=True, window=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ulysses_window_matches_dense(sp_mesh, use_flash):
    # Ulysses sees the full sequence locally, so the flash kernel's window
    # (and its O(seq·window) tile skip) applies directly.
    from fluxmpi_tpu.parallel import make_ulysses_attention

    q, k, v = _qkv(seq=64, heads=8, seed=51)
    fn = make_ulysses_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=use_flash, window=16
    )
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_window_zigzag_rejected(sp_mesh):
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    with pytest.raises(ValueError, match="zigzag"):
        make_ring_attention(
            sp_mesh, axis_name="sp", causal=True, schedule="zigzag", window=8
        )


@pytest.mark.parametrize("window", [3, 12, 100])
def test_ring_window_flash_matches_dense(sp_mesh, window):
    # Windowed causal attention on the FLASH ring (VERDICT r4 next #8):
    # the diag tick runs causal+window, each live past tick the band-only
    # kernel mask with the static per-tick displacement folded into the
    # window. Cases: window inside one shard (3), spanning shards (12),
    # covering the whole sequence (100 ≡ plain causal).
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seed=52)
    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=True,
        window=window, block_q=4, block_k=4,
    )
    out = fn(q, k, v)
    expected = _dense_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_window_flash_grad_matches_dense(sp_mesh):
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _qkv(seq=32, seed=53)

    def per_device(q, k, v):
        out = ring_attention(q, k, v, axis_name="sp", causal=True,
                             use_flash=True, window=10)
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "sp")

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(),
    )
    gf = jax.jit(jax.grad(lambda q, k, v: mapped(q, k, v), argnums=(0, 1, 2)))(
        q, k, v
    )

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_attention(q, k, v, causal=True,
                                                window=10)))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_window_flash_segments_match_dense(sp_mesh):
    # Window + packed/padded segments on the flash ring: the band-only
    # past-tick masks must AND with the rotated segment masks.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _qkv(seq=64, seed=55)
    seg = np.ones((2, 64), np.int32)
    seg[0, :24] = 1
    seg[0, 24:56] = 2
    seg[0, 56:] = 0  # pad tail
    seg[1, :40] = 3
    seg[1, 40:] = 4
    seg = jnp.asarray(seg)

    def per_device(q, k, v, seg):
        return ring_attention(
            q, k, v, axis_name="sp", causal=True, window=14,
            segment_ids=seg, use_flash=True, block_q=8, block_k=8,
        )

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(q, k, v, seg)
    expected = _dense_seg_attention(q, k, v, seg, seg, causal=True, window=14)
    ok = np.asarray(seg) != 0
    np.testing.assert_allclose(
        np.asarray(out)[ok], np.asarray(expected)[ok], atol=2e-5
    )


def test_ring_window_flash_dropout_matches_oracle(sp_mesh):
    # Window + in-kernel dropout on the flash ring: same exact oracle as
    # test_ring_flash_dropout_matches_oracle, with the causal+window band
    # on the scores. Only attended (device, tick) blocks contribute keep
    # masks; every entry of a never-attended block is outside the band, so
    # seeding those keep entries True leaves their zero weights untouched.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.ops.flash_attention import _dropout_keep
    from fluxmpi_tpu.parallel.ring import _fold_seed, ring_attention

    n, b, S, h, d = 8, 2, 64, 2, 16
    sq = S // n
    window = 20
    rate, kp, seed = 0.3, 0.7, 78
    q, k, v = _qkv(batch=b, seq=S, heads=h, dim=d, seed=82)

    def per_device(q, k, v):
        return ring_attention(
            q, k, v, axis_name="sp", causal=True, window=window,
            use_flash=True, block_q=8, block_k=8,
            dropout_rate=rate, dropout_seed=seed,
        )

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(q, k, v)

    # Keep masks for the ticks the windowed schedule attends: the diag
    # (s=0) and past ticks while the band lives; src = i - s (no mod).
    q_loc = jnp.broadcast_to(jnp.arange(sq)[:, None], (sq, sq))
    k_loc = jnp.broadcast_to(jnp.arange(sq)[None, :], (sq, sq))
    keep = np.ones((b, h, S, S), bool)
    for i in range(n):
        for s in range(n):
            if s > 0 and window - s * sq <= 1 - sq:
                break  # schedule stops rotating here
            if i < s:
                continue  # future block: causal-dead, never attended
            src = i - s
            blk_seed = _fold_seed(seed, i, src)
            km = jax.vmap(
                lambda bh: _dropout_keep(blk_seed, bh, q_loc, k_loc, kp)
            )(jnp.arange(b * h, dtype=jnp.uint32)).reshape(b, h, sq, sq)
            keep[:, :, i * sq:(i + 1) * sq, src * sq:(src + 1) * sq] = (
                np.asarray(km)
            )

    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    qpos = jnp.arange(S)[:, None]
    kpos = jnp.arange(S)[None, :]
    band = (qpos >= kpos) & (qpos - kpos < window)
    sc = jnp.where(band[None, None], sc, -1e30)
    w = jax.nn.softmax(sc, axis=-1)
    w = jnp.where(band[None, None], w, 0.0)
    w = jnp.where(jnp.asarray(keep), w / kp, 0.0)
    expected = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5
    )


def test_ring_window_flash_gqa_matches_dense(sp_mesh):
    # Window + GQA through the flash ring: rotating blocks keep h_kv
    # heads, the band-only past-tick masks must compose with the kernel's
    # grouped kv row mapping.
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    rng = np.random.default_rng(54)
    b, S, h, h_kv, d = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(b, S, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, S, h_kv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, S, h_kv, d)).astype(np.float32))
    fn = make_ring_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=True,
        window=9, block_q=4, block_k=4,
    )
    out = fn(q, k, v)
    kx = jnp.repeat(k, h // h_kv, axis=2)
    vx = jnp.repeat(v, h // h_kv, axis=2)
    expected = _dense_attention(q, kx, vx, causal=True, window=9)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


# ---- attention dropout through the SP layers ----


def test_ring_flash_dropout_matches_oracle(sp_mesh):
    # Exact oracle: rebuild every (device, tick) block's hash mask at the
    # JAX level and compare the ring output against global dense attention
    # with undropped softmax normalization and the dropped numerator —
    # the lse-merge must compose dropout exactly.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.ops.flash_attention import _dropout_keep
    from fluxmpi_tpu.parallel.ring import _fold_seed, ring_attention

    n, b, S, h, d = 8, 2, 64, 2, 16
    sq = S // n
    rate, kp, seed = 0.3, 0.7, 77
    q, k, v = _qkv(batch=b, seq=S, heads=h, dim=d, seed=80)

    def per_device(q, k, v):
        return ring_attention(
            q, k, v, axis_name="sp", use_flash=True,
            block_q=8, block_k=8, dropout_rate=rate, dropout_seed=seed,
        )

    mapped = shard_map_unchecked(
        per_device,
        mesh=sp_mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(q, k, v)

    # Assemble the global keep mask block by block.
    q_loc = jnp.broadcast_to(jnp.arange(sq)[:, None], (sq, sq))
    k_loc = jnp.broadcast_to(jnp.arange(sq)[None, :], (sq, sq))
    keep = np.zeros((b, h, S, S), bool)
    for i in range(n):
        for s in range(n):
            src = (i - s) % n
            blk_seed = _fold_seed(seed, i, src)
            km = jax.vmap(
                lambda bh: _dropout_keep(blk_seed, bh, q_loc, k_loc, kp)
            )(jnp.arange(b * h, dtype=jnp.uint32)).reshape(b, h, sq, sq)
            keep[:, :, i * sq:(i + 1) * sq, src * sq:(src + 1) * sq] = (
                np.asarray(km)
            )

    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    w = jax.nn.softmax(sc, axis=-1)
    w = jnp.where(jnp.asarray(keep), w / kp, 0.0)
    expected = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5
    )


@pytest.mark.parametrize("layer", ["zigzag", "ulysses"])
def test_sp_dropout_statistics(sp_mesh, layer):
    # Deterministic per seed, seed changes the mask, mean over seeds
    # approaches the undropped output (unbiasedness) — for the layers
    # whose per-attend seed bookkeeping makes an exact oracle unwieldy.
    # The mapped fn takes the seed as a TRACED scalar: one compile total.
    from jax.sharding import PartitionSpec as P

    from fluxmpi_tpu.parallel import ulysses_attention
    from fluxmpi_tpu.parallel.ring import (
        make_ring_attention, zigzag_indices, zigzag_ring_attention,
    )
    from fluxmpi_tpu.parallel import make_ulysses_attention

    q, k, v = _qkv(seq=64, heads=8, seed=81)
    rate = 0.25

    if layer == "zigzag":
        idxs = zigzag_indices(64, 8)
        inv = np.argsort(idxs)
        mapped = shard_map_unchecked(
            lambda q, k, v, seed: zigzag_ring_attention(
                q, k, v, axis_name="sp", use_flash=True,
                block_q=4, block_k=4,
                dropout_rate=rate, dropout_seed=seed,
            ),
            mesh=sp_mesh,
            in_specs=(P(None, "sp"),) * 3 + (P(),),
            out_specs=P(None, "sp"),
        )
        jitted = jax.jit(mapped)

        def run(seed):
            return np.asarray(
                jitted(q[:, idxs], k[:, idxs], v[:, idxs],
                       jnp.uint32(seed))[:, inv]
            )

        clean = np.asarray(make_ring_attention(
            sp_mesh, axis_name="sp", causal=True, use_flash=True,
            schedule="zigzag", block_q=4, block_k=4,
        )(q, k, v))
    else:
        mapped = shard_map_unchecked(
            lambda q, k, v, seed: ulysses_attention(
                q, k, v, axis_name="sp", causal=True, use_flash=True,
                dropout_rate=rate, dropout_seed=seed,
            ),
            mesh=sp_mesh,
            in_specs=(P(None, "sp"),) * 3 + (P(),),
            out_specs=P(None, "sp"),
        )
        jitted = jax.jit(mapped)

        def run(seed):
            return np.asarray(jitted(q, k, v, jnp.uint32(seed)))

        clean = np.asarray(
            make_ulysses_attention(
                sp_mesh, axis_name="sp", causal=True, use_flash=True
            )(q, k, v)
        )

    a1, a1b, a2 = run(1), run(1), run(2)
    np.testing.assert_array_equal(a1, a1b)
    assert np.abs(a1 - a2).max() > 1e-3
    acc = np.zeros_like(clean)
    nseeds = 24
    for s in range(nseeds):
        acc += run(100 + s)
    # Unbiasedness on rows with enough attendable keys for the seed-mean
    # to concentrate (early causal rows attend 1-2 keys — at any rate
    # their single-mask variance dominates a 24-seed average).
    np.testing.assert_allclose(
        (acc / nseeds)[:, 16:], clean[:, 16:], atol=0.3
    )


def test_sp_dropout_wrappers(sp_mesh):
    # The eager wrappers and flax adapters expose dropout end to end.
    from fluxmpi_tpu.parallel import make_ulysses_attention
    from fluxmpi_tpu.parallel.ring import make_ring_attention

    q, k, v = _qkv(seq=64, heads=8, seed=83)
    fn_u = make_ulysses_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=True,
        dropout_rate=0.2,
    )
    o1 = np.asarray(fn_u(q, k, v, dropout_seed=5))
    o2 = np.asarray(fn_u(q, k, v, dropout_seed=5))
    o3 = np.asarray(fn_u(q, k, v, dropout_seed=6))
    np.testing.assert_array_equal(o1, o2)
    assert np.abs(o1 - o3).max() > 1e-3
    with pytest.raises(ValueError, match="dropout_seed"):
        fn_u(q, k, v)

    fn_z = make_ring_attention(
        sp_mesh, axis_name="sp", causal=True, use_flash=True,
        schedule="zigzag", block_q=4, block_k=4, dropout_rate=0.2,
    )
    z1 = np.asarray(fn_z(q, k, v, dropout_seed=5))
    z2 = np.asarray(fn_z(q, k, v, dropout_seed=5))
    np.testing.assert_array_equal(z1, z2)
    with pytest.raises(ValueError, match="use_flash"):
        make_ring_attention(sp_mesh, axis_name="sp", dropout_rate=0.2)

    # flax adapter path: module with dropout trains through the ring.
    import flax.linen as nn

    from fluxmpi_tpu.models import TransformerEncoder
    from fluxmpi_tpu.parallel.ring import ring_attention_fn
    from jax.sharding import PartitionSpec as P

    model = TransformerEncoder(
        num_layers=1, d_model=32, num_heads=4, d_ff=64, dropout=0.1,
        attention_fn=ring_attention_fn(
            axis_name="sp", causal=True, use_flash=True, block_q=8,
            block_k=8,
        ),
    )
    x = jnp.asarray(
        np.random.default_rng(84).normal(size=(2, 64, 32)).astype(np.float32)
    )
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=True,
    )

    mapped = shard_map_unchecked(
        lambda v_, xx, key: model.apply(
            v_, xx, train=True, rngs={"dropout": key}
        ),
        mesh=sp_mesh,
        in_specs=(P(), P(None, "sp"), P()),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(mapped)(variables, x, jax.random.PRNGKey(2))
    assert np.all(np.isfinite(np.asarray(out)))


def test_sp_dropout_requires_flash(sp_mesh):
    from fluxmpi_tpu.parallel.ring import ring_attention

    q, k, v = _qkv(seed=82)
    with pytest.raises(ValueError, match="use_flash"):
        ring_attention(
            q, k, v, axis_name="sp", dropout_rate=0.1, dropout_seed=0
        )
