"""Data sharding tests (reference: test/test_data.jl)."""

import numpy as np
import pytest

import jax


class _ArrayDataset:
    def __init__(self, xs, ys):
        self.xs, self.ys = xs, ys

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return self.xs[i], self.ys[i]


def test_shard_lengths(world):
    # reference: test/test_data.jl:15-20 — ceil shards, remainder on last
    import fluxmpi_tpu as fm

    data = list(range(27))
    world_size = 4
    lengths = [
        len(fm.DistributedDataContainer(data, rank=r, world=world_size))
        for r in range(world_size)
    ]
    assert lengths == [7, 7, 7, 6]


def test_shard_contiguity(world):
    import fluxmpi_tpu as fm

    data = list(range(10))
    shard0 = list(fm.DistributedDataContainer(data, rank=0, world=3))
    shard1 = list(fm.DistributedDataContainer(data, rank=1, world=3))
    shard2 = list(fm.DistributedDataContainer(data, rank=2, world=3))
    assert shard0 == [0, 1, 2, 3]
    assert shard1 == [4, 5, 6, 7]
    assert shard2 == [8, 9]


def test_shard_sum_conservation(world):
    # reference: test/test_data.jl:22-26 — allreduce of shard sums == total
    import fluxmpi_tpu as fm

    rng = np.random.default_rng(0)
    data = rng.normal(size=64).tolist()
    world_size = 8
    shard_sums = np.array(
        [
            sum(fm.DistributedDataContainer(data, rank=r, world=world_size))
            for r in range(world_size)
        ],
        dtype=np.float64,
    )
    # the device-collective version of the oracle
    reduced = fm.unshard_ranks(
        fm.allreduce(shard_sums.astype(np.float32).reshape(world_size, 1), "+")
    )
    np.testing.assert_allclose(reduced[0, 0], sum(data), rtol=1e-5)
    np.testing.assert_allclose(shard_sums.sum(), sum(data))


def test_empty_shard_raises(world):
    # reference: BoundsError when a rank has no partition
    import fluxmpi_tpu as fm

    with pytest.raises(IndexError):
        fm.DistributedDataContainer(list(range(3)), rank=5, world=8)


def test_default_process_world(world):
    # single controller process → the whole dataset
    import fluxmpi_tpu as fm

    data = list(range(12))
    ddc = fm.DistributedDataContainer(data)
    assert len(ddc) == 12
    assert ddc.rank == 0 and ddc.world == 1


def test_loader_shapes_and_sharding(world):
    import fluxmpi_tpu as fm

    n = 64
    xs = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    ys = np.arange(n, dtype=np.float32)
    ds = _ArrayDataset(xs, ys)
    loader = fm.DistributedDataLoader(ds, global_batch_size=16)
    batches = list(loader)
    assert len(batches) == 4
    bx, by = batches[0]
    assert bx.shape == (16, 3) and by.shape == (16,)
    # batch laid out over the dp mesh axis: 8 shards of 2
    assert len(bx.sharding.device_set) == 8


def test_loader_shuffle_deterministic(world):
    import fluxmpi_tpu as fm

    n = 32
    xs = np.arange(n, dtype=np.float32).reshape(n, 1)
    ds = _ArrayDataset(xs, xs)
    l1 = fm.DistributedDataLoader(ds, 8, shuffle=True, seed=42)
    l2 = fm.DistributedDataLoader(ds, 8, shuffle=True, seed=42)
    b1 = np.asarray(next(iter(l1))[0])
    b2 = np.asarray(next(iter(l2))[0])
    np.testing.assert_array_equal(b1, b2)
    # second epoch reshuffles
    b1_e2 = np.asarray(next(iter(l1))[0])
    assert not np.array_equal(b1, b1_e2)


def test_loader_batch_divisibility(world):
    import fluxmpi_tpu as fm

    ds = _ArrayDataset(np.ones((32, 2)), np.ones((32,)))
    loader = fm.DistributedDataLoader(ds, 8)
    assert len(loader) == 4
    # batch not divisible by the dp axis → clear error, not an XLA failure
    with pytest.raises(ValueError, match="divisible"):
        fm.DistributedDataLoader(ds, 5)


def test_loader_with_container(world):
    # container + loader compose: per-process shard feeding global batches
    import fluxmpi_tpu as fm

    n = 40
    xs = np.arange(n, dtype=np.float32).reshape(n, 1)
    ds = _ArrayDataset(xs, xs)
    ddc = fm.DistributedDataContainer(ds)  # world of 1 process → all data
    loader = fm.DistributedDataLoader(ddc, 8)
    total = sum(np.asarray(b[0]).sum() for b in loader)
    np.testing.assert_allclose(total, xs.sum())


def test_array_dataset_fast_path(world):
    # ArrayDataset batches via the native gather must equal the generic path
    import fluxmpi_tpu as fm

    rng = np.random.default_rng(7)
    xs = rng.normal(size=(64, 5)).astype(np.float32)
    ys = rng.normal(size=(64,)).astype(np.float32)

    ads = fm.ArrayDataset({"x": xs, "y": ys})
    assert len(ads) == 64
    loader_fast = fm.DistributedDataLoader(ads, 16, shuffle=True, seed=3)

    class Generic:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return {"x": xs[i], "y": ys[i]}

    loader_slow = fm.DistributedDataLoader(Generic(), 16, shuffle=True, seed=3)
    for fast, slow in zip(loader_fast, loader_slow):
        np.testing.assert_array_equal(np.asarray(fast["x"]), np.asarray(slow["x"]))
        np.testing.assert_array_equal(np.asarray(fast["y"]), np.asarray(slow["y"]))


def test_array_dataset_in_container(world):
    import fluxmpi_tpu as fm

    xs = np.arange(40, dtype=np.float32).reshape(40, 1)
    ads = fm.ArrayDataset((xs,))
    ddc = fm.DistributedDataContainer(ads)
    loader = fm.DistributedDataLoader(ddc, 8)
    total = sum(float(np.asarray(b[0]).sum()) for b in loader)
    np.testing.assert_allclose(total, xs.sum())


def test_array_dataset_validation(world):
    import fluxmpi_tpu as fm

    with pytest.raises(ValueError):
        fm.ArrayDataset({"a": np.ones((3,)), "b": np.ones((4,))})
    with pytest.raises(ValueError):
        fm.ArrayDataset({})


def test_prefetch_queue_stays_ahead(world):
    # VERDICT r3 next #2: the device-side prefetch stage must run the batch
    # source AHEAD of the consumer, so each global batch's host→device
    # transfer is in flight while the previous step executes.
    import fluxmpi_tpu as fm

    xs = np.arange(64, dtype=np.float32).reshape(64, 1)
    loader = fm.DistributedDataLoader(fm.ArrayDataset((xs,)), 8, prefetch=2)
    pulled = []
    orig = loader._iter_batches

    def spy():
        for i, b in enumerate(orig()):
            pulled.append(i)
            yield b

    loader._iter_batches = spy
    it = iter(loader)
    next(it)
    # Consumer holds batch 0; the source has already produced (= initiated
    # transfer of) the next `prefetch` batches.
    assert len(pulled) == 3
    next(it)
    assert len(pulled) == 4
    # Full drain still yields every batch exactly once.
    rest = list(it)
    assert len(rest) == 8 - 2
    assert pulled == list(range(8))


def test_prefetch_zero_is_on_demand(world):
    import fluxmpi_tpu as fm

    xs = np.arange(32, dtype=np.float32).reshape(32, 1)
    loader = fm.DistributedDataLoader(fm.ArrayDataset((xs,)), 8, prefetch=0)
    pulled = []
    orig = loader._iter_batches

    def spy():
        for i, b in enumerate(orig()):
            pulled.append(i)
            yield b

    loader._iter_batches = spy
    it = iter(loader)
    next(it)
    assert len(pulled) == 1
    assert len(list(it)) == 3

    with pytest.raises(ValueError, match="prefetch"):
        fm.DistributedDataLoader(fm.ArrayDataset((xs,)), 8, prefetch=-1)


def test_prefetch_matches_unprefetched(world):
    # Same batches, same order, same values — prefetch only changes timing.
    import fluxmpi_tpu as fm

    rng = np.random.default_rng(11)
    xs = rng.normal(size=(48, 3)).astype(np.float32)
    a = fm.DistributedDataLoader(
        fm.ArrayDataset((xs,)), 8, shuffle=True, seed=5, prefetch=2
    )
    b = fm.DistributedDataLoader(
        fm.ArrayDataset((xs,)), 8, shuffle=True, seed=5, prefetch=0
    )
    for ba, bb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(ba[0]), np.asarray(bb[0]))


def test_global_shuffle_covers_and_reshards(world):
    # Global shuffle: the union of every simulated rank's epoch is exactly
    # the dataset (conservation), the assignment CHANGES across epochs
    # (unlike fixed shards), every rank computes the same permutation
    # (determinism), and batch counts stay in lockstep.
    import fluxmpi_tpu as fm

    n, w = 32, 4
    xs = np.arange(n, dtype=np.float32).reshape(n, 1)
    ds = fm.ArrayDataset((xs,))

    def epoch_values(rank, epoch_skip=0):
        cont = fm.DistributedDataContainer(ds, rank=rank, world=w)
        loader = fm.DistributedDataLoader(
            cont, 8, global_shuffle=True, seed=9, prefetch=0
        )
        for _ in range(epoch_skip):
            for _ in loader:
                pass
        return np.concatenate(
            [np.asarray(b[0]).ravel() for b in loader]
        )

    e0 = [epoch_values(r) for r in range(w)]
    assert sorted(np.concatenate(e0).tolist()) == xs.ravel().tolist()
    # Epoch 1 assigns rank 0 a different slice than epoch 0.
    e1_rank0 = epoch_values(0, epoch_skip=1)
    assert not np.array_equal(np.sort(e0[0]), np.sort(e1_rank0))
    # Same seed, same rank → identical epoch.
    np.testing.assert_array_equal(e0[1], epoch_values(1))
    # Lockstep batch counts across ranks.
    counts = {
        len(list(fm.DistributedDataLoader(
            fm.DistributedDataContainer(ds, rank=r, world=w), 8,
            global_shuffle=True, prefetch=0,
        ))) for r in range(w)
    }
    assert len(counts) == 1

    with pytest.raises(ValueError, match="global_shuffle"):
        fm.DistributedDataLoader(ds, 8, global_shuffle=True)


def test_global_shuffle_generic_dataset(world):
    # The non-array (generic __getitem__) path takes the same permuted
    # slice.
    import fluxmpi_tpu as fm

    class Generic:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return np.float32(i)

    cont = fm.DistributedDataContainer(Generic(), rank=0, world=2)
    loader = fm.DistributedDataLoader(
        cont, 8, global_shuffle=True, seed=3, prefetch=0
    )
    vals = np.concatenate([np.asarray(b).ravel() for b in loader])
    perm = np.random.default_rng(3).permutation(16)
    np.testing.assert_array_equal(vals, perm[:8].astype(np.float32))


def test_set_epoch_reproduces_resumed_shuffle(world):
    # Resume reproducibility: a fresh loader pinned to epoch k yields the
    # same batches the original loader produced on its k-th epoch — for
    # both per-shard shuffle and global shuffle.
    import fluxmpi_tpu as fm

    xs = np.arange(48, dtype=np.float32).reshape(48, 1)

    for kwargs in (dict(shuffle=True), dict(global_shuffle=True)):
        def make():
            data = fm.ArrayDataset((xs,))
            if "global_shuffle" in kwargs:
                data = fm.DistributedDataContainer(data)
            return fm.DistributedDataLoader(
                data, 8, seed=4, prefetch=0, **kwargs
            )

        original = make()
        epochs = [
            [np.asarray(b[0]).ravel() for b in original] for _ in range(3)
        ]
        resumed = make()
        resumed.set_epoch(2)
        resumed_epoch = [np.asarray(b[0]).ravel() for b in resumed]
        for a, b in zip(epochs[2], resumed_epoch):
            np.testing.assert_array_equal(a, b)
        # and it is genuinely epoch-dependent
        assert not all(
            np.array_equal(a, b) for a, b in zip(epochs[0], epochs[2])
        )


def test_scan_batches_feeds_scan_steps(world):
    # Loader-side half of multi-step dispatch: scan_batches(loader, k)
    # stacks k consecutive global batches on a leading scan axis
    # (P(None, dp)), the ragged tail group is dropped, and the result
    # drives make_train_step(scan_steps=k) directly.
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import fluxmpi_tpu as fm
    from fluxmpi_tpu.data import scan_batches
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate

    xs = np.arange(56, dtype=np.float32).reshape(56, 1)
    ys = xs * 2.0
    loader = fm.DistributedDataLoader(
        fm.ArrayDataset((xs, ys)), 8, prefetch=0
    )
    groups = list(scan_batches(loader, 3))
    # 7 batches of 8 -> 2 full groups of 3, tail dropped.
    assert len(groups) == 2
    gx, gy = groups[0]
    assert gx.shape == (3, 8, 1)
    assert gx.sharding.spec == P(None, "dp")
    # Content: consecutive loader batches in order.
    np.testing.assert_array_equal(np.asarray(gx).ravel(), xs[:24].ravel())

    model = MLP(features=(4, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 1)))
    opt = optax.sgd(0.01)

    def loss_fn(p, ms, batch):
        bx, by = batch
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    step = make_train_step(loss_fn, opt, style="auto", donate=False,
                           scan_steps=3)
    state = replicate(TrainState.create(params, opt))
    state, losses = step(state, groups[0])
    assert losses.shape == (3,)
    assert int(state.step) == 3


def test_transform_applied_on_both_assembly_paths(world):
    # The host-side transform hook runs on the generic per-sample path
    # AND the native C++ gather path, before the device transfer.
    import fluxmpi_tpu as fm

    n = 32
    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    y = np.arange(n, dtype=np.int32)

    def normalize(batch):
        bx, by = batch
        return (bx / 10.0, by)

    # Generic path (plain indexable dataset).
    class Plain:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return (x[i], y[i])

    for data in (Plain(), fm.ArrayDataset((x, y))):
        loader = fm.DistributedDataLoader(
            data, global_batch_size=8, prefetch=0, transform=normalize)
        bx, by = next(iter(loader))
        np.testing.assert_allclose(
            np.asarray(bx)[:, 0], np.arange(8, dtype=np.float32) / 10.0)
        np.testing.assert_array_equal(np.asarray(by), np.arange(8))


def test_transform_rng_deterministic_and_resumable(world):
    import fluxmpi_tpu as fm

    n = 16
    x = np.zeros((n, 2), np.float32)

    def jitter(batch, rng):
        return batch + rng.normal(size=batch.shape).astype(np.float32)

    def batches(epoch):
        loader = fm.DistributedDataLoader(
            fm.ArrayDataset(x), global_batch_size=8, prefetch=0,
            seed=3, transform=jitter)
        loader.set_epoch(epoch)
        return [np.asarray(b) for b in loader]

    a, b = batches(4), batches(4)
    for u, v in zip(a, b):
        np.testing.assert_array_equal(u, v)  # resume-stable
    c = batches(5)
    assert not np.allclose(a[0], c[0])  # epoch changes the draw
    assert not np.allclose(a[0], a[1])  # batch index changes the draw


def test_transform_must_preserve_batch_dim(world):
    import fluxmpi_tpu as fm

    x = np.zeros((16, 2), np.float32)
    loader = fm.DistributedDataLoader(
        fm.ArrayDataset(x), global_batch_size=8, prefetch=0,
        transform=lambda b: b[:4])
    with pytest.raises(ValueError, match="leading"):
        next(iter(loader))

    with pytest.raises(ValueError, match="callable"):
        fm.DistributedDataLoader(
            fm.ArrayDataset(x), global_batch_size=8, transform=42)


def test_transform_arity_ignores_defaulted_params(world):
    # f(batch, eps=1e-6) / f(batch, *, training=False) are 1-arg
    # transforms — defaulted or keyword-only params must not trigger the
    # rng call shape.
    import fluxmpi_tpu as fm

    x = np.ones((16, 2), np.float32)

    def with_default(batch, eps=100.0):
        return batch + eps  # would explode if eps received a Generator

    def with_kwonly(batch, *, training=False):
        assert training is False
        return batch

    for t in (with_default, with_kwonly):
        loader = fm.DistributedDataLoader(
            fm.ArrayDataset(x), global_batch_size=8, prefetch=0,
            transform=t)
        b = np.asarray(next(iter(loader)))
        assert np.isfinite(b).all()

    # A transform emitting a 0-d leaf gets the clear error, not an
    # IndexError from the validator itself.
    loader = fm.DistributedDataLoader(
        fm.ArrayDataset(x), global_batch_size=8, prefetch=0,
        transform=lambda b: {"x": b, "mean": float(b.mean())})
    with pytest.raises(ValueError, match="leading"):
        next(iter(loader))


# ---------------------------------------------------------------------------
# Steady-state hot path (PR 4): cached batch sharding and the device-side
# gather fast path.
# ---------------------------------------------------------------------------


def _arrays(n=256, feat=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, feat)).astype(np.float32)
    y = (np.arange(n) % 7).astype(np.int32)
    return x, y


def test_loader_sharding_is_memoized(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    x, y = _arrays()
    loader = DistributedDataLoader(ArrayDataset((x, y)), 32, mesh=world)
    assert loader._sharding() is loader._sharding()


def test_loader_batches_carry_constant_sharding_across_epoch(world):
    # Recompilation guard: every batch of an epoch (and the next epoch)
    # carries the SAME sharding object, so a jitted consumer never sees a
    # fresh sharding to re-hash — for both the host and device-gather
    # paths.
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    x, y = _arrays()
    for dg in (False, True):
        loader = DistributedDataLoader(
            ArrayDataset((x, y)), 32, mesh=world, device_gather=dg
        )
        seen = set()
        for _ in range(2):
            for bx, _by in loader:
                seen.add(id(bx.sharding))
        assert len(seen) == 1, f"device_gather={dg}"


def test_device_gather_matches_host_path(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    x, y = _arrays()
    host = DistributedDataLoader(
        ArrayDataset((x, y)), 32, mesh=world, device_gather=False,
        shuffle=True, seed=7,
    )
    dev = DistributedDataLoader(
        ArrayDataset((x, y)), 32, mesh=world, device_gather=True,
        shuffle=True, seed=7,
    )
    hb, db = list(host), list(dev)
    assert len(hb) == len(db) == 8
    for (hx, hy), (dx, dy) in zip(hb, db):
        np.testing.assert_array_equal(np.asarray(hx), np.asarray(dx))
        np.testing.assert_array_equal(np.asarray(hy), np.asarray(dy))
        assert dx.sharding.is_equivalent_to(hx.sharding, dx.ndim)


def test_device_gather_stages_once_and_never_retraces(world):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    x, y = _arrays()
    loader = DistributedDataLoader(
        ArrayDataset((x, y)), 32, mesh=world, device_gather=True,
        shuffle=True,
    )
    for _ in loader:
        pass
    cache1 = loader._gather_cache
    assert cache1 is not None
    for _ in loader:  # second epoch: new permutation, same staging
        pass
    assert loader._gather_cache is cache1
    gather_fn = cache1[3]
    # One trace covers every batch of every epoch (start is a traced
    # scalar, the permutation a same-shape array).
    assert gather_fn._cache_size() == 1


def test_device_gather_ragged_tail_and_container(world):
    from fluxmpi_tpu.data import (
        ArrayDataset,
        DistributedDataContainer,
        DistributedDataLoader,
    )

    x, y = _arrays(104)
    ds = DistributedDataContainer(ArrayDataset((x, y)))
    loader = DistributedDataLoader(
        ds, 24, mesh=world, device_gather=True, drop_last=False
    )
    sizes = [np.asarray(bx).shape[0] for bx, _ in loader]
    assert sizes == [24, 24, 24, 24, 8]
    # Content parity with the host path, tail included.
    host = DistributedDataLoader(
        DistributedDataContainer(ArrayDataset((x, y))), 24, mesh=world,
        device_gather=False, drop_last=False,
    )
    for (hx, hy), (dx, dy) in zip(host, loader):
        np.testing.assert_array_equal(np.asarray(hx), np.asarray(dx))
        np.testing.assert_array_equal(np.asarray(hy), np.asarray(dy))


def test_device_gather_validation_and_auto_fallbacks(world, monkeypatch):
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader

    x, y = _arrays()
    with pytest.raises(ValueError, match="device_gather"):
        DistributedDataLoader(
            ArrayDataset((x, y)), 32, mesh=world, device_gather="yes"
        )
    # True + transform: transforms are host-side — loud error, not a
    # silent fallback.
    with pytest.raises(ValueError, match="transform"):
        DistributedDataLoader(
            ArrayDataset((x, y)), 32, mesh=world, device_gather=True,
            transform=lambda b: b,
        )
    # True + non-array dataset: nothing to stage.
    with pytest.raises(ValueError, match="array-backed"):
        DistributedDataLoader(
            [(x[i], y[i]) for i in range(len(x))], 32, mesh=world,
            device_gather=True,
        )
    # auto + transform silently keeps the host path.
    loader = DistributedDataLoader(
        ArrayDataset((x, y)), 32, mesh=world,
        transform=lambda b: b,
    )
    assert not loader._use_device_gather(loader._array_backing())
    # auto respects the staging byte budget.
    loader2 = DistributedDataLoader(ArrayDataset((x, y)), 32, mesh=world)
    assert loader2._use_device_gather(loader2._array_backing())
    monkeypatch.setenv("FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES", "16")
    assert not loader2._use_device_gather(loader2._array_backing())


def test_device_gather_global_shuffle_epoch_disjoint(world):
    # global_shuffle must see every sample exactly once per epoch through
    # the device path too.
    from fluxmpi_tpu.data import (
        ArrayDataset,
        DistributedDataContainer,
        DistributedDataLoader,
    )

    x = np.arange(128, dtype=np.float32)[:, None]
    y = np.arange(128, dtype=np.int32)
    loader = DistributedDataLoader(
        DistributedDataContainer(ArrayDataset((x, y))), 32, mesh=world,
        device_gather=True, global_shuffle=True, seed=11,
    )
    seen = np.concatenate([np.asarray(by) for _, by in loader])
    assert sorted(seen.tolist()) == list(range(128))


def test_loader_skips_fetch_timing_when_telemetry_off(world):
    # Zero-cost-when-off on the data hot path: with the registry and
    # tracer disabled no fetch histogram is touched; the watchdog tick
    # stays.
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
    from fluxmpi_tpu.telemetry import get_registry, watchdog

    x, y = _arrays()
    loader = DistributedDataLoader(ArrayDataset((x, y)), 32, mesh=world)
    reg = get_registry()
    hist = reg.histogram("data.batch_fetch_seconds")
    n0 = hist.count
    p0 = watchdog._progress_value()
    reg.enabled = False
    try:
        for _ in loader:
            pass
    finally:
        reg.enabled = True
    assert hist.count == n0
    assert watchdog._progress_value() >= p0 + 8
    for _ in loader:  # re-enabled: timing resumes
        pass
    assert hist.count == n0 + 8
