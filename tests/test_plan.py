"""ParallelConfig composition-engine tests (parallel/plan.py): topology
validation, the strict rule engine, plan-derived specs vs the hand-written
rules, the dp×fsdp×tp GPT-2 end-to-end proof, and the plan's reach into
loader/manifest/restore/axis-name defaults."""

import contextlib

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@contextlib.contextmanager
def _fresh_runtime():
    """Swap the runtime out so a test can init() its own plan/mesh and
    hand the session fixture's world back untouched (the test_common
    save/restore pattern, extended with the plan slot)."""
    from fluxmpi_tpu import runtime

    saved = (
        runtime._state.initialized,
        runtime._state.mesh,
        runtime._state.plan,
    )
    runtime._state.initialized = False
    runtime._state.mesh = None
    runtime._state.plan = None
    try:
        yield
    finally:
        (
            runtime._state.initialized,
            runtime._state.mesh,
            runtime._state.plan,
        ) = saved


# ---------------------------------------------------------------------------
# Topology validation
# ---------------------------------------------------------------------------


def test_parallel_config_rejects_non_covering(world):
    from fluxmpi_tpu import ParallelConfig
    from fluxmpi_tpu.errors import TopologyMismatchError

    with pytest.raises(TopologyMismatchError, match="covers 6 device"):
        ParallelConfig(dp=3, tp=2).resolve()
    with pytest.raises(TopologyMismatchError, match="not divisible"):
        ParallelConfig(dp=-1, tp=3).resolve()
    with pytest.raises(ValueError, match="at most one"):
        ParallelConfig(dp=-1, tp=-1)
    with pytest.raises(ValueError, match="positive int or -1"):
        ParallelConfig(dp=0)
    with pytest.raises(ValueError, match="plan axes"):
        ParallelConfig(dp=8, axis_names={"zz": "z"})


def test_parallel_config_resolution(world):
    from fluxmpi_tpu import ParallelConfig

    # Default: everything data-parallel.
    plan = ParallelConfig().resolve()
    assert dict(plan.mesh.shape) == {"dp": 8}
    assert plan.data_parallel_size == 8
    assert plan.batch_spec == P("dp")

    # Canonical axis order, inference, composed batch spec.
    plan = ParallelConfig(fsdp=2, tp=2, dp=-1).resolve()
    assert tuple(plan.mesh.axis_names) == ("dp", "fsdp", "tp")
    assert dict(plan.mesh.shape) == {"dp": 2, "fsdp": 2, "tp": 2}
    assert plan.data_axes == ("dp", "fsdp")
    assert plan.data_parallel_size == 4
    assert plan.batch_spec == P(("dp", "fsdp"))
    assert plan.axis_name("tp") == "tp"
    assert plan.axis_name("pp") is None

    # sp rides the batch spec's sequence dim.
    plan = ParallelConfig(dp=4, sp=2).resolve()
    assert plan.batch_spec == P("dp", "sp")


def test_parallel_config_axis_name_overrides(world):
    from fluxmpi_tpu import ParallelConfig

    plan = ParallelConfig(
        dp=4, tp=2, axis_names={"dp": "data", "tp": "model"}
    ).resolve()
    assert dict(plan.mesh.shape) == {"data": 4, "model": 2}
    # The TP table follows the renamed axis.
    spec = plan.rule("encoder/block_0/ff1/kernel", (32, 64))
    assert spec == P(None, "model")


# ---------------------------------------------------------------------------
# The rule engine
# ---------------------------------------------------------------------------


def test_match_partition_rules_strict_raises(world):
    from fluxmpi_tpu import match_partition_rules

    tree = {
        "dense": {"kernel": jnp.ones((8, 4)), "bias": jnp.ones((4,))},
        "scalar": jnp.ones(()),
    }
    # Full coverage: every non-scalar leaf matched, scalars get P().
    specs = match_partition_rules(
        [(r"kernel$", P("dp", None)), (r"bias$", P())], tree
    )
    assert specs["dense"]["kernel"] == P("dp", None)
    assert specs["scalar"] == P()

    # An unmatched non-scalar path raises — no silent replication.
    with pytest.raises(ValueError, match="dense/bias"):
        match_partition_rules([(r"kernel$", P("dp", None))], tree)


def test_plan_strict_partition_specs(world):
    from fluxmpi_tpu import ParallelConfig

    tree = {"w": jnp.ones((16, 4)), "oddball": jnp.ones((4, 4))}
    plan = ParallelConfig(
        dp=8, rules=[(r"^w$", P("dp", None))], strict=True
    ).resolve()
    with pytest.raises(ValueError, match="oddball"):
        plan.partition_specs(tree)
    # Non-strict counts the fall-through instead.
    plan = ParallelConfig(dp=8, rules=[(r"^w$", P("dp", None))]).resolve()
    specs = plan.partition_specs(tree)
    assert specs["oddball"] == P()
    assert plan.rule_hits == {"table": 1, "replicated": 1}


def _tiny_lm():
    from fluxmpi_tpu.models import TransformerLM

    return TransformerLM(
        vocab_size=64, max_len=32, num_layers=2, d_model=32,
        num_heads=4, d_ff=64,
    )


def test_plan_specs_equal_handwritten_rules(world):
    """The plan's combined rule reproduces the hand-written
    transformer_tp_rules + fsdp_rule specs leaf-for-leaf on the
    transformer (params AND optax state, via the path-suffix
    convention)."""
    from fluxmpi_tpu import ParallelConfig
    from fluxmpi_tpu.parallel import TrainState, combine_rules, fsdp_rule
    from fluxmpi_tpu.parallel import transformer_tp_rules
    from fluxmpi_tpu.parallel.sharding import tree_partition_specs

    model = _tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 16), jnp.int32), train=False
    )
    state = TrainState.create(params, optax.adam(1e-2))

    plan = ParallelConfig(dp=2, fsdp=2, tp=2, fsdp_min_size=256).resolve()
    hand = combine_rules(
        transformer_tp_rules(tp_axis="tp"),
        fsdp_rule(plan.mesh, axis_name="fsdp", min_size=256),
    )
    expected = tree_partition_specs(state, plan.mesh, hand)
    got = plan.partition_specs(state)
    flat_e = jax.tree_util.tree_flatten(
        expected, is_leaf=lambda x: isinstance(x, P)
    )[0]
    flat_g = jax.tree_util.tree_flatten(
        got, is_leaf=lambda x: isinstance(x, P)
    )[0]
    assert flat_e == flat_g
    # And the TP table actually matched something.
    assert plan.rule_hits.get("tp", 0) > 0
    assert plan.rule_hits.get("fsdp", 0) > 0


# ---------------------------------------------------------------------------
# End-to-end: HF-imported GPT-2 under one composed ParallelConfig
# ---------------------------------------------------------------------------


def _gpt2_workload():
    """A real HF GPT-2 (tiny random config) through lm_from_gpt2 when
    torch/transformers are installed; the same-architecture TransformerLM
    otherwise — the composition proof must run in tier-1 either way."""
    try:
        import torch
        from transformers import GPT2Config, GPT2LMHeadModel

        from fluxmpi_tpu.models.hf_gpt2 import lm_from_gpt2

        # Seeded: the bitwise dp-vs-dp×fsdp comparison below must test
        # the LAYOUT, not sample the weight distribution (an unlucky
        # draw can land a reduce-scatter rounding one ULP off the
        # all-reduce order).
        torch.manual_seed(0)
        cfg = GPT2Config(
            vocab_size=128, n_positions=32, n_embd=32, n_layer=2,
            n_head=4, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        )
        model, variables = lm_from_gpt2(GPT2LMHeadModel(cfg))
        return model, variables, 128
    except ImportError:  # pragma: no cover - torch-less environments
        model = _tiny_lm()
        variables = model.init(
            jax.random.PRNGKey(0), jnp.ones((2, 16), jnp.int32),
            train=False,
        )
        return model, variables, 64


def _loss_trajectory(plan, model, variables, vocab, batches):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    optimizer = optax.adam(1e-2)

    def loss_fn(p, mstate, batch):
        x, y = batch
        logits = model.apply(p, x, train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y
        ).mean()
        return loss, mstate

    with _fresh_runtime():
        mesh = fm.init(parallel=plan)
        assert fm.global_plan() is plan
        # state.params carries the full variables dict ({"params": ...})
        # — the same convention the sharding tests use, so model.apply
        # consumes it directly.
        state = TrainState.create(jax.device_get(variables), optimizer)
        if plan.shards_parameters:
            state, shardings = plan.shard_state(state)
            assert plan.state_sharding is shardings
        else:
            state = replicate(state, mesh)
        step = make_train_step(loss_fn, optimizer, parallel=plan,
                               donate=False)
        losses = []
        for batch in batches:
            state, loss = step(
                state, shard_batch(batch, mesh, spec=plan.batch_spec)
            )
            losses.append(
                np.asarray(jax.device_get(loss)).astype(np.float64)
            )
    return np.array(losses)


def test_gpt2_composed_plan_matches_dp_only(world):
    """The composition proof: one HF-imported GPT-2, one ParallelConfig,
    three layouts on the 8-way virtual mesh. dp vs dp×fsdp is
    bit-identical (ZeRO is pure layout — same math, same reduction
    tree); adding tp stays within float32 reduction-order ULPs (the
    partitioner splits the matmul accumulations, so exact bit equality
    is not defined for that leg)."""
    from fluxmpi_tpu import ParallelConfig

    model, variables, vocab = _gpt2_workload()
    rng = np.random.default_rng(0)
    batches = [
        (
            rng.integers(0, vocab, size=(8, 16)).astype(np.int32),
            rng.integers(0, vocab, size=(8, 16)).astype(np.int32),
        )
        for _ in range(4)
    ]

    dp_only = _loss_trajectory(
        ParallelConfig(dp=-1).resolve(), model, variables, vocab, batches
    )
    dp_fsdp = _loss_trajectory(
        ParallelConfig(dp=4, fsdp=2, fsdp_min_size=256).resolve(),
        model, variables, vocab, batches,
    )
    composed = _loss_trajectory(
        ParallelConfig(dp=2, fsdp=2, tp=2, fsdp_min_size=256).resolve(),
        model, variables, vocab, batches,
    )
    assert np.isfinite(dp_only).all()
    # ZeRO composition: bit-for-bit.
    assert np.array_equal(dp_only, dp_fsdp), (dp_only, dp_fsdp)
    # + tensor parallelism: same trajectory to reduction-order ULPs.
    np.testing.assert_allclose(dp_only, composed, rtol=0, atol=1e-5)


def test_train_loop_fused_window_under_plan(world):
    """The scaling legs' contract in-tree: train_loop(fuse="window")
    drives a plan-sharded step at one dispatch per window — the
    dispatches-per-update assertion the bench makes, held under the
    plan-derived (dp×fsdp) sharding."""
    import fluxmpi_tpu as fm
    from fluxmpi_tpu import ParallelConfig
    from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop

    window = 4
    with _fresh_runtime():
        plan = ParallelConfig(dp=4, fsdp=2, fsdp_min_size=64).resolve()
        mesh = fm.init(parallel=plan)
        model = MLP(features=(32, 32, 1))
        params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
        optimizer = optax.adam(1e-3)

        def loss_fn(p, mstate, batch):
            x, y = batch
            return jnp.mean((model.apply(p, x) - y) ** 2), mstate

        state, _ = plan.shard_state(TrainState.create(params, optimizer))
        step = make_train_step(loss_fn, optimizer, parallel=plan)

        gbs = 16
        rng = np.random.default_rng(0)
        x = rng.normal(size=(gbs * window, 2)).astype(np.float32)
        dataset = ArrayDataset((x, (x**2).sum(-1, keepdims=True)))
        loader = DistributedDataLoader(dataset, gbs, mesh=mesh)
        # The loader's default batch axis comes from the installed plan.
        assert loader.axis_name == ("dp", "fsdp")

        state, summary = train_loop(
            step, state, loader, epochs=2, fuse="window",
            flush_every=window, metrics=False,
        )
        assert summary["fused_window"] == window
        assert summary["updates"] == 2 * window
        assert summary["dispatches"] / summary["updates"] == 1.0 / window
        assert np.isfinite(summary["loss"])


# ---------------------------------------------------------------------------
# Manifest / restore composition
# ---------------------------------------------------------------------------


def test_manifest_records_plan_and_restore_parallel(world, tmp_path):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu import ParallelConfig
    from fluxmpi_tpu.parallel import TrainState
    from fluxmpi_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )
    from fluxmpi_tpu.utils.manifest import read_manifest

    model = _tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 16), jnp.int32), train=False
    )
    optimizer = optax.adam(1e-2)
    path = str(tmp_path / "ckpt")

    with _fresh_runtime():
        plan = ParallelConfig(dp=2, fsdp=2, tp=2, fsdp_min_size=256).resolve()
        fm.init(parallel=plan)
        state, _ = plan.shard_state(TrainState.create(params, optimizer))
        save_checkpoint(path, state)
        manifest = read_manifest(path)
        assert manifest is not None
        assert manifest["parallel"] == {
            "axes": {"dp": 2, "fsdp": 2, "tp": 2},
            "axis_names": {"dp": "dp", "fsdp": "fsdp", "tp": "tp"},
        }

        # Restore THROUGH the plan: parallel= in place of (mesh=, rule=).
        host_like = jax.device_get(state)
        restored = restore_checkpoint(
            path, host_like, parallel=plan, allow_layout_change=True
        )
        blk = restored.params["params"]["encoder"]["block_0"]
        assert tuple(blk["ff1"]["kernel"].sharding.spec) == (None, "tp")
        with pytest.raises(ValueError, match="not both"):
            restore_checkpoint(
                path, host_like, parallel=plan, mesh=plan.mesh
            )

        # And elastically onto a DIFFERENT plan (dp-only: everything
        # replicated again).
        dp_plan = ParallelConfig(dp=-1).resolve()
        flat = restore_checkpoint(
            path, host_like, parallel=dp_plan, allow_layout_change=True
        )
        blk = flat.params["params"]["encoder"]["block_0"]
        assert all(s is None for s in tuple(blk["ff1"]["kernel"].sharding.spec))


# ---------------------------------------------------------------------------
# Axis-name resolution + observability board
# ---------------------------------------------------------------------------


def test_plan_axis_name_resolution(world):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu import ParallelConfig, config
    from fluxmpi_tpu.parallel import plan_axis_name

    # No plan installed: preferences win.
    assert plan_axis_name("pp") == config.PP_AXIS_NAME
    with _fresh_runtime():
        plan = ParallelConfig(
            dp=2, pp=2, sp=2, axis_names={"pp": "stage"}
        ).resolve()
        fm.init(parallel=plan)
        assert plan_axis_name("pp") == "stage"
        assert plan_axis_name("sp") == "sp"
        # An axis the plan lacks falls back to the preference.
        assert plan_axis_name("tp") == config.TP_AXIS_NAME
        assert fm.dp_axis_name() == "dp"


def test_parallel_status_board(world):
    from fluxmpi_tpu import ParallelConfig
    from fluxmpi_tpu.parallel.plan import post_board
    from fluxmpi_tpu.telemetry import export as export_mod
    from fluxmpi_tpu.telemetry.export import Exporter
    from fluxmpi_tpu.telemetry.schema import validate_status_record

    plan = ParallelConfig(dp=4, fsdp=2, fsdp_min_size=64).resolve()
    plan.partition_specs({"w": jnp.ones((64, 64))})
    exporter = Exporter(port=0)
    prev = export_mod.set_exporter(exporter)
    try:
        post_board(plan)
        status = exporter.build_status()
        assert validate_status_record(status) == []
        board = status["parallel"]
        assert board["mesh"] == {"dp": 4, "fsdp": 2}
        assert board["data_parallel_size"] == 8
        assert board["rule_hits"].get("fsdp", 0) >= 1
    finally:
        export_mod.set_exporter(prev)

    # fluxmpi_top renders the board.
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "_fm_top",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "fluxmpi_top.py"),
    )
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    frame = top.render_frame({"host0": status}, {})
    assert "PARALLEL" in frame
    assert "dp:4" in frame and "fsdp:2" in frame
