"""Pytree synchronize tests (reference: test/test_synchronize.jl).

Single-process, the transport is the identity (world of one controller), so
these tests verify the *leaf-dispatch semantics* — which leaves get broadcast
and which are no-ops — by recording transport calls, plus structure/type
preservation and the adapter paths. The root-wins propagation oracle itself
is covered at the device level in test_comm.py::test_bcast_root_pattern.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture()
def recorded_bcast(monkeypatch):
    """Record every transport broadcast issued by synchronize."""
    calls = []

    def fake_host_bcast(x, root=0):
        calls.append((np.asarray(x).shape, root))
        return np.asarray(x)

    import fluxmpi_tpu.sync as sync_mod

    monkeypatch.setattr(sync_mod, "host_bcast", fake_host_bcast)
    return calls


def test_nested_tree_sync(world, recorded_bcast):
    # reference: test/test_synchronize.jl:16-25 — nested NamedTuple sync
    import fluxmpi_tpu as fm

    tree = {
        "layer1": {"w": jnp.ones((4, 3)), "b": jnp.zeros((3,))},
        "layer2": (jnp.full((2,), 2.0), np.arange(5.0)),
    }
    out = fm.synchronize(tree)
    assert jax.tree_util.tree_structure(out) == jax.tree_util.tree_structure(tree)
    np.testing.assert_allclose(np.asarray(out["layer1"]["w"]), 1.0)
    # fused transport: one bcast per dtype group (3 f32 jax leaves + 1 f64
    # numpy leaf), not one per leaf as in the reference's MPI.Bcast walk
    assert len(recorded_bcast) == 2


def test_sync_preserves_values_single_process(world):
    import fluxmpi_tpu as fm

    tree = {"w": jnp.arange(6.0).reshape(2, 3)}
    out = fm.synchronize(tree)
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(tree["w"]))


def test_optimizer_state_sync(world, recorded_bcast):
    # reference: test/test_synchronize.jl:27-54 — Adam state sync (and
    # stateless SGD) via Optimisers.Leaf dispatch; optax states are plain
    # pytrees so recursion covers them.
    import fluxmpi_tpu as fm

    params = {"w": jnp.ones((3, 2)), "b": jnp.zeros((2,))}
    state = optax.adam(1e-3).init(params)
    out = fm.synchronize(state)
    assert jax.tree_util.tree_structure(out) == jax.tree_util.tree_structure(state)
    # mu and nu arrays fuse into one f32 bcast; the int32 count leaf rides
    # its own dtype group — 2 collectives for the whole optimizer state
    assert len(recorded_bcast) == 2

    sgd_state = optax.sgd(0.1).init(params)
    out2 = fm.synchronize(sgd_state)
    assert jax.tree_util.tree_structure(out2) == jax.tree_util.tree_structure(
        sgd_state
    )


def test_scalar_sync(world, recorded_bcast):
    # reference: test/test_synchronize.jl:29-31 — Number → 1-elem bcast
    import fluxmpi_tpu as fm

    assert fm.synchronize(3.5) == 3.5
    assert isinstance(fm.synchronize(7), int)
    assert fm.synchronize(True) is True
    assert len(recorded_bcast) == 3


def test_noop_leaves(world, recorded_bcast):
    # reference: test/test_synchronize.jl:81-97 — Nothing/Symbol no-ops
    import fluxmpi_tpu as fm

    fn = lambda x: x  # noqa: E731
    tree = {"a": None, "b": "a_symbol", "c": fn}
    out = fm.synchronize(tree)
    assert out["a"] is None
    assert out["b"] == "a_symbol"
    assert out["c"] is fn
    assert len(recorded_bcast) == 0


def test_empty_tree_fast_path(world):
    # reference: src/synchronize.jl:11
    import fluxmpi_tpu as fm

    assert fm.synchronize({}) == {}
    assert fm.synchronize(()) == ()


def test_object_array_recursion(world, recorded_bcast):
    # reference: src/synchronize.jl:20-22 — array-of-arrays recursion
    import fluxmpi_tpu as fm

    arr = np.empty((2,), dtype=object)
    arr[0] = np.ones((3,))
    arr[1] = np.zeros((2, 2))
    out = fm.synchronize(arr)
    assert out.dtype == object
    np.testing.assert_allclose(out[0], np.ones((3,)))
    assert len(recorded_bcast) == 2


def test_flat_param_vector_adapter(world, recorded_bcast):
    # reference: ext/FluxMPIComponentArraysExt.jl + test/test_synchronize.jl:56-66
    import fluxmpi_tpu as fm

    tree = {"w": jnp.ones((4, 3)), "b": jnp.arange(3.0)}
    fpv = fm.FlatParamVector.from_tree(tree)
    assert len(fpv) == 15
    synced = fm.synchronize(fpv)
    # ONE collective for the whole tree — the flat-vector win
    assert len(recorded_bcast) == 1
    back = synced.to_tree()
    np.testing.assert_allclose(np.asarray(back["w"]), 1.0)
    np.testing.assert_allclose(np.asarray(back["b"]), np.arange(3.0))


def test_wrapped_model_adapter(world, recorded_bcast):
    # reference: ext/FluxMPIFluxExt.jl — arbitrary mutable model structs
    import fluxmpi_tpu as fm

    class TinyModel:
        def __init__(self):
            self.weight = np.ones((2, 2))
            self.bias = np.zeros((2,))
            self.name = "tiny"

    m = TinyModel()
    wrapped = fm.synchronize(fm.FluxModelWrapper(m))
    assert isinstance(wrapped, fm.FluxModelWrapper)
    np.testing.assert_allclose(wrapped.model.weight, np.ones((2, 2)))
    assert wrapped.model.name == "tiny"
    assert len(recorded_bcast) == 2


def test_tuple_sync(world):
    # reference: test/test_synchronize.jl:69-79
    import fluxmpi_tpu as fm

    t = (jnp.ones((2,)), 5.0, None)
    out = fm.synchronize(t)
    assert isinstance(out, tuple)
    np.testing.assert_allclose(np.asarray(out[0]), 1.0)
    assert out[1] == 5.0 and out[2] is None


def test_synchronize_fuses_collectives(world, monkeypatch):
    # VERDICT r2 next #9: the collective count must be independent of the
    # leaf count — one flat host broadcast per dtype, not one per leaf.
    import fluxmpi_tpu.sync as sync_mod

    calls = []
    real = sync_mod.host_bcast

    def counting(x, root=0):
        calls.append(np.asarray(x).dtype)
        return real(x, root=root)

    monkeypatch.setattr(sync_mod, "host_bcast", counting)

    tree = {
        f"layer{i}": {
            "w": jnp.full((4, 4), float(i)),
            "b": jnp.zeros((4,)),
            "steps": jnp.asarray(i, jnp.int32),
        }
        for i in range(10)
    }
    synced = sync_mod.synchronize(tree)
    # 30 array leaves, 2 dtypes → exactly 2 collectives.
    assert len(calls) == 2
    np.testing.assert_allclose(
        np.asarray(synced["layer7"]["w"]), np.full((4, 4), 7.0)
    )
    assert synced["layer3"]["steps"].dtype == jnp.int32
    assert int(synced["layer3"]["steps"]) == 3

    # Mixed trees: exotic leaves keep per-leaf semantics, arrays still fuse.
    calls.clear()
    mixed = {"a": jnp.ones((3,)), "b": "keep-me", "c": 7, "d": None,
             "e": np.arange(5.0)}
    synced = sync_mod.synchronize(mixed)
    assert synced["b"] == "keep-me" and synced["c"] == 7
    assert isinstance(synced["e"], np.ndarray)
    np.testing.assert_allclose(synced["e"], np.arange(5.0))
    # float32 jax leaf + float64 numpy leaf fuse per dtype; the int scalar
    # broadcasts alone.
    assert len(calls) == 3
