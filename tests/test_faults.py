"""Fault-injection harness tests: spec grammar, deterministic firing,
the zero-cost-when-off fast-guard, the woven comm/data/checkpoint sites,
crash-consistent checkpoint commit + quarantine, retry backoff, and the
schema extensions. All tier-1 fast: no sleeps (the retry sleep is
injected), no subprocesses."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults
from fluxmpi_tpu.errors import (
    CheckpointDesyncError,
    CheckpointTimeoutError,
    FaultInjectedError,
)
from fluxmpi_tpu.telemetry import MetricsRegistry, set_registry, get_registry


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# The grammar/semantics tests below arm synthetic sites that are not
# woven into the framework; install()/scope() now validate against
# faults.KNOWN_SITES, so register them the way user-woven sites would be.
for _site in (
    "site.a",
    "site.b",
    "site.p",
    "site.c",
    "site.r",
    "site.x",
    "site.m",
    "outer.site",
    "inner.site",
):
    faults.register_site(_site)


# ---------------------------------------------------------------------------
# Grammar / schedule semantics
# ---------------------------------------------------------------------------


def test_parse_spec_grammar():
    s = faults.parse_spec("comm.allreduce@step=7")
    assert (s.site, s.step, s.times, s.p) == ("comm.allreduce", 7, 1, None)
    s = faults.parse_spec("ckpt.write:p=0.1:seed=5")
    assert (s.site, s.p, s.seed, s.times) == ("ckpt.write", 0.1, 5, None)
    s = faults.parse_spec("data.fetch@step=3:times=2:proc=1")
    assert (s.step, s.times, s.proc) == (3, 2, 1)
    # @step sugar and :step spelling are equivalent.
    assert faults.parse_spec("x:step=3").step == faults.parse_spec("x@step=3").step


def test_parse_spec_rejects_bad_entries():
    with pytest.raises(ValueError, match="key=value"):
        faults.parse_spec("site:banana")
    with pytest.raises(ValueError, match="unknown fault modifier"):
        faults.parse_spec("site:frequency=2")
    with pytest.raises(ValueError, match="mutually exclusive"):
        faults.FaultSpec("s", step=2, p=0.5)
    with pytest.raises(ValueError, match="step must be >= 1"):
        faults.FaultSpec("s", step=0)
    with pytest.raises(ValueError, match=r"p must be in \[0, 1\]"):
        faults.FaultSpec("s", p=1.5)


def test_step_trigger_fires_once_at_exact_hit():
    faults.install("site.a@step=3")
    for _ in range(2):
        faults.check("site.a")  # hits 1, 2: no fire
    with pytest.raises(FaultInjectedError) as exc:
        faults.check("site.a")
    assert exc.value.site == "site.a" and exc.value.hit == 3
    faults.check("site.a")  # times=1 default: spent
    assert faults.injected_count() == 1


def test_times_widens_the_firing_window():
    faults.install("site.a@step=2:times=2")
    faults.check("site.a")
    for expected_hit in (2, 3):
        with pytest.raises(FaultInjectedError):
            faults.check("site.a")
    faults.check("site.a")  # both injections spent
    assert faults.injected_count() == 2


def test_bare_entry_fires_immediately_once():
    faults.install("site.b")
    with pytest.raises(FaultInjectedError):
        faults.check("site.b")
    faults.check("site.b")


def test_probability_mode_is_seeded_and_deterministic():
    def run(seed):
        fired = []
        with faults.scope(f"site.p:p=0.5:seed={seed}:times=1000"):
            for i in range(50):
                try:
                    faults.check("site.p")
                except FaultInjectedError:
                    fired.append(i)
        return fired

    a, b = run(7), run(7)
    assert a == b and 5 < len(a) < 45  # same draws, plausibly ~half
    assert run(8) != a  # a different seed is a different schedule


def test_proc_targeting_skips_other_processes():
    # Single-process world is index 0: proc=1 entries never fire here.
    faults.install("site.c@step=1:proc=1")
    faults.check("site.c")
    assert faults.injected_count() == 0
    faults.install("site.c@step=1:proc=0")
    with pytest.raises(FaultInjectedError):
        faults.check("site.c")


def test_env_configure_and_clear(monkeypatch):
    monkeypatch.setenv("FLUXMPI_TPU_FAULTS", "comm.allreduce@step=2, data.fetch:p=0.5")
    specs = faults.configure()
    assert [s.site for s in specs] == ["comm.allreduce", "data.fetch"]
    assert faults.ARMED
    faults.configure(False)
    assert not faults.ARMED and faults.active() == []
    monkeypatch.delenv("FLUXMPI_TPU_FAULTS")
    faults.configure()  # unset env: no-op, stays clear
    assert not faults.ARMED


def test_env_configure_replay_keeps_hit_counters(monkeypatch):
    # init() is documented idempotent: a replay that finds the SAME env
    # schedule armed must not reset hit counters or re-arm fired
    # times=1 entries (determinism contract).
    monkeypatch.setenv("FLUXMPI_TPU_FAULTS", "site.r@step=2")
    faults.configure()
    faults.check("site.r")  # hit 1: no fire
    faults.configure()  # idempotent init() replay
    with pytest.raises(FaultInjectedError):
        faults.check("site.r")  # still hit 2, not reset to 1
    faults.configure()  # replay after the entry fired: stays spent
    faults.check("site.r")  # hit 3, times=1 exhausted — no re-fire
    monkeypatch.setenv("FLUXMPI_TPU_FAULTS", "site.r@step=5")
    faults.configure()  # a CHANGED env schedule does install fresh
    faults.check("site.r")  # hit 1 of the new schedule
    assert faults.injected_count() == 0


def test_explicit_configure_replay_keeps_hit_counters():
    # Same contract for init(faults=...) replays as for the env route,
    # in any spelling: grammar string or FaultSpec objects.
    faults.configure("site.x@step=2")
    faults.check("site.x")  # hit 1: no fire
    faults.configure("site.x@step=2")  # idempotent init() replay
    faults.configure([faults.FaultSpec("site.x", step=2)])  # same, object
    with pytest.raises(FaultInjectedError):
        faults.check("site.x")  # still hit 2, counters kept
    faults.configure("site.x@step=9")  # changed spec installs fresh
    faults.check("site.x")  # hit 1 of the new schedule
    assert faults.injected_count() == 0


def test_scope_invalid_spec_leaves_schedule_armed():
    faults.install("outer.site@step=1")
    with pytest.raises(ValueError):
        with faults.scope("outer.site@step"):  # bad modifier
            pass
    # The previous schedule survives a failed __enter__ untouched.
    assert faults.ARMED
    assert [s.site for s in faults.active()] == ["outer.site"]
    with pytest.raises(FaultInjectedError):
        faults.check("outer.site")


def test_scope_restores_previous_schedule():
    faults.install("outer.site@step=1")
    with faults.scope("inner.site@step=1"):
        assert [s.site for s in faults.active()] == ["inner.site"]
    assert [s.site for s in faults.active()] == ["outer.site"]
    faults.clear()
    with faults.scope("inner.site@step=1"):
        assert faults.ARMED
    assert not faults.ARMED


def test_injected_counter_reaches_registry():
    reg = MetricsRegistry()
    old = get_registry()
    set_registry(reg)
    try:
        faults.install("site.m@step=1")
        with pytest.raises(FaultInjectedError):
            faults.check("site.m")
        assert reg.counter("fault.injected", site="site.m").value == 1
    finally:
        set_registry(old)


# ---------------------------------------------------------------------------
# Zero-cost-when-off: the fast-guard contract
# ---------------------------------------------------------------------------


def test_disarmed_harness_never_enters_check(world, monkeypatch):
    """With no schedule armed, the woven sites must not even CALL
    faults.check — the one-attribute-read guard is the whole cost."""
    def boom(site):
        raise AssertionError(f"check({site!r}) entered while disarmed")

    monkeypatch.setattr(faults, "check", boom)
    assert not faults.ARMED
    x = np.arange(8, dtype=np.float32)
    fm.allreduce(x)  # comm site guarded
    fm.barrier()
    fm.host_allreduce(np.float32(1.0))
    loader = fm.DistributedDataLoader(
        fm.ArrayDataset((np.ones((16, 2), np.float32),)), 8, mesh=world
    )
    for _ in loader:  # data site guarded
        pass


def test_armed_comm_site_fires_deterministically(world):
    x = np.arange(8, dtype=np.float32)
    with faults.scope("comm.allreduce@step=2"):
        fm.allreduce(x)  # hit 1: clean
        with pytest.raises(FaultInjectedError, match="comm.allreduce"):
            fm.allreduce(x)
        fm.allreduce(x)  # spent
        # bcast is a different site: untouched.
        fm.bcast(x)


@pytest.mark.parametrize(
    "site,call",
    [
        ("comm.allreduce", lambda x: fm.allreduce(x)),
        ("comm.bcast", lambda x: fm.bcast(x)),
        ("comm.reduce", lambda x: fm.reduce(x)),
        ("comm.barrier", lambda x: fm.barrier()),
        ("comm.host_allreduce", lambda x: fm.host_allreduce(np.float32(1))),
        ("comm.host_allgather", lambda x: fm.host_allgather(np.float32(1))),
        ("comm.host_bcast", lambda x: fm.host_bcast(np.float32(1))),
    ],
)
def test_every_comm_site_is_injectable(world, site, call):
    # Every comm.* entry of faults.KNOWN_SITES has a live trigger — the
    # coverage contract the fluxlint unregistered-fault-site rule greps
    # this file for (each registered site must be exercised somewhere in
    # tests/).
    x = np.arange(8, dtype=np.float32)
    with faults.scope(site + "@step=1"):
        with pytest.raises(FaultInjectedError, match=site):
            call(x)
    call(x)  # disarmed: clean


# ---------------------------------------------------------------------------
# Site-registry validation (install raises, configure warns)
# ---------------------------------------------------------------------------


def test_install_rejects_unknown_site_naming_nearest():
    with pytest.raises(ValueError, match=r"ckpt\.write"):
        faults.install("ckpt.wrte@step=1")  # typo: nearest is named
    assert not faults.ARMED  # nothing armed by the failed install


def test_scope_rejects_unknown_site_and_preserves_schedule():
    faults.install("site.a@step=1")
    with pytest.raises(ValueError, match="unknown fault site"):
        with faults.scope("data.fetchh@step=1"):
            pass
    # The failed scope never touched the armed schedule.
    assert [s.site for s in faults.active()] == ["site.a"]
    assert faults.ARMED


def test_configure_warns_on_unknown_env_site(monkeypatch):
    # A typo'd FLUXMPI_TPU_FAULTS degrades with a warning naming the
    # nearest registered site — it must not crash init().
    monkeypatch.setenv("FLUXMPI_TPU_FAULTS", "comm.allredcue@step=1")
    with pytest.warns(UserWarning, match=r"comm\.allreduce"):
        specs = faults.configure()
    assert [s.site for s in specs] == ["comm.allredcue"]  # installed as asked


def test_register_site_extends_the_registry():
    site = faults.register_site("userlib.flush")
    assert site in faults.registered_sites()
    faults.install("userlib.flush@step=1")  # no raise: registered
    with pytest.raises(FaultInjectedError):
        faults.check("userlib.flush")


def test_armed_data_fetch_site_fires(world):
    ds = fm.ArrayDataset((np.arange(32, dtype=np.float32).reshape(32, 1),))
    loader = fm.DistributedDataLoader(ds, 8, mesh=world, prefetch=0)
    with faults.scope("data.fetch@step=3"):
        it = iter(loader)
        next(it)
        next(it)
        with pytest.raises(FaultInjectedError, match="data.fetch"):
            next(it)


# ---------------------------------------------------------------------------
# Crash-consistent checkpoints: commit protocol, quarantine, retries
# ---------------------------------------------------------------------------


def _state():
    return {"w": jnp.arange(4.0), "b": jnp.ones((2,))}


def test_ckpt_write_fault_exercises_retries(world, tmp_path, monkeypatch):
    from fluxmpi_tpu.utils import CheckpointManager, checkpoint as ckpt_mod

    sleeps = []
    monkeypatch.setattr(ckpt_mod, "_retry_sleep", sleeps.append)
    reg = MetricsRegistry()
    old = get_registry()
    set_registry(reg)
    try:
        mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
        with faults.scope("ckpt.write@step=1:times=2"):
            mgr.save(1, _state())  # two injected failures, then success
        assert mgr.all_steps() == [1]
        assert reg.counter("checkpoint.retries").value == 2
        # Capped exponential backoff, never slept for real.
        assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]
        _, restored = mgr.restore(_state())
        np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(4.0))
    finally:
        set_registry(old)


def test_ckpt_write_fault_exhausts_retries_and_raises(world, tmp_path, monkeypatch):
    from fluxmpi_tpu.utils import CheckpointManager, checkpoint as ckpt_mod

    monkeypatch.setattr(ckpt_mod, "_retry_sleep", lambda s: None)
    monkeypatch.setenv("FLUXMPI_TPU_CKPT_RETRIES", "1")
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    with faults.scope("ckpt.write:p=1:seed=0"):  # every attempt fails
        with pytest.raises(FaultInjectedError, match="ckpt.write"):
            mgr.save(1, _state())
    # The failed save left nothing committed and nothing discoverable,
    # and the abort cleaned its own staging dir + peer-failure sentinel.
    assert mgr.latest_step() is None
    leftovers = [
        n
        for n in os.listdir(mgr.directory)
        if n.endswith(".tmp") or ".write_failed." in n
    ]
    assert leftovers == []


def test_peer_write_failure_aborts_save_everywhere(world, tmp_path, monkeypatch):
    """A peer process whose write exhausted retries (simulated via the
    monkeypatchable sentinel read) aborts the save on THIS healthy
    process too: staging cleaned, nothing decommitted, the previous
    committed checkpoint still restorable."""
    from fluxmpi_tpu.utils import CheckpointManager, checkpoint as ckpt_mod

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    mgr.save(1, _state())
    monkeypatch.setattr(ckpt_mod, "_peer_write_failures", lambda tmp: [1])
    with pytest.raises(OSError, match=r"peer process\(es\) \[1\]"):
        mgr.save(2, _state())
    monkeypatch.undo()
    # Local write succeeded, but the save must not commit half a world:
    # step 2 is invisible, step 1 untouched, staging gone.
    assert mgr.all_steps() == [1]
    step, restored = mgr.restore(_state())
    assert step == 1
    leftovers = [n for n in os.listdir(mgr.directory) if n.endswith(".tmp")]
    assert leftovers == []


def test_crash_between_rename_and_commit_is_invisible(world, tmp_path):
    """A save that dies after the rename but before the COMMIT marker
    (the ckpt.commit site) must never be returned by discovery, and the
    next manager startup quarantines the partial directory."""
    from fluxmpi_tpu.utils import CheckpointManager

    d = str(tmp_path / "run")
    mgr = CheckpointManager(d, async_save=False)
    mgr.save(1, _state())
    with faults.scope("ckpt.commit@step=1"):
        with pytest.raises(FaultInjectedError, match="ckpt.commit"):
            mgr.save(2, _state())
    # The torn step 2 is invisible: latest committed step is still 1.
    assert mgr.all_steps() == [1]
    assert mgr.latest_step() == 1
    step, restored = mgr.restore(_state())
    assert step == 1
    # Uncommitted dir is still on disk until the next startup sweep...
    assert os.path.isdir(os.path.join(d, "step_00000002"))
    with pytest.warns(UserWarning, match="quarantined"):
        mgr2 = CheckpointManager(d, async_save=False)
    assert mgr2.quarantined == ["step_00000002"]
    assert not os.path.isdir(os.path.join(d, "step_00000002"))
    assert os.path.isdir(os.path.join(d, "_quarantine", "step_00000002"))
    assert mgr2.all_steps() == [1]  # committed history untouched


def test_stale_tmp_dir_is_quarantined(world, tmp_path):
    from fluxmpi_tpu.utils import CheckpointManager

    d = tmp_path / "run"
    d.mkdir()
    (d / "step_00000003.tmp").mkdir()  # crash mid-write
    with pytest.warns(UserWarning, match="quarantined"):
        mgr = CheckpointManager(str(d), async_save=False)
    assert mgr.quarantined == ["step_00000003.tmp"]
    assert mgr.latest_step() is None


def test_save_overwrites_and_recommits(world, tmp_path):
    from fluxmpi_tpu.utils import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    mgr.save(1, _state())
    mgr.save(1, {"w": jnp.arange(4.0) + 10, "b": jnp.ones((2,))}, force=True)
    _, restored = mgr.restore(_state())
    np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(4.0) + 10)


def test_ckpt_read_fault_site(world, tmp_path):
    from fluxmpi_tpu.utils import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    mgr.save(1, _state())
    with faults.scope("ckpt.read@step=1"):
        with pytest.raises(FaultInjectedError, match="ckpt.read"):
            mgr.restore(_state())
    mgr.restore(_state())  # transient: the next read succeeds


def test_step_desync_aborts_save_with_flight_context(world, tmp_path, monkeypatch):
    from fluxmpi_tpu.utils import CheckpointManager, checkpoint as ckpt_mod

    monkeypatch.setattr(
        ckpt_mod, "_gather_steps", lambda step: np.asarray([step, step + 1])
    )
    d = str(tmp_path / "run")
    mgr = CheckpointManager(d, async_save=False)
    with pytest.raises(CheckpointDesyncError, match="disagree"):
        mgr.save(5, _state())
    assert mgr.latest_step() is None  # nothing banked
    dump = os.path.join(d, "ckpt_desync_flight.0.json")
    assert os.path.exists(dump)
    with open(dump) as f:
        rec = json.load(f)
    assert rec["kind"] == "flight_recorder"


def test_wait_with_diagnostic_hard_deadline(monkeypatch):
    from concurrent.futures import Future

    from fluxmpi_tpu.utils.checkpoint import _wait_with_diagnostic

    fut: Future = Future()  # never completes
    monkeypatch.setenv("FLUXMPI_TPU_CKPT_TIMEOUT", "0.05")
    with pytest.raises(CheckpointTimeoutError, match="hard deadline"):
        with pytest.warns(UserWarning):
            _wait_with_diagnostic(fut, "test save", warn_after_s=0.01)
    # Default-off: unset env keeps the warn-forever contract (bounded
    # here by completing the future after the first warning window).
    monkeypatch.delenv("FLUXMPI_TPU_CKPT_TIMEOUT")
    done: Future = Future()
    done.set_result(None)
    _wait_with_diagnostic(done, "test save", warn_after_s=0.01)


def test_shutdown_resets_fault_plane(world):
    """shutdown() is the runtime reset: a fault schedule or preemption
    flag surviving an init/shutdown cycle would poison the next run
    (collectives raising FaultInjectedError, train_loop "preempting" at
    its first dispatch boundary)."""
    from fluxmpi_tpu import runtime

    saved = (runtime._state.initialized, runtime._state.mesh)
    try:
        faults.install("comm.allreduce:p=1:seed=0")
        runtime.install_preemption_handlers()
        runtime.request_preemption()
        runtime.shutdown()
        assert faults.active() == []
        assert not faults.ARMED
        assert not runtime.preemption_requested()
        assert not runtime.preemption_handlers_installed()
    finally:
        runtime.uninstall_preemption_handlers()
        runtime._state.initialized, runtime._state.mesh = saved


# ---------------------------------------------------------------------------
# Schema extensions (satellite: fault.injected / checkpoint.retries /
# train.resumes names + the preemption trace-event type)
# ---------------------------------------------------------------------------


def test_schema_knows_fault_tolerance_metrics():
    from fluxmpi_tpu.telemetry import schema

    for name in ("fault.injected", "checkpoint.retries", "train.resumes"):
        assert name in schema.KNOWN_METRIC_NAMES
        assert not schema.validate_metric(
            {"name": name, "type": "counter", "labels": {}, "value": 1}
        )
    # Drift inside a framework-owned namespace is an error...
    assert schema.validate_metric(
        {"name": "fault.bogus", "type": "counter", "labels": {}, "value": 1}
    )
    assert schema.validate_metric(
        {"name": "checkpoint.bogus", "type": "gauge", "labels": {}, "value": 1}
    )
    # ...while user-minted names elsewhere stay legal.
    assert not schema.validate_metric(
        {"name": "train.my_metric", "type": "gauge", "labels": {}, "value": 1}
    )


def test_schema_validates_preemption_trace_event():
    from fluxmpi_tpu.telemetry import schema

    good = {
        "name": schema.PREEMPTION_EVENT,
        "ph": "i",
        "ts": 1.0,
        "pid": 1,
        "tid": 1,
        "args": {"step": 12},
    }
    assert not schema.validate_trace_event(good)
    bad_phase = dict(good, ph="X", dur=1.0)
    assert any("instant" in e for e in schema.validate_trace_event(bad_phase))
    no_step = dict(good, args={})
    assert any("args.step" in e for e in schema.validate_trace_event(no_step))


def test_check_metrics_schema_script_accepts_fault_metrics(world, tmp_path):
    """End to end: a JSONL carrying the new counters passes the PR-time
    drift checker; a drifted name in a closed namespace fails it."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_cms", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "check_metrics_schema.py",
        ),
    )
    cms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cms)
    schema = cms._load_schema()

    reg = MetricsRegistry()
    reg.counter("fault.injected", site="comm.allreduce").inc()
    reg.counter("checkpoint.retries").inc()
    reg.counter("train.resumes").inc()
    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps(reg.flush()) + "\n")
    assert cms.check_file(str(good), schema) == []

    bad_rec = reg.flush()
    bad_rec["metrics"].append(
        {"name": "fault.unknown", "type": "counter", "labels": {}, "value": 1}
    )
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(bad_rec) + "\n")
    assert cms.check_file(str(bad), schema)


# ---------------------------------------------------------------------------
# Bench result banking (satellite: merge keyed by config, not clobber)
# ---------------------------------------------------------------------------


def test_bench_jsonl_merges_by_config(world, tmp_path, monkeypatch):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_cms2", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "check_metrics_schema.py",
        ),
    )
    cms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cms)
    schema = cms._load_schema()

    import bench

    path = tmp_path / "bench.jsonl"
    monkeypatch.setenv("FLUXMPI_TPU_BENCH_JSONL", str(path))

    def result(metric, value, **extra):
        rec = {"metric": metric, "value": value, "unit": "samples/s",
               "vs_baseline": 1.0, "platform": "cpu", "device_kind": "cpu"}
        rec.update(extra)
        return rec

    bench._emit_telemetry(result("mlp_samples_per_sec_per_chip", 100.0))
    bench._emit_telemetry(result("resnet_samples_per_sec_per_chip", 50.0))
    # Re-running the first config REPLACES its line (interrupted-sweep
    # accumulation), it does not append a duplicate.
    bench._emit_telemetry(result("mlp_samples_per_sec_per_chip", 120.0))
    lines = [json.loads(ln) for ln in path.read_text().splitlines() if ln]
    assert len(lines) == 2
    by_metric = {rec["bench"]["metric"]: rec["bench"]["value"] for rec in lines}
    assert by_metric == {
        "mlp_samples_per_sec_per_chip": 120.0,
        "resnet_samples_per_sec_per_chip": 50.0,
    }
    # A different config (n_chips) of the same metric banks separately.
    bench._emit_telemetry(result("mlp_samples_per_sec_per_chip", 80.0, n_chips=8))
    assert len(path.read_text().splitlines()) == 3
    # Non-bench telemetry lines in the same file survive the merge.
    with open(path, "a") as f:
        reg = MetricsRegistry()
        reg.counter("train.steps").inc(3)
        f.write(json.dumps(reg.flush()) + "\n")
    bench._emit_telemetry(result("mlp_samples_per_sec_per_chip", 130.0))
    lines = [json.loads(ln) for ln in path.read_text().splitlines() if ln]
    assert len(lines) == 4
    assert sum(1 for rec in lines if "bench" not in rec) == 1
    # The merged stream still validates against the documented schemas.
    assert cms.check_file(str(path), schema) == []


# ---------------------------------------------------------------------------
# delay= entries: stall injection (the liveness-chaos producer)
# ---------------------------------------------------------------------------


def test_delay_modifier_grammar_round_trip():
    spec = faults.parse_spec("data.fetch@step=2:delay=0.05")
    assert spec.delay == pytest.approx(0.05)
    assert spec.step == 2
    assert "delay=0.05" in str(spec)
    # and the canonical string re-parses to the same schedule
    again = faults.parse_spec(str(spec))
    assert again.delay == spec.delay and again.step == spec.step


def test_delay_modifier_validation():
    with pytest.raises(ValueError):
        faults.parse_spec("data.fetch:delay=0")
    with pytest.raises(ValueError):
        faults.parse_spec("data.fetch:delay=-1")


def test_delay_entry_stalls_instead_of_raising():
    """A delay= entry is a STALL, not a crash: the firing hit sleeps in
    place and continues — no FaultInjectedError — while still counting
    as an injection (counter + trace instant ride the same path)."""
    import time as _time

    with faults.scope("data.fetch@step=2:delay=0.05"):
        t0 = _time.perf_counter()
        faults.check("data.fetch")  # hit 1: not yet
        fast = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        faults.check("data.fetch")  # hit 2: stalls, returns normally
        stalled = _time.perf_counter() - t0
        assert faults.injected_count() == 1
        t0 = _time.perf_counter()
        faults.check("data.fetch")  # times=1 default: spent
        spent = _time.perf_counter() - t0
    assert stalled >= 0.05
    assert fast < 0.04 and spent < 0.04
