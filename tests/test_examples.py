"""Smoke tests: every example under ``examples/`` must run end-to-end on
the simulated 8-device CPU mesh and print its success sentinel.

The examples are the user-facing surface of the package (the reference
ships its walkthroughs as docs, docs/src/examples/*.md); running them in
CI means a signature drift in ``make_train_step``, the models, or the
sync/loader APIs fails loudly instead of shipping silently (VERDICT r4
weak #5). Each example is a fresh interpreter (its own platform pinning),
so these run as subprocesses with small step counts.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

_REPO = Path(__file__).resolve().parent.parent
_EXAMPLES = _REPO / "examples"

# (file, extra argv) — every example self-pins to the simulated CPU mesh
# via --simulate (or its own in-file default). Step counts stay at each
# example's default when its convergence assert needs them.
_CASES = [
    ("quickstart.py", ["--simulate", "8", "--epochs", "10"], "QUICKSTART_OK"),
    ("cifar_cnn.py", ["--simulate", "8", "--epochs", "2"], "CIFAR_CNN_OK"),
    ("deq_regression.py", ["--simulate", "8"], "DEQ_OK"),
    ("transformer_ring.py", ["--simulate", "8"], "TRANSFORMER_RING_OK"),
    ("vit_classification.py", ["--simulate", "8", "--epochs", "2"],
     "VIT_EXAMPLE_OK"),
    ("adapter_sync.py", ["--simulate", "8"], "ADAPTER_SYNC_OK"),
    # Trains to convergence (the generation check needs a sharp model).
    ("lm_pretrain.py", ["--simulate", "8"], "LM_PRETRAIN_OK", 900),
    ("ddpm_toy.py", ["--simulate", "8", "--steps", "60"], "DDPM_TOY_OK",
     600),
    ("parallelism_3d.py", [], "PARALLELISM_3D_OK"),
    ("long_context_zigzag.py", [], "LONG_CONTEXT_ZIGZAG_OK"),
]

# Examples whose convergence run dominates the tier-1 wall clock (the
# 14-epoch lm_pretrain alone is ~7 minutes on the CPU mesh) run in the
# slow tier; `pytest -m slow` still exercises them end to end.
_SLOW = {"lm_pretrain.py"}


def test_every_example_is_covered():
    """A new example must get a smoke test (or be excluded here on
    purpose)."""
    on_disk = {p.name for p in _EXAMPLES.glob("*.py")}
    covered = {c[0] for c in _CASES}
    assert on_disk == covered, (
        f"examples without a smoke test: {sorted(on_disk - covered)}; "
        f"smoke tests without a file: {sorted(covered - on_disk)}"
    )


@pytest.mark.parametrize(
    "name,argv,sentinel,timeout",
    [
        pytest.param(
            *(c if len(c) == 4 else (*c, 420)),
            id=c[0],
            marks=[pytest.mark.slow] if c[0] in _SLOW else [],
        )
        for c in _CASES
    ],
)
def test_example_runs(name, argv, sentinel, timeout):
    env = dict(os.environ)
    # The package is used from a source checkout (never pip-installed in
    # this image); examples import it by name, so the child needs the
    # repo root on its path regardless of the launcher's environment.
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (str(_REPO), env.get("PYTHONPATH")) if p
    )
    # Examples without a --simulate flag pin themselves; for the rest the
    # flag sets both env vars before importing jax. Either way the
    # subprocess must never touch a real accelerator from the test suite.
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    proc = subprocess.run(
        [sys.executable, str(_EXAMPLES / name), *argv],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_REPO,
    )
    tail = "\n".join(proc.stdout.splitlines()[-5:] +
                     proc.stderr.splitlines()[-15:])
    assert proc.returncode == 0, f"{name} failed (rc={proc.returncode}):\n{tail}"
    assert sentinel in proc.stdout, f"{name} missing {sentinel}:\n{tail}"
