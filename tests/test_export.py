"""Live export plane tests (telemetry/export.py + scripts/fluxmpi_top.py):
name-mangling round trips, Prometheus rendering, the three endpoints
over real HTTP, /healthz stall semantics (fake clock AND a real injected
data.fetch stall), the zero-cost-when-off contract, the full
telemetry.shutdown() reset — parametrized over EVERY plane — and the
terminal dashboard CLI."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import optax

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults
from fluxmpi_tpu import telemetry
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import (
    MemorySink,
    MetricsRegistry,
    export,
    get_registry,
)
from fluxmpi_tpu.telemetry.export import (
    Exporter,
    demangle_name,
    exposed_base_name,
    mangle_name,
    render_prometheus,
)
from fluxmpi_tpu.telemetry.schema import (
    KNOWN_METRIC_NAMES,
    _CLOSED_NAMESPACES,
    validate_status_record,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOP = os.path.join(_REPO, "scripts", "fluxmpi_top.py")


def _get(port, path):
    """(status code, body bytes) — 503s come back as data, not raises."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


def _series_names(metrics_text):
    names = set()
    for line in metrics_text.splitlines():
        if not line or line.startswith("#"):
            continue
        names.add(line.split("{", 1)[0].split(" ", 1)[0])
    return names


def _assert_closed_namespace_clean(metrics_text):
    """The smoke contract: every exposed series demangles, and every
    closed-namespace name is schema-known — the exporter is not a side
    channel around the closed namespace."""
    names = _series_names(metrics_text)
    assert names, "no series exposed"
    for series in names:
        base = exposed_base_name(series)  # raises on a foreign name
        if base.startswith(_CLOSED_NAMESPACES):
            assert base in KNOWN_METRIC_NAMES, (series, base)


# ---------------------------------------------------------------------------
# Name mangling
# ---------------------------------------------------------------------------


def test_mangle_round_trips_every_known_name():
    for name in KNOWN_METRIC_NAMES:
        assert demangle_name(mangle_name(name)) == name


def test_mangle_is_injective_and_prometheus_legal():
    import re

    legal = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    tricky = set(KNOWN_METRIC_NAMES) | {
        "a.b_c",
        "a_b.c",
        "a__b.c",
        "a.b.c_d_e",
        "train.step_seconds",
    }
    mangled = {mangle_name(n) for n in tricky}
    assert len(mangled) == len(tricky)  # injective: no two names collide
    for m in mangled:
        assert legal.match(m), m
        assert not m.startswith("__")  # the reserved Prometheus prefix


def test_demangle_rejects_foreign_series():
    with pytest.raises(ValueError):
        demangle_name("node_cpu_seconds_total")


def test_exposed_base_name_strips_histogram_suffixes():
    base = mangle_name("train.step_seconds")
    for suffix in (
        "_count", "_sum", "_min", "_max", "_mean", "_last", "_bucket",
    ):
        assert exposed_base_name(base + suffix) == "train.step_seconds"
    # A plain gauge whose name merely ends like a suffix stays itself.
    assert exposed_base_name(mangle_name("goodput.updates")) == (
        "goodput.updates"
    )


def test_bucket_suffix_round_trips_every_bucketed_name():
    """The _bucket series of every edge-declared histogram demangles
    back to its schema name (the quantile series must validate through
    the same closed-namespace smoke as every other)."""
    from fluxmpi_tpu.telemetry.schema import HISTOGRAM_BUCKET_EDGES

    for name in HISTOGRAM_BUCKET_EDGES:
        assert name in KNOWN_METRIC_NAMES
        assert exposed_base_name(mangle_name(name) + "_bucket") == name


def test_token_count_buckets_render_for_serving_size_histograms():
    """The request-size histograms carry the powers-of-2 token-count
    ladder: observations land in cumulative le= buckets that render,
    round-trip, and stay inside the closed namespace."""
    reg = MetricsRegistry()
    reg.histogram("serving.prompt_tokens").observe(5)
    reg.histogram("serving.output_tokens").observe(100)
    text = render_prometheus(reg.snapshot())
    base = mangle_name("serving.prompt_tokens")
    assert f'{base}_bucket{{le="4"}} 0' in text
    assert f'{base}_bucket{{le="8"}} 1' in text
    assert f'{base}_bucket{{le="+Inf"}} 1' in text
    out = mangle_name("serving.output_tokens")
    assert f'{out}_bucket{{le="64"}} 0' in text
    assert f'{out}_bucket{{le="128"}} 1' in text
    for name in ("serving.prompt_tokens", "serving.output_tokens"):
        assert exposed_base_name(mangle_name(name) + "_bucket") == name
    _assert_closed_namespace_clean(text)


# ---------------------------------------------------------------------------
# Prometheus rendering
# ---------------------------------------------------------------------------


def test_render_prometheus_kinds_and_labels():
    reg = MetricsRegistry()
    reg.counter("comm.calls", op="allreduce", path="device").inc(3)
    reg.gauge("train.loss", shard='a"b\\c').set(1.5)
    reg.histogram("train.step_seconds").observe(0.25)
    reg.histogram("train.step_seconds").observe(0.75)
    text = render_prometheus(reg.snapshot())
    assert '# TYPE fluxmpi_comm_calls counter' in text
    assert (
        'fluxmpi_comm_calls{op="allreduce",path="device"} 3' in text
    )
    assert '# TYPE fluxmpi_train_loss gauge' in text
    assert 'shard="a\\"b\\\\c"' in text  # exposition escaping
    assert "fluxmpi_train_step__seconds_count 2" in text
    assert "fluxmpi_train_step__seconds_sum 1" in text
    assert "fluxmpi_train_step__seconds_max 0.75" in text
    # Schema-declared buckets render as cumulative _bucket{le} series
    # with the +Inf terminator — the histogram_quantile() shape.
    assert '# TYPE fluxmpi_train_step__seconds_bucket counter' in text
    assert 'fluxmpi_train_step__seconds_bucket{le="0.25"} 1' in text
    assert 'fluxmpi_train_step__seconds_bucket{le="1"} 2' in text
    assert 'fluxmpi_train_step__seconds_bucket{le="+Inf"} 2' in text
    # One TYPE line per family even with several label sets.
    reg.counter("comm.calls", op="bcast", path="device").inc()
    text = render_prometheus(reg.snapshot())
    assert text.count("# TYPE fluxmpi_comm_calls counter") == 1


def test_render_prometheus_nonfinite_values():
    reg = MetricsRegistry()
    reg.gauge("train.loss").set(float("nan"))
    reg.gauge("train.grad_norm").set(float("inf"))
    text = render_prometheus(reg.snapshot())
    assert "fluxmpi_train_loss NaN" in text
    assert "fluxmpi_train_grad__norm +Inf" in text


def test_render_prometheus_later_duplicates_win():
    metrics = [
        {"name": "goodput.fraction", "type": "gauge", "labels": {},
         "value": 0.1},
        {"name": "goodput.fraction", "type": "gauge", "labels": {},
         "value": 0.9},
    ]
    text = render_prometheus(metrics)
    assert "fluxmpi_goodput_fraction 0.9" in text
    assert "0.1" not in text


# ---------------------------------------------------------------------------
# The three endpoints over real HTTP (the tier-1 smoke satellite)
# ---------------------------------------------------------------------------


def test_exporter_smoke_metrics_status_healthz():
    reg = MetricsRegistry()
    reg.counter("train.steps").inc(7)
    reg.gauge("goodput.fraction").set(0.5)
    reg.histogram("train.step_seconds").observe(0.01)
    exp = Exporter(0, "127.0.0.1", registry=reg, deadline=60.0)
    exp.start()
    try:
        code, body = _get(exp.port, "/metrics")
        assert code == 200
        text = body.decode()
        _assert_closed_namespace_clean(text)
        assert "fluxmpi_train_steps 7" in text
        # Self-telemetry rode the same scrape discipline.
        assert 'fluxmpi_export_requests{endpoint="metrics"}' in text

        code, body = _get(exp.port, "/status")
        assert code == 200
        status = json.loads(body)
        assert validate_status_record(status) == []
        assert status["run_id"] == exp.run_id

        code, body = _get(exp.port, "/healthz")
        assert code == 200
        health = json.loads(body)
        assert health["healthy"] is True

        code, _ = _get(exp.port, "/nonsense")
        assert code == 404
    finally:
        exp.stop()


def test_metrics_scrape_sees_live_goodput_without_flush():
    from fluxmpi_tpu.telemetry import goodput as goodput_mod

    fake = {"now": 0.0}
    tracker = goodput_mod.GoodputTracker(
        clock=lambda: fake["now"], enabled=True
    )
    prev = goodput_mod.set_goodput_tracker(tracker)
    exp = Exporter(0, "127.0.0.1", registry=MetricsRegistry(), deadline=60.0)
    exp.start()
    try:
        with tracker.segment("step"):
            fake["now"] += 2.0
        fake["now"] += 2.0
        _, body = _get(exp.port, "/metrics")
        text = body.decode()
        # NO flush ever happened, yet the scrape carries the tracker's
        # live numbers.
        assert 'fluxmpi_goodput_bucket__seconds{bucket="step"} 2' in text
        assert "fluxmpi_goodput_fraction 0.5" in text
        _, body = _get(exp.port, "/status")
        status = json.loads(body)
        assert status["goodput"]["goodput_fraction"] == pytest.approx(0.5)
    finally:
        exp.stop()
        goodput_mod.set_goodput_tracker(prev)


# ---------------------------------------------------------------------------
# /healthz semantics (fake clock — the watchdog test discipline)
# ---------------------------------------------------------------------------


def test_healthz_stall_semantics_fake_clock():
    fake = {"now": 0.0, "progress": 0}
    exp = Exporter(
        0,
        "127.0.0.1",
        deadline=10.0,
        clock=lambda: fake["now"],
        sources=[lambda: fake["progress"]],
    )
    # No server needed: health() is the endpoint's whole brain.
    assert exp.health()["healthy"] is True  # baseline scrape
    fake["now"] += 100.0
    # Progress never observed: an idle process is alive, merely idle.
    h = exp.health()
    assert h["healthy"] is True and h["progress_seen"] is False
    # Training starts: progress advances.
    fake["progress"] += 1
    assert exp.health()["healthy"] is True
    # Stall past the deadline -> unhealthy.
    fake["now"] += 10.5
    h = exp.health()
    assert h["healthy"] is False
    assert h["seconds_since_progress"] == pytest.approx(10.5)
    # Progress resumes -> healthy again immediately.
    fake["progress"] += 1
    assert exp.health()["healthy"] is True


def test_healthz_late_probe_sees_wedge_before_first_scrape():
    """A probe attached AFTER the host wedged: the baseline read finds
    monotonic sources already past zero — that IS progress having
    happened, so the plateau must still flip 503 past the deadline
    (the orchestrator-restarts-a-wedged-host contract)."""
    fake = {"now": 0.0}
    exp = Exporter(
        0,
        "127.0.0.1",
        deadline=10.0,
        clock=lambda: fake["now"],
        sources=[lambda: 100],  # trained, then wedged before any scrape
    )
    assert exp.health()["progress_seen"] is True  # baseline: already >0
    fake["now"] += 10.5
    assert exp.health()["healthy"] is False


def test_run_id_honors_launcher_env(monkeypatch):
    monkeypatch.setenv("FLUXMPI_TPU_RUN_ID", "job-abc123")
    exp = Exporter(0, "127.0.0.1")
    assert exp.run_id == "job-abc123"  # identical on every host of a job
    monkeypatch.delenv("FLUXMPI_TPU_RUN_ID")
    assert Exporter(0, "127.0.0.1").run_id  # local fallback stamp


def test_healthz_deadline_follows_armed_watchdog():
    from fluxmpi_tpu.telemetry import watchdog as watchdog_mod

    fake = {"now": 0.0, "progress": 0}
    exp = Exporter(
        0,
        "127.0.0.1",
        clock=lambda: fake["now"],
        sources=[lambda: fake["progress"]],
    )
    try:
        watchdog_mod.arm_watchdog(deadline=7.0)
        assert exp.health()["deadline_seconds"] == 7.0
    finally:
        watchdog_mod.disarm_watchdog()
    assert exp.health()["deadline_seconds"] == 300.0  # the default


# ---------------------------------------------------------------------------
# Wiring: configure() forms, init kwarg, idempotency, shutdown reset
# ---------------------------------------------------------------------------


def test_configure_forms_and_idempotent_replay(monkeypatch):
    monkeypatch.delenv("FLUXMPI_TPU_EXPORT_PORT", raising=False)
    assert export.configure(None) is None  # env unset: no-op
    exp = export.configure(Exporter(0, "127.0.0.1"))
    try:
        assert exp is export.get_exporter() and exp.running
        port = exp.port
        # Replay naming the running port keeps the instance (status
        # board and all) instead of bouncing the socket.
        monkeypatch.setenv("FLUXMPI_TPU_EXPORT_ADDR", "127.0.0.1")
        again = export.configure(port)
        assert again is exp
        assert export.configure(str(port)) is exp
    finally:
        export.shutdown()
    assert export.get_exporter() is None
    with pytest.raises(ValueError):
        export.configure(object())


def test_configure_env_port(monkeypatch):
    # Reserve an ephemeral port, then hand it to the env route.
    probe = Exporter(0, "127.0.0.1")
    probe.start()
    port = probe.port
    probe.stop()
    monkeypatch.setenv("FLUXMPI_TPU_EXPORT_PORT", str(port))
    monkeypatch.setenv("FLUXMPI_TPU_EXPORT_ADDR", "127.0.0.1")
    try:
        exp = export.configure(None)
        assert exp is not None and exp.running and exp.port == port
        assert exp.addr == "127.0.0.1"
    finally:
        export.shutdown()


def test_configure_env_typo_degrades_not_crashes(monkeypatch):
    # The faults.configure convention: an env typo must not crash a
    # training job at init() — warn, leave the plane off.
    monkeypatch.setenv("FLUXMPI_TPU_EXPORT_PORT", "auto")
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_EXPORT_PORT"):
        assert export.configure(None) is None
    assert export.get_exporter() is None
    # An explicit programmatic spec still raises (a typo in CODE is a
    # bug to surface, not an environment to survive).
    monkeypatch.delenv("FLUXMPI_TPU_EXPORT_PORT")
    with pytest.raises(ValueError):
        export.configure("auto")


def test_configure_bind_failure_degrades_not_crashes():
    # A monitoring socket must never kill training: a taken port warns
    # and leaves the plane off.
    squatter = Exporter(0, "127.0.0.1")
    squatter.start()
    try:
        with pytest.warns(UserWarning, match="cannot bind"):
            got = export.configure(Exporter(squatter.port, "127.0.0.1"))
        assert got is None
        assert export.get_exporter() is None
    finally:
        squatter.stop()


def test_init_kwarg_starts_exporter(world):
    exp = Exporter(0, "127.0.0.1")
    try:
        fm.init(export=exp)
        assert export.get_exporter() is exp and exp.running
        code, _ = _get(exp.port, "/healthz")
        assert code == 200
    finally:
        export.shutdown()


def test_shutdown_frees_port_for_immediate_reinit():
    exp = Exporter(0, "127.0.0.1")
    export.configure(exp)
    port = exp.port
    telemetry.shutdown()  # the full-plane teardown, not export.shutdown
    assert export.get_exporter() is None
    # The port is immediately rebindable: socket closed, thread joined.
    again = Exporter(port, "127.0.0.1")
    again.start()
    try:
        assert again.port == port
        code, _ = _get(port, "/healthz")
        assert code == 200
    finally:
        again.stop()


# ---------------------------------------------------------------------------
# telemetry.shutdown() resets EVERY plane (the parametrized leak test —
# a new plane that skips the discipline fails here, not in review)
# ---------------------------------------------------------------------------


def _arm_registry(tmp_path):
    get_registry().add_sink(MemorySink())


def _check_registry():
    assert get_registry().sinks == ()


def _arm_tracer(tmp_path):
    from fluxmpi_tpu.telemetry import tracing

    tracing.configure(str(tmp_path / "trace.{process}.json"))
    tracing.instant("mark")
    assert len(tracing.get_tracer()) > 0


def _check_tracer():
    from fluxmpi_tpu.telemetry import tracing

    tracer = tracing.get_tracer()
    assert not tracer.enabled
    assert len(tracer) == 0
    assert tracing._export_path is None


def _arm_flight_recorder(tmp_path):
    rec = telemetry.get_flight_recorder()
    entry = rec.begin("allreduce", "device", 64)
    rec.complete(entry)
    assert len(rec) > 0


def _check_flight_recorder():
    assert len(telemetry.get_flight_recorder()) == 0


def _arm_watchdog(tmp_path):
    telemetry.arm_watchdog(deadline=60.0)


def _check_watchdog():
    assert telemetry.get_watchdog() is None


def _arm_goodput(tmp_path):
    from fluxmpi_tpu.telemetry import goodput as goodput_mod

    goodput_mod.configure(True)
    tracker = goodput_mod.get_goodput_tracker()
    tracker.start_run()
    assert tracker.enabled


def _check_goodput():
    from fluxmpi_tpu.telemetry import goodput as goodput_mod

    tracker = goodput_mod.get_goodput_tracker()
    assert not tracker.enabled
    assert tracker.wall_seconds() == 0.0  # run window dropped


def _arm_anomaly(tmp_path):
    from fluxmpi_tpu.telemetry import anomaly as anomaly_mod

    anomaly_mod.configure(True)


def _check_anomaly():
    assert telemetry.get_anomaly_detector() is None


def _arm_modelstats(tmp_path):
    from fluxmpi_tpu.telemetry import modelstats as modelstats_mod

    modelstats_mod.configure(True)


def _check_modelstats():
    from fluxmpi_tpu.telemetry import modelstats as modelstats_mod

    assert modelstats_mod.get_model_stats() is None


def _arm_compileplane(tmp_path):
    from fluxmpi_tpu.telemetry import compileplane as compileplane_mod

    compileplane_mod.configure(True)


def _check_compileplane():
    assert telemetry.get_compile_monitor() is None


def _arm_memory(tmp_path):
    from fluxmpi_tpu.telemetry import memory as memory_mod

    memory_mod.configure(True)
    with memory_mod._watermark_lock:
        memory_mod._watermark = 123.0


def _check_memory():
    from fluxmpi_tpu.telemetry import memory as memory_mod

    assert not memory_mod.enabled()
    assert memory_mod.peak_watermark_bytes() == 0.0


def _arm_profiler(tmp_path):
    from fluxmpi_tpu.utils import profiling

    profiling.configure_auto_profiler(str(tmp_path / "profiles"))


def _check_profiler():
    from fluxmpi_tpu.utils import profiling

    assert profiling.get_auto_profiler() is None


def _arm_exporter(tmp_path):
    export.configure(Exporter(0, "127.0.0.1"))
    assert export.get_exporter().running


def _check_exporter():
    assert export.get_exporter() is None


def _arm_serving(tmp_path):
    from fluxmpi_tpu import serving

    serving.configure(True)

    class _StubEngine:
        closed = False

        def close(self):
            self.closed = True

    _arm_serving.engine = _StubEngine()
    serving.set_engine(_arm_serving.engine)


def _check_serving():
    from fluxmpi_tpu import serving

    assert serving.get_engine() is None
    assert not serving.enabled()
    assert _arm_serving.engine.closed


def _arm_request_log(tmp_path):
    from fluxmpi_tpu.serving import observe

    obs = observe.configure(str(tmp_path / "requests.{process}.jsonl"))
    obs.burn.observe(True)
    obs.log.write({"probe": 1})
    assert obs.log._file is not None and obs.burn.total == 1
    _arm_request_log.obs = obs


def _check_request_log():
    from fluxmpi_tpu.serving import observe

    assert observe.get_request_observer() is None
    obs = _arm_request_log.obs
    assert not obs.enabled
    assert obs.log._file is None  # stream closed
    assert obs.burn.total == 0  # windows cleared


def _arm_fleet(tmp_path):
    from fluxmpi_tpu.telemetry import fleet as fleet_mod

    collector = fleet_mod.FleetCollector(
        ["127.0.0.1:1"], interval=60.0
    ).start()
    fleet_mod.configure(collector)
    assert fleet_mod.enabled() and collector.running
    _arm_fleet.collector = collector


def _check_fleet():
    from fluxmpi_tpu.telemetry import fleet as fleet_mod

    assert not fleet_mod.enabled()
    assert fleet_mod.get_fleet_collector() is None
    assert not _arm_fleet.collector.running  # thread stopped, not leaked


_PLANES = [
    ("registry", _arm_registry, _check_registry),
    ("tracer", _arm_tracer, _check_tracer),
    ("flight_recorder", _arm_flight_recorder, _check_flight_recorder),
    ("watchdog", _arm_watchdog, _check_watchdog),
    ("goodput", _arm_goodput, _check_goodput),
    ("anomaly", _arm_anomaly, _check_anomaly),
    ("modelstats", _arm_modelstats, _check_modelstats),
    ("compileplane", _arm_compileplane, _check_compileplane),
    ("memory", _arm_memory, _check_memory),
    ("profiler", _arm_profiler, _check_profiler),
    ("exporter", _arm_exporter, _check_exporter),
    ("serving", _arm_serving, _check_serving),
    ("request_log", _arm_request_log, _check_request_log),
    ("fleet", _arm_fleet, _check_fleet),
]


@pytest.mark.parametrize(
    "plane,arm,check", _PLANES, ids=[p[0] for p in _PLANES]
)
def test_shutdown_resets_every_plane(plane, arm, check, tmp_path):
    """The fault-plane leak rule, asserted in ONE place for EVERY plane:
    arm it, run the full telemetry.shutdown(), and the plane's state is
    gone. A new plane that skips the discipline must be added to
    _PLANES — and then fails here until its shutdown() resets it."""
    arm(tmp_path)
    telemetry.shutdown()
    check()


# ---------------------------------------------------------------------------
# train_loop wiring: zero-cost-when-off + the status board
# ---------------------------------------------------------------------------


def _mlp_pieces(world, n=256):
    import jax.numpy as jnp

    from fluxmpi_tpu.models import MLP

    model = MLP(features=(8, 8, 1))

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), np.zeros((2, 1), np.float32))
    )
    return loss_fn, opt, params, ArrayDataset((x, x**2))


def test_train_loop_fully_off_never_touches_exporter(world, monkeypatch):
    """The zero-cost contract, monkeypatch-explode style: with no
    exporter configured, a train_loop run must never start a server,
    bind a socket, render, or post status."""
    assert export.get_exporter() is None

    def explode(*a, **k):
        raise AssertionError("exporter touched on the fully-off path")

    monkeypatch.setattr(Exporter, "start", explode)
    monkeypatch.setattr(Exporter, "note_status", explode)
    monkeypatch.setattr(export, "render_prometheus", explode)
    loss_fn, opt, params, ds = _mlp_pieces(world)
    loader = DistributedDataLoader(ds, 64, mesh=world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state = replicate(TrainState.create(params, opt, None), world)
    _, summary = train_loop(step, state, loader, epochs=1, flush_every=2)
    assert summary["updates"] == 4


def test_train_loop_posts_status_board(world):
    get_registry().reset()
    exp = Exporter(0, "127.0.0.1", deadline=3600.0)
    export.configure(exp)
    try:
        loss_fn, opt, params, ds = _mlp_pieces(world)
        loader = DistributedDataLoader(ds, 64, mesh=world)
        step = make_train_step(loss_fn, opt, mesh=world, metrics=True)
        state = replicate(TrainState.create(params, opt, None), world)
        _, summary = train_loop(
            step, state, loader, epochs=2, flush_every=2, fuse=False
        )
        code, body = _get(exp.port, "/status")
        assert code == 200
        status = json.loads(body)
        assert validate_status_record(status) == []
        train = status["train"]
        assert train["phase"] == "finished"
        assert train["updates"] == summary["updates"] == 8
        assert train["epochs"] == 2
        assert train["loss"] == pytest.approx(summary["loss"])
        assert train["preempted"] is False and train["anomaly"] is None
        # The flush's registry state is scrapeable too, schema-clean.
        code, body = _get(exp.port, "/metrics")
        assert code == 200
        _assert_closed_namespace_clean(body.decode())
    finally:
        export.shutdown()


def test_e2e_healthz_stall_roundtrip(world):
    """The acceptance loop: a live run with export on serves
    schema-valid /metrics mid-run; an injected data.fetch stall (the
    faults plane's delay= entry) drives /healthz 200 -> 503; progress
    resuming flips it back to 200."""
    get_registry().reset()
    exp = Exporter(0, "127.0.0.1", deadline=0.25)
    export.configure(exp)
    codes: list[int] = []
    midrun_metrics: list[str] = []
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            try:
                code, _ = _get(exp.port, "/healthz")
                codes.append(code)
                if len(midrun_metrics) < 1:
                    c2, body = _get(exp.port, "/metrics")
                    if c2 == 200:
                        midrun_metrics.append(body.decode())
            except Exception:
                pass
            time.sleep(0.03)

    poller = threading.Thread(target=poll, daemon=True)
    try:
        loss_fn, opt, params, ds = _mlp_pieces(world, n=64 * 40)
        loader = DistributedDataLoader(ds, 64, mesh=world)  # 40 batches
        step = make_train_step(loss_fn, opt, mesh=world, metrics=True)
        state = replicate(TrainState.create(params, opt, None), world)
        poller.start()
        # The 12th fetch stalls 0.8 s — far past the 0.25 s deadline.
        with faults.scope("data.fetch@step=12:delay=0.8"):
            _, summary = train_loop(
                step, state, loader, epochs=1, flush_every=4, fuse=False
            )
        assert summary["updates"] == 40
        # The run is over (progress idle); tick progress and ask again:
        # liveness keys on progress advancing, so this is the
        # deterministic "stall cleared" probe.
        telemetry.notify_progress()
        code, _ = _get(exp.port, "/healthz")
        assert code == 200
    finally:
        stop.set()
        poller.join(timeout=5)
        export.shutdown()
    assert 503 in codes, f"no unhealthy sample during the stall: {codes}"
    assert codes[0] == 200, codes  # healthy before the stall
    assert midrun_metrics, "no /metrics scrape landed mid-run"
    _assert_closed_namespace_clean(midrun_metrics[0])


# ---------------------------------------------------------------------------
# fluxmpi_top
# ---------------------------------------------------------------------------


def _run_top(*args):
    return subprocess.run(
        [sys.executable, _TOP, *args],
        capture_output=True,
        text=True,
        timeout=60,
    )


def test_fluxmpi_top_once_renders_live_host():
    reg = MetricsRegistry()
    reg.gauge("monitor.heartbeat_age_seconds").set(1.5)
    exp = Exporter(0, "127.0.0.1", registry=reg, deadline=3600.0)
    exp.start()
    exp.note_status(phase="running", updates=1234, loss=0.5)
    try:
        out = _run_top(f"127.0.0.1:{exp.port}", "--once")
        assert out.returncode == 0, out.stderr
        assert "1234" in out.stdout
        assert "phase running" in out.stdout
        assert "ok" in out.stdout
        jout = _run_top(f"127.0.0.1:{exp.port}", "--once", "--json")
        assert jout.returncode == 0
        payload = json.loads(jout.stdout)
        assert payload[f"127.0.0.1:{exp.port}"]["train"]["updates"] == 1234
    finally:
        exp.stop()


def test_fluxmpi_top_unreachable_exits_2():
    out = _run_top("127.0.0.1:1", "--once", "--timeout", "0.3")
    assert out.returncode == 2
    assert "UNREACHABLE" in out.stdout


def test_fluxmpi_top_jsonl_fallback(tmp_path):
    rec = {
        "schema": "fluxmpi_tpu.telemetry/v1",
        "time_unix": time.time(),
        "process": 0,
        "metrics": [
            {"name": "train.steps", "type": "counter", "labels": {},
             "value": 640.0},
            {"name": "train.loss", "type": "gauge", "labels": {},
             "value": 0.125},
            {"name": "goodput.wall_seconds", "type": "gauge",
             "labels": {}, "value": 10.0},
            {"name": "goodput.fraction", "type": "gauge", "labels": {},
             "value": 0.9},
            {"name": "monitor.heartbeat_unix", "type": "gauge",
             "labels": {}, "value": time.time() - 3.0},
        ],
    }
    bank = tmp_path / "run.0.jsonl"
    bank.write_text(json.dumps(rec) + "\n" + '{"torn', encoding="utf-8")
    out = _run_top("--jsonl", str(bank), "--once")
    assert out.returncode == 0, out.stderr
    assert "640" in out.stdout
    assert "90.0%" in out.stdout
    assert "jsonl" in out.stdout  # health source, no live probe to ask
