"""Gradient layer tests (reference: test/test_optimizer.jl).

The central oracle is the reference's equivalence test
(test/test_optimizer.jl:20-26): a DistributedOptimizer update with identical
per-worker gradients must equal a plain optimizer update fed
``grads * total_workers()`` (sum semantics, not mean).
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    except AttributeError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def test_distributed_optimizer_equivalence(world, nworkers):
    # reference: test/test_optimizer.jl:20-26
    import fluxmpi_tpu as fm

    params = {"w": jnp.ones((3, 2)), "b": jnp.zeros((2,))}
    grads = {"w": jnp.full((3, 2), 0.1), "b": jnp.full((2,), 0.2)}

    dopt = fm.DistributedOptimizer(optax.adam(1e-3), axis_name="dp")

    def dstep(p, g):
        state = dopt.init(p)
        upd, _ = dopt.update(g, state, p)
        return optax.apply_updates(p, upd)

    mesh = fm.global_mesh()
    dist_params = _shard_map(dstep, mesh, (P(), P()), P())(params, grads)

    sopt = optax.adam(1e-3)
    sstate = sopt.init(params)
    scaled = jax.tree_util.tree_map(lambda g: g * nworkers, grads)
    supd, _ = sopt.update(scaled, sstate, params)
    serial_params = optax.apply_updates(params, supd)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6
        ),
        dist_params,
        serial_params,
    )


def test_allreduce_gradients_sum_scaling(world, nworkers):
    # reference: test/test_optimizer.jl:29-36
    import fluxmpi_tpu as fm

    grads = {"w": jnp.full((4,), 0.5), "nested": {"b": jnp.ones((2, 2))}}

    def step(g):
        return fm.allreduce_gradients(g, axis_name="dp")

    mesh = fm.global_mesh()
    out = _shard_map(step, mesh, (P(),), P())(grads)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.5 * nworkers)
    np.testing.assert_allclose(np.asarray(out["nested"]["b"]), float(nworkers))


def test_allreduce_gradients_mean(world, nworkers):
    import fluxmpi_tpu as fm

    grads = {"w": jnp.full((4,), 2.0)}

    def step(g):
        return fm.allreduce_gradients(g, axis_name="dp", reduce_op="mean")

    mesh = fm.global_mesh()
    out = _shard_map(step, mesh, (P(),), P())(grads)
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0)


def test_allreduce_gradients_rank_varying(world, nworkers):
    # distinct per-worker grads: sum across slices
    import fluxmpi_tpu as fm

    stacked = jnp.arange(float(nworkers)).reshape(nworkers, 1)

    def step(g):
        return fm.allreduce_gradients(g, axis_name="dp")

    mesh = fm.global_mesh()
    out = _shard_map(step, mesh, (P("dp"),), P("dp"))(stacked)
    expected = np.full((nworkers, 1), np.arange(nworkers).sum())
    np.testing.assert_allclose(np.asarray(out), expected)


def test_allreduce_gradients_eager_single_process(world):
    # Eager path: world of one controller process → values unchanged,
    # structure and dtypes preserved.
    import fluxmpi_tpu as fm

    grads = {"w": jnp.full((3,), 1.5, dtype=jnp.bfloat16), "b": np.ones((2,))}
    out = fm.allreduce_gradients(grads)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out["w"], dtype=np.float32), 1.5)
    np.testing.assert_allclose(out["b"], 1.0)


def test_allreduce_gradients_empty(world):
    import fluxmpi_tpu as fm

    assert fm.allreduce_gradients({}) == {}


def test_distributed_optimizer_init_delegates(world):
    # reference: src/optimizer.jl:25 — init delegates to the inner rule
    import fluxmpi_tpu as fm

    params = {"w": jnp.ones((2,))}
    dopt = fm.DistributedOptimizer(optax.adam(1e-3))
    state = dopt.init(params)
    inner = optax.adam(1e-3).init(params)
    assert jax.tree_util.tree_structure(state.inner) == jax.tree_util.tree_structure(
        inner
    )


def test_reduce_op_validation(world):
    import fluxmpi_tpu as fm

    with pytest.raises(ValueError):
        fm.allreduce_gradients({"w": jnp.ones(2)}, reduce_op="median")


def test_allreduce_gradients_eager_device_sharded_raises(world, nworkers):
    # VERDICT r1 weak #4: eagerly-divergent per-device values (shard_ranks
    # layout) must never silently pass through. They are ambiguous in the
    # eager path (an FSDP-sharded grad is one global value; a shard_ranks
    # stack is per-worker) → loud error pointing at the correct spellings.
    import pytest

    import fluxmpi_tpu as fm

    per_worker = np.arange(nworkers, dtype=np.float32).reshape(nworkers, 1)
    grads = {
        "sharded": fm.shard_ranks(per_worker),
        "replicated": jnp.full((2,), 7.0),
    }
    with pytest.raises(ValueError, match="device-sharded leaf"):
        fm.allreduce_gradients(grads)

    # The pointed-to spelling does reduce the per-worker stack.
    out = fm.unshard_ranks(fm.allreduce(grads["sharded"]))
    np.testing.assert_allclose(out, np.full((nworkers, 1), per_worker.sum()))
