"""Serving plane tests: the paged KV cache's free-list round trip,
token-budget admission control, continuous batching's bit-identity with
``generate()``, zero-retrace mid-flight joins, streaming delivery,
preemption draining under load, the ``serving.admit``/``serving.decode``
fault sites, and the telemetry/status wiring."""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults, runtime, serving
from fluxmpi_tpu.errors import FaultInjectedError, RequestRejectedError
from fluxmpi_tpu.models import TransformerLM
from fluxmpi_tpu.models.generate import generate
from fluxmpi_tpu.serving import BlockKVCache, InferenceEngine, blocks_for_tokens
from fluxmpi_tpu.serving import observe
from fluxmpi_tpu.telemetry import Exporter, export, get_registry
from fluxmpi_tpu.telemetry import compileplane, tracing
from fluxmpi_tpu.telemetry.anomaly import AnomalyDetector, set_anomaly_detector
from fluxmpi_tpu.telemetry.schema import (
    KNOWN_METRIC_NAMES,
    validate_metric,
    validate_record,
    validate_status_record,
)


@pytest.fixture(scope="module")
def model(world):
    lm = TransformerLM(vocab_size=32, max_len=64, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    variables = lm.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return lm, variables


@pytest.fixture()
def engine_factory(model):
    lm, variables = model
    built = []

    def make(**kwargs):
        kwargs.setdefault("slots", 2)
        kwargs.setdefault("block_size", 8)
        eng = InferenceEngine(lm, variables, **kwargs)
        built.append(eng)
        return eng

    yield make
    for eng in built:
        eng.close()
    serving.shutdown()
    observe.shutdown()
    runtime.clear_preemption()
    get_registry().reset()


def _prompt(rng, n):
    return rng.integers(0, 32, size=(n,)).astype(np.int32)


# ---------------------------------------------------------------------------
# Block cache / free-list allocator
# ---------------------------------------------------------------------------


def test_free_list_round_trip():
    cache = BlockKVCache(num_layers=2, num_heads=4, head_dim=8,
                         num_blocks=9, block_size=16, max_blocks_per_seq=4)
    assert cache.free_blocks == 8  # block 0 is the reserved trash block
    assert cache.capacity_tokens == 8 * 16
    a = cache.alloc(40)  # 3 blocks
    assert len(a) == 3 and 0 not in a
    b = cache.alloc(16)
    assert cache.used_blocks == 4
    cache.free(a)
    assert cache.free_blocks == 7
    # Freed blocks are reused (LIFO — the most recently freed first).
    c = cache.alloc(48)
    assert set(c) <= set(a) | set(range(1, 9))
    assert set(a) & set(c), "freed blocks must be handed out again"
    cache.free(b)
    cache.free(c)
    assert cache.free_blocks == 8


def test_allocator_rejects_bad_frees_and_exhaustion():
    cache = BlockKVCache(num_layers=1, num_heads=1, head_dim=4,
                         num_blocks=4, block_size=8, max_blocks_per_seq=3)
    blocks = cache.alloc(24)  # all 3
    assert not cache.can_alloc(1)
    with pytest.raises(RuntimeError, match="exhausted"):
        cache.alloc(8)
    with pytest.raises(ValueError, match="outside the pool"):
        cache.free([0])
    cache.free(blocks)
    with pytest.raises(ValueError, match="double free"):
        cache.free([blocks[0]])


def test_blocks_for_tokens_math():
    assert blocks_for_tokens(1, 16) == 1
    assert blocks_for_tokens(16, 16) == 1
    assert blocks_for_tokens(17, 16) == 2


def test_table_row_pads_with_trash():
    cache = BlockKVCache(num_layers=1, num_heads=1, head_dim=4,
                         num_blocks=8, block_size=8, max_blocks_per_seq=5)
    row = cache.table_row([3, 1])
    assert row.tolist() == [3, 1, 0, 0, 0]


def test_memory_plane_admission_check(model, monkeypatch):
    """The OOM-safe construction check: a pool that cannot fit the
    device's remaining HBM refuses at engine build (PR 9 memory plane),
    never at the first admission."""
    from fluxmpi_tpu.telemetry import memory as memory_mod

    lm, variables = model
    monkeypatch.setattr(
        memory_mod, "device_memory_stats",
        lambda d: {"bytes_limit": 1024.0, "bytes_in_use": 0.0},
    )
    with pytest.raises(RuntimeError, match="device memory"):
        InferenceEngine(lm, variables, slots=2, block_size=8)
    serving.shutdown()
    # Stat-less backends (CPU) have nothing to check against: fine.
    monkeypatch.setattr(memory_mod, "device_memory_stats", lambda d: {})
    eng = InferenceEngine(lm, variables, slots=2, block_size=8)
    eng.close()


# ---------------------------------------------------------------------------
# Correctness: engine output == generate()
# ---------------------------------------------------------------------------


def test_greedy_streams_bit_identical_to_generate(model, engine_factory):
    """The serving correctness proof: for a mixed-length batch of
    requests flowing through admission -> batched prefill -> continuous
    decode -> eviction, every streamed greedy continuation is
    bit-identical to ``generate()`` on the same prompt."""
    lm, variables = model
    eng = engine_factory(slots=3)
    eng.warmup(prompt_lengths=(3, 9, 16))
    rng = np.random.default_rng(7)
    cases = [(5, 8, None), (9, 4, None), (3, 12, None), (16, 6, None),
             (6, 20, 3), (4, 1, None)]
    reqs = [
        (eng.submit(_prompt(rng, plen), mnew, eos_token=eos), mnew, eos)
        for plen, mnew, eos in cases
    ]
    summary = eng.run()
    assert summary["completed"] == len(cases)
    for (req, mnew, eos) in reqs:
        ref = np.asarray(
            generate(lm, variables, jnp.asarray(req.prompt[None]), mnew,
                     eos_token=eos)
        )[0][len(req.prompt):]
        if eos is not None:
            hits = np.where(ref == eos)[0]
            if len(hits):
                ref = ref[: hits[0] + 1]  # engine stops AT eos
        np.testing.assert_array_equal(
            np.asarray(req.tokens, np.int32), ref
        )
    # Eviction returned every block: the pool is whole again.
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1


def test_midflight_join_zero_retrace(model, engine_factory):
    """A request admitted mid-flight joins the decode batch without
    recompiling the decode step: the compile monitor sees ZERO compile
    events after the warmup boundary, and the decode jit's cache holds
    exactly one entry."""
    lm, variables = model
    mon = compileplane.CompileMonitor()
    compileplane.set_compile_monitor(mon)
    try:
        eng = engine_factory(slots=2)
        eng.warmup(prompt_lengths=(5, 9, 16))
        mon.observe_flush()  # warmup boundary
        rng = np.random.default_rng(1)
        eng.submit(_prompt(rng, 9), 20)
        for _ in range(3):
            eng.step()
        late = eng.submit(_prompt(rng, 5), 8)   # joins mid-flight
        later = eng.submit(_prompt(rng, 12), 6)  # different length, same buckets
        summary = eng.run()
        assert summary["completed"] == 3
        info = mon.observe_flush()
        assert info["events"] == 0, f"steady-state compiles: {info}"
        assert mon.retraces == []
        assert eng._decode_step._cache_size() == 1
        ref = np.asarray(
            generate(lm, variables, jnp.asarray(late.prompt[None]), 8)
        )[0][5:]
        np.testing.assert_array_equal(np.asarray(late.tokens, np.int32), ref)
        assert later.status == "finished"
    finally:
        compileplane.set_compile_monitor(None)


def test_flash_decode_bit_identical_with_midflight_join(model, engine_factory):
    """Kernel plane (ISSUE 19): ``attention="flash"`` routes every
    decode attend through the Pallas kernel (interpret mode on CPU),
    reading K/V gathered through the paged block table. The greedy
    token streams must stay bit-identical to ``generate()`` on the
    naive path, and a mid-flight join must still cost zero steady-state
    retraces — the kernel swap must not perturb the PR 13 contract."""
    lm, variables = model
    mon = compileplane.CompileMonitor()
    compileplane.set_compile_monitor(mon)
    try:
        eng = engine_factory(slots=2, attention="flash")
        assert eng.attention == "flash"
        eng.warmup(prompt_lengths=(5, 9))
        mon.observe_flush()  # warmup boundary
        rng = np.random.default_rng(3)
        first = eng.submit(_prompt(rng, 9), 10)
        for _ in range(3):
            eng.step()
        late = eng.submit(_prompt(rng, 5), 8)  # joins mid-flight
        summary = eng.run()
        assert summary["completed"] == 2
        info = mon.observe_flush()
        assert info["events"] == 0, f"steady-state compiles: {info}"
        assert mon.retraces == []
        assert eng._decode_step._cache_size() == 1
        for req, mnew in ((first, 10), (late, 8)):
            ref = np.asarray(
                generate(lm, variables, jnp.asarray(req.prompt[None]), mnew)
            )[0][len(req.prompt):]
            np.testing.assert_array_equal(
                np.asarray(req.tokens, np.int32), ref
            )
    finally:
        compileplane.set_compile_monitor(None)


def test_flash_decode_masks_trash_block_garbage(model, engine_factory):
    """The segment-ids mask doubles as the padding/alias mask over the
    block-table-gathered cache: every gathered row past a request's
    cache index — trash-block rows included — lands in segment 0 and
    must not contaminate the output. Poison the reserved trash block
    (block 0) with large finite garbage (stale K/V is what it really
    holds after warmup); greedy streams must stay bit-identical to
    ``generate()``, which never sees a paged pool at all. (The sharper
    NaN variant that PROVES fully-masked tiles skip compute lives at
    the adapter level: test_ops.py
    test_flash_fn_decode_prefix_mask_skips_garbage_tiles.)"""
    lm, variables = model
    eng = engine_factory(slots=2, attention="flash")
    eng.warmup(prompt_lengths=(4, 6))
    poison = jnp.full_like(eng.cache.k_pool[:, 0], 1e6)
    eng.cache.k_pool = eng.cache.k_pool.at[:, 0].set(poison)
    eng.cache.v_pool = eng.cache.v_pool.at[:, 0].set(poison)
    rng = np.random.default_rng(11)
    # plen + max_new <= 2 blocks each: most of every gathered row is
    # trash-block garbage.
    reqs = [(eng.submit(_prompt(rng, plen), mnew), plen, mnew)
            for plen, mnew in ((4, 6), (6, 4), (5, 8))]
    summary = eng.run()
    assert summary["completed"] == len(reqs)
    for req, plen, mnew in reqs:
        toks = np.asarray(req.tokens, np.int32)
        assert np.all(toks >= 0) and np.all(toks < 32)
        ref = np.asarray(
            generate(lm, variables, jnp.asarray(req.prompt[None]), mnew)
        )[0][plen:]
        np.testing.assert_array_equal(toks, ref)


def test_engine_attention_option_validation(model):
    """The attention option's error paths: an unknown mode raises, a
    model without the switch raises a named error, and the env-var
    default (FLUXMPI_TPU_SERVING_ATTENTION) reaches the engine."""
    lm, variables = model
    with pytest.raises(ValueError, match="naive.*flash.*auto"):
        InferenceEngine(lm, variables, slots=2, block_size=8,
                        attention="fast")
    os.environ["FLUXMPI_TPU_SERVING_ATTENTION"] = "naive"
    try:
        eng = InferenceEngine(lm, variables, slots=2, block_size=8)
        assert eng.attention == "naive"
        eng.close()
    finally:
        del os.environ["FLUXMPI_TPU_SERVING_ATTENTION"]
    serving.shutdown()


def test_warmup_touches_only_the_trash_block(model, engine_factory):
    eng = engine_factory()
    free_before = eng.cache.free_blocks
    eng.warmup(prompt_lengths=(4, 11))
    assert eng.cache.free_blocks == free_before
    assert eng.queue_depth == 0 and eng.active_count == 0


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


def test_queue_full_rejects_with_counter(model, engine_factory):
    get_registry().reset()
    eng = engine_factory(slots=1, max_queue=2)
    rng = np.random.default_rng(0)
    reqs = [eng.submit(_prompt(rng, 4), 4) for _ in range(3)]
    assert [r.status for r in reqs[:2]] == ["queued", "queued"]
    assert reqs[2].status == "rejected"
    assert reqs[2].reject_reason == "queue_full"
    with pytest.raises(RuntimeError, match="queue_full"):
        reqs[2].result()
    snap = {
        (m["name"], tuple(sorted(m["labels"].items()))): m
        for m in get_registry().snapshot()
    }
    key = ("serving.admission_rejects", (("reason", "queue_full"),))
    assert snap[key]["value"] == 1
    eng.run()


def test_oversized_request_raises(model, engine_factory):
    eng = engine_factory()
    rng = np.random.default_rng(0)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(_prompt(rng, 30), eng.max_len)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(_prompt(rng, 4), 0)
    with pytest.raises(ValueError, match="vocabulary"):
        eng.submit(_prompt(rng, 4), 4, eos_token=99)


def test_capacity_queueing_and_block_reuse(model, engine_factory):
    """Token-budget admission: a pool sized for ONE request at a time
    queues the second until eviction frees its blocks — then serves it
    from the recycled blocks, correctly."""
    lm, variables = model
    # 5 usable blocks of 8 = 40 tokens; each request reserves 4 blocks.
    eng = engine_factory(slots=2, num_blocks=6, max_queue=8)
    rng = np.random.default_rng(3)
    a = eng.submit(_prompt(rng, 8), 16)   # 24 tokens -> 3 blocks
    b = eng.submit(_prompt(rng, 10), 12)  # 22 tokens -> 3 blocks, must wait
    eng.step()
    assert a.status == "active" and b.status == "queued"
    eng.run()
    assert a.status == "finished" and b.status == "finished"
    for req, mnew in ((a, 16), (b, 12)):
        ref = np.asarray(
            generate(lm, variables, jnp.asarray(req.prompt[None]), mnew)
        )[0][len(req.prompt):]
        np.testing.assert_array_equal(np.asarray(req.tokens, np.int32), ref)
    assert eng.cache.free_blocks == 5


def test_static_batching_gangs_admissions(model, engine_factory):
    """continuous=False is the A/B baseline: a new group is admitted
    only when every slot has drained, so a short request gangs behind a
    long one and total decode steps grow — the loss continuous batching
    exists to recover."""
    rng = np.random.default_rng(5)
    workload = [(6, 16), (4, 2), (5, 2), (4, 2)]

    def run_mode(continuous):
        eng = engine_factory(slots=2, continuous=continuous)
        for plen, mnew in workload:
            eng.submit(_prompt(rng, plen), mnew)
        return eng.run()

    static = run_mode(False)
    cont = run_mode(True)
    assert static["completed"] == cont["completed"] == 4
    assert static["tokens"] == cont["tokens"]
    assert cont["decode_steps"] < static["decode_steps"]


# ---------------------------------------------------------------------------
# Streaming + latency accounting
# ---------------------------------------------------------------------------


def test_streaming_callback_iterator_and_latency(model, engine_factory):
    lm, variables = model
    eng = engine_factory()
    eng.warmup(prompt_lengths=(5,))
    rng = np.random.default_rng(11)
    seen = []
    eng.start()
    try:
        req = eng.submit(_prompt(rng, 5), 10, on_token=seen.append)
        streamed = list(req.stream(timeout=30.0))
    finally:
        eng.stop()
    assert req.status == "finished"
    assert streamed == req.tokens == seen
    ref = np.asarray(
        generate(lm, variables, jnp.asarray(req.prompt[None]), 10)
    )[0][5:]
    np.testing.assert_array_equal(np.asarray(streamed, np.int32), ref)
    assert req.queue_wait_s is not None and req.queue_wait_s >= 0
    assert req.ttft_s is not None and req.ttft_s >= req.queue_wait_s
    assert req.per_token_s is not None and req.per_token_s >= 0


def test_slo_violation_counter(model, engine_factory):
    get_registry().reset()
    # Impossible SLOs: every completion violates both.
    eng = engine_factory(slo_ttft_s=0.0, slo_token_s=0.0)
    rng = np.random.default_rng(2)
    eng.submit(_prompt(rng, 4), 4)
    summary = eng.run()
    assert summary["slo_violations"] == 2
    snap = {
        (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
        for m in get_registry().snapshot()
        if m["name"] == "serving.slo_violations"
    }
    assert snap[("serving.slo_violations", (("kind", "ttft"),))] == 1
    assert snap[("serving.slo_violations", (("kind", "per_token"),))] == 1


# ---------------------------------------------------------------------------
# Preemption + faults under load (the PR 8 convention)
# ---------------------------------------------------------------------------


def test_sigterm_drains_inflight_rejects_new(model, engine_factory):
    """The preemption contract under load: in-flight requests decode to
    completion, queued and new admissions reject, and the summary
    reports the drained/rejected split."""
    lm, variables = model
    eng = engine_factory(slots=2, max_queue=8)
    rng = np.random.default_rng(9)
    a = eng.submit(_prompt(rng, 5), 24)
    b = eng.submit(_prompt(rng, 7), 24)
    c = eng.submit(_prompt(rng, 4), 4)  # queued behind the two slots
    eng.step()  # admit a + b
    runtime.request_preemption()
    try:
        summary = eng.run()
    finally:
        runtime.clear_preemption()
    assert summary["preempted"] is True
    assert summary["drained"] == 2
    assert summary["rejected"] == 1
    assert a.status == "finished" and len(a.tokens) == 24
    assert b.status == "finished" and len(b.tokens) == 24
    assert c.status == "rejected" and c.reject_reason == "preempted"
    # Drained output is still the exact generate() continuation.
    ref = np.asarray(
        generate(lm, variables, jnp.asarray(a.prompt[None]), 24)
    )[0][5:]
    np.testing.assert_array_equal(np.asarray(a.tokens, np.int32), ref)
    late = eng.submit(_prompt(rng, 4), 4)
    assert late.status == "rejected" and late.reject_reason == "draining"


@pytest.mark.parametrize("site", ["serving.admit", "serving.decode"])
def test_serving_sites_are_injectable(model, engine_factory, site):
    # Every serving.* entry of faults.KNOWN_SITES has a live trigger —
    # the coverage contract the fluxlint unregistered-fault-site rule
    # greps this file for.
    eng = engine_factory()
    rng = np.random.default_rng(4)
    with faults.scope(site + "@step=1"):
        with pytest.raises(FaultInjectedError, match=site):
            if site == "serving.admit":
                eng.submit(_prompt(rng, 4), 4)
            else:
                eng.submit(_prompt(rng, 4), 4)
                eng.run()
    # Disarmed: the engine still serves (the decode crash left its slot
    # active; the rerun drains it cleanly).
    req = eng.submit(_prompt(rng, 4), 4)
    eng.run()
    assert req.status == "finished"


def test_decode_stall_feeds_watchdog_clock(model, engine_factory):
    """A delay= fault at serving.decode stalls the loop in place — and
    the engine's per-iteration notify_progress keeps feeding the same
    clock /healthz reads, so a stuck decode is visible liveness, not
    silence."""
    from fluxmpi_tpu.telemetry.watchdog import progress_value

    eng = engine_factory()
    rng = np.random.default_rng(4)
    before = progress_value()
    with faults.scope("serving.decode@step=1:delay=0.05"):
        eng.submit(_prompt(rng, 4), 3)
        summary = eng.run()
    assert summary["completed"] == 1
    assert progress_value() > before


# ---------------------------------------------------------------------------
# Telemetry, status board, env wiring, shutdown discipline
# ---------------------------------------------------------------------------


def test_metrics_schema_valid_and_namespace_closed(model, engine_factory):
    get_registry().reset()
    eng = engine_factory()
    rng = np.random.default_rng(6)
    eng.submit(_prompt(rng, 5), 6)
    eng.run()
    rec = get_registry().flush()
    assert validate_record(rec) == []
    emitted = {m["name"] for m in rec["metrics"] if m["name"].startswith("serving.")}
    assert emitted and emitted <= KNOWN_METRIC_NAMES
    # The namespace is CLOSED: an off-schema serving.* name is producer
    # drift, rejected by the validator (and fluxlint at PR time).
    bad = {"name": "serving.bogus", "type": "gauge", "labels": {}, "value": 1.0}
    assert any("framework-owned" in e for e in validate_metric(bad))


def test_status_board_and_fluxmpi_top_serving_view(model, engine_factory):
    exp = Exporter(0, "127.0.0.1", deadline=3600.0)
    export.configure(exp)
    observe.configure(True)  # the request plane enriches the board
    try:
        eng = engine_factory()
        rng = np.random.default_rng(8)
        for _ in range(3):
            eng.submit(_prompt(rng, 5), 6)
        summary = eng.run()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/status", timeout=5
        ) as resp:
            status = json.load(resp)
        assert validate_status_record(status) == []
        srv = status["serving"]
        assert srv["phase"] == "finished"
        assert srv["completed"] == summary["completed"] == 3
        assert srv["tokens"] == summary["tokens"]
        assert srv["kv_blocks_in_use"] == 0
        # Request-plane enrichment: burn + TTFT percentiles + the
        # logged-record count ride the same snapshot.
        assert srv["requests_logged"] == 3
        assert srv["burn_rate"] == 0.0  # healthy run burns nothing
        assert srv["ttft_p50"] is not None and srv["ttft_p99"] is not None
        # The fleet dashboard renders the serving view from the same
        # snapshot (stdlib CLI, --once exit semantics unchanged).
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "scripts", "fluxmpi_top.py"),
             f"http://127.0.0.1:{exp.port}", "--once"],
            capture_output=True, text=True, timeout=30,
        )
        assert proc.returncode == 0, proc.stderr
        assert "SERVING" in proc.stdout
        assert "finished" in proc.stdout
        assert "burn" in proc.stdout  # the request-plane ticker line
    finally:
        observe.shutdown()
        export.shutdown()


def test_configure_env_forms(model, monkeypatch):
    serving.shutdown()
    monkeypatch.setenv("FLUXMPI_TPU_SERVING", "1")
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_SLOTS", "3")
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_BLOCK_SIZE", "4")
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_QUEUE", "5")
    serving.configure()
    assert serving.enabled()
    lm, variables = model
    eng = InferenceEngine(lm, variables)
    try:
        assert eng.slots == 3
        assert eng.block_size == 4
        assert eng.max_queue == 5
    finally:
        eng.close()
        serving.shutdown()
    assert not serving.enabled()


def test_configure_dict_and_env_typo(model, monkeypatch):
    cfg = serving.configure({"slots": 5, "block_size": 8})
    assert cfg.slots == 5
    with pytest.raises(ValueError, match="unknown serving config"):
        serving.configure({"slotz": 5})
    serving.shutdown()
    # An env typo degrades with a warning, never crashes bring-up (the
    # faults.configure convention).
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_SLOTS", "many")
    lm, variables = model
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_SERVING_SLOTS"):
        eng = InferenceEngine(lm, variables, block_size=8)
    try:
        assert eng.slots == 8  # the built-in default
    finally:
        eng.close()
        serving.shutdown()


def test_init_serving_kwarg(model, world):
    fm.init(serving={"slots": 3})
    assert serving.enabled()
    lm, variables = model
    eng = InferenceEngine(lm, variables, block_size=8)
    assert eng.slots == 3
    eng.close()
    fm.init(serving=False)
    assert not serving.enabled()


def test_env_typo_on_master_switch_warns_not_crashes(monkeypatch):
    # FLUXMPI_TPU_SERVING="true" (a natural typo for "1") must degrade
    # with a warning, never crash init() of a job that may not even
    # serve — the export-plane env-typo convention.
    serving.shutdown()
    monkeypatch.setenv("FLUXMPI_TPU_SERVING", "true")
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_SERVING"):
        cfg = serving.configure()
    assert cfg is None and not serving.enabled()
    # The programmatic spelling still raises (a code bug, not a typo).
    with pytest.raises(ValueError, match="serving spec"):
        serving.configure("true")


def test_serve_thread_error_fails_pending_requests(model, engine_factory):
    """A dying serve thread must not strand consumers: an error inside
    an iteration (here the serving.decode chaos site) rejects every
    pending request with reason="error" and banks the exception."""
    eng = engine_factory()
    eng.warmup(prompt_lengths=(4,))
    rng = np.random.default_rng(0)
    with faults.scope("serving.decode@step=1"):
        eng.start()
        req = eng.submit(_prompt(rng, 4), 8)
        assert req.wait(timeout=60.0)
    assert req.status == "rejected" and req.reject_reason == "error"
    with pytest.raises(RuntimeError, match="error"):
        list(req.stream(timeout=5.0))
    assert isinstance(eng.serve_error, FaultInjectedError)
    eng.stop()


def test_stop_then_run_inline_serves_again(model, engine_factory):
    """The documented driver switch — stop() the serve thread, then
    drive run() inline — must actually serve: submissions landing in
    the parked window QUEUE (a parked engine simply has no driver yet)
    and the next run() drains them; nothing is silently shed."""
    lm, variables = model
    eng = engine_factory()
    rng = np.random.default_rng(3)
    eng.start()
    first = eng.submit(_prompt(rng, 4), 4)
    assert first.wait(timeout=60.0)
    eng.stop()
    parked = eng.submit(_prompt(rng, 4), 6)
    assert parked.status == "queued"
    summary = eng.run()
    assert parked.status == "finished" and len(parked.tokens) == 6
    assert summary["completed"] >= 1
    ref = np.asarray(
        generate(lm, variables, jnp.asarray(parked.prompt[None]), 6)
    )[0][4:]
    np.testing.assert_array_equal(np.asarray(parked.tokens, np.int32), ref)
    # tokens_per_sec is per-RUN: the lifetime token count must not be
    # divided by one run's wall (an idle follow-up run rates 0, while
    # the lifetime counters keep their totals).
    idle = eng.run()
    assert idle["tokens_per_sec"] == 0.0
    assert idle["tokens"] == summary["tokens"] == 10


def test_registry_counters_match_summary_across_driver_switch(
    model, engine_factory
):
    """Decode ticks between the last flush and a driver switch must
    still reach the cumulative registry counters — the delta baselines
    survive _resolve_run instead of being silently re-based."""
    get_registry().reset()
    eng = engine_factory(flush_every=16)
    rng = np.random.default_rng(1)
    eng.submit(_prompt(rng, 4), 8)
    for _ in range(4):  # admit + a few un-flushed ticks (< flush_every)
        eng.step()
    summary = eng.run()
    snap = {
        m["name"]: m["value"]
        for m in get_registry().snapshot()
        if m["type"] == "counter"
    }
    assert snap["serving.decode_steps"] == summary["decode_steps"]
    assert snap["serving.tokens_generated"] == summary["tokens"]


def test_idle_serve_thread_does_not_feed_watchdog(model, engine_factory):
    """An idle background serving loop must NOT advance the process
    watchdog progress counter: it would mask a co-resident train
    loop's stall from the watchdog and /healthz. Progress only moves
    when the engine admits or decodes."""
    import time as _time

    from fluxmpi_tpu.telemetry.watchdog import progress_value

    eng = engine_factory()
    eng.start()
    try:
        _time.sleep(0.2)  # several idle poll cycles
        before = progress_value()
        _time.sleep(0.3)
        assert progress_value() == before
        rng = np.random.default_rng(0)
        req = eng.submit(_prompt(rng, 4), 4)
        assert req.wait(timeout=60.0)
        assert progress_value() > before
    finally:
        eng.stop()


def test_warmup_refuses_while_serving(model, engine_factory):
    # warmup dispatches DONATE the pool buffers — racing the serve
    # thread would invalidate the arrays under its in-flight dispatch.
    eng = engine_factory()
    eng.start()
    try:
        with pytest.raises(RuntimeError, match="donate"):
            eng.warmup(prompt_lengths=(8,))
    finally:
        eng.stop()


def test_stream_timeout_raises_timeout_error(model, engine_factory):
    # The documented exception type — not the internal queue.Empty.
    eng = engine_factory()
    rng = np.random.default_rng(0)
    req = eng.submit(_prompt(rng, 4), 4)  # queued; nothing drives it
    with pytest.raises(TimeoutError, match="no token"):
        list(req.stream(timeout=0.05))
    eng.run()
    assert req.status == "finished"


def test_engine_close_fails_pending_and_drops_pools(model):
    get_registry().reset()
    lm, variables = model
    eng = InferenceEngine(lm, variables, slots=1, block_size=8, max_queue=4)
    rng = np.random.default_rng(1)
    active = eng.submit(_prompt(rng, 5), 30)
    queued = eng.submit(_prompt(rng, 5), 30)
    eng.step()
    assert serving.get_engine() is eng
    rejected_before = eng._rejected
    eng.close()
    assert active.status == "rejected" and active.reject_reason == "shutdown"
    assert queued.status == "rejected" and queued.reject_reason == "shutdown"
    # Shutdown rejections ride the same accounting as every other
    # rejection path — the summary/board must not undercount them.
    assert eng._rejected == rejected_before + 2
    snap = {
        (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
        for m in get_registry().snapshot()
        if m["name"] == "serving.admission_rejects"
    }
    assert snap[("serving.admission_rejects", (("reason", "shutdown"),))] == 2
    assert eng.cache._k_pool is None and eng.cache._v_pool is None
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1
    assert serving.get_engine() is None


# ---------------------------------------------------------------------------
# Request-observability plane (serving/observe.py)
# ---------------------------------------------------------------------------


def test_kv_high_watermark_and_fragmentation():
    """The forensics gauges: the watermark is a pool-lifetime peak (it
    never comes back down), fragmentation measures free-list scatter —
    1 - longest contiguous free run / free blocks."""
    cache = BlockKVCache(num_layers=2, num_heads=4, head_dim=8,
                         num_blocks=9, block_size=8, max_blocks_per_seq=8)
    assert cache.high_watermark_blocks == 0
    assert cache.fragmentation == 0.0  # pristine free list is one run
    a = cache.alloc(24)  # blocks 1,2,3
    b = cache.alloc(24)  # blocks 4,5,6
    assert cache.high_watermark_blocks == 6
    cache.free(a)
    # The watermark is a peak, not an occupancy gauge.
    assert cache.used_blocks == 3 and cache.high_watermark_blocks == 6
    # Free ids {1,2,3,7,8}: longest run 3 of 5 free -> 0.4 scattered.
    assert cache.fragmentation == pytest.approx(1.0 - 3.0 / 5.0)
    cache.free(b)
    assert cache.fragmentation == 0.0  # coalesced back to one run
    assert cache.high_watermark_blocks == 6


def test_slo_burn_tracker_multi_window_math():
    now = {"t": 0.0}
    t = observe.SLOBurnTracker(
        window=120.0, slo_target=0.9, clock=lambda: now["t"]
    )
    assert t.windows == (10.0, 120.0)
    assert t.budget == pytest.approx(0.1)
    # An idle service burns nothing — and alerts on nothing.
    assert t.burn_rate() == 0.0
    assert t.alert_rate() is None
    for _ in range(8):
        t.observe(True)
    for _ in range(2):
        t.observe(False)
    # 2 bad of 10 over a 10% budget = burning 2x as fast as it accrues.
    assert t.burn_rate(10.0) == pytest.approx(2.0)
    assert t.burn_rate(120.0) == pytest.approx(2.0)
    assert t.alert_rate() == pytest.approx(2.0)
    # A recovered service: the short window clears first, and the
    # multi-window AND (min) stops alerting even while the long window
    # still remembers the bad minutes.
    now["t"] = 50.0
    t.observe(True)
    assert t.burn_rate(10.0) == 0.0
    assert t.burn_rate(120.0) == pytest.approx((2.0 / 11.0) / 0.1)
    assert t.alert_rate() == 0.0
    t.reset()
    assert t.total == 0 and t.good == 0
    assert t.alert_rate() is None
    with pytest.raises(ValueError, match="window"):
        observe.SLOBurnTracker(window=0.0)
    with pytest.raises(ValueError, match="slo_target"):
        observe.SLOBurnTracker(slo_target=1.0)


def test_slo_burn_anomaly_rule():
    get_registry().reset()
    det = AnomalyDetector(dump=False)
    assert det.policies["slo_burn"] == "warn"
    # Below threshold (default 2.0): quiet.
    assert det.observe(slo_burn=1.5, step=1) == []
    with pytest.warns(UserWarning, match="slo_burn"):
        events = det.observe(slo_burn=2.5, step=2)
    assert [e["rule"] for e in events] == ["slo_burn"]
    assert events[0]["action"] == "warn"
    snap = {
        (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
        for m in get_registry().snapshot()
    }
    assert snap[("anomaly.triggered", (("rule", "slo_burn"),))] == 1
    get_registry().reset()


def test_request_log_complete_under_sigterm_drain(
    model, engine_factory, tmp_path
):
    """The drain-completeness contract (and the reject live-lookup):
    every in-flight, queued, AND post-drain request lands in the
    request log with its terminal status — asserted end-to-end through
    the schema checker."""
    path_spec = str(tmp_path / "requests.{process}.jsonl")
    observe.configure(path_spec)
    eng = engine_factory(slots=2, max_queue=8)
    rng = np.random.default_rng(9)
    a = eng.submit(_prompt(rng, 5), 24)
    b = eng.submit(_prompt(rng, 7), 24)
    c = eng.submit(_prompt(rng, 4), 4)  # queued behind the two slots
    eng.step()  # admit a + b
    runtime.request_preemption()
    try:
        summary = eng.run()
    finally:
        runtime.clear_preemption()
    assert summary["drained"] == 2 and summary["rejected"] == 1
    late = eng.submit(_prompt(rng, 4), 4)
    assert late.status == "rejected" and late.reject_reason == "draining"
    path = path_spec.format(process=0)
    with open(path, encoding="utf-8") as f:
        records = {r["request_id"]: r for r in map(json.loads, f)}
    assert set(records) == {req.id for req in (a, b, c, late)}
    assert records[a.id]["status"] == "finished"
    assert records[a.id]["output_tokens"] == 24
    assert records[b.id]["status"] == "finished"
    assert records[c.id]["status"] == "rejected"
    assert records[c.id]["reason"] == "preempted"
    assert records[late.id]["reason"] == "draining"
    # Drained completions carry full timings; rejects carry the nulls
    # the schema allows.
    assert records[a.id]["ttft_s"] is not None
    assert records[late.id]["ttft_s"] is None
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(here, "scripts", "check_metrics_schema.py"), path],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_rejected_requests_raise_typed_error(model, engine_factory):
    """result()/stream() on a rejected request raise
    RequestRejectedError — a RuntimeError subclass carrying the reason
    so callers branch without string-matching (the retry/resubmit
    split)."""
    eng = engine_factory(slots=1, max_queue=1)
    rng = np.random.default_rng(0)
    eng.submit(_prompt(rng, 4), 4)
    shed = eng.submit(_prompt(rng, 4), 4)
    assert shed.status == "rejected"
    with pytest.raises(RequestRejectedError, match="queue_full") as exc_info:
        shed.result()
    assert exc_info.value.reject_reason == "queue_full"
    assert isinstance(exc_info.value, RuntimeError)  # old except clauses hold
    with pytest.raises(RequestRejectedError, match="queue_full"):
        list(shed.stream(timeout=1.0))
    eng.run()


def test_request_plane_fully_off_never_touches_observer(
    model, engine_factory, monkeypatch
):
    """The PR 4 zero-cost contract: with the plane off, a full serving
    run — including a load-shed reject — never calls ANY plane method.
    Exploding mocks, not timers."""
    observe.shutdown()
    assert observe.get_request_observer() is None

    def boom(*a, **k):
        raise AssertionError("request plane touched while off")

    monkeypatch.setattr(observe.RequestObserver, "observe_terminal", boom)
    monkeypatch.setattr(observe.RequestObserver, "board", boom)
    monkeypatch.setattr(observe.RequestObserver, "maybe_write_bundle", boom)
    monkeypatch.setattr(observe.SLOBurnTracker, "observe", boom)
    monkeypatch.setattr(observe.RequestLog, "write", boom)
    eng = engine_factory(slots=1, max_queue=1)
    rng = np.random.default_rng(2)
    ok = eng.submit(_prompt(rng, 4), 4)
    shed = eng.submit(_prompt(rng, 4), 4)  # queue_full reject path
    eng.run()
    assert ok.status == "finished" and len(ok.tokens) == 4
    assert shed.status == "rejected" and shed.reject_reason == "queue_full"


def test_request_plane_e2e_trace_log_report(model, engine_factory, tmp_path):
    """The acceptance loop: one plane-on run yields (a) a Perfetto-valid
    merged trace with the request span chains on named tracks, (b) a
    schema-valid request JSONL, and (c) a serving_report aggregation
    whose totals match the registry counters."""
    get_registry().reset()
    log_spec = str(tmp_path / "requests.{process}.jsonl")
    trace_spec = str(tmp_path / "trace.{process}.json")
    tracing.configure(trace_spec)
    obs = observe.configure(log_spec)
    obs.dump_dir = str(tmp_path)  # the queue_full bundle lands here too
    try:
        eng = engine_factory(slots=2, max_queue=2)
        rng = np.random.default_rng(7)
        good = [eng.submit(_prompt(rng, 5), 6) for _ in range(2)]
        shed = [eng.submit(_prompt(rng, 5), 6) for _ in range(3)]
        summary = eng.run()
        assert [r.status for r in good] == ["finished", "finished"]
        assert {r.reject_reason for r in shed} == {"queue_full"}
        trace_path = tracing.shutdown()
        assert trace_path is not None
    finally:
        tracing.configure(False)
        tracing.reset()
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    merged = str(tmp_path / "merged.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "scripts", "merge_traces.py"),
         "-o", merged, trace_path],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    log_path = log_spec.format(process=0)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(here, "scripts", "check_metrics_schema.py"),
         merged, log_path],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(merged, encoding="utf-8") as f:
        trace = json.load(f)
    names = {e.get("name") for e in trace["traceEvents"]}
    assert {"request.queue", "request.prefill", "request.decode",
            "request.done", "request.rejected"} <= names
    # Every request rides its own named virtual track.
    track_names = {
        e["args"]["name"]
        for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert {f"request {r.id}" for r in good} <= track_names
    # serving_report totals must agree with the registry counters — the
    # two accounting paths (JSONL records, metric counters) cannot
    # drift.
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "scripts", "serving_report.py"),
         "--json", log_path],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    snap = {}
    for m in get_registry().snapshot():
        if m["type"] == "counter":
            snap.setdefault(m["name"], 0)
            snap[m["name"]] += m["value"]
    assert report["requests"] == 5
    assert report["finished"] == snap["serving.requests_completed"] == 2
    assert report["rejected"] == snap["serving.admission_rejects"] == 3
    assert report["reject_reasons"] == {"queue_full": 3}
    assert report["output_tokens"] == summary["tokens"]
    assert report["ttft"]["count"] == 2
    assert report["slo_ok"] == 2


def test_slo_burn_anomaly_fires_on_regression_silent_when_healthy(
    model, engine_factory
):
    """The burn alert end-to-end: an injected latency regression (an
    SLO floor no real request can meet) trips the slo_burn rule through
    the engine's flush; a healthy run with the same wiring stays
    silent."""
    get_registry().reset()
    set_anomaly_detector(AnomalyDetector(dump=False))
    observe.configure(True)
    try:
        eng = engine_factory(slo_ttft_s=1e-9)  # every completion violates
        rng = np.random.default_rng(4)
        for _ in range(3):
            eng.submit(_prompt(rng, 4), 4)
        with pytest.warns(UserWarning, match="slo_burn"):
            eng.run()
        snap = {
            (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
            for m in get_registry().snapshot()
            if m["type"] == "counter"
        }
        assert snap[("anomaly.triggered", (("rule", "slo_burn"),))] >= 1
        # Healthy service, same wiring: silent.
        observe.shutdown()
        observe.configure(True)
        set_anomaly_detector(AnomalyDetector(dump=False))
        get_registry().reset()
        eng2 = engine_factory()
        for _ in range(3):
            eng2.submit(_prompt(rng, 4), 4)
        eng2.run()
        assert not any(
            m["name"] == "anomaly.triggered"
            for m in get_registry().snapshot()
        )
    finally:
        set_anomaly_detector(None)
        observe.shutdown()


def test_queue_full_load_shed_writes_debug_bundle_once(
    model, engine_factory, tmp_path
):
    """The first load-shed writes the OOM-style pool-census bundle (who
    ate the KV pool, at the moment it mattered); later sheds do not
    rewrite it — forensics are rate-limited to the triggering event."""
    obs = observe.configure(True)
    obs.dump_dir = str(tmp_path)
    eng = engine_factory(slots=1, max_queue=1)
    rng = np.random.default_rng(6)
    held = eng.submit(_prompt(rng, 5), 24)
    eng.step()  # admit: the slot now holds blocks the census reports
    eng.submit(_prompt(rng, 4), 4)  # fills the queue
    shed = eng.submit(_prompt(rng, 4), 4)
    assert shed.reject_reason == "queue_full"
    bundle_path = os.path.join(str(tmp_path), "fluxmpi_serving.0.json")
    assert obs.last_dump_path == bundle_path
    with open(bundle_path, encoding="utf-8") as f:
        bundle = json.load(f)
    srv = bundle["serving"]
    assert srv["blocks_total"] == eng.cache.num_blocks - 1
    assert srv["blocks_in_use"] > 0
    assert srv["census"][0]["request_id"] == held.id
    assert srv["census"][0]["blocks"] == len(eng._slots[0].blocks)
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(here, "scripts", "check_metrics_schema.py"),
         bundle_path],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Rate-limited: a second shed does NOT rewrite the bundle.
    os.unlink(bundle_path)
    again = eng.submit(_prompt(rng, 4), 4)
    assert again.reject_reason == "queue_full"
    assert not os.path.exists(bundle_path)
    eng.run()


def test_request_log_configure_env_forms_and_typo(monkeypatch, tmp_path):
    observe.shutdown()
    monkeypatch.delenv("FLUXMPI_TPU_REQUEST_LOG", raising=False)
    # Unset env: configure(None) is a no-op.
    assert observe.configure() is None
    # "1": plane on without a file log (spans/burn/forensics only).
    obs = observe.configure(True)
    assert obs is not None and obs.log is None
    assert observe.configure("1") is obs  # idempotent replay reuses
    # A path spec installs a log; an equivalent replay keeps the
    # observer (and its burn windows).
    spec = str(tmp_path / "requests.{process}.jsonl")
    obs2 = observe.configure(spec)
    assert obs2 is not obs and obs2.log.path == spec.format(process=0)
    assert observe.configure(spec) is obs2
    # The env spelling of a malformed path warns and degrades...
    observe.shutdown()
    monkeypatch.setenv("FLUXMPI_TPU_REQUEST_LOG", "req.{proc}.jsonl")
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_REQUEST_LOG"):
        assert observe.configure() is None
    # ...the programmatic spelling raises (a code bug, not a typo).
    with pytest.raises(ValueError, match="not formattable"):
        observe.configure("req.{proc}.jsonl")
    with pytest.raises(ValueError, match="request_log spec"):
        observe.configure(3.5)
    monkeypatch.delenv("FLUXMPI_TPU_REQUEST_LOG")
    observe.configure(True)
    assert observe.configure(False) is None
    assert observe.get_request_observer() is None
    # The burn-window env var follows the same warn-and-degrade rule.
    monkeypatch.setenv("FLUXMPI_TPU_SLO_WINDOW", "soon")
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_SLO_WINDOW"):
        t = observe.SLOBurnTracker()
    assert t.windows[-1] == 300.0  # the built-in default held


def test_init_request_log_kwarg(world, tmp_path):
    spec = str(tmp_path / "requests.{process}.jsonl")
    fm.init(request_log=spec)
    obs = observe.get_request_observer()
    assert obs is not None and obs.log.path_spec == spec
    fm.init(request_log=False)
    assert observe.get_request_observer() is None
