"""Serving plane tests: the paged KV cache's free-list round trip,
token-budget admission control, continuous batching's bit-identity with
``generate()``, zero-retrace mid-flight joins, streaming delivery,
preemption draining under load, the ``serving.admit``/``serving.decode``
fault sites, and the telemetry/status wiring."""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults, runtime, serving
from fluxmpi_tpu.errors import FaultInjectedError
from fluxmpi_tpu.models import TransformerLM
from fluxmpi_tpu.models.generate import generate
from fluxmpi_tpu.serving import BlockKVCache, InferenceEngine, blocks_for_tokens
from fluxmpi_tpu.telemetry import Exporter, export, get_registry
from fluxmpi_tpu.telemetry import compileplane
from fluxmpi_tpu.telemetry.schema import (
    KNOWN_METRIC_NAMES,
    validate_metric,
    validate_record,
    validate_status_record,
)


@pytest.fixture(scope="module")
def model(world):
    lm = TransformerLM(vocab_size=32, max_len=64, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    variables = lm.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return lm, variables


@pytest.fixture()
def engine_factory(model):
    lm, variables = model
    built = []

    def make(**kwargs):
        kwargs.setdefault("slots", 2)
        kwargs.setdefault("block_size", 8)
        eng = InferenceEngine(lm, variables, **kwargs)
        built.append(eng)
        return eng

    yield make
    for eng in built:
        eng.close()
    serving.shutdown()
    runtime.clear_preemption()
    get_registry().reset()


def _prompt(rng, n):
    return rng.integers(0, 32, size=(n,)).astype(np.int32)


# ---------------------------------------------------------------------------
# Block cache / free-list allocator
# ---------------------------------------------------------------------------


def test_free_list_round_trip():
    cache = BlockKVCache(num_layers=2, num_heads=4, head_dim=8,
                         num_blocks=9, block_size=16, max_blocks_per_seq=4)
    assert cache.free_blocks == 8  # block 0 is the reserved trash block
    assert cache.capacity_tokens == 8 * 16
    a = cache.alloc(40)  # 3 blocks
    assert len(a) == 3 and 0 not in a
    b = cache.alloc(16)
    assert cache.used_blocks == 4
    cache.free(a)
    assert cache.free_blocks == 7
    # Freed blocks are reused (LIFO — the most recently freed first).
    c = cache.alloc(48)
    assert set(c) <= set(a) | set(range(1, 9))
    assert set(a) & set(c), "freed blocks must be handed out again"
    cache.free(b)
    cache.free(c)
    assert cache.free_blocks == 8


def test_allocator_rejects_bad_frees_and_exhaustion():
    cache = BlockKVCache(num_layers=1, num_heads=1, head_dim=4,
                         num_blocks=4, block_size=8, max_blocks_per_seq=3)
    blocks = cache.alloc(24)  # all 3
    assert not cache.can_alloc(1)
    with pytest.raises(RuntimeError, match="exhausted"):
        cache.alloc(8)
    with pytest.raises(ValueError, match="outside the pool"):
        cache.free([0])
    cache.free(blocks)
    with pytest.raises(ValueError, match="double free"):
        cache.free([blocks[0]])


def test_blocks_for_tokens_math():
    assert blocks_for_tokens(1, 16) == 1
    assert blocks_for_tokens(16, 16) == 1
    assert blocks_for_tokens(17, 16) == 2


def test_table_row_pads_with_trash():
    cache = BlockKVCache(num_layers=1, num_heads=1, head_dim=4,
                         num_blocks=8, block_size=8, max_blocks_per_seq=5)
    row = cache.table_row([3, 1])
    assert row.tolist() == [3, 1, 0, 0, 0]


def test_memory_plane_admission_check(model, monkeypatch):
    """The OOM-safe construction check: a pool that cannot fit the
    device's remaining HBM refuses at engine build (PR 9 memory plane),
    never at the first admission."""
    from fluxmpi_tpu.telemetry import memory as memory_mod

    lm, variables = model
    monkeypatch.setattr(
        memory_mod, "device_memory_stats",
        lambda d: {"bytes_limit": 1024.0, "bytes_in_use": 0.0},
    )
    with pytest.raises(RuntimeError, match="device memory"):
        InferenceEngine(lm, variables, slots=2, block_size=8)
    serving.shutdown()
    # Stat-less backends (CPU) have nothing to check against: fine.
    monkeypatch.setattr(memory_mod, "device_memory_stats", lambda d: {})
    eng = InferenceEngine(lm, variables, slots=2, block_size=8)
    eng.close()


# ---------------------------------------------------------------------------
# Correctness: engine output == generate()
# ---------------------------------------------------------------------------


def test_greedy_streams_bit_identical_to_generate(model, engine_factory):
    """The serving correctness proof: for a mixed-length batch of
    requests flowing through admission -> batched prefill -> continuous
    decode -> eviction, every streamed greedy continuation is
    bit-identical to ``generate()`` on the same prompt."""
    lm, variables = model
    eng = engine_factory(slots=3)
    eng.warmup(prompt_lengths=(3, 9, 16))
    rng = np.random.default_rng(7)
    cases = [(5, 8, None), (9, 4, None), (3, 12, None), (16, 6, None),
             (6, 20, 3), (4, 1, None)]
    reqs = [
        (eng.submit(_prompt(rng, plen), mnew, eos_token=eos), mnew, eos)
        for plen, mnew, eos in cases
    ]
    summary = eng.run()
    assert summary["completed"] == len(cases)
    for (req, mnew, eos) in reqs:
        ref = np.asarray(
            generate(lm, variables, jnp.asarray(req.prompt[None]), mnew,
                     eos_token=eos)
        )[0][len(req.prompt):]
        if eos is not None:
            hits = np.where(ref == eos)[0]
            if len(hits):
                ref = ref[: hits[0] + 1]  # engine stops AT eos
        np.testing.assert_array_equal(
            np.asarray(req.tokens, np.int32), ref
        )
    # Eviction returned every block: the pool is whole again.
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1


def test_midflight_join_zero_retrace(model, engine_factory):
    """A request admitted mid-flight joins the decode batch without
    recompiling the decode step: the compile monitor sees ZERO compile
    events after the warmup boundary, and the decode jit's cache holds
    exactly one entry."""
    lm, variables = model
    mon = compileplane.CompileMonitor()
    compileplane.set_compile_monitor(mon)
    try:
        eng = engine_factory(slots=2)
        eng.warmup(prompt_lengths=(5, 9, 16))
        mon.observe_flush()  # warmup boundary
        rng = np.random.default_rng(1)
        eng.submit(_prompt(rng, 9), 20)
        for _ in range(3):
            eng.step()
        late = eng.submit(_prompt(rng, 5), 8)   # joins mid-flight
        later = eng.submit(_prompt(rng, 12), 6)  # different length, same buckets
        summary = eng.run()
        assert summary["completed"] == 3
        info = mon.observe_flush()
        assert info["events"] == 0, f"steady-state compiles: {info}"
        assert mon.retraces == []
        assert eng._decode_step._cache_size() == 1
        ref = np.asarray(
            generate(lm, variables, jnp.asarray(late.prompt[None]), 8)
        )[0][5:]
        np.testing.assert_array_equal(np.asarray(late.tokens, np.int32), ref)
        assert later.status == "finished"
    finally:
        compileplane.set_compile_monitor(None)


def test_warmup_touches_only_the_trash_block(model, engine_factory):
    eng = engine_factory()
    free_before = eng.cache.free_blocks
    eng.warmup(prompt_lengths=(4, 11))
    assert eng.cache.free_blocks == free_before
    assert eng.queue_depth == 0 and eng.active_count == 0


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


def test_queue_full_rejects_with_counter(model, engine_factory):
    get_registry().reset()
    eng = engine_factory(slots=1, max_queue=2)
    rng = np.random.default_rng(0)
    reqs = [eng.submit(_prompt(rng, 4), 4) for _ in range(3)]
    assert [r.status for r in reqs[:2]] == ["queued", "queued"]
    assert reqs[2].status == "rejected"
    assert reqs[2].reject_reason == "queue_full"
    with pytest.raises(RuntimeError, match="queue_full"):
        reqs[2].result()
    snap = {
        (m["name"], tuple(sorted(m["labels"].items()))): m
        for m in get_registry().snapshot()
    }
    key = ("serving.admission_rejects", (("reason", "queue_full"),))
    assert snap[key]["value"] == 1
    eng.run()


def test_oversized_request_raises(model, engine_factory):
    eng = engine_factory()
    rng = np.random.default_rng(0)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(_prompt(rng, 30), eng.max_len)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(_prompt(rng, 4), 0)
    with pytest.raises(ValueError, match="vocabulary"):
        eng.submit(_prompt(rng, 4), 4, eos_token=99)


def test_capacity_queueing_and_block_reuse(model, engine_factory):
    """Token-budget admission: a pool sized for ONE request at a time
    queues the second until eviction frees its blocks — then serves it
    from the recycled blocks, correctly."""
    lm, variables = model
    # 5 usable blocks of 8 = 40 tokens; each request reserves 4 blocks.
    eng = engine_factory(slots=2, num_blocks=6, max_queue=8)
    rng = np.random.default_rng(3)
    a = eng.submit(_prompt(rng, 8), 16)   # 24 tokens -> 3 blocks
    b = eng.submit(_prompt(rng, 10), 12)  # 22 tokens -> 3 blocks, must wait
    eng.step()
    assert a.status == "active" and b.status == "queued"
    eng.run()
    assert a.status == "finished" and b.status == "finished"
    for req, mnew in ((a, 16), (b, 12)):
        ref = np.asarray(
            generate(lm, variables, jnp.asarray(req.prompt[None]), mnew)
        )[0][len(req.prompt):]
        np.testing.assert_array_equal(np.asarray(req.tokens, np.int32), ref)
    assert eng.cache.free_blocks == 5


def test_static_batching_gangs_admissions(model, engine_factory):
    """continuous=False is the A/B baseline: a new group is admitted
    only when every slot has drained, so a short request gangs behind a
    long one and total decode steps grow — the loss continuous batching
    exists to recover."""
    rng = np.random.default_rng(5)
    workload = [(6, 16), (4, 2), (5, 2), (4, 2)]

    def run_mode(continuous):
        eng = engine_factory(slots=2, continuous=continuous)
        for plen, mnew in workload:
            eng.submit(_prompt(rng, plen), mnew)
        return eng.run()

    static = run_mode(False)
    cont = run_mode(True)
    assert static["completed"] == cont["completed"] == 4
    assert static["tokens"] == cont["tokens"]
    assert cont["decode_steps"] < static["decode_steps"]


# ---------------------------------------------------------------------------
# Streaming + latency accounting
# ---------------------------------------------------------------------------


def test_streaming_callback_iterator_and_latency(model, engine_factory):
    lm, variables = model
    eng = engine_factory()
    eng.warmup(prompt_lengths=(5,))
    rng = np.random.default_rng(11)
    seen = []
    eng.start()
    try:
        req = eng.submit(_prompt(rng, 5), 10, on_token=seen.append)
        streamed = list(req.stream(timeout=30.0))
    finally:
        eng.stop()
    assert req.status == "finished"
    assert streamed == req.tokens == seen
    ref = np.asarray(
        generate(lm, variables, jnp.asarray(req.prompt[None]), 10)
    )[0][5:]
    np.testing.assert_array_equal(np.asarray(streamed, np.int32), ref)
    assert req.queue_wait_s is not None and req.queue_wait_s >= 0
    assert req.ttft_s is not None and req.ttft_s >= req.queue_wait_s
    assert req.per_token_s is not None and req.per_token_s >= 0


def test_slo_violation_counter(model, engine_factory):
    get_registry().reset()
    # Impossible SLOs: every completion violates both.
    eng = engine_factory(slo_ttft_s=0.0, slo_token_s=0.0)
    rng = np.random.default_rng(2)
    eng.submit(_prompt(rng, 4), 4)
    summary = eng.run()
    assert summary["slo_violations"] == 2
    snap = {
        (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
        for m in get_registry().snapshot()
        if m["name"] == "serving.slo_violations"
    }
    assert snap[("serving.slo_violations", (("kind", "ttft"),))] == 1
    assert snap[("serving.slo_violations", (("kind", "per_token"),))] == 1


# ---------------------------------------------------------------------------
# Preemption + faults under load (the PR 8 convention)
# ---------------------------------------------------------------------------


def test_sigterm_drains_inflight_rejects_new(model, engine_factory):
    """The preemption contract under load: in-flight requests decode to
    completion, queued and new admissions reject, and the summary
    reports the drained/rejected split."""
    lm, variables = model
    eng = engine_factory(slots=2, max_queue=8)
    rng = np.random.default_rng(9)
    a = eng.submit(_prompt(rng, 5), 24)
    b = eng.submit(_prompt(rng, 7), 24)
    c = eng.submit(_prompt(rng, 4), 4)  # queued behind the two slots
    eng.step()  # admit a + b
    runtime.request_preemption()
    try:
        summary = eng.run()
    finally:
        runtime.clear_preemption()
    assert summary["preempted"] is True
    assert summary["drained"] == 2
    assert summary["rejected"] == 1
    assert a.status == "finished" and len(a.tokens) == 24
    assert b.status == "finished" and len(b.tokens) == 24
    assert c.status == "rejected" and c.reject_reason == "preempted"
    # Drained output is still the exact generate() continuation.
    ref = np.asarray(
        generate(lm, variables, jnp.asarray(a.prompt[None]), 24)
    )[0][5:]
    np.testing.assert_array_equal(np.asarray(a.tokens, np.int32), ref)
    late = eng.submit(_prompt(rng, 4), 4)
    assert late.status == "rejected" and late.reject_reason == "draining"


@pytest.mark.parametrize("site", ["serving.admit", "serving.decode"])
def test_serving_sites_are_injectable(model, engine_factory, site):
    # Every serving.* entry of faults.KNOWN_SITES has a live trigger —
    # the coverage contract the fluxlint unregistered-fault-site rule
    # greps this file for.
    eng = engine_factory()
    rng = np.random.default_rng(4)
    with faults.scope(site + "@step=1"):
        with pytest.raises(FaultInjectedError, match=site):
            if site == "serving.admit":
                eng.submit(_prompt(rng, 4), 4)
            else:
                eng.submit(_prompt(rng, 4), 4)
                eng.run()
    # Disarmed: the engine still serves (the decode crash left its slot
    # active; the rerun drains it cleanly).
    req = eng.submit(_prompt(rng, 4), 4)
    eng.run()
    assert req.status == "finished"


def test_decode_stall_feeds_watchdog_clock(model, engine_factory):
    """A delay= fault at serving.decode stalls the loop in place — and
    the engine's per-iteration notify_progress keeps feeding the same
    clock /healthz reads, so a stuck decode is visible liveness, not
    silence."""
    from fluxmpi_tpu.telemetry.watchdog import progress_value

    eng = engine_factory()
    rng = np.random.default_rng(4)
    before = progress_value()
    with faults.scope("serving.decode@step=1:delay=0.05"):
        eng.submit(_prompt(rng, 4), 3)
        summary = eng.run()
    assert summary["completed"] == 1
    assert progress_value() > before


# ---------------------------------------------------------------------------
# Telemetry, status board, env wiring, shutdown discipline
# ---------------------------------------------------------------------------


def test_metrics_schema_valid_and_namespace_closed(model, engine_factory):
    get_registry().reset()
    eng = engine_factory()
    rng = np.random.default_rng(6)
    eng.submit(_prompt(rng, 5), 6)
    eng.run()
    rec = get_registry().flush()
    assert validate_record(rec) == []
    emitted = {m["name"] for m in rec["metrics"] if m["name"].startswith("serving.")}
    assert emitted and emitted <= KNOWN_METRIC_NAMES
    # The namespace is CLOSED: an off-schema serving.* name is producer
    # drift, rejected by the validator (and fluxlint at PR time).
    bad = {"name": "serving.bogus", "type": "gauge", "labels": {}, "value": 1.0}
    assert any("framework-owned" in e for e in validate_metric(bad))


def test_status_board_and_fluxmpi_top_serving_view(model, engine_factory):
    exp = Exporter(0, "127.0.0.1", deadline=3600.0)
    export.configure(exp)
    try:
        eng = engine_factory()
        rng = np.random.default_rng(8)
        for _ in range(3):
            eng.submit(_prompt(rng, 5), 6)
        summary = eng.run()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/status", timeout=5
        ) as resp:
            status = json.load(resp)
        assert validate_status_record(status) == []
        srv = status["serving"]
        assert srv["phase"] == "finished"
        assert srv["completed"] == summary["completed"] == 3
        assert srv["tokens"] == summary["tokens"]
        assert srv["kv_blocks_in_use"] == 0
        # The fleet dashboard renders the serving view from the same
        # snapshot (stdlib CLI, --once exit semantics unchanged).
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "scripts", "fluxmpi_top.py"),
             f"http://127.0.0.1:{exp.port}", "--once"],
            capture_output=True, text=True, timeout=30,
        )
        assert proc.returncode == 0, proc.stderr
        assert "SERVING" in proc.stdout
        assert "finished" in proc.stdout
    finally:
        export.shutdown()


def test_configure_env_forms(model, monkeypatch):
    serving.shutdown()
    monkeypatch.setenv("FLUXMPI_TPU_SERVING", "1")
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_SLOTS", "3")
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_BLOCK_SIZE", "4")
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_QUEUE", "5")
    serving.configure()
    assert serving.enabled()
    lm, variables = model
    eng = InferenceEngine(lm, variables)
    try:
        assert eng.slots == 3
        assert eng.block_size == 4
        assert eng.max_queue == 5
    finally:
        eng.close()
        serving.shutdown()
    assert not serving.enabled()


def test_configure_dict_and_env_typo(model, monkeypatch):
    cfg = serving.configure({"slots": 5, "block_size": 8})
    assert cfg.slots == 5
    with pytest.raises(ValueError, match="unknown serving config"):
        serving.configure({"slotz": 5})
    serving.shutdown()
    # An env typo degrades with a warning, never crashes bring-up (the
    # faults.configure convention).
    monkeypatch.setenv("FLUXMPI_TPU_SERVING_SLOTS", "many")
    lm, variables = model
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_SERVING_SLOTS"):
        eng = InferenceEngine(lm, variables, block_size=8)
    try:
        assert eng.slots == 8  # the built-in default
    finally:
        eng.close()
        serving.shutdown()


def test_init_serving_kwarg(model, world):
    fm.init(serving={"slots": 3})
    assert serving.enabled()
    lm, variables = model
    eng = InferenceEngine(lm, variables, block_size=8)
    assert eng.slots == 3
    eng.close()
    fm.init(serving=False)
    assert not serving.enabled()


def test_env_typo_on_master_switch_warns_not_crashes(monkeypatch):
    # FLUXMPI_TPU_SERVING="true" (a natural typo for "1") must degrade
    # with a warning, never crash init() of a job that may not even
    # serve — the export-plane env-typo convention.
    serving.shutdown()
    monkeypatch.setenv("FLUXMPI_TPU_SERVING", "true")
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_SERVING"):
        cfg = serving.configure()
    assert cfg is None and not serving.enabled()
    # The programmatic spelling still raises (a code bug, not a typo).
    with pytest.raises(ValueError, match="serving spec"):
        serving.configure("true")


def test_serve_thread_error_fails_pending_requests(model, engine_factory):
    """A dying serve thread must not strand consumers: an error inside
    an iteration (here the serving.decode chaos site) rejects every
    pending request with reason="error" and banks the exception."""
    eng = engine_factory()
    eng.warmup(prompt_lengths=(4,))
    rng = np.random.default_rng(0)
    with faults.scope("serving.decode@step=1"):
        eng.start()
        req = eng.submit(_prompt(rng, 4), 8)
        assert req.wait(timeout=60.0)
    assert req.status == "rejected" and req.reject_reason == "error"
    with pytest.raises(RuntimeError, match="error"):
        list(req.stream(timeout=5.0))
    assert isinstance(eng.serve_error, FaultInjectedError)
    eng.stop()


def test_stop_then_run_inline_serves_again(model, engine_factory):
    """The documented driver switch — stop() the serve thread, then
    drive run() inline — must actually serve: submissions landing in
    the parked window QUEUE (a parked engine simply has no driver yet)
    and the next run() drains them; nothing is silently shed."""
    lm, variables = model
    eng = engine_factory()
    rng = np.random.default_rng(3)
    eng.start()
    first = eng.submit(_prompt(rng, 4), 4)
    assert first.wait(timeout=60.0)
    eng.stop()
    parked = eng.submit(_prompt(rng, 4), 6)
    assert parked.status == "queued"
    summary = eng.run()
    assert parked.status == "finished" and len(parked.tokens) == 6
    assert summary["completed"] >= 1
    ref = np.asarray(
        generate(lm, variables, jnp.asarray(parked.prompt[None]), 6)
    )[0][4:]
    np.testing.assert_array_equal(np.asarray(parked.tokens, np.int32), ref)
    # tokens_per_sec is per-RUN: the lifetime token count must not be
    # divided by one run's wall (an idle follow-up run rates 0, while
    # the lifetime counters keep their totals).
    idle = eng.run()
    assert idle["tokens_per_sec"] == 0.0
    assert idle["tokens"] == summary["tokens"] == 10


def test_registry_counters_match_summary_across_driver_switch(
    model, engine_factory
):
    """Decode ticks between the last flush and a driver switch must
    still reach the cumulative registry counters — the delta baselines
    survive _resolve_run instead of being silently re-based."""
    get_registry().reset()
    eng = engine_factory(flush_every=16)
    rng = np.random.default_rng(1)
    eng.submit(_prompt(rng, 4), 8)
    for _ in range(4):  # admit + a few un-flushed ticks (< flush_every)
        eng.step()
    summary = eng.run()
    snap = {
        m["name"]: m["value"]
        for m in get_registry().snapshot()
        if m["type"] == "counter"
    }
    assert snap["serving.decode_steps"] == summary["decode_steps"]
    assert snap["serving.tokens_generated"] == summary["tokens"]


def test_idle_serve_thread_does_not_feed_watchdog(model, engine_factory):
    """An idle background serving loop must NOT advance the process
    watchdog progress counter: it would mask a co-resident train
    loop's stall from the watchdog and /healthz. Progress only moves
    when the engine admits or decodes."""
    import time as _time

    from fluxmpi_tpu.telemetry.watchdog import progress_value

    eng = engine_factory()
    eng.start()
    try:
        _time.sleep(0.2)  # several idle poll cycles
        before = progress_value()
        _time.sleep(0.3)
        assert progress_value() == before
        rng = np.random.default_rng(0)
        req = eng.submit(_prompt(rng, 4), 4)
        assert req.wait(timeout=60.0)
        assert progress_value() > before
    finally:
        eng.stop()


def test_warmup_refuses_while_serving(model, engine_factory):
    # warmup dispatches DONATE the pool buffers — racing the serve
    # thread would invalidate the arrays under its in-flight dispatch.
    eng = engine_factory()
    eng.start()
    try:
        with pytest.raises(RuntimeError, match="donate"):
            eng.warmup(prompt_lengths=(8,))
    finally:
        eng.stop()


def test_stream_timeout_raises_timeout_error(model, engine_factory):
    # The documented exception type — not the internal queue.Empty.
    eng = engine_factory()
    rng = np.random.default_rng(0)
    req = eng.submit(_prompt(rng, 4), 4)  # queued; nothing drives it
    with pytest.raises(TimeoutError, match="no token"):
        list(req.stream(timeout=0.05))
    eng.run()
    assert req.status == "finished"


def test_engine_close_fails_pending_and_drops_pools(model):
    get_registry().reset()
    lm, variables = model
    eng = InferenceEngine(lm, variables, slots=1, block_size=8, max_queue=4)
    rng = np.random.default_rng(1)
    active = eng.submit(_prompt(rng, 5), 30)
    queued = eng.submit(_prompt(rng, 5), 30)
    eng.step()
    assert serving.get_engine() is eng
    rejected_before = eng._rejected
    eng.close()
    assert active.status == "rejected" and active.reject_reason == "shutdown"
    assert queued.status == "rejected" and queued.reject_reason == "shutdown"
    # Shutdown rejections ride the same accounting as every other
    # rejection path — the summary/board must not undercount them.
    assert eng._rejected == rejected_before + 2
    snap = {
        (m["name"], tuple(sorted(m["labels"].items()))): m["value"]
        for m in get_registry().snapshot()
        if m["name"] == "serving.admission_rejects"
    }
    assert snap[("serving.admission_rejects", (("reason", "shutdown"),))] == 2
    assert eng.cache._k_pool is None and eng.cache._v_pool is None
    assert eng.cache.free_blocks == eng.cache.num_blocks - 1
    assert serving.get_engine() is None
