"""Elastic checkpoints: the topology manifest every save writes, N→M
reshard-on-restore over virtual meshes, the loader's global-sample-offset
cursor remap, and train_loop's topology-change resume — plus the chaos
coverage for the new ``ckpt.manifest`` commit window. The real
multi-process 4→2 / 2→4 SIGTERM-and-resume proof is the slow-marked
subprocess test at the bottom; the fast tests cover the same remap and
reshard logic single-process with virtual meshes (see
docs/fault_tolerance.md, "Elastic resume")."""

import json
import os
import signal
import socket
import subprocess
import sys
import warnings

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.errors import FaultInjectedError, TopologyMismatchError
from fluxmpi_tpu.parallel import (
    TrainState,
    fsdp_rule,
    make_train_step,
    shard_tree,
    train_loop,
)
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import MetricsRegistry
from fluxmpi_tpu.telemetry.schema import validate_manifest
from fluxmpi_tpu.utils import (
    CheckpointManager,
    read_manifest,
    restore_checkpoint,
    save_checkpoint,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCHEMA_CHECKER = os.path.join(_REPO, "scripts", "check_metrics_schema.py")


@pytest.fixture(autouse=True)
def _clean_flags():
    faults.clear()
    fm.clear_preemption()
    yield
    faults.clear()
    fm.clear_preemption()


def _submesh(n):
    return Mesh(np.asarray(jax.devices()[:n]).reshape(n), ("dp",))


def _sharded_state(mesh, *, min_size=64):
    params = {
        "w": jnp.arange(64 * 8, dtype=jnp.float32).reshape(64, 8),
        "b": jnp.ones((8,)),
    }
    state, shardings = shard_tree(
        TrainState.create(params, optax.adam(1e-3)),
        mesh,
        fsdp_rule(mesh, min_size=min_size),
    )
    return params, state, shardings


def _host_zeros(tree):
    return jax.tree_util.tree_map(
        lambda x: np.zeros_like(np.asarray(jax.device_get(x)))
        if isinstance(x, (jax.Array, np.ndarray))
        else x,
        tree,
    )


# ---------------------------------------------------------------------------
# Manifest: written with every save, schema-valid, CLI-validated
# ---------------------------------------------------------------------------


def test_every_save_writes_a_valid_manifest(world, tmp_path):
    _, state, _ = _sharded_state(world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    mgr.save(3, state)
    mpath = tmp_path / "run" / "step_00000003.manifest.json"
    assert mpath.exists()
    man = json.loads(mpath.read_text())
    assert validate_manifest(man) == []
    assert man["layout"] == "sharded"
    assert man["step"] == 3
    assert man["process_count"] == jax.process_count()
    assert man["mesh"]["axes"] == {"dp": 8}
    leaves = {leaf["path"]: leaf for leaf in man["leaves"]}
    assert leaves["params/w"]["shape"] == [64, 8]
    assert leaves["params/w"]["dtype"] == "float32"
    assert leaves["params/w"]["spec"] == ["dp", None]
    assert leaves["params/b"]["spec"] == []  # below min_size: replicated
    # Ad-hoc saves carry no loader/counters sections.
    assert man["loader"] is None and man["counters"] is None
    # read_manifest round-trips through the manager too.
    assert mgr.read_manifest()["step"] == 3
    assert mgr.read_manifest(step=3)["layout"] == "sharded"


def test_schema_checker_validates_manifest_files(world, tmp_path):
    """The CI round trip: save → manifest → scripts/check_metrics_schema.py
    accepts it, and rejects a corrupted one."""
    state = {"w": jnp.arange(8.0)}
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    mgr.save(1, state)
    mpath = str(tmp_path / "run" / "step_00000001.manifest.json")

    def check(path):
        return subprocess.run(
            [sys.executable, _SCHEMA_CHECKER, path],
            capture_output=True, text=True,
        )

    ok = check(mpath)
    assert ok.returncode == 0, ok.stderr
    bad = dict(json.loads(open(mpath).read()))
    bad["layout"] = "diagonal"
    del bad["process_count"]
    bad_path = str(tmp_path / "bad.manifest.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rejected = check(bad_path)
    assert rejected.returncode == 1
    assert "layout" in rejected.stderr and "process_count" in rejected.stderr


def test_manifest_banks_loader_geometry_and_counters(world, tmp_path):
    loss_fn, opt, fresh, loader = _train_pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), loader(32), steps=2, checkpoint=mgr,
               save_every=2)
    man = mgr.read_manifest()
    assert man is not None and validate_manifest(man) == []
    assert man["counters"] == {"updates": 2, "examples": 64, "epochs": 0}
    loader_geom = man["loader"]
    assert loader_geom["cursor"] == 2
    assert loader_geom["global_batch_size"] == 32
    assert loader_geom["num_batches"] == 4
    assert loader_geom["process_count"] == 1


# ---------------------------------------------------------------------------
# Reshard-on-restore: N→M over virtual meshes
# ---------------------------------------------------------------------------


def test_elastic_restore_shrink_via_manifest_specs(world, tmp_path):
    """8-device FSDP checkpoint restores onto a 4-device mesh with NO
    rule and NO pre-sharded template: the manifest's partition specs are
    re-validated against the new mesh and orbax reshards on read."""
    params, state8, _ = _sharded_state(world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state8)
    zeros = _host_zeros(state8)
    mesh4 = _submesh(4)
    r4 = restore_checkpoint(path, zeros, mesh=mesh4)
    w4 = r4.params["w"]
    assert len(w4.sharding.device_set) == 4
    assert not w4.is_fully_replicated
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(w4)), np.asarray(params["w"])
    )
    # Optimizer moments reshard too (they carry the same manifest specs).
    mu = r4.opt_state[0].mu["w"]
    assert len(mu.sharding.device_set) == 4


def test_elastic_restore_regrow_with_rule(world, tmp_path):
    """4-device checkpoint regrows onto the full 8-device mesh through an
    explicit partition rule (capacity came back)."""
    mesh4 = _submesh(4)
    params = {"w": jnp.arange(64 * 8, dtype=jnp.float32).reshape(64, 8)}
    state4, _ = shard_tree(
        TrainState.create(params, optax.adam(1e-3)),
        mesh4,
        fsdp_rule(mesh4, min_size=64),
    )
    path = str(tmp_path / "ck")
    save_checkpoint(path, state4)
    r8 = restore_checkpoint(
        path, _host_zeros(state4), mesh=world,
        rule=fsdp_rule(world, min_size=64),
    )
    w8 = r8.params["w"]
    assert len(w8.sharding.device_set) == 8
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(w8)), np.asarray(params["w"])
    )


def test_elastic_restore_mismatched_axis_raises_named_error(world, tmp_path):
    _, state8, _ = _sharded_state(world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state8)
    mesh3 = _submesh(3)  # 64 % 3 != 0 and 8 % 3 != 0: nothing divides
    with pytest.raises(TopologyMismatchError, match="params/w"):
        restore_checkpoint(path, _host_zeros(state8), mesh=mesh3)
    with pytest.raises(TopologyMismatchError, match="'dp'"):
        restore_checkpoint(path, _host_zeros(state8), mesh=mesh3)


def test_elastic_restore_replicated_checkpoint_onto_sharded_layout(
    world, tmp_path
):
    """A replicated checkpoint lands directly in a sharded target layout
    when restored with mesh+rule (root-broadcast read, then reshard)."""
    params = {"w": jnp.arange(64 * 8, dtype=jnp.float32).reshape(64, 8)}
    state = replicate(TrainState.create(params, optax.sgd(0.1)), world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state)
    assert read_manifest(path)["layout"] == "replicated"
    r = restore_checkpoint(
        path, _host_zeros(state), mesh=world,
        rule=fsdp_rule(world, min_size=64),
    )
    assert not r.params["w"].is_fully_replicated
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(r.params["w"])), np.asarray(params["w"])
    )


def test_elastic_restore_without_manifest_needs_a_rule(world, tmp_path):
    _, state8, _ = _sharded_state(world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state8)
    os.remove(path + ".manifest.json")
    # The missing-manifest degradation warns (once per path) AND the
    # spec-less elastic restore refuses actionably.
    with pytest.warns(UserWarning, match="no topology manifest"):
        with pytest.raises(ValueError, match="manifest"):
            restore_checkpoint(path, _host_zeros(state8), mesh=_submesh(4))
    # With a rule the manifest is not needed (the rule IS the layout).
    r4 = restore_checkpoint(
        path, _host_zeros(state8), mesh=_submesh(4),
        rule=fsdp_rule(_submesh(4), min_size=64),
    )
    assert len(r4.params["w"].sharding.device_set) == 4


def test_elastic_restore_accepts_shape_dtype_struct_template(world, tmp_path):
    """An abstract ShapeDtypeStruct `like` tree — the natural spelling of
    "structure and global shapes only" — goes through the same template
    building and shape checks as concrete host arrays."""
    params, state8, _ = _sharded_state(world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state8)
    sds_like = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        if isinstance(x, (jax.Array, np.ndarray))
        else x,
        jax.device_get(state8),
    )
    mesh4 = _submesh(4)
    r4 = restore_checkpoint(path, sds_like, mesh=mesh4)
    w4 = r4.params["w"]
    assert len(w4.sharding.device_set) == 4
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(w4)), np.asarray(params["w"])
    )
    # ...and the mismatch refusal applies to SDS templates too.
    with pytest.raises(TopologyMismatchError, match="params/w"):
        restore_checkpoint(path, sds_like, mesh=_submesh(3))


def test_adhoc_loader_shaped_section_keeps_manifest_valid(world, tmp_path):
    """A user tree with a loader-SHAPED int section is not a train_loop
    payload: the section is dropped, the sidecar (leaf specs included)
    survives."""
    path = str(tmp_path / "ck")
    save_checkpoint(
        path,
        {"w": jnp.arange(8.0), "loader": {"num_workers": np.int64(4)}},
    )
    man = read_manifest(path)
    assert man is not None and validate_manifest(man) == []
    assert man["loader"] is None
    assert any(leaf["path"] == "w" for leaf in man["leaves"])


def test_manifest_shape_mismatch_refuses_before_bytes_move(world, tmp_path):
    state = replicate({"w": jnp.arange(4.0)}, world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state)
    wrong = replicate({"w": jnp.zeros((3,))}, world)
    with pytest.raises(ValueError, match="does not match"):
        restore_checkpoint(path, wrong)


# ---------------------------------------------------------------------------
# Degradation and layout-marker error paths (satellites)
# ---------------------------------------------------------------------------


def test_missing_manifest_degrades_to_pr5_restore_with_warning(
    world, tmp_path
):
    """A checkpoint written before this PR (simulated: manifest deleted)
    still restores same-topology — warned, never a crash."""
    state = replicate({"w": jnp.arange(8.0)}, world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state)
    os.remove(path + ".manifest.json")
    with pytest.warns(UserWarning, match="no topology manifest"):
        restored = restore_checkpoint(path, replicate({"w": jnp.zeros(8)},
                                                      world))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored["w"])), np.arange(8.0)
    )


def test_allow_layout_change_on_missing_marker_warns(world, tmp_path):
    """Satellite: allow_layout_change=True on a checkpoint with no layout
    marker used to proceed silently; now it warns (once, lead process)
    that 'old checkpoint' and 'wrong family' are indistinguishable."""
    state = replicate({"w": jnp.arange(8.0)}, world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state)
    os.remove(path + ".fluxmpi_layout")  # pre-marker-era checkpoint
    with pytest.warns(UserWarning, match="no layout marker"):
        restore_checkpoint(
            path, replicate({"w": jnp.zeros(8)}, world),
            allow_layout_change=True,
        )
    # Once per path: a second restore stays quiet about the marker.
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        restore_checkpoint(
            path, replicate({"w": jnp.zeros(8)}, world),
            allow_layout_change=True,
        )
    assert not [w for w in caught if "layout marker" in str(w.message)]


def test_check_layout_marker_error_paths(world, tmp_path):
    """Satellite: the _check_layout refusal in both directions, plus the
    no-marker pass-through — previously only exercised incidentally."""
    from fluxmpi_tpu.utils.checkpoint import _check_layout

    path = str(tmp_path / "ck")
    state = replicate({"w": jnp.arange(8.0)}, world)
    save_checkpoint(path, state)  # writes a "replicated" marker
    _check_layout(path, "replicated")  # matching: no raise
    with pytest.raises(ValueError, match="replicated layout"):
        _check_layout(path, "sharded")
    os.remove(path + ".fluxmpi_layout")
    _check_layout(path, "sharded")  # no marker: no opinion, no raise
    # End to end: a sharded checkpoint + replicated template refuses.
    _, sharded, _ = _sharded_state(world)
    spath = str(tmp_path / "sharded")
    save_checkpoint(spath, sharded)
    with pytest.raises(ValueError, match="sharded layout"):
        restore_checkpoint(
            spath, replicate(_host_zeros(sharded), world)
        )


# ---------------------------------------------------------------------------
# ckpt.manifest chaos: crash between data commit and manifest write
# ---------------------------------------------------------------------------


def test_crash_before_manifest_write_quarantines_cleanly(world, tmp_path):
    """Satellite: a crash in the data-commit→manifest window leaves a
    renamed dir with neither manifest nor marker — invisible to
    discovery, quarantined at the next startup, and the previous
    committed checkpoint (with its manifest) stays restorable."""
    d = str(tmp_path / "run")
    state = {"w": jnp.arange(8.0)}
    mgr = CheckpointManager(d, async_save=False)
    mgr.save(1, state)
    with faults.scope("ckpt.manifest@step=1"):
        with pytest.raises(FaultInjectedError, match="ckpt.manifest"):
            mgr.save(2, jax.tree_util.tree_map(lambda x: x + 1, state))
    # Torn step 2: renamed dir present, no manifest, no marker.
    assert os.path.isdir(os.path.join(d, "step_00000002"))
    assert not os.path.exists(os.path.join(d, "step_00000002.manifest.json"))
    assert mgr.all_steps() == [1]
    # Step 1 (and its manifest) still restorable.
    assert mgr.read_manifest() is not None
    step, restored = mgr.restore(state)
    assert step == 1
    with pytest.warns(UserWarning, match="quarantined"):
        mgr2 = CheckpointManager(d, async_save=False)
    assert mgr2.quarantined == ["step_00000002"]
    assert mgr2.all_steps() == [1]
    assert os.path.exists(os.path.join(d, "step_00000001.manifest.json"))


def test_crash_after_manifest_quarantines_sidecar_too(world, tmp_path):
    """The manifest→marker window (ckpt.commit): the uncommitted dir AND
    its already-written manifest both leave the directory at startup, so
    no orphan sidecar can shadow a later save of the same step."""
    d = str(tmp_path / "run")
    state = {"w": jnp.arange(8.0)}
    mgr = CheckpointManager(d, async_save=False)
    mgr.save(1, state)
    with faults.scope("ckpt.commit@step=1"):
        with pytest.raises(FaultInjectedError, match="ckpt.commit"):
            mgr.save(2, state)
    assert os.path.exists(os.path.join(d, "step_00000002.manifest.json"))
    with pytest.warns(UserWarning, match="quarantined"):
        mgr2 = CheckpointManager(d, async_save=False)
    assert mgr2.quarantined == ["step_00000002"]
    assert not os.path.exists(os.path.join(d, "step_00000002.manifest.json"))
    assert os.path.exists(
        os.path.join(d, "_quarantine", "step_00000002.manifest.json")
    )
    assert mgr2.all_steps() == [1]


def test_elastic_restore_fault_site_fires_before_bytes_move(world, tmp_path):
    """The elastic.restore chaos site covers the template-building path:
    a failure there leaves the checkpoint untouched and restorable."""
    _, state8, _ = _sharded_state(world)
    path = str(tmp_path / "ck")
    save_checkpoint(path, state8)
    with faults.scope("elastic.restore@step=1"):
        with pytest.raises(FaultInjectedError, match="elastic.restore"):
            restore_checkpoint(path, _host_zeros(state8), mesh=_submesh(4))
    r4 = restore_checkpoint(path, _host_zeros(state8), mesh=_submesh(4))
    assert len(r4.params["w"].sharding.device_set) == 4


def test_manifest_write_failure_commits_without_sidecar(world, tmp_path,
                                                        monkeypatch):
    """A manifest I/O failure must not abort (or, multi-process, wedge)
    the save: the step commits WITHOUT the sidecar, warned, and restore
    degrades to the topology-blind path."""
    from fluxmpi_tpu.utils import checkpoint as ckpt_mod

    def boom(path, manifest):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt_mod._manifest, "write_manifest", boom)
    d = str(tmp_path / "run")
    mgr = CheckpointManager(d, async_save=False)
    state = {"w": jnp.arange(8.0)}
    with pytest.warns(UserWarning, match="WITHOUT"):
        mgr.save(1, state)
    monkeypatch.undo()
    assert mgr.all_steps() == [1]  # committed despite the sidecar failure
    assert not os.path.exists(os.path.join(d, "step_00000001.manifest.json"))
    with pytest.warns(UserWarning, match="no topology manifest"):
        step, restored = mgr.restore(state)
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored["w"])), np.arange(8.0)
    )


def test_corrupt_manifest_sidecar_does_not_brick_resume(world, tmp_path):
    """A PR 6 checkpoint whose sidecar got corrupted still resumes: the
    unreadable manifest is ignored (warned) and the restore retries with
    the geometry-carrying payload template."""
    loss_fn, opt, fresh, loader = _train_pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), loader(32), steps=2, checkpoint=mgr,
               save_every=2)
    mpath = tmp_path / "run" / "step_00000002.manifest.json"
    mpath.write_text("{ corrupted")
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    with pytest.warns(UserWarning):
        _, summary = train_loop(step, fresh(), loader(32), epochs=1,
                                checkpoint=mgr2, resume=True)
    assert summary["resumed_from"] == 2
    assert summary["epochs"] == 1


def test_orphan_manifest_is_removed_at_startup(world, tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    (d / "step_00000004.manifest.json").write_text("{}")  # dir vanished
    with pytest.warns(UserWarning, match="orphan"):
        mgr = CheckpointManager(str(d), async_save=False)
    assert mgr.quarantined == ["step_00000004.manifest.json"]
    assert not (d / "step_00000004.manifest.json").exists()


def test_retention_deletes_manifest_with_step(world, tmp_path):
    state = {"w": jnp.arange(4.0)}
    mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=2,
                            async_save=False)
    for s in (1, 2, 3):
        mgr.save(s, state)
    assert mgr.all_steps() == [2, 3]
    names = sorted(os.listdir(tmp_path / "run"))
    assert "step_00000001.manifest.json" not in names
    assert "step_00000002.manifest.json" in names


# ---------------------------------------------------------------------------
# Loader cursor remap (fast, single-process N→M geometry changes)
# ---------------------------------------------------------------------------


def _id_dataset(n=128):
    ids = np.arange(n, dtype=np.int32)
    x = np.linspace(-2, 2, n, dtype=np.float32)[:, None]
    return ArrayDataset((x, x**2, ids))


def _ids(batch):
    return np.asarray(jax.device_get(batch[2])).tolist()


def _loader(world, gbs, **kw):
    kw.setdefault("shuffle", True)
    kw.setdefault("seed", 7)
    kw.setdefault("prefetch", 0)
    kw.setdefault("device_gather", False)
    return DistributedDataLoader(_id_dataset(), gbs, mesh=world, **kw)


def test_cursor_remap_is_sample_exact_across_batch_widths(world):
    """gbs 32 → 16 mid-epoch: the remapped cursor consumes exactly the
    remaining samples, in the same global order — no skip, no repeat."""
    reference = [i for b in _loader(world, 32) for i in _ids(b)]

    first = _loader(world, 32)
    it = iter(first)
    got = [i for _ in range(2) for i in _ids(next(it))]
    banked = {**first.state_dict(), **first.geometry()}
    assert banked["cursor"] == 2 and banked["global_batch_size"] == 32

    resumed = _loader(world, 16)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        resumed.load_state_dict(banked)
    # A clean whole-batch remap between single-process (batch-major by
    # construction) geometries is exact: no re-seen log, no
    # elastic_order caveat.
    assert not caught, [str(w.message) for w in caught]
    assert resumed.resume_cursor == 4  # 2*32 samples = 4 gbs-16 batches
    got += [i for b in resumed for i in _ids(b)]
    assert got == reference


def test_cursor_remap_grow_direction(world):
    reference = [i for b in _loader(world, 16) for i in _ids(b)]
    first = _loader(world, 16)
    it = iter(first)
    got = [i for _ in range(4) for i in _ids(next(it))]
    banked = {**first.state_dict(), **first.geometry()}
    resumed = _loader(world, 32)
    resumed.load_state_dict(banked)
    assert resumed.resume_cursor == 2
    got += [i for b in resumed for i in _ids(b)]
    assert got == reference


def test_cursor_remap_ragged_offset_rounds_down_and_logs(world):
    """An offset that lands mid-batch in the new width rounds DOWN (the
    partial batch replays; nothing is skipped) and the re-seen count is
    logged."""
    first = _loader(world, 8)
    banked = {**first.state_dict(), **first.geometry(), "cursor": 3}
    resumed = _loader(world, 16)
    with pytest.warns(UserWarning, match=r"8 already-consumed sample"):
        resumed.load_state_dict(banked)
    assert resumed.resume_cursor == 1  # 24 samples // 16 = 1 whole batch
    seen = [i for b in resumed for i in _ids(b)]
    full = [i for b in _loader(world, 16) for i in _ids(b)]
    assert seen == full[16:]  # replays from batch 1: samples 16.. re-seen


def test_cursor_at_epoch_end_remaps_to_next_epoch(world):
    first = _loader(world, 32)
    banked = {**first.state_dict(), **first.geometry(),
              "cursor": len(first)}  # epoch fully consumed
    resumed = _loader(world, 16)
    resumed.load_state_dict(banked)
    ref = _loader(world, 16)
    ref.set_epoch(1)
    assert [_ids(b) for b in resumed] == [_ids(b) for b in ref]


def test_epoch_end_remap_stays_epoch_end_under_wider_coverage(world):
    """A COMPLETE saved epoch (the banked epoch count includes it) must
    remap to epoch-end even when the new width's epoch covers MORE
    samples (old ragged tail < new coverage) — landing mid-epoch would
    replay the tail of an already-counted pass and double-count it."""
    ds = _id_dataset(112)  # gbs=32: 3 batches (96 covered); gbs=16: 7
    old = DistributedDataLoader(ds, 32, mesh=world, shuffle=True, seed=7,
                                prefetch=0, device_gather=False)
    banked = {**old.state_dict(), **old.geometry(), "cursor": len(old)}
    assert banked["num_batches"] == 3
    new = DistributedDataLoader(ds, 16, mesh=world, shuffle=True, seed=7,
                                prefetch=0, device_gather=False)
    new.load_state_dict(banked)
    # Next epoch's first batch, NOT batch 6 of the already-counted pass.
    assert new.resume_cursor == 0
    assert new.state_dict()["epoch"] == banked["epoch"] + 1


def test_incomplete_pass_past_new_coverage_warns_dropped_tail(world):
    """An incomplete old pass whose offset exceeds the new width's
    whole-batch coverage drops the old epoch's tail into the new ragged
    tail — counted and logged, then resumes at the next epoch."""
    ds = _id_dataset(112)
    old = DistributedDataLoader(ds, 8, mesh=world, shuffle=True, seed=7,
                                prefetch=0, device_gather=False)
    # cursor 13 of 14: 104 of 112 samples consumed, pass incomplete.
    banked = {**old.state_dict(), **old.geometry(), "cursor": 13}
    new = DistributedDataLoader(ds, 32, mesh=world, shuffle=True, seed=7,
                                prefetch=0, device_gather=False)  # 3×32=96
    with pytest.warns(UserWarning, match=r"8 sample\(s\) fall into"):
        new.load_state_dict(banked)
    assert new.resume_cursor == 0
    assert new.state_dict()["epoch"] == banked["epoch"] + 1


def test_pre_elastic_state_names_topology_in_error(world):
    """Satellite: a 3-key (pre-elastic) state whose cursor cannot fit
    this loader's epoch fails actionably — naming the probable topology
    mismatch, not just 'out of range'."""
    loader = _loader(world, 32)
    with pytest.raises(ValueError) as e:
        loader.load_state_dict({"epoch": 0, "cursor": 99, "seed": 7})
    msg = str(e.value)
    assert "cursor" in msg
    assert "process count" in msg and "batch size" in msg
    # A geometry-carrying state with an out-of-range cursor names the
    # SAVED geometry.
    with pytest.raises(ValueError, match="saved geometry"):
        loader.load_state_dict(
            {"epoch": 0, "cursor": 99, "seed": 7, "process_count": 1,
             "global_batch_size": 16, "num_batches": 8, "elastic_order": 0}
        )


def test_elastic_order_flag_validation(world):
    # Single-process: accepted and a no-op (iteration is already
    # batch-major); geometry records it.
    loader = DistributedDataLoader(_id_dataset(), 16, mesh=world,
                                   elastic_order=True, prefetch=0,
                                   device_gather=False)
    assert loader.geometry()["elastic_order"] == 1
    plain = DistributedDataLoader(_id_dataset(), 16, mesh=world,
                                  prefetch=0, device_gather=False)
    assert [_ids(b) for b in loader] == [_ids(b) for b in plain]


# ---------------------------------------------------------------------------
# train_loop: topology-change resume end to end (single-process)
# ---------------------------------------------------------------------------


def _train_pieces(world, n=128):
    from fluxmpi_tpu.models import MLP

    model = MLP(features=(16, 1))

    def loss_fn(p, ms, b):
        bx, by, _ = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1)))
    )

    def fresh():
        return replicate(TrainState.create(params, opt), world)

    consumed = []

    def track(batch):
        consumed.append(_ids(batch) if len(batch) > 2 else [])
        return batch

    def loader(gbs):
        ld = _loader(world, gbs, transform=track)
        return ld

    loader.consumed = consumed
    return loss_fn, opt, fresh, loader


def test_train_loop_elastic_resume_is_sample_exact(world, tmp_path):
    """Crash a gbs=32 epoch mid-way, resume it at gbs=16: the resumed
    run consumes exactly the remaining samples of the interrupted epoch
    (concatenated consumption log == uninterrupted run's), and the
    topology-changed resume is labeled on train.resumes."""
    loss_fn, opt, fresh, loader = _train_pieces(world)
    consumed = loader.consumed
    step = make_train_step(loss_fn, opt, mesh=world)

    consumed.clear()
    state_ref, s_ref = train_loop(step, fresh(), loader(32), epochs=1)
    reference = [i for b in consumed for i in b]
    assert len(reference) == 128

    consumed.clear()
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    with faults.scope("data.fetch@step=3"):
        with pytest.raises(FaultInjectedError):
            train_loop(step, fresh(), loader(32), epochs=1,
                       checkpoint=mgr, save_every=1)
    assert mgr.latest_step() == 2  # batches 0-1 trained and banked
    trained_prefix = [i for b in consumed[:2] for i in b]

    consumed.clear()
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    reg = MetricsRegistry()
    _, summary = train_loop(step, fresh(), loader(16), epochs=1,
                            checkpoint=mgr2, resume=True, metrics=reg)
    resumed_tail = [i for b in consumed for i in b]
    assert summary["resumed_from"] == 2
    assert summary["epochs"] == 1
    assert trained_prefix + resumed_tail == reference  # sample-exact
    assert reg.counter("train.resumes").value == 1
    assert reg.counter("train.resumes", topology_changed="true").value == 1


def test_train_loop_same_topology_resume_label_stays_false(world, tmp_path):
    loss_fn, opt, fresh, loader = _train_pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), loader(32), steps=2, checkpoint=mgr,
               save_every=2)
    reg = MetricsRegistry()
    _, summary = train_loop(step, fresh(), loader(32), steps=4,
                            checkpoint=mgr, resume=True, metrics=reg)
    assert summary["updates"] == 4
    assert reg.counter("train.resumes").value == 1
    assert reg.counter("train.resumes", topology_changed="true").value == 0


def test_train_loop_resumes_pre_manifest_checkpoint(world, tmp_path):
    """A checkpoint banked before this PR (simulated: legacy payload
    without geometry keys, manifest deleted) still resumes same-topology
    — the restore template degrades to the PR 5 shape, warned, never a
    crash."""
    loss_fn, opt, fresh, loader = _train_pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    legacy_payload = {
        "state": fresh(),
        "loop": {
            "updates": np.asarray(2, np.int64),
            "examples": np.asarray(64, np.int64),
            "epochs": np.asarray(0, np.int64),
        },
        "loader": {
            "epoch": np.asarray(0, np.int64),
            "cursor": np.asarray(2, np.int64),
            "seed": np.asarray(7, np.int64),
        },
    }
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    mgr.save(2, legacy_payload)
    os.remove(str(tmp_path / "run" / "step_00000002.manifest.json"))
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    with pytest.warns(UserWarning, match="no topology manifest"):
        _, summary = train_loop(step, fresh(), loader(32), epochs=1,
                                checkpoint=mgr2, resume=True)
    assert summary["resumed_from"] == 2
    assert summary["epochs"] == 1
    assert summary["updates"] == 4  # finished the remaining 2 dispatches


def test_injected_read_fault_propagates_through_legacy_resume(world,
                                                              tmp_path):
    """The manifest-less resume retry must not swallow injected faults
    (or real I/O errors): only the structure-mismatch family triggers
    the full-template retry."""
    loss_fn, opt, fresh, loader = _train_pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), loader(32), steps=2, checkpoint=mgr,
               save_every=2)
    os.remove(str(tmp_path / "run" / "step_00000002.manifest.json"))
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    with faults.scope("ckpt.read@step=1"):
        with pytest.raises(FaultInjectedError, match="ckpt.read"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                train_loop(step, fresh(), loader(32), epochs=1,
                           checkpoint=mgr2, resume=True)


def test_train_loop_remap_reseats_scan_group_boundary(world, tmp_path):
    """A remapped cursor that lands mid-scan-group re-seats to the group
    boundary (round-down: the partial group replays) instead of shifting
    the scan phase."""
    loss_fn, opt, fresh, loader = _train_pieces(world)
    step1 = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    # Bank cursor=3 at gbs=16 (48 samples): remap to gbs=32 gives
    # cursor 1 — odd against scan_steps=2 — which re-seats to 0.
    with faults.scope("data.fetch@step=4"):
        with pytest.raises(FaultInjectedError):
            train_loop(step1, fresh(), loader(16), epochs=1,
                       checkpoint=mgr, save_every=1)
    assert mgr.latest_step() == 3
    step2 = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the re-seen round-down warning
        _, summary = train_loop(step2, fresh(), loader(32), epochs=1,
                                checkpoint=mgr, resume=True)
    assert summary["resumed_from"] == 3
    # The whole 4-batch gbs-32 epoch replays as 2 scan groups of 2:
    # 3 banked + 4 new updates.
    assert summary["updates"] == 7
    assert summary["epochs"] == 1


# ---------------------------------------------------------------------------
# Real multi-process 4→2 and 2→4 SIGTERM-and-resume (slow)
# ---------------------------------------------------------------------------

_ELASTIC_CHILD = """
import json, os, sys
coordinator, nprocs, pid, ckpt_dir, log_dir, epochs = sys.argv[1:7]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import optax
import fluxmpi_tpu as fm
from fluxmpi_tpu.data import (ArrayDataset, DistributedDataContainer,
                              DistributedDataLoader)
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.utils import CheckpointManager
from fluxmpi_tpu.models import MLP

mesh = fm.init(distributed=True, coordinator_address=coordinator,
               num_processes=int(nprocs), process_id=int(pid),
               preemption=True)

n = 256
rng = np.random.default_rng(0)  # same data on every process
x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
ids = np.arange(n, dtype=np.int32)
ds = ArrayDataset((x, x**2, ids))

log = open(os.path.join(log_dir, f"consumed.{nprocs}.{pid}.jsonl"), "a",
           buffering=1)

def track(batch):
    log.write(json.dumps(np.asarray(batch[2]).tolist()) + "\\n")
    return batch

loader = DistributedDataLoader(
    DistributedDataContainer(ds), 16, mesh=mesh, shuffle=True, seed=5,
    elastic_order=True, prefetch=0, device_gather=False, transform=track,
)

model = MLP(features=(16, 1))

def loss_fn(p, ms, b):
    bx, by, _ = b
    return jnp.mean((model.apply(p, bx) - by) ** 2), ms

opt = optax.adam(1e-3)
params = fm.synchronize(model.init(jax.random.PRNGKey(0), x[:2]))
state = replicate(TrainState.create(params, opt), mesh)
step = make_train_step(loss_fn, opt, mesh=mesh)
mgr = CheckpointManager(ckpt_dir, async_save=False)
print("READY", flush=True)
state, summary = train_loop(step, state, loader, epochs=int(epochs),
                            checkpoint=mgr, save_every=4, flush_every=2,
                            resume=True)
print("SUMMARY " + json.dumps(
    {"updates": summary["updates"], "epochs": summary["epochs"],
     "preempted": summary["preempted"], "loss": summary["loss"],
     "resumed_from": summary["resumed_from"]}), flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_world(script, nprocs, ckpt_dir, log_dir, epochs, tmp_path):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(nprocs), str(i),
             str(ckpt_dir), str(log_dir), str(epochs)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for i in range(nprocs)
    ]


def _consumed_ids(log_dir, nprocs):
    out = []
    for i in range(nprocs):
        p = os.path.join(log_dir, f"consumed.{nprocs}.{i}.jsonl")
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                out.extend(json.loads(line))
    return out


@pytest.mark.slow
@pytest.mark.parametrize("n_before,n_after", [(4, 2), (2, 4)])
def test_sigterm_resume_across_topologies_is_sample_exact(
    world, tmp_path, n_before, n_after
):
    """Kill an N-process run mid-epoch with a real SIGTERM, resume it on
    M processes: the concatenated sample-consumption log matches the
    uninterrupted run's (no example skipped, none repeated) and the
    final loss agrees."""
    import time as _time

    script = tmp_path / "child.py"
    script.write_text(_ELASTIC_CHILD)
    epochs = 2

    # Uninterrupted reference at the BEFORE topology.
    ref_ckpt, ref_logs = tmp_path / "ref_ck", tmp_path / "ref_logs"
    ref_logs.mkdir()
    procs = _spawn_world(script, n_before, ref_ckpt, ref_logs, epochs,
                         tmp_path)
    ref_summaries = []
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=360)
        assert p.returncode == 0, f"ref rank {i}:\n{out}"
        line = [ln for ln in out.splitlines() if ln.startswith("SUMMARY ")][-1]
        ref_summaries.append(json.loads(line[len("SUMMARY "):]))
    ref_ids = sorted(_consumed_ids(str(ref_logs), n_before))
    assert len(ref_ids) == 256 * epochs  # 256 % 16 == 0: no remainder

    # Interrupted run: SIGTERM every process mid-epoch.
    ckpt, logs = tmp_path / "ck", tmp_path / "logs"
    logs.mkdir()
    procs = _spawn_world(script, n_before, ckpt, logs, epochs, tmp_path)
    try:
        for p in procs:
            assert p.stdout.readline().strip() == "READY"
        _time.sleep(2.0)
        for p in procs:
            p.send_signal(signal.SIGTERM)
        pre_summaries = []
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=360)
            assert p.returncode == 0, f"preempted rank {i}:\n{out}"
            line = [ln for ln in out.splitlines()
                    if ln.startswith("SUMMARY ")][-1]
            pre_summaries.append(json.loads(line[len("SUMMARY "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    assert all(s["preempted"] for s in pre_summaries)
    banked = pre_summaries[0]["updates"]
    assert 0 < banked < 16 * epochs

    # Resume at the AFTER topology, same checkpoint directory.
    procs = _spawn_world(script, n_after, ckpt, logs, epochs, tmp_path)
    post_summaries = []
    try:
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=360)
            assert p.returncode == 0, f"resumed rank {i}:\n{out}"
            line = [ln for ln in out.splitlines()
                    if ln.startswith("SUMMARY ")][-1]
            post_summaries.append(json.loads(line[len("SUMMARY "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    assert all(s["resumed_from"] == banked for s in post_summaries)
    assert all(s["epochs"] == epochs for s in post_summaries)
    assert all(not s["preempted"] for s in post_summaries)

    # Sample-exact across the topology change: every id consumed exactly
    # `epochs` times over interrupted+resumed, same multiset as the
    # uninterrupted run.
    got = sorted(
        _consumed_ids(str(logs), n_before) + _consumed_ids(str(logs),
                                                           n_after)
    )
    assert got == ref_ids
    # Same samples in the same global batches → the final loss agrees
    # (bit-for-bit within each world; fp-reduction drift across worlds).
    np.testing.assert_allclose(
        post_summaries[0]["loss"], ref_summaries[0]["loss"], rtol=5e-3
    )
