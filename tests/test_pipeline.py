"""Pipeline-parallel tests: the GPipe schedule over the ``pp`` axis is
numerically identical — forward AND backward — to applying the stages
sequentially on one device (the pipeline analogue of the repo's serial
equivalence oracles)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh


def _mesh_pp(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("pp",))


def _stage_fn(params, x):
    return jax.nn.gelu(x @ params["w"] + params["b"])


def _stages(n_stages, d, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(
                rng.normal(scale=0.5, size=(d, d)).astype(np.float32)
            ),
            "b": jnp.asarray(rng.normal(size=(d,)).astype(np.float32)),
        }
        for _ in range(n_stages)
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_pipeline_forward_matches_sequential(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 4, 8
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d)
    stacked = stack_stage_params(stages)

    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(8, d)).astype(np.float32)
    )
    fn = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4)
    y = fn(stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_pipeline_microbatch_counts(world):
    """Any microbatch count dividing the batch gives the same answer."""
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 2, 4
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d, seed=2)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(
        np.random.default_rng(3).normal(size=(12, d)).astype(np.float32)
    )
    ref = _sequential(stages, x)
    for m in (1, 2, 3, 6, 12):
        y = make_pipeline_fn(_stage_fn, mesh, n_microbatches=m)(stacked, x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6
        )


def test_pipeline_grads_match_sequential(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 4, 8
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d, seed=4)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(8, d)).astype(np.float32)
    )
    y_target = jnp.asarray(
        np.random.default_rng(6).normal(size=(8, d)).astype(np.float32)
    )

    pipe = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4)

    def pipe_loss(stacked_params):
        return jnp.mean((pipe(stacked_params, x) - y_target) ** 2)

    def seq_loss(stages_list):
        return jnp.mean((_sequential(stages_list, x) - y_target) ** 2)

    g_pipe = jax.grad(pipe_loss)(stacked)
    g_seq = jax.grad(seq_loss)(stages)

    for s in range(n_stages):
        np.testing.assert_allclose(
            np.asarray(g_pipe["w"][s]),
            np.asarray(g_seq[s]["w"]),
            rtol=1e-4,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(g_pipe["b"][s]),
            np.asarray(g_seq[s]["b"]),
            rtol=1e-4,
            atol=1e-6,
        )


def test_pipeline_rules_spec(world):
    from fluxmpi_tpu.parallel.pipeline import pipeline_rules

    rule = pipeline_rules()
    assert tuple(rule("w", (4, 8, 8))) == ("pp", None, None)
    assert tuple(rule("b", (4, 8))) == ("pp", None)
    assert rule("scalar", ()) is None


def test_pipeline_batch_divisibility_error(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    mesh = _mesh_pp(2)
    stacked = stack_stage_params(_stages(2, 4))
    x = jnp.ones((7, 4), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        make_pipeline_fn(_stage_fn, mesh, n_microbatches=2)(stacked, x)


def test_pipeline_transformer_stage_grads_exact(world):
    """VERDICT r1 next #9 done-criterion: a real transformer-block stage_fn
    at pp=2 is gradient-exact against the sequential stack."""
    from fluxmpi_tpu.models.transformer import EncoderBlock
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    d_model, seq, batch = 16, 8, 4
    block = EncoderBlock(d_model=d_model, num_heads=2, d_ff=32, dropout=0.0,
                         dtype=jnp.float32)

    def stage_fn(params, x):
        return block.apply({"params": params}, x, train=False)

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(batch, seq, d_model)).astype(np.float32))
    stages = [
        block.init(jax.random.PRNGKey(i), x, train=False)["params"]
        for i in range(2)
    ]
    stacked = stack_stage_params(stages)
    mesh = _mesh_pp(2)

    pipe = make_pipeline_fn(stage_fn, mesh, n_microbatches=2)
    y_target = jnp.asarray(
        rng.normal(size=(batch, seq, d_model)).astype(np.float32)
    )

    def pipe_loss(p):
        return jnp.mean((pipe(p, x) - y_target) ** 2)

    def seq_loss(stages_list):
        h = x
        for p in stages_list:
            h = stage_fn(p, h)
        return jnp.mean((h - y_target) ** 2)

    np.testing.assert_allclose(
        float(pipe_loss(stacked)), float(seq_loss(stages)), rtol=1e-5
    )
    g_pipe = jax.grad(pipe_loss)(stacked)
    g_seq = jax.grad(seq_loss)(stages)
    for s in range(2):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
            ),
            jax.tree_util.tree_map(lambda l: l[s], g_pipe),
            g_seq[s],
        )


def test_pipeline_remat_matches(world):
    """remat_stages=True (the 1F1B-equivalent activation-memory lever) is
    numerically identical in forward and backward."""
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 4, 8
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d, seed=8)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(
        np.random.default_rng(9).normal(size=(8, d)).astype(np.float32)
    )

    plain = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4)
    remat = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4,
                             remat_stages=True)
    np.testing.assert_allclose(
        np.asarray(plain(stacked, x)), np.asarray(remat(stacked, x)),
        rtol=1e-6,
    )
    gp = jax.grad(lambda p: jnp.mean(plain(p, x) ** 2))(stacked)
    gr = jax.grad(lambda p: jnp.mean(remat(p, x) ** 2))(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_pipeline_scalar_leaf_clear_error(world):
    """ADVICE r1: an unstacked scalar leaf raises a clear ValueError naming
    the leaf path, not an IndexError."""
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    mesh = _mesh_pp(2)
    stacked = stack_stage_params(_stages(2, 4))
    stacked["gamma"] = jnp.float32(1.0)  # rank-0 intruder
    x = jnp.ones((4, 4), jnp.float32)
    with pytest.raises(ValueError, match="gamma.*scalar|scalar.*gamma"):
        make_pipeline_fn(_stage_fn, mesh, n_microbatches=2)(stacked, x)


def test_pipeline_output_sharded_over_pp(world):
    """The output accumulator is pp-sharded (one copy across the axis), not
    replicated — each device stores only its owned microbatches."""
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 4, 8
    mesh = _mesh_pp(n_stages)
    stacked = stack_stage_params(_stages(n_stages, d, seed=10))
    x = jnp.ones((8, d), jnp.float32)
    y = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4)(stacked, x)
    assert not y.is_fully_replicated
    shard_rows = {s.data.shape[0] for s in y.addressable_shards}
    assert shard_rows == {x.shape[0] // n_stages}


def test_pipeline_input_sharded_over_pp(world):
    """VERDICT r2 next #8: the input stream is pp-sharded too — the
    compiled program wants x laid out over the pp axis (O(B/S) per device),
    and grads through the feed ring stay exact vs a single-stage oracle."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 4, 8
    mesh = _mesh_pp(n_stages)
    params_list = _stages(n_stages, d, seed=11)
    stacked = stack_stage_params(params_list)
    x = jnp.asarray(
        np.random.default_rng(12).normal(size=(8, d)).astype(np.float32)
    )
    fn = make_pipeline_fn(_stage_fn, mesh, n_microbatches=8)

    # The compiled step consumes x sharded over pp, not replicated.
    compiled = fn.lower(stacked, x).compile()
    x_sharding = jax.tree_util.tree_leaves(compiled.input_shardings[0])[-1]
    expected = NamedSharding(mesh, P("pp"))
    assert x_sharding.is_equivalent_to(expected, x.ndim)

    # Feed-ring forward and grads match the unpipelined composition.
    def serial(params_list, x):
        for p in params_list:
            x = _stage_fn(p, x)
        return x

    np.testing.assert_allclose(
        np.asarray(fn(stacked, x)), np.asarray(serial(params_list, x)),
        rtol=2e-6, atol=2e-6,
    )
    gp = jax.grad(lambda xx: jnp.sum(jnp.sin(fn(stacked, xx))))(x)
    gs = jax.grad(lambda xx: jnp.sum(jnp.sin(serial(params_list, xx))))(x)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                               rtol=2e-5, atol=2e-6)


# ---- interleaved (virtual-stage) schedule (VERDICT r3 next #6) ----


def test_interleaved_forward_matches_sequential(world):
    # v=2 chunks per device: 8 virtual stages on 4 devices, natural layer
    # order in, round-robin placement inside.
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, v, d = 4, 2, 8
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages * v, d, seed=40)
    stacked = stack_stage_params(stages, n_stages=n_stages, interleave=v)
    x = jnp.asarray(
        np.random.default_rng(41).normal(size=(16, d)).astype(np.float32)
    )
    fn = make_pipeline_fn(_stage_fn, mesh, n_microbatches=8, interleave=v)
    y = fn(stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("m", [1, 2, 4, 8, 16])
def test_interleaved_microbatch_counts(world, m):
    # Small microbatch counts force the 3S-3 period floor (the conveyor
    # round-trip); every count must still be exact.
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, v, d = 4, 3, 4
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages * v, d, seed=42)
    stacked = stack_stage_params(stages, n_stages=n_stages, interleave=v)
    x = jnp.asarray(
        np.random.default_rng(43).normal(size=(16, d)).astype(np.float32)
    )
    ref = _sequential(stages, x)
    y = make_pipeline_fn(_stage_fn, mesh, n_microbatches=m, interleave=v)(
        stacked, x
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_interleaved_grads_match_sequential(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, v, d = 2, 2, 4
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages * v, d, seed=44)
    stacked = stack_stage_params(stages, n_stages=n_stages, interleave=v)
    x = jnp.asarray(
        np.random.default_rng(45).normal(size=(8, d)).astype(np.float32)
    )
    fn = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4, interleave=v)

    def loss_pp(params, x):
        return jnp.sum(jnp.sin(fn(params, x)))

    def loss_seq(stage_list, x):
        y = _sequential(stage_list, x)
        return jnp.sum(jnp.sin(y))

    gp = jax.grad(loss_pp)(stacked, x)
    # Gradient of the sequential oracle per chunk, restacked into the same
    # round-robin layout the pipeline uses.
    gs = stack_stage_params(
        jax.grad(loss_seq)(stages, x), n_stages=n_stages, interleave=v
    )
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_interleaved_cuts_bubble(world):
    # The whole point: for the same device count and model depth, v chunks
    # per device shrink the schedule relative to v sequential GPipe sweeps,
    # and the useful-work fraction strictly improves over running the same
    # depth as v-fold-bigger GPipe stages.
    from fluxmpi_tpu.parallel.pipeline import pipeline_tick_count

    S, M = 4, 8
    for v in (2, 4):
        inter = pipeline_tick_count(M, S, interleave=v)
        gpipe = pipeline_tick_count(M, S, interleave=1)
        # v sequential sweeps would cost v·gpipe ticks; overlap wins.
        assert inter < v * gpipe
        # Utilization: interleaved does v·M unit-chunk computations in
        # `inter` ticks; plain GPipe covers the same depth with v-unit
        # stages: M·v units of work in gpipe·v tick-units.
        util_inter = (v * M) / inter  # per-device busy-tick fraction
        util_gpipe = (M) / gpipe
        assert util_inter > util_gpipe
    # v=1 reduces to the documented GPipe length M_pad + 2(S-1).
    assert pipeline_tick_count(8, 4, 1) == 8 + 2 * 3


def test_interleaved_rejects_bad_args(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 2, 4
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d, seed=46)  # only S chunks for v=2
    stacked = stack_stage_params(stages)
    x = jnp.ones((8, d), jnp.float32)
    fn = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4, interleave=2)
    with pytest.raises(ValueError, match="leading dim"):
        fn(stacked, x)


def test_pipeline_composes_with_dp(world):
    # 2-D mesh {dp, pp}: each dp slice runs its own pipeline over the pp
    # axis (params replicated over dp, stage-sharded over pp; batch sharded
    # over BOTH). The shard_map-body form composes directly — this is the
    # documented dp x pp pattern.
    from jax.sharding import Mesh, PartitionSpec as P

    from fluxmpi_tpu.parallel.pipeline import pipeline_apply, stack_stage_params

    dp, pp, d = 2, 4, 8
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(dp, pp), ("dp", "pp"))
    stages = _stages(pp, d, seed=50)
    stacked = stack_stage_params(stages)

    n_micro, mb = 4, 2  # per dp slice: 4 microbatches of 2 rows
    B = dp * n_micro * mb
    x = jnp.asarray(
        np.random.default_rng(51).normal(size=(B, d)).astype(np.float32)
    )

    from fluxmpi_tpu.parallel._compat import shard_map_unchecked

    def body(params, xx):
        return pipeline_apply(
            _stage_fn, params, xx, n_microbatches=n_micro,
            axis_name="pp", input_sharded=True,
        )

    mapped = shard_map_unchecked(
        body, mesh,
        in_specs=(P("pp"), P(("dp", "pp"))),
        out_specs=P(("dp", "pp")),
    )
    y = jax.jit(mapped)(stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)

    # ...and the composition differentiates (grads summed over dp slices
    # equal the sequential stack's).
    def loss_pp(params):
        return jnp.sum(jnp.sin(jax.jit(mapped)(params, x)))

    def loss_seq(stage_list):
        return jnp.sum(jnp.sin(_sequential(stage_list, x)))

    gp = jax.grad(loss_pp)(stacked)
    gs = stack_stage_params(jax.grad(loss_seq)(stages))
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-5)
