"""Pipeline-parallel tests: the GPipe schedule over the ``pp`` axis is
numerically identical — forward AND backward — to applying the stages
sequentially on one device (the pipeline analogue of the repo's serial
equivalence oracles)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh


def _mesh_pp(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("pp",))


def _stage_fn(params, x):
    return jax.nn.gelu(x @ params["w"] + params["b"])


def _stages(n_stages, d, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(
                rng.normal(scale=0.5, size=(d, d)).astype(np.float32)
            ),
            "b": jnp.asarray(rng.normal(size=(d,)).astype(np.float32)),
        }
        for _ in range(n_stages)
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_pipeline_forward_matches_sequential(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 4, 8
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d)
    stacked = stack_stage_params(stages)

    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(8, d)).astype(np.float32)
    )
    fn = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4)
    y = fn(stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_pipeline_microbatch_counts(world):
    """Any microbatch count dividing the batch gives the same answer."""
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 2, 4
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d, seed=2)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(
        np.random.default_rng(3).normal(size=(12, d)).astype(np.float32)
    )
    ref = _sequential(stages, x)
    for m in (1, 2, 3, 6, 12):
        y = make_pipeline_fn(_stage_fn, mesh, n_microbatches=m)(stacked, x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6
        )


def test_pipeline_grads_match_sequential(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    n_stages, d = 4, 8
    mesh = _mesh_pp(n_stages)
    stages = _stages(n_stages, d, seed=4)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(8, d)).astype(np.float32)
    )
    y_target = jnp.asarray(
        np.random.default_rng(6).normal(size=(8, d)).astype(np.float32)
    )

    pipe = make_pipeline_fn(_stage_fn, mesh, n_microbatches=4)

    def pipe_loss(stacked_params):
        return jnp.mean((pipe(stacked_params, x) - y_target) ** 2)

    def seq_loss(stages_list):
        return jnp.mean((_sequential(stages_list, x) - y_target) ** 2)

    g_pipe = jax.grad(pipe_loss)(stacked)
    g_seq = jax.grad(seq_loss)(stages)

    for s in range(n_stages):
        np.testing.assert_allclose(
            np.asarray(g_pipe["w"][s]),
            np.asarray(g_seq[s]["w"]),
            rtol=1e-4,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(g_pipe["b"][s]),
            np.asarray(g_seq[s]["b"]),
            rtol=1e-4,
            atol=1e-6,
        )


def test_pipeline_rules_spec(world):
    from fluxmpi_tpu.parallel.pipeline import pipeline_rules

    rule = pipeline_rules()
    assert tuple(rule("w", (4, 8, 8))) == ("pp", None, None)
    assert tuple(rule("b", (4, 8))) == ("pp", None)
    assert rule("scalar", ()) is None


def test_pipeline_batch_divisibility_error(world):
    from fluxmpi_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    mesh = _mesh_pp(2)
    stacked = stack_stage_params(_stages(2, 4))
    x = jnp.ones((7, 4), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        make_pipeline_fn(_stage_fn, mesh, n_microbatches=2)(stacked, x)
