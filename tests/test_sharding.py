"""Sharding-rule tests: FSDP and tensor-parallel layouts match the serial
oracle (the richer-layout extension of the reference's optimizer equivalence
oracle, test/test_optimizer.jl:20-26 — the reference itself only ever
replicates, SURVEY.md §2 parallelism inventory)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _mesh(world, shape):
    devs = np.asarray(jax.devices()).reshape(tuple(shape.values()))
    return Mesh(devs, tuple(shape.keys()))


def _is_sharded(leaf):
    return any(axis is not None for axis in tuple(leaf.sharding.spec))


def test_fsdp_matches_serial(world):
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState, fsdp_rule, make_train_step, shard_tree
    from fluxmpi_tpu.parallel.train import shard_batch

    mesh = _mesh(world, {"dp": 8})
    model = MLP(features=(16, 16, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
    optimizer = optax.adam(0.05)
    state = TrainState.create(params, optimizer)

    def loss_fn(p, mstate, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2), mstate

    rule = fsdp_rule(mesh, min_size=16)
    sharded_state, shardings = shard_tree(state, mesh, rule)
    # The big kernels must actually be sharded, and Adam's moments must
    # follow the same layout (ZeRO: optimizer state sharded too).
    assert _is_sharded(sharded_state.params["params"]["dense_0"]["kernel"])
    mu = sharded_state.opt_state[0].mu["params"]["dense_0"]["kernel"]
    assert _is_sharded(mu)

    step = make_train_step(
        loss_fn, optimizer, mesh=mesh, state_sharding=shardings, donate=False
    )
    rng = np.random.default_rng(1)
    batch = (
        rng.normal(size=(16, 2)).astype(np.float32),
        rng.normal(size=(16, 1)).astype(np.float32),
    )
    new_state, loss = step(sharded_state, shard_batch(batch, mesh))

    (sloss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, None, batch)
    updates, _ = optimizer.update(grads, optimizer.init(params), params)
    serial_params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        new_state.params,
        serial_params,
    )
    # Output layout is preserved: still sharded after the update.
    assert _is_sharded(new_state.params["params"]["dense_0"]["kernel"])


def _tiny_lm():
    from fluxmpi_tpu.models import TransformerLM

    return TransformerLM(
        vocab_size=64,
        max_len=32,
        num_layers=2,
        d_model=32,
        num_heads=4,
        d_ff=64,
    )


def _lm_loss(model):
    def loss_fn(p, mstate, batch):
        tokens, targets = batch
        logits = model.apply(p, tokens, train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return jnp.mean(loss), mstate

    return loss_fn


def test_tp_transformer_matches_serial(world):
    from fluxmpi_tpu.parallel import (
        TrainState,
        make_train_step,
        shard_tree,
        transformer_tp_rules,
    )
    from fluxmpi_tpu.parallel.train import shard_batch

    mesh = _mesh(world, {"dp": 2, "tp": 4})
    model = _tiny_lm()
    tokens = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)
    optimizer = optax.sgd(0.1)
    state = TrainState.create(params, optimizer)
    loss_fn = _lm_loss(model)

    sharded_state, shardings = shard_tree(state, mesh, transformer_tp_rules())
    blk = sharded_state.params["params"]["encoder"]["block_0"]
    assert tuple(blk["ff1"]["kernel"].sharding.spec) == (None, "tp")
    assert tuple(blk["attn"]["out"]["kernel"].sharding.spec) == ("tp", None, None)
    assert tuple(
        sharded_state.params["params"]["embed"]["embedding"].sharding.spec
    ) == ("tp", None)

    step = make_train_step(
        loss_fn,
        optimizer,
        mesh=mesh,
        state_sharding=shardings,
        batch_spec=P("dp"),
        donate=False,
    )
    rng = np.random.default_rng(2)
    batch = (
        rng.integers(0, 64, size=(8, 16)).astype(np.int32),
        rng.integers(0, 64, size=(8, 16)).astype(np.int32),
    )
    new_state, loss = step(
        sharded_state, shard_batch(batch, mesh, axis_name="dp")
    )

    (sloss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, None, batch)
    updates, _ = optimizer.update(grads, optimizer.init(params), params)
    serial_params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        ),
        new_state.params,
        serial_params,
    )


def test_tp_fsdp_sp_composed(world):
    """Full 3-axis layout: dp×sp×tp mesh, TP table + FSDP fallback, batch
    sharded over dp AND sequence over sp — one compiled step, finite loss."""
    from fluxmpi_tpu.parallel import (
        TrainState,
        combine_rules,
        fsdp_rule,
        make_train_step,
        shard_tree,
        transformer_tp_rules,
    )
    from fluxmpi_tpu.parallel.train import shard_batch

    mesh = _mesh(world, {"dp": 2, "sp": 2, "tp": 2})
    model = _tiny_lm()
    tokens = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)
    optimizer = optax.adam(1e-2)
    state = TrainState.create(params, optimizer)

    rule = combine_rules(transformer_tp_rules(), fsdp_rule(mesh, min_size=256))
    sharded_state, shardings = shard_tree(state, mesh, rule)

    step = make_train_step(
        _lm_loss(model),
        optimizer,
        mesh=mesh,
        state_sharding=shardings,
        batch_spec=P("dp", "sp"),
        donate=False,
    )
    rng = np.random.default_rng(3)
    batch = (
        rng.integers(0, 64, size=(4, 16)).astype(np.int32),
        rng.integers(0, 64, size=(4, 16)).astype(np.int32),
    )
    new_state, loss = step(
        sharded_state, shard_batch(batch, mesh, spec=P("dp", "sp"))
    )
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1


def test_rule_validation_degrades_to_replicated(world):
    """Specs that don't divide the leaf shape fall back to replicated dims
    instead of failing at compile time."""
    from fluxmpi_tpu.parallel.sharding import rule_from_table, tree_partition_specs

    mesh = _mesh(world, {"dp": 8})
    tree = {"w": jnp.ones((6, 4)), "b": jnp.ones((3,))}
    rule = rule_from_table([(r".*", P("dp"))])
    with pytest.warns(UserWarning, match="not divisible"):
        specs = tree_partition_specs(tree, mesh, rule)
    assert all(a is None for a in tuple(specs["w"]))
    assert all(a is None for a in tuple(specs["b"]))

    # A typo'd / absent mesh axis is also loud (ADVICE r1).
    bad_axis = rule_from_table([(r".*", P("tp"))])
    with pytest.warns(UserWarning, match="absent from mesh axes"):
        tree_partition_specs({"w": jnp.ones((8, 4))}, mesh, bad_axis)

    tree2 = {"w": jnp.ones((16, 4))}
    specs2 = tree_partition_specs(tree2, mesh, rule)
    assert tuple(specs2["w"])[0] == "dp"


def test_fsdp_rule_min_size(world):
    from fluxmpi_tpu.parallel import fsdp_rule

    mesh = _mesh(world, {"dp": 8})
    rule = fsdp_rule(mesh, min_size=1024)
    assert rule("small/bias", (8,)) is None
    assert rule("big/kernel", (64, 64)) == P("dp", None)
    # largest divisible dim wins
    assert rule("big/kernel", (64, 128)) == P(None, "dp")


def test_fsdp_lowering_guard(world):
    """VERDICT r2 next #4 (FSDP side): the compiled ZeRO-3 step must (a)
    reduce gradients collectively (reduce-scatter on TPU; XLA's CPU
    pipeline lacks the AR→RS rewrite, so all-reduce is the accepted CPU
    spelling), (b) keep params AND optimizer moments sharded end-to-end in
    its output layout, and (c) all-gather each sharded weight at most twice
    (fwd + bwd re-gather) — never accumulate full-tree gathers."""
    from fluxmpi_tpu.models import MLP
    from fluxmpi_tpu.parallel import TrainState, fsdp_rule, make_train_step, shard_tree
    from fluxmpi_tpu.parallel.train import shard_batch

    mesh = _mesh(None, {"dp": 8})
    model = MLP(features=(64, 64, 1))
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 2)))
    optimizer = optax.adam(0.05)

    def loss_fn(p, mstate, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2), mstate

    rule = fsdp_rule(mesh, min_size=16)
    state, shardings = shard_tree(TrainState.create(params, optimizer), mesh, rule)
    n_sharded = sum(
        1 for s in jax.tree_util.tree_leaves(shardings.params)
        if tuple(x for x in s.spec if x)
    )
    assert n_sharded >= 2

    step = make_train_step(
        loss_fn, optimizer, mesh=mesh, state_sharding=shardings, donate=False
    )
    rng = np.random.default_rng(1)
    batch = shard_batch(
        (rng.normal(size=(16, 2)).astype(np.float32),
         rng.normal(size=(16, 1)).astype(np.float32)),
        mesh,
    )
    compiled = step.lower(state, batch).compile()
    hlo = compiled.as_text()

    # (a) collective gradient reduction exists.
    assert hlo.count("reduce-scatter") + hlo.count("all-reduce(") > 0

    # (b) the OUTPUT state keeps the ZeRO layout: params and both Adam
    # moments of every sharded kernel come back dp-sharded, not replicated.
    out_state_shardings = compiled.output_shardings[0]
    for tree in (out_state_shardings.params,
                 out_state_shardings.opt_state[0].mu,
                 out_state_shardings.opt_state[0].nu):
        specs = [
            tuple(x for x in s.spec if x)
            for s in jax.tree_util.tree_leaves(tree)
        ]
        assert any(("dp",) == sp for sp in specs), specs

    # (c) bounded weight re-gathers: ≤ 2 per sharded leaf.
    assert hlo.count("all-gather(") <= 2 * n_sharded
