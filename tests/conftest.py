"""Test config: simulate an 8-device TPU world on CPU.

The TPU analogue of the reference's self-spawning MPI test harness
(reference: test/runtests.jl:11-16 runs every test file under
``mpiexec -n N``): instead of N OS processes over localhost MPI, we run one
process with N virtual XLA CPU devices
(``--xla_force_host_platform_device_count``) and exercise the real XLA
collective path over the simulated mesh — no mock backend.
"""

import os

# Force CPU even when the host environment preselects a TPU platform: the
# test world is 8 simulated devices.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU platform (jax_platforms
# becomes "axon,cpu"); pin the config back to CPU before backend init.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def world():
    """Initialized runtime over the 8-device CPU mesh."""
    import fluxmpi_tpu as fm

    mesh = fm.init(verbose=True)
    yield mesh


@pytest.fixture()
def nworkers(world):
    import fluxmpi_tpu as fm

    return fm.total_workers()
