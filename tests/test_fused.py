"""One-program flush windows (train_loop fuse="window"): fused-vs-
pipelined bit-exactness (final state AND summary metrics, including the
scan_steps path and a mid-epoch kill-and-resume landing inside a
window), auto-enable/forced-raise resolution, window-boundary flush
metrics + preemption, AOT compile attribution on the device/run-health
planes, the zero-cost-when-off contract on the fused path, and the
device-gather budget env hardening."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.errors import FaultInjectedError
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import make_window_program, replicate
from fluxmpi_tpu.telemetry import (
    AnomalyDetector,
    CompileMonitor,
    GoodputTracker,
    MetricsRegistry,
    anomaly,
    compileplane,
    goodput,
)
from fluxmpi_tpu.utils import CheckpointManager


@pytest.fixture(autouse=True)
def _clean_flags():
    faults.clear()
    fm.clear_preemption()
    yield
    faults.clear()
    fm.clear_preemption()


@pytest.fixture()
def planes_off():
    """Run-health + device planes guaranteed off around a test."""
    prev_tracker = goodput.set_goodput_tracker(GoodputTracker(enabled=False))
    prev_detector = anomaly.set_anomaly_detector(None)
    prev_monitor = compileplane.set_compile_monitor(None)
    try:
        yield
    finally:
        goodput.set_goodput_tracker(prev_tracker)
        anomaly.set_anomaly_detector(prev_detector)
        compileplane.set_compile_monitor(prev_monitor)


def _pieces(n=256, features=(16, 16, 1)):
    from fluxmpi_tpu.models import MLP

    model = MLP(features=features)

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1)))
    )
    return loss_fn, opt, params, ArrayDataset((x, x**2))


def _fresh(params, opt, world):
    return replicate(TrainState.create(params, opt, None), world)


def _leaves_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        ),
        a, b,
    )


def _loader(ds, world, **kw):
    kw.setdefault("shuffle", True)
    kw.setdefault("seed", 11)
    return DistributedDataLoader(ds, 64, mesh=world, **kw)


# ---------------------------------------------------------------------------
# Equivalence: the fused window must not change the math.
# ---------------------------------------------------------------------------


def test_fused_bit_identical_to_pipelined_and_scan(world):
    # Same batches, same update sequence -> bit-identical final state
    # across the per-batch pipelined path, the scan_steps multi-step
    # path, and the fused window; summary metrics match the per-batch
    # path exactly (loss is the last update's on both).
    loss_fn, opt, params, ds = _pieces()

    step = make_train_step(loss_fn, opt, mesh=world)
    s_pipe, sum_pipe = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse=False,
    )

    step_k = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    s_scan, sum_scan = train_loop(
        step_k, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse=False,
    )

    s_fused, sum_fused = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse="window",
    )

    _leaves_equal(s_pipe.params, s_fused.params)
    _leaves_equal(s_pipe.opt_state, s_fused.opt_state)
    _leaves_equal(s_scan.params, s_fused.params)
    for key in ("updates", "epochs", "examples", "loss"):
        assert sum_fused[key] == sum_pipe[key]
        if key != "loss":  # scan summary loss means over the last group
            assert sum_fused[key] == sum_scan[key]
    # The host-cost contract: one dispatch per window (flush_every=50
    # clamps to the 4-batch epoch -> one window per pass) vs one per
    # batch on the pipelined path.
    assert sum_fused["fused_window"] == 4
    assert sum_fused["dispatches"] == 2
    assert sum_pipe["dispatches"] == 8


def test_fused_scan_steps_step_is_subsumed(world):
    # A step built with scan_steps=K still fuses (the window does its
    # own scan over the banked single-update body) and stays
    # bit-identical to its own pipelined multi-step run.
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    s_pipe, _ = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse=False,
    )
    s_fused, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse="window", flush_every=2,
    )
    _leaves_equal(s_pipe.params, s_fused.params)
    assert summary["fused_window"] == 2
    assert summary["dispatches"] == 4  # 2 windows x 2 epochs


# ---------------------------------------------------------------------------
# Resolution: auto-enable, clamping, forced failures.
# ---------------------------------------------------------------------------


def test_fuse_auto_engages_on_device_gather_loader(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=1
    )
    assert summary["fused_window"] == 4  # flush_every=50 clamped to epoch
    assert summary["dispatches"] == 1


def test_fuse_auto_falls_back_on_host_path(world):
    # A transform forces the host loader path: auto quietly keeps the
    # pipelined driver instead of failing.
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    loader = _loader(ds, world, transform=lambda b: b, device_gather=False)
    _, summary = train_loop(
        step, _fresh(params, opt, world), loader, epochs=1
    )
    assert summary["fused_window"] is None
    assert summary["dispatches"] == 4


def test_fuse_auto_falls_back_on_indivisible_flush_every(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=1,
        flush_every=3,  # 4-batch epoch % 3 != 0
    )
    assert summary["fused_window"] is None


def test_fuse_auto_keeps_exact_steps_budget(world):
    # Window dispatch rounds a steps budget up to whole windows; AUTO
    # must never silently change what `steps` means, so a misaligned
    # budget keeps the pipelined path (forcing fuse="window" opts into
    # the documented rounding).
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), steps=10
    )
    assert summary["updates"] == 10
    assert summary["fused_window"] is None
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), steps=8
    )
    assert summary["updates"] == 8
    assert summary["fused_window"] == 4


def test_fuse_window_forced_raises_naming_the_reason(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    with pytest.raises(ValueError, match="not a DistributedDataLoader"):
        train_loop(step, _fresh(params, opt, world),
                   iter(list(_loader(ds, world))), steps=2, fuse="window")
    with pytest.raises(ValueError, match="device-gather"):
        train_loop(
            step, _fresh(params, opt, world),
            _loader(ds, world, transform=lambda b: b, device_gather=False),
            epochs=1, fuse="window",
        )
    with pytest.raises(ValueError, match="divide"):
        train_loop(step, _fresh(params, opt, world), _loader(ds, world),
                   epochs=1, fuse="window", flush_every=3)
    with pytest.raises(ValueError, match="fuse must be"):
        train_loop(step, _fresh(params, opt, world), _loader(ds, world),
                   epochs=1, fuse="sideways")


def test_fuse_window_forced_rejects_shard_map_steps(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world, style="shard_map")
    with pytest.raises(ValueError, match="metadata"):
        train_loop(step, _fresh(params, opt, world), _loader(ds, world),
                   epochs=1, fuse="window")


def test_make_window_program_validates(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    with pytest.raises(ValueError, match="width"):
        make_window_program(step, width=0, lbs=8)
    with pytest.raises(ValueError, match="style='auto'"):
        make_window_program(lambda s, b: (s, 0.0), width=2, lbs=8)


# ---------------------------------------------------------------------------
# Window-boundary instrumentation and budgets.
# ---------------------------------------------------------------------------


def test_fused_flush_metrics_at_window_granularity(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    reg = MetricsRegistry()
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=3,
        flush_every=2, metrics=reg,
    )
    assert summary["updates"] == 12
    assert summary["fused_window"] == 2
    assert reg.counter("train.steps").value == 12
    assert reg.counter("train.examples").value == 12 * 64
    # Every window is a flush: 6 windows -> 6 interval observations.
    assert reg.histogram("train.step_seconds").count == 6
    assert reg.gauge("train.window.size").value == 2.0
    assert reg.counter("train.window.dispatches").value == 6
    assert reg.gauge("train.loss").value == pytest.approx(summary["loss"])


def test_fused_instrumented_step_reports_grad_norm(world):
    loss_fn, opt, params, ds = _pieces()
    reg = MetricsRegistry()
    step = make_train_step(loss_fn, opt, mesh=world, metrics=True)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=1,
        metrics=reg,
    )
    assert summary["fused_window"] == 4
    assert reg.gauge("train.grad_norm").value > 0.0


def test_fused_hook_receives_window_stats(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    records = []
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        metrics=records.append,
    )
    assert sum(r["steps"] for r in records) == summary["updates"]
    for r in records:
        # The scan carry's on-device interval reduction, surfaced.
        assert r["loss_window_max"] >= r["loss"]
        assert r["loss_window_mean"] > 0


def test_fused_steps_budget_rounds_up_to_windows(world):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), steps=5,
        fuse="window", flush_every=4,
    )
    # Whole windows only: 5 updates round up to 2 windows = 8.
    assert summary["updates"] == 8
    assert summary["dispatches"] == 2


def test_fused_window_program_cache_survives_runs(world):
    # A second train_loop over the same step must reuse the AOT
    # executable, not re-lower it (the compile-once contract).
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    train_loop(step, _fresh(params, opt, world), _loader(ds, world),
               epochs=1)
    hot = step.__fluxmpi_compiled__ if hasattr(
        step, "__fluxmpi_compiled__") else step
    cache = getattr(hot, "__fluxmpi_window_cache__")
    assert len(cache) == 1
    (key,) = cache
    assert key[:2] == (4, 64)  # (width, lbs, state/data/perm avals...)
    first = cache[key]
    train_loop(step, _fresh(params, opt, world), _loader(ds, world),
               epochs=1)
    assert cache[key] is first and len(cache) == 1


def test_fused_window_cache_keys_on_dataset_avals(world):
    # Reusing one step across differently-sized datasets must compile a
    # fresh window program, not dispatch run 1's executable against run
    # 2's staged arrays (AOT executables check nothing at call time).
    loss_fn, opt, params, ds_small = _pieces(n=256)
    _, _, _, ds_big = _pieces(n=512)
    step = make_train_step(loss_fn, opt, mesh=world)
    _, s1 = train_loop(step, _fresh(params, opt, world),
                       _loader(ds_small, world), epochs=1, fuse="window",
                       flush_every=4)
    _, s2 = train_loop(step, _fresh(params, opt, world),
                       _loader(ds_big, world), epochs=1, fuse="window",
                       flush_every=4)
    assert s1["fused_window"] == s2["fused_window"] == 4
    assert s2["updates"] == 8  # 512 samples / gbs 64 = 8 batches
    hot = getattr(step, "__fluxmpi_compiled__", step)
    assert len(hot.__fluxmpi_window_cache__) == 2


# ---------------------------------------------------------------------------
# Fault tolerance: resume (mid-window included) and preemption.
# ---------------------------------------------------------------------------


def test_fused_kill_and_resume_bit_identical(world, tmp_path):
    # Crash a PIPELINED run mid-epoch (its checkpoint cursor lands at a
    # window-unaligned batch), resume FUSED: the first window is short
    # (realigning the flush grid), and the final state is bit-identical
    # to the uninterrupted reference.
    loss_fn, opt, params, ds = _pieces()

    def fresh():
        return _fresh(params, opt, world)

    step = make_train_step(loss_fn, opt, mesh=world)
    state_ref, sum_ref = train_loop(
        step, fresh(), _loader(ds, world), steps=8, fuse=False
    )

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    step2 = make_train_step(loss_fn, opt, mesh=world)
    with faults.scope("data.fetch@step=6"):
        with pytest.raises(FaultInjectedError):
            train_loop(step2, fresh(), _loader(ds, world), steps=8,
                       fuse=False, checkpoint=mgr, save_every=3)
    banked = mgr.latest_step()
    assert banked == 3  # mid-epoch, NOT aligned to the 4-batch window

    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    step3 = make_train_step(loss_fn, opt, mesh=world)
    state_res, summary = train_loop(
        step3, fresh(), _loader(ds, world), steps=8, fuse="window",
        flush_every=4, checkpoint=mgr2, resume=True,
    )
    assert summary["resumed_from"] == banked
    assert summary["updates"] == 8
    assert summary["fused_window"] == 4
    # Cursor 3 lands inside epoch 0's window: one short 1-update window
    # realigns the grid, then epoch 1 runs as one full window.
    assert summary["dispatches"] == 2
    _leaves_equal(state_res.params, state_ref.params)
    _leaves_equal(state_res.opt_state, state_ref.opt_state)
    assert summary["loss"] == sum_ref["loss"]


def test_fused_save_and_resume_fused_both_sides(world, tmp_path):
    # Fused run interrupted by its steps budget, resumed fused: saves
    # land at window boundaries and the concatenated run matches the
    # uninterrupted one exactly.
    loss_fn, opt, params, ds = _pieces()

    def fresh():
        return _fresh(params, opt, world)

    step = make_train_step(loss_fn, opt, mesh=world)
    state_ref, _ = train_loop(
        step, fresh(), _loader(ds, world), epochs=3, fuse="window",
        flush_every=2,
    )
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), _loader(ds, world), steps=6, fuse="window",
               flush_every=2, checkpoint=mgr, save_every=2)
    state_res, summary = train_loop(
        step, fresh(), _loader(ds, world), epochs=3, fuse="window",
        flush_every=2, checkpoint=mgr, resume=True,
    )
    assert summary["resumed_from"] == 6
    assert summary["updates"] == 12
    assert summary["epochs"] == 3
    _leaves_equal(state_res.params, state_ref.params)


def test_fused_preemption_drains_at_window_boundary(world, tmp_path):
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    fm.request_preemption()
    state, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse="window", flush_every=2, checkpoint=mgr,
    )
    # The flag is honored at the first window boundary: exactly one
    # window ran, the emergency checkpoint banked it.
    assert summary["preempted"] is True
    assert summary["updates"] == 2
    assert mgr.latest_step() == 2
    fm.clear_preemption()
    state_res, summary2 = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse="window", flush_every=2, checkpoint=mgr, resume=True,
    )
    assert summary2["updates"] == 8
    state_ref, _ = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        fuse="window", flush_every=2,
    )
    _leaves_equal(state_res.params, state_ref.params)


# ---------------------------------------------------------------------------
# Device/run-health planes on the fused path.
# ---------------------------------------------------------------------------


def test_fused_aot_compile_attributed(world, planes_off):
    # The AOT-lowered window program has no jit cache to poll: the
    # monitor's executable-handle path must still attribute it —
    # compile.function_seconds{train_loop.window} and the aot counters
    # appear, and warmup compiles never read as steady-state retraces.
    mon = CompileMonitor()
    compileplane.set_compile_monitor(mon)
    reg = MetricsRegistry()
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        flush_every=2, metrics=reg,
    )
    assert summary["fused_window"] == 2
    assert (
        reg.counter(
            "compile.aot_programs", function="train_loop.window"
        ).value == 1
    )
    assert (
        reg.counter(
            "compile.aot_seconds", function="train_loop.window"
        ).value > 0
    )
    assert (
        reg.counter(
            "compile.function_seconds", function="train_loop.window"
        ).value > 0
    )
    # One warmup compile, zero steady-state retraces.
    assert mon.retraces == []
    assert (
        reg.counter(
            "compile.retraces", function="train_loop.window"
        ).value == 0
    )


def test_fuse_auto_falls_back_when_elastic_remap_breaks_budget(world,
                                                               tmp_path):
    # Same-geometry resumes keep updates ≡ cursor (mod window); an
    # ELASTIC remap (different global batch size) rescales the cursor
    # while updates stays, so window boundaries would straddle — and
    # overshoot — an aligned steps budget. AUTO must fall back to the
    # pipelined path and stop exactly at the budget.
    loss_fn, opt, params, ds = _pieces()

    def fresh():
        return _fresh(params, opt, world)

    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    # gbs 64 (4 batches/epoch): bank updates=2 at cursor=2.
    train_loop(step, fresh(), _loader(ds, world), steps=2, fuse=False,
               checkpoint=mgr, save_every=2)
    # Resume with gbs 32 (8 batches/epoch): cursor remaps 2 -> 4 while
    # updates stays 2 — updates ≢ cursor (mod 4). Fused windows would
    # land at updates 6, 10: past steps=8.
    loader = DistributedDataLoader(ds, 32, mesh=world, shuffle=True,
                                   seed=11)
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    _, summary = train_loop(step, fresh(), loader, steps=8,
                            flush_every=4, checkpoint=mgr2, resume=True)
    assert summary["resumed_from"] == 2
    assert summary["fused_window"] is None  # auto fell back
    assert summary["updates"] == 8  # budget hit EXACTLY


def test_fused_mid_window_resume_is_not_a_retrace(world, tmp_path,
                                                  planes_off):
    # A mid-window resume compiles TWO widths (the short realignment
    # window + the full one). Both must land inside warmup: the full
    # program is pre-built before the short window's flush marks the
    # run steady, so a legitimate resume never fires
    # steady_state_retrace (or burns the once-per-run auto-profile).
    loss_fn, opt, params, ds = _pieces()

    def fresh():
        return _fresh(params, opt, world)

    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    with faults.scope("data.fetch@step=6"):
        with pytest.raises(FaultInjectedError):
            train_loop(step, fresh(), _loader(ds, world), steps=8,
                       fuse=False, checkpoint=mgr, save_every=3)
    assert mgr.latest_step() == 3  # window-unaligned cursor

    mon = CompileMonitor()
    compileplane.set_compile_monitor(mon)
    reg = MetricsRegistry()
    step2 = make_train_step(loss_fn, opt, mesh=world)
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    _, summary = train_loop(
        step2, fresh(), _loader(ds, world), steps=8, fuse="window",
        flush_every=4, checkpoint=mgr2, resume=True, metrics=reg,
    )
    assert summary["dispatches"] == 2  # short 1-update window + full 4
    assert mon.retraces == []
    assert (
        reg.counter(
            "compile.retraces", function="train_loop.window"
        ).value == 0
    )
    assert (
        reg.counter(
            "compile.aot_programs", function="train_loop.window"
        ).value == 2
    )


def test_compile_monitor_aot_retrace_after_steady():
    # Unit-level: an AOT compile AFTER the warmup boundary reads as a
    # steady-state retrace naming the program.
    mon = CompileMonitor()
    reg = MetricsRegistry()
    mon.track_aot("train_loop.window")
    mon.note_aot_compile("train_loop.window", 0.5)
    info = mon.observe_flush(reg)  # warmup boundary
    assert info["steady"] is False
    mon.note_aot_compile("train_loop.window", 0.25)
    mon._note_duration(
        "/jax/core/compile/backend_compile_duration", 0.25
    )
    info = mon.observe_flush(reg)
    assert info["steady"] is True
    assert info["functions"] == ["train_loop.window"]
    assert (
        reg.counter(
            "compile.aot_programs", function="train_loop.window"
        ).value == 2
    )
    assert reg.counter(
        "compile.aot_seconds", function="train_loop.window"
    ).value == pytest.approx(0.75)
    assert (
        reg.counter(
            "compile.retraces", function="train_loop.window"
        ).value == 1
    )


def test_fused_goodput_books_aot_compile(world, planes_off):
    tracker = GoodputTracker()
    goodput.set_goodput_tracker(tracker)
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    # Fresh step object -> fresh AOT cache -> the compile is paid (and
    # booked) inside this run.
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        flush_every=2,
    )
    rep = summary["goodput"]
    assert summary["fused_window"] == 2
    assert rep["buckets"]["compile"] > 0
    assert rep["buckets"]["step"] > 0
    assert rep["updates"] == 8
    # FLOPs came from the window executable's cost model.
    assert rep["flops_per_update"] and rep["flops_per_update"] > 0


def test_fused_mfu_survives_window_cache_hit(world, planes_off):
    # reset_run() clears the per-run FLOPs at every train_loop start; a
    # second fused run that cache-hits the banked window executable must
    # still re-derive them (MFU would otherwise silently vanish from
    # run 2 while the pipelined path keeps reporting it).
    tracker = GoodputTracker()
    goodput.set_goodput_tracker(tracker)
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    _, s1 = train_loop(step, _fresh(params, opt, world),
                       _loader(ds, world), epochs=1, flush_every=2)
    assert s1["fused_window"] == 2
    assert s1["goodput"]["flops_per_update"]
    _, s2 = train_loop(step, _fresh(params, opt, world),
                       _loader(ds, world), epochs=1, flush_every=2)
    hot = getattr(step, "__fluxmpi_compiled__", step)
    assert len(hot.__fluxmpi_window_cache__) == 1  # run 2 cache-hit
    assert s2["goodput"]["flops_per_update"] == s1["goodput"][
        "flops_per_update"
    ]


def test_fuse_auto_falls_back_on_ragged_scan_epoch(world):
    # A scan_steps step on an epoch its stacking adapter would truncate:
    # the pipelined path drops the ragged trailing scan group (4 updates
    # from 5 batches at k=2); fusing would train all 5 — AUTO must not
    # silently change what an epoch means, so it keeps the pipelined
    # path (forcing fuse="window" opts into the whole-epoch behavior).
    loss_fn, opt, params, ds = _pieces(n=320)  # 5 batches at gbs=64
    step = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=1
    )
    assert summary["fused_window"] is None
    assert summary["updates"] == 4  # (5 // 2) * 2: ragged group dropped
    _, forced = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=1,
        fuse="window", flush_every=5,
    )
    assert forced["fused_window"] == 5
    assert forced["updates"] == 5  # explicit opt-in trains the whole epoch


def test_fuse_auto_falls_back_on_scan_misaligned_steps(world):
    # steps window-aligned but NOT scan-aligned: pipelined scan groups
    # round the budget UP (steps=6 at k=4 -> 8 updates); fusing would
    # stop at 6 — a silent budget-semantics change AUTO must refuse.
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world, scan_steps=4)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), steps=6,
        flush_every=2,
    )
    assert summary["fused_window"] is None
    assert summary["updates"] == 8  # scan quantization, as before
    # A scan-aligned budget fuses fine.
    _, aligned = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), steps=8,
        flush_every=2,
    )
    assert aligned["fused_window"] == 2
    assert aligned["updates"] == 8


def test_fused_ticks_watchdog_per_window(world):
    from fluxmpi_tpu.telemetry import watchdog

    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    before = watchdog._progress_value()
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=2,
        flush_every=2,
    )
    assert summary["dispatches"] == 4
    # One liveness tick per window dispatch PLUS the flush's
    # interval-updates tick — the stall detector is never blind for
    # more than one window.
    assert watchdog._progress_value() >= before + 4 + summary["updates"]


def test_fused_fully_off_costs_nothing(world, planes_off, monkeypatch):
    # The monkeypatch-explode contract extended to the fused path: with
    # every plane off, one fused run performs no tracker clock reads,
    # segments, compile-monitor calls, or AOT notes.
    tracker = goodput.get_goodput_tracker()
    assert not tracker.enabled
    assert compileplane.get_compile_monitor() is None

    def boom(*a, **k):
        raise AssertionError("plane touched on the fused off path")

    tracker._clock = boom
    tracker.segment = boom
    tracker.add = boom
    tracker.note_updates = boom
    tracker.record = boom
    monkeypatch.setattr(CompileMonitor, "track", boom)
    monkeypatch.setattr(CompileMonitor, "track_aot", boom)
    monkeypatch.setattr(CompileMonitor, "note_aot_compile", boom)
    monkeypatch.setattr(CompileMonitor, "observe_flush", boom)
    monkeypatch.setattr(AnomalyDetector, "observe", boom)
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=1
    )
    assert summary["fused_window"] == 4
    assert summary["updates"] == 4
    assert "goodput" not in summary


# ---------------------------------------------------------------------------
# Loader surface: device_epoch contract + env hardening.
# ---------------------------------------------------------------------------


def test_device_epoch_rejects_host_path_loader(world):
    _, _, _, ds = _pieces()
    loader = _loader(ds, world, device_gather=False)
    assert not loader.fusible()
    with pytest.raises(ValueError, match="device-gather"):
        loader.device_epoch()


def test_device_epoch_matches_iteration_order(world):
    # The fused pass must consume exactly the batches iterating would:
    # same permutation, same epoch bookkeeping.
    _, _, _, ds = _pieces()
    a = _loader(ds, world)
    b = _loader(ds, world)
    it_batches = [
        np.asarray(jax.device_get(batch[0])) for batch in a
    ]
    staged, perm, start = b.device_epoch()
    assert start == 0
    perm_h = np.asarray(jax.device_get(perm))
    data_x = np.asarray(jax.device_get(staged[0]))
    for i, ref in enumerate(it_batches):
        got = data_x[perm_h[i * 64:(i + 1) * 64]]
        np.testing.assert_array_equal(got, ref)
    b.note_consumed(len(it_batches))
    assert a.state_dict() == b.state_dict()


def test_device_gather_budget_env_hardening(world, monkeypatch):
    _, _, _, ds = _pieces()
    loader = _loader(ds, world)
    backing = loader._array_backing()
    monkeypatch.setenv("FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES", "256MiB")
    with pytest.warns(UserWarning, match="not an integer"):
        assert loader._use_device_gather(backing) is True  # default budget
    # A parseable tiny budget still disables the path (no warning).
    monkeypatch.setenv("FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES", "16")
    assert loader._use_device_gather(backing) is False


def test_compile_cache_wiring(world, monkeypatch):
    # On the CPU test backend the persistent cache must refuse (stale
    # XLA:CPU entries can SIGILL) — silently for the implicit default,
    # loudly when explicitly requested; the init() spec plumbing mirrors
    # the other planes.
    from fluxmpi_tpu import runtime

    monkeypatch.delenv("FLUXMPI_TPU_COMPILE_CACHE", raising=False)
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error")  # implicit call: no warning
        assert runtime.enable_compile_cache() is False
    with pytest.warns(UserWarning, match="TPU-only"):
        assert runtime.enable_compile_cache("/tmp/cache") is False
    monkeypatch.setenv("FLUXMPI_TPU_COMPILE_CACHE", "/tmp/cache")
    with pytest.warns(UserWarning, match="TPU-only"):
        runtime._configure_compile_cache(None)
    monkeypatch.delenv("FLUXMPI_TPU_COMPILE_CACHE", raising=False)
    runtime._configure_compile_cache(None)  # unset env: no-op
    runtime._configure_compile_cache(False)  # explicit off: no-op
    with pytest.raises(ValueError, match="compile_cache"):
        runtime._configure_compile_cache(0.5)
    # init() replay applies the spec (idempotent path).
    with pytest.warns(UserWarning, match="TPU-only"):
        fm.init(compile_cache="/tmp/cache")


def test_fused_respects_tiny_budget_fallback(world, monkeypatch):
    # Auto mode: dataset over the staging budget -> host path -> the
    # fused window quietly disengages.
    loss_fn, opt, params, ds = _pieces()
    step = make_train_step(loss_fn, opt, mesh=world)
    monkeypatch.setenv("FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES", "16")
    _, summary = train_loop(
        step, _fresh(params, opt, world), _loader(ds, world), epochs=1
    )
    assert summary["fused_window"] is None
    assert summary["updates"] == 4
