"""Shared dense-attention oracles for the test suite (single source — the
segment-mask semantics must not drift between test files)."""

import numpy as np

import jax
import jax.numpy as jnp


def dense_seg_attention(q, k, v, qseg, kseg, causal=False, window=None):
    """Dense oracle with the kernel's segment semantics: attend iff ids
    equal and key id nonzero. Fully-masked rows are garbage here (uniform
    softmax) — compare valid rows only."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = (qseg[:, :, None] == kseg[:, None, :]) & (kseg[:, None, :] != 0)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        pos = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        if window is not None:
            pos = pos & (
                jnp.arange(sq)[:, None] - jnp.arange(sk)[None, :] < window
            )
        mask = mask & pos[None]
    s = jnp.where(mask[:, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
