"""Model zoo tests: every BASELINE config builds, runs forward, and takes a
DP train step on the 8-device mesh; DEQ gradients match the unrolled oracle;
BatchNorm state flows through the train step and synchronize."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# forward shapes
# ---------------------------------------------------------------------------


def test_cnn_forward(world):
    from fluxmpi_tpu.models import CNN

    model = CNN(num_classes=10)
    x = jnp.ones((4, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (4, 10)
    assert "batch_stats" in variables


def test_resnet18_forward(world):
    from fluxmpi_tpu.models import ResNet18

    model = ResNet18(num_classes=10)
    x = jnp.ones((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)


def test_resnet50_builds(world):
    from fluxmpi_tpu.models import ResNet50

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    x = jnp.ones((2, 64, 64, 3), jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 1000)
    assert out.dtype == jnp.float32  # f32 head
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    assert 20e6 < n_params < 30e6  # ~25.5M — the ResNet-50 signature


def test_deq_forward(world):
    from fluxmpi_tpu.models import DEQ

    model = DEQ(hidden=32, out=1)
    x = jnp.ones((4, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (4, 1)
    assert np.all(np.isfinite(np.asarray(out)))


def test_transformer_forward(world):
    from fluxmpi_tpu.models import TransformerEncoder, TransformerLM

    enc = TransformerEncoder(num_layers=2, d_model=32, num_heads=4, d_ff=64)
    x = jnp.ones((2, 16, 32))
    variables = enc.init(jax.random.PRNGKey(0), x, train=False)
    out = enc.apply(variables, x, train=False)
    assert out.shape == (2, 16, 32)

    lm = TransformerLM(vocab_size=64, max_len=32, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    toks = jnp.zeros((2, 16), jnp.int32)
    variables = lm.init(jax.random.PRNGKey(0), toks, train=False)
    logits = lm.apply(variables, toks, train=False)
    assert logits.shape == (2, 16, 64)


# ---------------------------------------------------------------------------
# DEQ implicit gradient oracle
# ---------------------------------------------------------------------------


def test_deq_implicit_gradient_matches_unrolled(world):
    from fluxmpi_tpu.models.deq import fixed_point_solve

    hidden, batch = 8, 4
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    W = jax.random.normal(k1, (hidden, hidden)) * 0.1
    U = jax.random.normal(k2, (3, hidden)) * 0.5
    b = jnp.zeros((hidden,))
    x = jax.random.normal(k3, (batch, 3))

    def cell(params, xx, z):
        W_, U_, b_ = params
        return jnp.tanh(z @ W_ + xx @ U_ + b_)

    def loss_implicit(params):
        z0 = jnp.zeros((batch, hidden))
        z = fixed_point_solve(cell, params, x, z0, 1e-8, 200, 1.0)
        return jnp.sum(z**2)

    def loss_unrolled(params):
        z = jnp.zeros((batch, hidden))
        for _ in range(200):  # plain unrolled AD as oracle
            z = cell(params, x, z)
        return jnp.sum(z**2)

    g_imp = jax.grad(loss_implicit)((W, U, b))
    g_unr = jax.grad(loss_unrolled)((W, U, b))
    for a, b_ in zip(g_imp, g_unr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


def test_deq_trains_under_dp(world):
    # collectives + custom VJP under jit over the mesh (SURVEY.md §7 hard part)
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import DEQ
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model = DEQ(hidden=16, out=1)
    x = np.random.default_rng(0).normal(size=(32, 3)).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) ** 2).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))
    optimizer = optax.adam(1e-2)

    def loss_fn(p, ms, batch):
        bx, by = batch
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    # shard_map style: the custom VJP runs per-device with explicit psum after
    step = make_train_step(
        loss_fn, optimizer, style="shard_map", grad_reduce="mean", donate=False
    )
    state = replicate(TrainState.create(params, optimizer))
    batch = shard_batch((jnp.asarray(x), jnp.asarray(y)))
    losses = []
    for _ in range(30):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# BatchNorm model state under DP
# ---------------------------------------------------------------------------


def _cnn_setup():
    from fluxmpi_tpu.models import CNN

    model = CNN(num_classes=10, channels=(8, 16))
    x = np.random.default_rng(0).normal(size=(16, 16, 16, 3)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 10, size=(16,)).astype(np.int32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]), train=False)
    return model, variables, x, y


def test_cnn_train_step_updates_batch_stats(world):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model, variables, x, y = _cnn_setup()
    optimizer = optax.sgd(0.1)

    def loss_fn(params, batch_stats, batch):
        bx, by = batch
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats},
            bx,
            train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()
        return loss, updates["batch_stats"]

    step = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    state = replicate(
        TrainState.create(variables["params"], optimizer, variables["batch_stats"])
    )
    batch = shard_batch((jnp.asarray(x), jnp.asarray(y)))
    before = np.asarray(
        jax.tree_util.tree_leaves(state.model_state)[0]
    ).copy()
    state, loss = step(state, batch)
    after = np.asarray(jax.tree_util.tree_leaves(state.model_state)[0])
    assert np.isfinite(float(loss))
    assert not np.array_equal(before, after)  # running stats moved


def test_cnn_sync_bn_matches_global_stats(world, nworkers):
    # Cross-replica BN in shard_map must equal global-batch BN in auto style
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import CNN
    from fluxmpi_tpu.parallel import make_train_step, TrainState
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    x = np.random.default_rng(0).normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = np.zeros((16,), np.int32)
    optimizer = optax.sgd(0.1)

    results = {}
    for style, axis_name in (("auto", None), ("shard_map", "dp")):
        model = CNN(num_classes=4, channels=(8,), axis_name=axis_name)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.asarray(x[:2]), train=False
        )

        def loss_fn(params, batch_stats, batch, model=model):
            bx, by = batch
            logits, updates = model.apply(
                {"params": params, "batch_stats": batch_stats},
                bx,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, by
            ).mean()
            return loss, updates["batch_stats"]

        step = make_train_step(
            loss_fn, optimizer, style=style, grad_reduce="mean",
            state_reduce="mean", donate=False
        )
        state = replicate(
            TrainState.create(
                variables["params"], optimizer, variables["batch_stats"]
            )
        )
        batch = shard_batch((jnp.asarray(x), jnp.asarray(y)))
        state, _ = step(state, batch)
        results[style] = jax.tree_util.tree_map(np.asarray, state.model_state)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        results["auto"],
        results["shard_map"],
    )


def test_transformer_trains(world):
    import fluxmpi_tpu as fm
    from fluxmpi_tpu.models import TransformerLM
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model = TransformerLM(vocab_size=32, max_len=16, num_layers=2, d_model=32,
                          num_heads=2, d_ff=64)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 32, size=(16, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(toks[:2]), train=False)
    optimizer = optax.adam(1e-3)

    def loss_fn(p, ms, batch):
        b = batch
        logits = model.apply(p, b, train=True)
        targets = jnp.roll(b, -1, axis=-1)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], targets[:, :-1]
        ).mean()
        return loss, ms

    step = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    state = replicate(TrainState.create(params, optimizer))
    batch = shard_batch(jnp.asarray(toks))
    losses = []
    for _ in range(10):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_vit_forward(world):
    from fluxmpi_tpu.models import ViT

    model = ViT(num_classes=10, patch=8, num_layers=2, d_model=32,
                num_heads=2, d_ff=64)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    # 32/8 = 4x4 patches + CLS = 17 position embeddings
    assert variables["params"]["pos_embed"].shape == (1, 17, 32)
    with pytest.raises(ValueError, match="patch"):
        model.init(jax.random.PRNGKey(0), jnp.ones((1, 30, 30, 3)),
                   train=False)


def test_vit_trains_under_dp(world):
    from fluxmpi_tpu.models import ViT
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model = ViT(num_classes=4, patch=8, num_layers=2, d_model=32,
                num_heads=2, d_ff=64)
    rng = np.random.default_rng(1)
    xs = jnp.asarray(rng.normal(size=(16, 16, 16, 3)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 4, size=(16,)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), xs[:2], train=False)
    optimizer = optax.adam(1e-3)

    def loss_fn(p, ms, batch):
        bx, by = batch
        logits = model.apply(p, bx, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, by
        ).mean(), ms

    step = make_train_step(loss_fn, optimizer, style="auto", donate=False)
    state = replicate(TrainState.create(params, optimizer))
    batch = shard_batch((xs, ys))
    losses = []
    for _ in range(10):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_vit_with_flash_attention(world):
    # The attention_fn hook composes: ViT through the flash kernel matches
    # the dense encoder (196-token sequences are exactly the shape the
    # kernel auto-picks blocks for).
    from fluxmpi_tpu.models import ViT
    from fluxmpi_tpu.ops import flash_attention_fn

    kw = dict(num_classes=4, patch=8, num_layers=1, d_model=32,
              num_heads=2, d_ff=64)
    dense = ViT(**kw)
    # 17 tokens (16 patches + CLS): the auto-picker takes the full axis as
    # one block — indivisible sequence lengths work out of the box.
    flash = ViT(**kw, attention_fn=flash_attention_fn())
    x = jnp.asarray(
        np.random.default_rng(2).normal(size=(2, 32, 32, 3)).astype(np.float32)
    )
    variables = dense.init(jax.random.PRNGKey(0), x, train=False)
    np.testing.assert_allclose(
        np.asarray(dense.apply(variables, x, train=False)),
        np.asarray(flash.apply(variables, x, train=False)),
        atol=3e-5,
    )


# ---- Anderson-accelerated DEQ solver ----


def test_anderson_matches_damped_fixed_point(world):
    # Same cell, same tolerance: both solvers land on the same fixed point,
    # Anderson in (far) fewer iterations.
    from fluxmpi_tpu.models.deq import _anderson_iteration, _damped_iteration

    rng = np.random.default_rng(70)
    d = 32
    W = jnp.asarray(
        (rng.normal(size=(d, d)) * 0.2 / np.sqrt(d)).astype(np.float32)
    )
    b = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))

    def g(z):
        return jnp.tanh(z @ W + b)

    z0 = jnp.zeros((8, d), jnp.float32)
    z_damped, it_damped = _damped_iteration(g, z0, 1e-6, 500, 0.7)
    z_anderson, it_anderson = _anderson_iteration(g, z0, 1e-6, 500, m=5)
    np.testing.assert_allclose(
        np.asarray(z_anderson), np.asarray(z_damped), atol=1e-4
    )
    assert int(it_anderson) < int(it_damped), (
        int(it_anderson), int(it_damped),
    )


def test_deq_anderson_grads_match_damped(world):
    # The implicit gradients are solver-independent (same z*, same IFT
    # adjoint solution).
    from fluxmpi_tpu.models import DEQ

    rng = np.random.default_rng(71)
    x = jnp.asarray(rng.normal(size=(16, 3)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(16, 1)).astype(np.float32))

    kw = dict(hidden=32, out=1, tol=1e-6, max_iter=300)
    damped = DEQ(**kw, solver="damped")
    anderson = DEQ(**kw, solver="anderson")
    params = damped.init(jax.random.PRNGKey(0), x)

    def loss(model):
        return lambda p: jnp.mean((model.apply(p, x) - y) ** 2)

    ld, gd = jax.value_and_grad(loss(damped))(params)
    la, ga = jax.value_and_grad(loss(anderson))(params)
    np.testing.assert_allclose(float(la), float(ld), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_deq_anderson_trains_under_dp(world):
    from fluxmpi_tpu.models import DEQ
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    model = DEQ(hidden=32, out=1, solver="anderson")
    rng = np.random.default_rng(72)
    xs = jnp.asarray(rng.uniform(-2, 2, size=(32, 1)).astype(np.float32))
    ys = xs**2
    params = model.init(jax.random.PRNGKey(0), xs[:2])

    def loss_fn(p, ms, batch):
        bx, by = batch
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    step = make_train_step(loss_fn, optax.adam(1e-2), donate=False)
    state = replicate(TrainState.create(params, optax.adam(1e-2)))
    batch = shard_batch((xs, ys))
    losses = []
    for _ in range(20):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_broyden_matches_damped_fixed_point(world):
    from fluxmpi_tpu.models.deq import _broyden_iteration, _damped_iteration

    rng = np.random.default_rng(73)
    d = 32
    W = jnp.asarray(
        (rng.normal(size=(d, d)) * 0.2 / np.sqrt(d)).astype(np.float32)
    )
    b = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))

    def g(z):
        return jnp.tanh(z @ W + b)

    z0 = jnp.zeros((8, d), jnp.float32)
    z_damped, it_damped = _damped_iteration(g, z0, 1e-6, 500, 0.7)
    z_broyden, it_broyden = _broyden_iteration(g, z0, 1e-6, 500, m=8)
    np.testing.assert_allclose(
        np.asarray(z_broyden), np.asarray(z_damped), atol=1e-4
    )
    assert int(it_broyden) < int(it_damped)


def test_deq_broyden_grads_match_damped(world):
    from fluxmpi_tpu.models import DEQ

    rng = np.random.default_rng(74)
    x = jnp.asarray(rng.normal(size=(16, 3)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(16, 1)).astype(np.float32))

    kw = dict(hidden=32, out=1, tol=1e-6, max_iter=300)
    damped = DEQ(**kw, solver="damped")
    broyden = DEQ(**kw, solver="broyden")
    params = damped.init(jax.random.PRNGKey(0), x)

    def loss(model):
        return lambda p: jnp.mean((model.apply(p, x) - y) ** 2)

    ld, gd = jax.value_and_grad(loss(damped))(params)
    lb, gb = jax.value_and_grad(loss(broyden))(params)
    np.testing.assert_allclose(float(lb), float(ld), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gb),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_transformer_fused_loss_matches_dense_head(world):
    # targets= path: per-token losses from the chunked fused head equal
    # softmax-CE over the dense logits (same params, f32 model dtype),
    # and gradients agree — the [tokens, vocab] tensor is never built.
    import optax

    from fluxmpi_tpu.models import TransformerLM

    lm = TransformerLM(vocab_size=64, max_len=32, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 64, size=(2, 16)).astype(np.int32))
    tgts = jnp.asarray(rng.integers(0, 64, size=(2, 16)).astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(0), toks, train=False)

    def fused(v):
        return jnp.mean(lm.apply(v, toks, train=False, targets=tgts,
                                 loss_chunk=16))

    def dense(v):
        logits = lm.apply(v, toks, train=False)
        return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts))

    lf, gf = jax.value_and_grad(fused)(variables)
    ld, gd = jax.value_and_grad(dense)(variables)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-4
        ),
        gf, gd,
    )


# ---------------------------------------------------------------------------
# Autoregressive generation (KV-cache decode)
# ---------------------------------------------------------------------------


def test_decode_logits_match_full_forward(world):
    # The cached single-position decode pass must reproduce the training
    # forward's logits position by position (same params, dense path).
    from fluxmpi_tpu.models import TransformerLM
    from fluxmpi_tpu.models.generate import _decode_twin

    lm = TransformerLM(vocab_size=32, max_len=16, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 32, size=(2, 10)).astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(0), toks, train=False)
    full_logits = lm.apply(variables, toks, train=False)  # [2, 10, 32]

    twin = _decode_twin(lm)
    cache = twin.init(jax.random.PRNGKey(0), jnp.zeros((2, 10), jnp.int32),
                      train=False)["cache"]
    for pos in range(10):
        step_logits, mut = twin.apply(
            {"params": variables["params"], "cache": cache},
            toks[:, pos:pos + 1], train=False, pos_offset=pos,
            mutable=["cache"],
        )
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, pos]),
            atol=2e-5, rtol=1e-4,
        )


def test_generate_greedy_matches_naive_loop(world):
    # One-scan prefill+generate == the naive recompute-everything loop.
    from fluxmpi_tpu.models import TransformerLM, generate

    lm = TransformerLM(vocab_size=32, max_len=24, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, 32, size=(2, 5)).astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)

    out = generate(lm, variables, prompt, max_new_tokens=8)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))

    naive = np.asarray(prompt)
    for _ in range(8):
        logits = lm.apply(variables, jnp.asarray(naive), train=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))[:, None]
        naive = np.concatenate([naive, nxt], axis=1)
    np.testing.assert_array_equal(np.asarray(out), naive)


def test_batched_prefill_bit_identical_to_scan(world):
    """The batched-prefill fast path (one causal forward populates the
    KV caches) is bit-for-bit equivalent to the one-token-per-tick scan
    prefill for greedy decoding — and, because the rng stream advances
    identically, for sampled and eos-absorbed decoding too."""
    from fluxmpi_tpu.models import TransformerLM, generate

    lm = TransformerLM(vocab_size=32, max_len=32, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    rng = np.random.default_rng(3)
    variables = lm.init(jax.random.PRNGKey(0), jnp.zeros((2, 4), jnp.int32),
                        train=False)
    for plen in (1, 2, 7):
        prompt = jnp.asarray(
            rng.integers(0, 32, size=(2, plen)).astype(np.int32)
        )
        greedy_scan = generate(lm, variables, prompt, 8, prefill="scan")
        greedy_batched = generate(lm, variables, prompt, 8)
        np.testing.assert_array_equal(
            np.asarray(greedy_scan), np.asarray(greedy_batched)
        )
        key = jax.random.PRNGKey(plen)
        s_scan = generate(lm, variables, prompt, 8, temperature=1.0,
                          top_k=5, rng=key, prefill="scan")
        s_batched = generate(lm, variables, prompt, 8, temperature=1.0,
                             top_k=5, rng=key, prefill="batched")
        np.testing.assert_array_equal(np.asarray(s_scan), np.asarray(s_batched))
        e_scan = generate(lm, variables, prompt, 8, eos_token=3,
                          prefill="scan")
        e_batched = generate(lm, variables, prompt, 8, eos_token=3)
        np.testing.assert_array_equal(np.asarray(e_scan), np.asarray(e_batched))
    with pytest.raises(ValueError, match="prefill"):
        generate(lm, variables, prompt, 4, prefill="bogus")


def test_moe_generate_auto_prefill_keeps_scan_path(world):
    """prefill="auto" must NOT silently switch MoE models to the
    batched prompt forward: capacity routing can drop over-capacity
    prompt tokens there that the one-token-per-tick scan never drops,
    changing outputs. auto == scan for MoE, bit-for-bit."""
    from fluxmpi_tpu.models import MoETransformerLM, TransformerLM, generate

    assert TransformerLM.batched_prefill_safe is True
    assert MoETransformerLM.batched_prefill_safe is False
    lm = MoETransformerLM(vocab_size=32, max_len=24, num_layers=2,
                          d_model=32, num_heads=4, d_ff=64,
                          num_experts=2, capacity_factor=1.0)
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, 32, size=(2, 6)).astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)
    auto = generate(lm, variables, prompt, 6)
    scan = generate(lm, variables, prompt, 6, prefill="scan")
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(scan))


def test_prefill_kv_matches_scan_warmed_cache(world):
    """prefill_kv/prefill_cache produce the cache state the scan would
    reach: K/V for every prompt position (float-close — the batched and
    single-query attends reduce in different orders) with cache_index
    advanced past the prompt."""
    from fluxmpi_tpu.models import TransformerLM
    from fluxmpi_tpu.models.generate import (
        _decode_twin, _sized_cache, prefill_cache, prefill_kv,
    )

    lm = TransformerLM(vocab_size=32, max_len=24, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, 32, size=(2, 6)).astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)

    k, v, logits = prefill_kv(lm, variables, prompt)
    assert k.shape == (2, 2, 6, 4, 8)  # [layers, batch, plen, heads, hd]
    assert logits.shape == (2, 6, 32)

    twin = _decode_twin(lm)
    scan_cache = _sized_cache(twin, 2, 12)
    for pos in range(6):
        _, mut = twin.apply(
            {"params": variables["params"], "cache": scan_cache},
            prompt[:, pos:pos + 1], train=False, pos_offset=pos,
            mutable=["cache"],
        )
        scan_cache = mut["cache"]
    batched_cache, last = prefill_cache(lm, variables, prompt, 12)
    flat_scan = jax.tree_util.tree_leaves(scan_cache)
    flat_batched = jax.tree_util.tree_leaves(batched_cache)
    for a, b in zip(flat_scan, flat_batched):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=2e-5, rtol=1e-4,
        )
    full = lm.apply(variables, prompt, train=False)
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(last, -1)),
        np.asarray(jnp.argmax(full[:, -1], -1)),
    )


def test_generate_sampling_and_validation(world):
    from fluxmpi_tpu.models import TransformerLM, generate

    lm = TransformerLM(vocab_size=32, max_len=16, num_layers=1, d_model=16,
                       num_heads=2, d_ff=32)
    prompt = jnp.zeros((1, 4), jnp.int32)
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)

    # Deterministic per key, key changes the sample.
    a = generate(lm, variables, prompt, 6, temperature=1.0,
                 rng=jax.random.PRNGKey(1))
    b = generate(lm, variables, prompt, 6, temperature=1.0,
                 rng=jax.random.PRNGKey(1))
    c = generate(lm, variables, prompt, 6, temperature=5.0,
                 rng=jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))

    with pytest.raises(ValueError, match="max_len"):
        generate(lm, variables, prompt, 100)
    with pytest.raises(ValueError, match="rng"):
        generate(lm, variables, prompt, 4, temperature=1.0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(lm, variables, prompt, 0)


def test_generate_works_with_flash_trained_model(world):
    # A model TRAINED with the flash attention_fn generates through the
    # dense decode twin — identical parameter tree.
    from fluxmpi_tpu.models import TransformerLM, generate
    from fluxmpi_tpu.ops import flash_attention_fn

    lm = TransformerLM(vocab_size=32, max_len=16, num_layers=1, d_model=32,
                       num_heads=4, d_ff=64,
                       attention_fn=flash_attention_fn(causal=True))
    prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)
    out = generate(lm, variables, prompt, 5)
    assert out.shape == (1, 8)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < 32))


def test_attention_switch_flash_matches_naive_oracle(world):
    """The kernel-plane switch (ISSUE 19): attention="flash" must be a
    pure kernel substitution — same params, same batch, the fused-CE
    training loss AND its gradients (through the flash custom_vjp)
    match the naive dense attend to dtype tolerance, and greedy decode
    streams bit-identical tokens."""
    from fluxmpi_tpu.models import TransformerLM, generate

    naive = TransformerLM(vocab_size=32, max_len=32, num_layers=2,
                          d_model=32, num_heads=4, d_ff=64)
    flash = naive.clone(attention="flash")
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.integers(0, 32, size=(2, 24)).astype(np.int32))
    y = jnp.asarray(rng.integers(0, 32, size=(2, 24)).astype(np.int32))
    variables = naive.init(jax.random.PRNGKey(0), x, train=False)

    def loss(model):
        def fn(p):
            return model.apply(p, x, train=True, targets=y).mean()
        return fn

    l_n, g_n = jax.value_and_grad(loss(naive))(variables)
    l_f, g_f = jax.value_and_grad(loss(flash))(variables)
    np.testing.assert_allclose(float(l_f), float(l_n), atol=1e-5)
    flat_n = jax.tree_util.tree_leaves(g_n)
    flat_f = jax.tree_util.tree_leaves(g_f)
    for a, b in zip(flat_f, flat_n):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        )

    prompt = x[:, :5]
    np.testing.assert_array_equal(
        np.asarray(generate(flash, variables, prompt, 6)),
        np.asarray(generate(naive, variables, prompt, 6)),
    )


def test_attention_switch_validation(world):
    """Switch error paths: an unknown mode raises at apply time,
    attention='flash' conflicts with an explicit attention_fn, and
    'auto' resolves to naive off-TPU (this suite runs on CPU)."""
    from fluxmpi_tpu.models import TransformerLM
    from fluxmpi_tpu.models.transformer import _resolve_attention_mode
    from fluxmpi_tpu.ops import flash_attention_fn

    assert _resolve_attention_mode("auto") == "naive"  # CPU backend
    with pytest.raises(ValueError, match="attention must be"):
        _resolve_attention_mode("fast")

    x = jnp.zeros((1, 8), jnp.int32)
    lm = TransformerLM(vocab_size=32, max_len=16, num_layers=1, d_model=32,
                       num_heads=4, d_ff=64, attention="flash",
                       attention_fn=flash_attention_fn(causal=True))
    with pytest.raises(ValueError, match="conflicts"):
        lm.init(jax.random.PRNGKey(0), x, train=False)


def test_beam_search_beam1_matches_greedy(world):
    from fluxmpi_tpu.models import TransformerLM, beam_search, generate

    lm = TransformerLM(vocab_size=32, max_len=24, num_layers=2, d_model=32,
                       num_heads=4, d_ff=64)
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, 32, size=(2, 5)).astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)

    greedy = generate(lm, variables, prompt, max_new_tokens=7)
    toks, scores = beam_search(lm, variables, prompt, max_new_tokens=7,
                               beam_size=1)
    assert toks.shape == (2, 12) and scores.shape == (2,)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(greedy))
    assert np.all(np.isfinite(np.asarray(scores)))


def test_beam_search_finds_global_optimum(world):
    # With beam_size = vocab**max_new_tokens the search is exhaustive, so
    # the result must equal the true argmax over all continuations scored
    # by teacher-forced log-likelihood on the TRAINING forward — an
    # independent oracle path (full forward, no KV cache).
    from itertools import product

    from fluxmpi_tpu.models import TransformerLM, beam_search

    vocab, plen, new = 6, 2, 3
    lm = TransformerLM(vocab_size=vocab, max_len=8, num_layers=1,
                       d_model=16, num_heads=2, d_ff=32)
    rng = np.random.default_rng(7)
    prompt = jnp.asarray(rng.integers(0, vocab, size=(2, plen))
                         .astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(2), prompt, train=False)

    best_toks, best_scores = beam_search(
        lm, variables, prompt, max_new_tokens=new, beam_size=vocab ** new)

    conts = np.array(list(product(range(vocab), repeat=new)), np.int32)
    n = len(conts)  # 216
    for row in range(2):
        seqs = np.concatenate(
            [np.tile(np.asarray(prompt[row]), (n, 1)), conts], axis=1)
        logits = lm.apply(variables, jnp.asarray(seqs), train=False)
        logp = np.asarray(jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1))
        scores = np.zeros(n)
        for t in range(plen - 1, plen + new - 1):
            scores += logp[np.arange(n), t, seqs[:, t + 1]]
        k = int(np.argmax(scores))
        np.testing.assert_allclose(float(best_scores[row]), scores[k],
                                   atol=1e-4, rtol=1e-5)
        np.testing.assert_array_equal(
            np.asarray(best_toks[row, plen:]), conts[k])


def test_beam_search_eos_absorbing_and_validation(world):
    from fluxmpi_tpu.models import TransformerLM, beam_search

    vocab = 4
    lm = TransformerLM(vocab_size=vocab, max_len=12, num_layers=1,
                       d_model=16, num_heads=2, d_ff=32)
    prompt = jnp.asarray([[1, 2], [0, 3]], jnp.int32)
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)

    for eos in range(vocab):
        toks, scores = beam_search(lm, variables, prompt, max_new_tokens=6,
                                   beam_size=3, eos_token=eos,
                                   length_penalty=0.6)
        gen = np.asarray(toks[:, 2:])
        assert np.all(np.isfinite(np.asarray(scores)))
        for row in gen:
            hits = np.flatnonzero(row == eos)
            if hits.size:  # everything after the first eos is eos
                assert np.all(row[hits[0]:] == eos)
        # Returned score == teacher-forced rescoring of the returned
        # sequence, length-penalized at the finish length (independent
        # full-forward oracle, no KV cache).
        hits = np.flatnonzero(gen[0] == eos)
        flen = int(hits[0]) + 1 if hits.size else 6
        seq = np.asarray(toks[0:1, :2 + flen])
        logp = np.asarray(jax.nn.log_softmax(
            lm.apply(variables, jnp.asarray(seq),
                     train=False).astype(jnp.float32), axis=-1))
        raw = sum(logp[0, t, seq[0, t + 1]] for t in range(1, 1 + flen))
        lp = ((5.0 + flen) / 6.0) ** 0.6
        np.testing.assert_allclose(float(scores[0]), raw / lp,
                                   atol=1e-4, rtol=1e-5)

    with pytest.raises(ValueError, match="beam_size"):
        beam_search(lm, variables, prompt, 4, beam_size=0)
    with pytest.raises(ValueError, match="max_len"):
        beam_search(lm, variables, prompt, 100, beam_size=2)
    with pytest.raises(ValueError, match="vocabulary"):
        beam_search(lm, variables, prompt, 4, beam_size=2, eos_token=vocab)


def test_transformer_hidden_escape_hatch(world):
    # hidden=True exposes (pre-head states, tied table) so custom heads
    # (e.g. the TP vocab-sharded CE) compose; consistent with logits.
    from fluxmpi_tpu.models import TransformerLM

    lm = TransformerLM(vocab_size=32, max_len=16, num_layers=1, d_model=16,
                       num_heads=2, d_ff=32)
    toks = jnp.zeros((2, 8), jnp.int32)
    variables = lm.init(jax.random.PRNGKey(0), toks, train=False)
    h, table = lm.apply(variables, toks, train=False, hidden=True)
    assert h.shape == (2, 8, 16) and table.shape == (32, 16)
    logits = lm.apply(variables, toks, train=False)
    np.testing.assert_allclose(
        np.asarray(logits),
        np.asarray(h.astype(jnp.float32) @ table.astype(jnp.float32).T),
        atol=1e-5,
    )
    with pytest.raises(ValueError, match="either targets or hidden"):
        lm.apply(variables, toks, train=False, hidden=True,
                 targets=jnp.zeros((2, 8), jnp.int32))


def test_generate_eos_and_top_k(world):
    from fluxmpi_tpu.models import TransformerLM, generate

    lm = TransformerLM(vocab_size=16, max_len=20, num_layers=1, d_model=16,
                       num_heads=2, d_ff=32)
    prompt = jnp.zeros((2, 3), jnp.int32)
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)

    # Greedy with eos = whatever the model emits first: everything after
    # the first occurrence must be eos too.
    free = np.asarray(generate(lm, variables, prompt, 8))
    eos = int(free[0, 3])
    out = np.asarray(generate(lm, variables, prompt, 8, eos_token=eos))
    for row in out:
        hits = np.where(row[3:] == eos)[0]
        if hits.size:
            assert np.all(row[3 + hits[0]:] == eos)

    # top_k=1 sampling == greedy regardless of temperature.
    topk1 = np.asarray(generate(lm, variables, prompt, 8, temperature=2.0,
                                top_k=1, rng=jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(topk1, free)

    import pytest as _pytest

    with _pytest.raises(ValueError, match="top_k"):
        generate(lm, variables, prompt, 4, temperature=1.0, top_k=0,
                 rng=jax.random.PRNGKey(0))


def test_generate_top_p(world):
    from fluxmpi_tpu.models import TransformerLM, generate

    lm = TransformerLM(vocab_size=16, max_len=20, num_layers=1, d_model=16,
                       num_heads=2, d_ff=32)
    prompt = jnp.zeros((2, 3), jnp.int32)
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)

    greedy = np.asarray(generate(lm, variables, prompt, 8))
    # A tiny nucleus keeps only the argmax token: sampling == greedy at
    # any temperature.
    tiny = np.asarray(generate(lm, variables, prompt, 8, temperature=3.0,
                               top_p=1e-6, rng=jax.random.PRNGKey(4)))
    np.testing.assert_array_equal(tiny, greedy)

    # top_p=1.0 is a no-op: bit-identical to unfiltered sampling with the
    # same key.
    full = np.asarray(generate(lm, variables, prompt, 8, temperature=1.0,
                               top_p=1.0, rng=jax.random.PRNGKey(5)))
    plain = np.asarray(generate(lm, variables, prompt, 8, temperature=1.0,
                                rng=jax.random.PRNGKey(5)))
    np.testing.assert_array_equal(full, plain)

    # Composes with top_k and stays in-vocab / finite.
    both = np.asarray(generate(lm, variables, prompt, 8, temperature=1.0,
                               top_k=8, top_p=0.9,
                               rng=jax.random.PRNGKey(6)))
    assert both.shape == (2, 11)
    assert (both >= 0).all() and (both < 16).all()

    import pytest as _pytest

    with _pytest.raises(ValueError, match="top_p"):
        generate(lm, variables, prompt, 4, temperature=1.0, top_p=0.0,
                 rng=jax.random.PRNGKey(0))
    with _pytest.raises(ValueError, match="top_p"):
        generate(lm, variables, prompt, 4, temperature=1.0, top_p=1.5,
                 rng=jax.random.PRNGKey(0))
