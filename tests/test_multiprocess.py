"""Multi-process SPMD integration test — the reference's self-spawning MPI
harness rebuilt on jax.distributed over a localhost coordinator
(reference: test/runtests.jl:11-16: ``mpiexec -n N julia <file>``; here:
N python subprocesses joining one jax.distributed world, each holding one
CPU device). The outer assertion mirrors the reference's ``@test true`` on
subprocess exit."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(
    os.environ.get("JAX_PLATFORMS", "cpu") == "cpu"
    and os.environ.get("FLUXMPI_TEST_FORCE_MULTIPROCESS", "") != "1",
    reason=(
        "CPU-backend limitation in this jax/jaxlib (0.4.37/0.4.36): without the "
        "gloo opt-in the backend rejects every cross-process computation "
        "('Multiprocess computations aren't implemented on the CPU backend'); "
        "with it (parallel/_compat.enable_cpu_cross_process_collectives, applied "
        "by runtime.init) the world comes up and runs real collectives but the "
        "gloo TCP transport aborts when XLA and multihost_utils collectives "
        "interleave on one pair (gloo/transport/tcp/pair.cc:446 'op.preamble."
        "length <= op.nbytes', SIGABRT) — an upstream transport bug, even with "
        "async dispatch serialized. Set FLUXMPI_TEST_FORCE_MULTIPROCESS=1 to "
        "run anyway (e.g. on a jax with a fixed gloo, or a TPU/GPU backend)."
    ),
)
@pytest.mark.parametrize("nprocs", [2, 3, 4, 8])
def test_process_world(nprocs, tmp_path):
    """Spawn an nprocs jax.distributed world running the full worker suite:
    identity, host collectives, synchronize, eager gradient allreduce, a
    compiled train step over the process-spanning mesh, replicated AND
    sharded checkpoint round-trips, ragged-shard loader lockstep, and
    barrier-serialized println ordering (VERDICT r1 next #5 — the
    reference runs every test file at 2-4 ranks, test/runtests.jl:11-16)."""
    coordinator = f"127.0.0.1:{_free_port()}"
    script = os.path.join(os.path.dirname(__file__), "multiprocess_worker.py")

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own (1 device per process)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    order_file = tmp_path / "print_order.txt"
    env["FLUXMPI_TEST_ORDER_FILE"] = str(order_file)
    env["FLUXMPI_TEST_CKPT_DIR"] = str(tmp_path / "ckpts")

    procs = [
        subprocess.Popen(
            [sys.executable, script, coordinator, str(nprocs), str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for i in range(nprocs)
    ]
    outputs = []
    try:
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=360)
            outputs.append(out)
            assert p.returncode == 0, f"rank {i} failed:\n{out}"
    finally:
        # A failed/hung rank must not leave its peers blocked in a collective
        # holding the coordinator port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, out in enumerate(outputs):
        assert f"WORKER_{i}_OK" in out
    # rank-tagged printing made it out of at least the lead rank
    assert any(f"[0 / {nprocs}]" in out for out in outputs)

    # println serialization: the shared append-only file must hold exactly
    # one line per rank, in strict rank order (each rank wrote at its
    # barrier-gated turn).
    lines = order_file.read_text().strip().splitlines()
    ranks = [int(ln.rsplit("rank=", 1)[1]) for ln in lines]
    assert ranks == list(range(nprocs)), ranks
