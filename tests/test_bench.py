"""Unit tests for bench.py's measurement machinery (VERDICT r2 weak #2:
the MFU path must not be cold code that first executes on the TPU run)."""

import json

import numpy as np
import pytest

import bench


def test_chip_peak_flops_lookup():
    assert bench._chip_peak_flops("TPU v5 lite") == 197e12
    assert bench._chip_peak_flops("TPU v5e") == 197e12
    assert bench._chip_peak_flops("TPU v4") == 275e12
    assert bench._chip_peak_flops("TPU v6 lite") == 918e12
    assert bench._chip_peak_flops("cpu") is None


def test_mfu_math():
    # 1e12 FLOPs/step at 98.5 steps/s on one v5e (197e12 peak) = 50%.
    assert bench._mfu(1e12, 98.5, 1, "TPU v5 lite") == 0.5
    # Per-chip normalization.
    assert bench._mfu(2e12, 98.5, 2, "TPU v5 lite") == 0.5
    # Unknown chip or missing FLOPs → None.
    assert bench._mfu(1e12, 10.0, 1, "cpu") is None
    assert bench._mfu(None, 10.0, 1, "TPU v5 lite") is None
    assert bench._mfu(0.0, 10.0, 1, "TPU v5 lite") is None


def test_mfu_discards_impossible_values():
    # MFU > 1 means a broken clock or FLOPs estimate (round 2's first TPU
    # number was 6.33): must be dropped, never reported.
    assert bench._mfu(1e12, 1000.0, 1, "TPU v5 lite") is None


def test_scaling_efficiency_math():
    assert bench._scaling_efficiency(100.0, 85.0) == 0.85
    assert bench._scaling_efficiency(0.0, 50.0) == 0.0


def test_device_fingerprint_keys_cpu_by_core_count():
    # ADVICE r2 #3: anchors from another machine must not be compared.
    import os

    assert bench._device_fingerprint("tpu", "TPU v5 lite") == "TPU v5 lite"
    assert bench._device_fingerprint("cpu", "cpu") == f"cpu{os.cpu_count()}"


def test_parse_json_line_takes_last_valid():
    out = "garbage\n{\"a\": 1}\nnoise {\nfinal\n" + json.dumps(
        {"metric": "m", "value": 2.0}
    )
    parsed = bench._parse_json_line(out)
    assert parsed == {"metric": "m", "value": 2.0}
    assert bench._parse_json_line("no json here") is None


def test_steps_per_sec_slope_cancels_fixed_overhead():
    # Synthetic step with a large fixed per-sync cost: the two-point slope
    # must recover the true per-step rate (round 2's direct-timing number
    # was 20× off through the tunnel).
    class FakeClock:
        def __init__(self):
            self.t = 0.0

    clock = FakeClock()
    step_cost, sync_cost = 0.01, 0.5

    def fake_step(state, data):
        clock.t += step_cost
        return state, None

    real_sync = bench._sync
    real_counter = bench.time.perf_counter
    real_each = bench._sync_each_step
    bench._sync = lambda x: setattr(clock, "t", clock.t + sync_cost)
    bench.time.perf_counter = lambda: clock.t
    # Model the TPU regime (one sync per measurement, async dispatch) —
    # that is where the fixed cost must cancel; the CPU regime syncs every
    # step to serialize collective launches.
    bench._sync_each_step = lambda: False
    try:
        rate, _ = bench._steps_per_sec(fake_step, None, None, warmup=1, steps=20)
    finally:
        bench._sync = real_sync
        bench.time.perf_counter = real_counter
        bench._sync_each_step = real_each
    assert rate == pytest.approx(1.0 / step_cost, rel=1e-6)


def test_anchor_table_keyed_by_fingerprint():
    key = ("resnet50_images_per_sec_per_chip", "tpu", "TPU v5 lite")
    assert key in bench._ANCHORS
    # No bare (metric, platform) keys left (every anchor carries a device
    # fingerprint).
    assert all(len(k) == 3 for k in bench._ANCHORS)


def test_run_scaling_config_selection(monkeypatch):
    # On a real multi-chip TPU the scaling mode must run the headline
    # resnet50 workload with stable mode "accelerator" + backend "tpu";
    # elsewhere the mlp plumbing proxy on the cpu-virtual mesh
    # (VERDICT r3 next #7; mode/backend split per ADVICE r4).
    calls = []

    def fake_run_child(config, timeout, platform, extra_env=None):
        calls.append((config, platform, dict(extra_env or {})))
        return {"metric": "x", "value": 100.0, "unit": "u",
                "vs_baseline": 1.0, "n_chips": 1}

    monkeypatch.setattr(bench, "_run_child", fake_run_child)

    out = bench._run_scaling(
        3000.0, {"platform": "tpu", "n_devices": 4}, None
    )
    assert out["mode"] == "accelerator"
    assert out["backend"] == "tpu"
    assert out["config"] == "resnet50"
    assert [c[0] for c in calls] == ["resnet50", "resnet50"]
    assert calls[0][2]["FLUXMPI_TPU_BENCH_DEVICES"] == "1"
    assert calls[1][2]["FLUXMPI_TPU_BENCH_DEVICES"] == "4"

    calls.clear()
    out = bench._run_scaling(3000.0, None, None)
    assert out["mode"] == "cpu-virtual"
    # The cpu-virtual legs run the REAL driver (train_loop fuse="window"
    # under a ParallelConfig), not the synthetic-step mlp child.
    assert out["config"] == "train_loop"
    assert [c[0] for c in calls] == ["train_loop", "train_loop"]

    # Env override wins.
    monkeypatch.setenv("FLUXMPI_TPU_BENCH_SCALING_CONFIG", "cnn")
    calls.clear()
    out = bench._run_scaling(
        3000.0, {"platform": "tpu", "n_devices": 8}, None
    )
    assert out["config"] == "cnn"


def test_run_scaling_single_chip_falls_back(monkeypatch):
    # One visible chip → cpu-virtual plumbing proof, never a fake "tpu"
    # scaling number.
    def fake_run_child(config, timeout, platform, extra_env=None):
        return {"metric": "x", "value": 10.0, "unit": "u",
                "vs_baseline": 1.0, "n_chips": 1}

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    out = bench._run_scaling(
        3000.0, {"platform": "tpu", "n_devices": 1}, None
    )
    assert out["mode"] == "cpu-virtual"


def test_peak_table_orders_v5p_before_v5_lite():
    # Substring lookup: "TPU v5p" must hit the v5p row, not "v5 lite"/v5e.
    assert bench._chip_peak_flops("TPU v5p") == 459e12
    assert bench._chip_peak_flops("TPU v5 lite") == 197e12


def test_parse_json_line_rejects_non_dict():
    assert bench._parse_json_line("[1, 2]\n") is None


def test_probe_ladder_outlasts_lease_ttl():
    """Round-5 invariant (BENCH_NOTES_r05.md): after an unclean client
    kill the next backend init blocks ~1500 s; one probe attempt must
    outlast that or a merely-queued chip is reported dead — and the
    default budget must still leave the headline child its slot after
    the full ladder runs."""
    assert max(bench._DEFAULT_PROBE_TIMEOUTS) >= 1560
    ladder = sum(bench._DEFAULT_PROBE_TIMEOUTS)
    headline = dict(bench._CONFIGS)["resnet50"]
    assert bench._DEFAULT_BUDGET_S >= ladder + headline + 60


def test_leg_breakdown_lifts_diagnostics():
    rec = {
        "metric": "mlp_quickstart_samples_per_sec_per_chip",
        "value": 100.0,
        "loader_fed_mlp_quickstart_samples_per_sec_per_chip": 80.0,
        "loader_fed_path": "device_gather",
        "assembly_samples_per_sec": 900.0,
        "dispatch": {"per_dispatch_us": 12.5, "n_dev": 8},
        "scan_steps": 8,
    }
    out = bench._leg_breakdown(rec)
    assert out == {
        "synthetic": 100.0,
        "loader_fed": 80.0,
        "loader_path": "device_gather",
        "assembly": 900.0,
        "dispatch_us": 12.5,
        "scan_steps": 8,
    }
    # Minimal record: only the synthetic rate.
    assert bench._leg_breakdown({"value": 5.0}) == {"synthetic": 5.0}


def test_leg_breakdown_lifts_fused_window():
    rec = {
        "value": 100.0,
        "fused_window": {
            "window": 8,
            "pipelined": {"samples_per_sec_per_chip": 4000.0,
                          "dispatches_per_update": 1.0},
            "fused": {"samples_per_sec_per_chip": 20000.0,
                      "dispatches_per_update": 0.125},
            "dispatch_reduction": 8.0,
            "speedup": 5.0,
        },
    }
    out = bench._leg_breakdown(rec)
    assert out["fused_window"] == {
        "window": 8,
        "pipelined_dispatches_per_update": 1.0,
        "fused_dispatches_per_update": 0.125,
        "dispatch_reduction": 8.0,
        "speedup": 5.0,
    }


def test_leg_breakdown_lifts_attention_ab():
    rec = {
        "value": 100.0,
        "attention_ab": {
            "train": {"speedup": 1.4, "hbm_temp_saved_bytes": 1995872.0},
            "decode": {"speedup": 1.1},
        },
    }
    out = bench._leg_breakdown(rec)
    assert out["attention_ab"] == {
        "train_speedup": 1.4,
        "decode_speedup": 1.1,
        "hbm_temp_saved_bytes": 1995872.0,
    }


def test_run_scaling_includes_breakdown(monkeypatch):
    def fake_run_child(config, timeout, platform, extra_env=None):
        n = extra_env.get("FLUXMPI_TPU_BENCH_DEVICES", "1")
        return {
            "metric": "x", "value": 100.0 / int(n), "unit": "u",
            "vs_baseline": 1.0, "n_chips": int(n),
            "dispatch": {"per_dispatch_us": 10.0 * int(n), "n_dev": int(n)},
            "assembly_samples_per_sec": 1000.0,
        }

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    out = bench._run_scaling(3000.0, None, None)
    assert set(out["breakdown"]) == {"dp1", "dpN"}
    assert out["breakdown"]["dp1"]["dispatch_us"] == 10.0
    assert out["breakdown"]["dpN"]["dispatch_us"] == 80.0
    assert out["breakdown"]["dpN"]["assembly"] == 1000.0


def test_dispatch_probe_on_test_mesh(world):
    # The null-step probe must produce a sane per-dispatch cost on the
    # 8-device CPU mesh (the number the scaling breakdown attributes
    # dispatch overhead with).
    out = bench._dispatch_probe(world)
    assert out is not None
    assert out["n_dev"] == 8
    assert out["per_dispatch_us"] > 0


def test_bench_smoke_mode_emits_schema_valid_json(tmp_path):
    """The FLUXMPI_TPU_BENCH_SMOKE=1 contract: one real child spawn on
    CPU with capped steps, stdout JSON + JSONL sink both validating
    against scripts/check_metrics_schema.py. (The scaling pair is
    exercised by the slow-marked variant below — this one must stay
    cheap enough for tier-1.)"""
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(bench.__file__))
    jsonl = tmp_path / "smoke.jsonl"
    env = {
        **os.environ,
        "FLUXMPI_TPU_BENCH_SMOKE": "1",
        "FLUXMPI_TPU_BENCH_SMOKE_SCALING": "0",
        "FLUXMPI_TPU_BENCH_STEPS": "4",
        "FLUXMPI_TPU_BENCH_MLP_BATCH": "128",
        "FLUXMPI_TPU_BENCH_JSONL": str(jsonl),
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py")],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=here,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = bench._parse_json_line(proc.stdout)
    assert result is not None and result["metric"] != "bench_failed", (
        proc.stderr[-2000:]
    )
    assert result.get("smoke") == 1
    assert "dispatch" in result
    # Fused-window leg (PR 11): the one-dispatch-per-window claim is
    # asserted in the record itself — dispatches per update reduced >=5x
    # vs the pipelined path.
    fused = result.get("fused_window")
    assert fused, "mlp child must carry the fused A/B leg"
    assert fused["fused"]["dispatches_per_update"] == pytest.approx(
        1.0 / fused["window"]
    )
    assert fused["pipelined"]["dispatches_per_update"] == 1.0
    assert fused["dispatch_reduction"] >= 5.0
    json_path = tmp_path / "smoke.json"
    json_path.write_text(json.dumps(result))
    check = subprocess.run(
        [
            sys.executable,
            os.path.join(here, "scripts", "check_metrics_schema.py"),
            str(json_path),
            str(jsonl),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert check.returncode == 0, check.stdout + check.stderr


def test_bench_serving_ab_smoke(tmp_path):
    """The serving child's tier-1 smoke (FLUXMPI_TPU_BENCH_SMOKE=1 +
    _CONFIG=serving): static-batch vs continuous-batch A/B on the
    mixed-length workload. The acceptance claims are asserted in the
    record itself — continuous batching beats static on total token
    throughput (>= 1.5x on the CPU smoke) over the SAME token count,
    and mid-flight joins cost zero steady-state retraces."""
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(bench.__file__))
    env = {
        **os.environ,
        "FLUXMPI_TPU_BENCH_SMOKE": "1",
        "FLUXMPI_TPU_BENCH_CONFIG": "serving",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=here,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = bench._parse_json_line(proc.stdout)
    assert result is not None and result["metric"] == "serving_tokens_per_sec", (
        proc.stderr[-2000:]
    )
    assert result.get("smoke") == 1
    ab = result["serving"]
    assert ab["static"]["tokens"] == ab["continuous"]["tokens"] > 0
    assert ab["speedup"] >= 1.5, ab
    assert ab["continuous"]["decode_steps"] < ab["static"]["decode_steps"]
    assert ab["steady_retraces"] == 0
    json_path = tmp_path / "serving.json"
    json_path.write_text(json.dumps(result))
    check = subprocess.run(
        [
            sys.executable,
            os.path.join(here, "scripts", "check_metrics_schema.py"),
            str(json_path),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert check.returncode == 0, check.stdout + check.stderr


def test_bench_attention_ab_smoke(tmp_path):
    """The kernel-plane A/B's tier-1 smoke (FLUXMPI_TPU_BENCH_SMOKE=1 +
    _CONFIG=attention_ab): flash vs naive through the model switch on
    both hot paths. The acceptance claims asserted from the record:
    zero steady-state retraces on every leg (training AND paged decode
    with mid-flight joins), the same decoded token count in both modes
    (the kernel swap changes no scheduling), and a strictly smaller
    compiled temp footprint for flash — the dense attend materializes
    [s, s] scores, flash streams tiles. Throughput speedups are NOT
    asserted here: on CPU the flash legs run in pallas interpret mode
    (emulation, not a fast path)."""
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(bench.__file__))
    env = {
        **os.environ,
        "FLUXMPI_TPU_BENCH_SMOKE": "1",
        "FLUXMPI_TPU_BENCH_CONFIG": "attention_ab",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=here,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = bench._parse_json_line(proc.stdout)
    assert result is not None and result["metric"] == "attention_ab_tokens_per_sec", (
        proc.stderr[-2000:]
    )
    assert result.get("smoke") == 1
    ab = result["attention_ab"]
    for path in ("train", "decode"):
        for mode in ("naive", "flash"):
            assert ab[path][mode]["steady_retraces"] == 0, (path, mode, ab)
    assert ab["decode"]["naive"]["tokens"] == ab["decode"]["flash"]["tokens"] > 0
    naive_hbm = ab["train"]["naive"]["compiled_hbm"]
    flash_hbm = ab["train"]["flash"]["compiled_hbm"]
    assert flash_hbm["temp_bytes"] < naive_hbm["temp_bytes"], ab
    assert ab["train"]["hbm_temp_saved_bytes"] > 0
    json_path = tmp_path / "attention_ab.json"
    json_path.write_text(json.dumps(result))
    check = subprocess.run(
        [
            sys.executable,
            os.path.join(here, "scripts", "check_metrics_schema.py"),
            str(json_path),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert check.returncode == 0, check.stdout + check.stderr


def test_parse_parallel_env(monkeypatch):
    monkeypatch.delenv("FLUXMPI_TPU_BENCH_PARALLEL", raising=False)
    assert bench._parse_parallel_env() == {"dp": -1}
    monkeypatch.setenv("FLUXMPI_TPU_BENCH_PARALLEL", "dp=4,fsdp=2")
    assert bench._parse_parallel_env() == {"dp": 4, "fsdp": 2}
    # Env typos degrade to the default (warn-and-default convention).
    for bad in ("dp=four", "dp=4,", "dp4"):
        monkeypatch.setenv("FLUXMPI_TPU_BENCH_PARALLEL", bad)
        assert bench._parse_parallel_env() == {"dp": -1}


def test_run_axis_bench_composes_legs(monkeypatch):
    calls = []

    def fake_run_child(config, timeout, platform, extra_env=None):
        calls.append((config, platform, dict(extra_env or {})))
        return {
            "metric": "train_loop_tokens_per_sec_per_chip", "value": 50.0,
            "unit": "tokens/sec/chip", "vs_baseline": 1.0, "n_chips": 8,
            "parallel": {"axes": {"dp": 4}, "data_parallel_size": 4,
                         "dispatches_per_update": 0.125,
                         "sharded_param_leaves": 3, "rule_hits": {}},
        }

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    out = bench._run_axis_bench(3000.0)
    assert set(out) == {"dp", "dp_fsdp", "dp_tp"}
    specs = [c[2]["FLUXMPI_TPU_BENCH_PARALLEL"] for c in calls]
    assert specs == ["dp=8", "dp=4,fsdp=2", "dp=4,tp=2"]
    assert all(c[0] == "train_loop" for c in calls)
    assert all(
        "--xla_force_host_platform_device_count=8" in c[2]["XLA_FLAGS"]
        for c in calls
    )
    assert out["dp"]["dispatches_per_update"] == 0.125
    # No budget → no legs, not a crash.
    assert bench._run_axis_bench(30.0) is None


def test_bench_train_loop_dp_fsdp_leg_smoke(tmp_path):
    """The smoke dp×fsdp composition leg (tier-1): the train_loop child
    forced through smoke mode under FLUXMPI_TPU_BENCH_PARALLEL=dp=4,fsdp=2
    — the scaling legs' real-driver contract, asserted in the record:
    fused windows engaged (dispatches_per_update == 1/window) under the
    plan-derived sharding (sharded parameter leaves > 0), schema-valid."""
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(bench.__file__))
    env = {
        **os.environ,
        "FLUXMPI_TPU_BENCH_SMOKE": "1",
        "FLUXMPI_TPU_BENCH_CONFIG": "train_loop",
        "FLUXMPI_TPU_BENCH_PARALLEL": "dp=4,fsdp=2",
        "FLUXMPI_TPU_BENCH_STEPS": "16",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=here,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = bench._parse_json_line(proc.stdout)
    assert result is not None, proc.stderr[-2000:]
    assert result["metric"] == "train_loop_tokens_per_sec_per_chip", result
    assert result.get("smoke") == 1
    par = result["parallel"]
    assert par["axes"] == {"dp": 4, "fsdp": 2}
    assert par["data_parallel_size"] == 8
    assert par["sharded_param_leaves"] > 0
    assert par["dispatches_per_update"] == pytest.approx(
        1.0 / par["fused_window"]
    )
    json_path = tmp_path / "train_loop.json"
    json_path.write_text(json.dumps(result))
    check = subprocess.run(
        [
            sys.executable,
            os.path.join(here, "scripts", "check_metrics_schema.py"),
            str(json_path),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert check.returncode == 0, check.stdout + check.stderr


@pytest.mark.slow
def test_bench_smoke_mode_full_with_scaling(tmp_path):
    """Full smoke including the dp1/dpN scaling pair + breakdown."""
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(bench.__file__))
    env = {
        **os.environ,
        "FLUXMPI_TPU_BENCH_SMOKE": "1",
        "FLUXMPI_TPU_BENCH_STEPS": "4",
        "FLUXMPI_TPU_BENCH_MLP_BATCH": "128",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py")],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=here,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = bench._parse_json_line(proc.stdout)
    assert result is not None
    scaling = result.get("scaling")
    assert scaling and "breakdown" in scaling
    assert scaling["breakdown"]["dpN"]["synthetic"] == scaling[
        "per_chip_at_dpN"
    ]
    # The scaling legs ride the real fused driver now: the train_loop
    # child's dispatch accounting is in the breakdown.
    assert scaling["config"] == "train_loop"
    assert scaling["breakdown"]["dpN"].get("dispatches_per_update") is not None
    # And the smoke dp×fsdp composition leg banked alongside.
    axes = result.get("parallel_axes")
    assert axes and "dp_fsdp" in axes
    assert axes["dp_fsdp"]["sharded_param_leaves"] > 0
