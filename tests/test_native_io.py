"""Native C++ gather/prefetch runtime tests (with numpy-fallback parity)."""

import numpy as np
import pytest


def test_native_builds():
    from fluxmpi_tpu.io import native_available

    assert native_available()  # g++ is in the image


def test_gather_matches_numpy():
    from fluxmpi_tpu.io import gather_rows

    rng = np.random.default_rng(0)
    arr = rng.normal(size=(1000, 17)).astype(np.float32)
    idx = rng.integers(0, 1000, size=256)
    np.testing.assert_array_equal(gather_rows(arr, idx), arr[idx])


def test_gather_multidim_rows():
    from fluxmpi_tpu.io import gather_rows

    rng = np.random.default_rng(1)
    arr = rng.normal(size=(100, 8, 8, 3)).astype(np.float32)
    idx = np.array([5, 1, 99, 0])
    np.testing.assert_array_equal(gather_rows(arr, idx), arr[idx])


def test_prefetcher_yields_all_batches_in_order():
    from fluxmpi_tpu.io import NativePrefetcher

    arr = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)
    order = np.arange(64)[::-1].copy()
    pf = NativePrefetcher(arr, order, batch_rows=8)
    assert len(pf) == 8
    batches = list(pf)
    assert len(batches) == 8
    expected = arr[order]
    got = np.concatenate(batches)
    np.testing.assert_array_equal(got, expected)


def test_prefetcher_drop_last():
    from fluxmpi_tpu.io import NativePrefetcher

    arr = np.ones((10, 2), np.float32)
    pf = NativePrefetcher(arr, np.arange(10), batch_rows=4)
    assert len(list(pf)) == 2  # 10 // 4


def test_prefetcher_large_stress():
    from fluxmpi_tpu.io import NativePrefetcher

    rng = np.random.default_rng(2)
    arr = rng.normal(size=(4096, 32)).astype(np.float32)
    order = rng.permutation(4096)
    pf = NativePrefetcher(arr, order, batch_rows=128, queue_capacity=4)
    total = 0.0
    count = 0
    for b in pf:
        total += float(b.sum())
        count += 1
    assert count == 32
    np.testing.assert_allclose(total, float(arr[order].sum()), rtol=1e-4)


def test_gather_multidim_indices_numpy_parity():
    from fluxmpi_tpu.io import gather_rows

    rng = np.random.default_rng(3)
    arr = rng.normal(size=(50, 6)).astype(np.float32)
    idx = rng.integers(0, 50, size=(4, 2))
    np.testing.assert_array_equal(gather_rows(arr, idx), arr[idx])


def test_fast_path_ragged_tail(world):
    # drop_last=False with an ArrayDataset must yield the ragged final
    # batch, matching the generic path and len(loader).
    import fluxmpi_tpu as fm

    # 24 rows, global batch 16 on the 8-device mesh → one full batch of 16
    # plus a ragged tail of 8 (divisible by the axis, so valid).
    xs = np.arange(24 * 4, dtype=np.float32).reshape(24, 4)
    ads = fm.ArrayDataset((xs,))
    loader = fm.DistributedDataLoader(ads, 16, drop_last=False)
    batches = list(loader)
    assert len(batches) == len(loader) == 2
    assert batches[0][0].shape[0] == 16
    assert batches[1][0].shape[0] == 8
    total = sum(float(np.asarray(b[0]).sum()) for b in batches)
    np.testing.assert_allclose(total, xs.sum())
