"""MoE layer tests: routing algebra oracles + expert-parallel training.

No reference analogue (SURVEY.md §2: expert parallelism absent there); the
oracles follow the repo's test style — exact algebraic checks on tiny
fixtures (single-expert equivalence, capacity overflow, aux-loss value) plus
a compiled expert-parallel train step on the simulated mesh."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _mesh(shape):
    devs = np.asarray(jax.devices()).reshape(tuple(shape.values()))
    return Mesh(devs, tuple(shape.keys()))


def test_single_expert_matches_dense(world):
    """With one expert and capacity >= tokens, MoE == a plain gelu MLP with
    the expert's weights (gate prob is softmax over one logit == 1)."""
    import flax.linen as nn

    from fluxmpi_tpu.models import MoEMLP

    model = MoEMLP(num_experts=1, d_ff=16, capacity_factor=2.0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 5, 8)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    y = model.apply(params, x)

    w1 = params["params"]["w1"][0]
    b1 = params["params"]["b1"][0]
    w2 = params["params"]["w2"][0]
    b2 = params["params"]["b2"][0]
    flat = x.reshape(-1, 8)
    ref = nn.gelu(flat @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(ref.reshape(3, 5, 8)), rtol=1e-4, atol=1e-5
    )


def test_capacity_overflow_drops_tokens(world):
    """Identical tokens all route to one expert; tokens beyond its capacity
    get zero output (the residual path carries them in a full block)."""
    from fluxmpi_tpu.models import MoEMLP

    n_tokens, d = 8, 4
    model = MoEMLP(num_experts=2, d_ff=8, capacity_factor=0.5)  # capacity 2
    x = jnp.ones((1, n_tokens, d), jnp.float32)
    params = model.init(jax.random.PRNGKey(1), x)
    y = np.asarray(model.apply(params, x))[0]

    norms = np.linalg.norm(y, axis=-1)
    assert np.all(norms[:2] > 0), "tokens within capacity must be processed"
    np.testing.assert_allclose(norms[2:], 0.0, atol=1e-7)


def test_aux_loss_sowed(world):
    from fluxmpi_tpu.models import MoEMLP

    model = MoEMLP(num_experts=4, d_ff=8)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8, 8)), jnp.float32)
    params = {"params": model.init(jax.random.PRNGKey(2), x)["params"]}
    _, mutated = model.apply(params, x, mutable=["losses"])
    (aux,) = mutated["losses"]["moe_aux_loss"]
    # Switch aux loss is E * sum_e f_e P_e >= 1 with equality at perfect
    # balance; must always be a finite positive scalar. (The z-loss rides
    # the same collection under its own key.)
    assert aux.shape == ()
    assert float(aux) >= 0.99


def test_expert_parallel_train_step(world):
    """dp×ep mesh: expert weights sharded over ep, one compiled step."""
    from fluxmpi_tpu.models import MoETransformerLM, expert_parallel_rules
    from fluxmpi_tpu.parallel import (
        TrainState,
        combine_rules,
        fsdp_rule,
        make_train_step,
        shard_tree,
    )
    from fluxmpi_tpu.parallel.train import shard_batch

    mesh = _mesh({"dp": 2, "ep": 4})
    model = MoETransformerLM(
        vocab_size=64,
        max_len=32,
        num_layers=2,
        d_model=32,
        num_heads=4,
        d_ff=64,
        num_experts=4,
    )
    tokens = jnp.ones((4, 16), jnp.int32)
    params = {
        "params": model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]
    }
    optimizer = optax.adam(1e-2)

    rule = combine_rules(expert_parallel_rules(), fsdp_rule(mesh, min_size=512))
    state, shardings = shard_tree(TrainState.create(params, optimizer), mesh, rule)
    w1 = state.params["params"]["encoder"]["block_0"]["moe"]["w1"]
    assert tuple(w1.sharding.spec)[0] == "ep"

    def loss_fn(p, mstate, batch):
        bx, by = batch
        logits, mutated = model.apply(p, bx, train=True, mutable=["losses"])
        task = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, by)
        )
        from fluxmpi_tpu.models import collect_moe_losses

        aux, zl = collect_moe_losses(mutated["losses"])
        return task + 0.01 * aux + 1e-3 * zl, mstate

    step = make_train_step(
        loss_fn,
        optimizer,
        mesh=mesh,
        state_sharding=shardings,
        batch_spec=P("dp"),
        donate=False,
    )
    rng = np.random.default_rng(5)
    batch = shard_batch(
        (
            rng.integers(0, 64, size=(8, 16)).astype(np.int32),
            rng.integers(0, 64, size=(8, 16)).astype(np.int32),
        ),
        mesh,
        spec=P("dp"),
    )
    losses = []
    for _ in range(3):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss should drop: {losses}"
    # Layout preserved across steps.
    w1 = state.params["params"]["encoder"]["block_0"]["moe"]["w1"]
    assert tuple(w1.sharding.spec)[0] == "ep"


def test_grouped_routing_is_group_local(world):
    """Routing/capacity are per group (default: one group per batch row) —
    overflow in one row cannot displace another row's tokens, and the
    cumsum carries no cross-row dependency (ADVICE r1)."""
    from fluxmpi_tpu.models import MoEMLP

    n_rows, n_tokens, d = 3, 8, 4
    model = MoEMLP(num_experts=2, d_ff=8, capacity_factor=0.5)  # cap 2/row
    x = jnp.ones((n_rows, n_tokens, d), jnp.float32)
    params = model.init(jax.random.PRNGKey(1), x)
    y = np.asarray(model.apply(params, x))

    norms = np.linalg.norm(y, axis=-1)  # [rows, tokens]
    for r in range(n_rows):
        assert np.all(norms[r, :2] > 0), f"row {r} within-capacity dropped"
        np.testing.assert_allclose(norms[r, 2:], 0.0, atol=1e-7)


def test_grouped_routing_explicit_groups(world):
    from fluxmpi_tpu.models import MoEMLP

    x = jnp.ones((1, 12, 4), jnp.float32)
    model = MoEMLP(num_experts=2, d_ff=8, n_groups=3, capacity_factor=1.0)
    params = model.init(jax.random.PRNGKey(2), x)
    y = model.apply(params, x)
    assert y.shape == x.shape

    bad = MoEMLP(num_experts=2, d_ff=8, n_groups=5)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="must divide token count"):
        bad.init(jax.random.PRNGKey(2), x)


def test_ep_moe_lowers_to_all_to_all(world):
    """VERDICT r2 next #4: the ep-sharded MoE step must MOVE TOKENS
    (all-to-all over ep) rather than all-gather full expert weights onto
    every device. The MoE layer's sharding pins (MoEMLP.mesh) force the
    lowering; this guard keeps it pinned."""
    import re

    from fluxmpi_tpu.models import MoETransformerLM, expert_parallel_rules
    from fluxmpi_tpu.parallel import (
        TrainState,
        combine_rules,
        fsdp_rule,
        make_train_step,
        shard_tree,
    )
    from fluxmpi_tpu.parallel.train import shard_batch

    mesh = _mesh({"dp": 2, "ep": 4})
    num_experts, d_model, d_ff = 4, 32, 64
    model = MoETransformerLM(
        vocab_size=64, max_len=32, num_layers=1, d_model=d_model,
        num_heads=4, d_ff=d_ff, num_experts=num_experts, mesh=mesh,
    )
    tokens = jnp.ones((8, 16), jnp.int32)
    params = {
        "params": model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]
    }
    optimizer = optax.adam(1e-2)
    rule = combine_rules(expert_parallel_rules(), fsdp_rule(mesh, min_size=512))
    state, shardings = shard_tree(TrainState.create(params, optimizer), mesh, rule)

    def loss_fn(p, mstate, batch):
        bx, by = batch
        logits, mutated = model.apply(p, bx, train=True, mutable=["losses"])
        task = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, by)
        )
        from fluxmpi_tpu.models import collect_moe_losses

        aux, zl = collect_moe_losses(mutated["losses"])
        return task + 0.01 * aux + 1e-3 * zl, mstate

    step = make_train_step(
        loss_fn, optimizer, mesh=mesh, state_sharding=shardings,
        batch_spec=P(("dp", "ep")), donate=False,
    )
    rng = np.random.default_rng(5)
    batch = shard_batch(
        (rng.integers(0, 64, size=(8, 16)).astype(np.int32),
         rng.integers(0, 64, size=(8, 16)).astype(np.int32)),
        mesh, spec=P(("dp", "ep")),
    )
    hlo = step.lower(state, batch).compile().as_text()

    assert hlo.count("all-to-all") > 0, "EP einsums no longer lower to all-to-all"
    # No all-gather may materialize a full expert weight stack
    # [E, d_model, d_ff] / [E, d_ff, d_model] on any device.
    full_shapes = (
        f"[{num_experts},{d_model},{d_ff}]",
        f"[{num_experts},{d_ff},{d_model}]",
    )
    gathers = re.findall(r"= \S+ all-gather\([^\n]*", hlo)
    offenders = [g for g in gathers if any(s in g for s in full_shapes)]
    assert not offenders, f"full expert-weight all-gather: {offenders[:2]}"

    # And the step still trains.
    losses = []
    for _ in range(3):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_top2_routing_matches_oracle(world):
    # GShard top-2: with ample capacity every token's output is the
    # renormalized-gate-weighted sum of its two best experts' FFN outputs.
    from fluxmpi_tpu.models import MoEMLP

    d_model, d_ff, E = 8, 16, 4
    layer = MoEMLP(num_experts=E, d_ff=d_ff, capacity_factor=8.0, top_k=2)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 6, d_model)).astype(np.float32))
    params = layer.init(jax.random.PRNGKey(0), x, train=False)
    out, _ = layer.apply(params, x, train=False, mutable=["losses"])

    p = params["params"]
    logits = np.asarray(x) @ np.asarray(p["router"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    w1, b1 = np.asarray(p["w1"]), np.asarray(p["b1"])
    w2, b2 = np.asarray(p["w2"]), np.asarray(p["b2"])

    def expert_ffn(e, t):
        import jax.nn as jnn

        h = np.asarray(jnn.gelu(jnp.asarray(t @ w1[e] + b1[e])))
        return h @ w2[e] + b2[e]

    expected = np.zeros_like(np.asarray(out))
    for b in range(x.shape[0]):
        for s in range(x.shape[1]):
            pr = probs[b, s]
            top2 = np.argsort(-pr)[:2]
            g = pr[top2] / pr[top2].sum()
            for gi, e in zip(g, top2):
                expected[b, s] += gi * expert_ffn(e, np.asarray(x[b, s]))
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5)


def test_top2_first_choice_has_capacity_priority(world):
    # Capacity 1 per expert; t0 first-chooses e0 (second e1), t1 the
    # mirror. Correct priority: both first choices keep their slots, both
    # second choices find the OTHER expert already full (the prior-choice
    # count offset) and drop — so each token's output carries ONLY its
    # first expert's signature. Dropping the offset (or inverting the
    # choice order) would keep a second choice and mix both signatures.
    from fluxmpi_tpu.models import MoEMLP

    d_model, E = 2, 2
    layer = MoEMLP(num_experts=E, d_ff=4, capacity_factor=0.5, top_k=2)
    # logits: t0=[1,0] → [2,1] (e0 first); t1=[0,1] → [1,2] (e1 first)
    x = jnp.asarray([[[1.0, 0.0], [0.0, 1.0]]])
    params = layer.init(jax.random.PRNGKey(0), x, train=False)
    p = jax.tree_util.tree_map(lambda a: a, params)  # shallow copy ok
    pp = dict(p["params"])
    pp["router"] = jnp.asarray([[2.0, 1.0], [1.0, 2.0]])
    # Experts output exactly b2[e] (w2 = 0): a per-expert signature.
    pp["w2"] = jnp.zeros_like(pp["w2"])
    pp["b2"] = jnp.asarray([[10.0, 0.0], [0.0, 10.0]])
    params = {"params": pp}

    out, _ = layer.apply(params, x, train=False, mutable=["losses"])
    out = np.asarray(out)

    probs = np.exp([2.0, 1.0])
    g0 = probs[0] / probs.sum()  # renormalized top-2 first gate ≈ 0.731
    np.testing.assert_allclose(out[0, 0], [10.0 * g0, 0.0], atol=1e-5)
    np.testing.assert_allclose(out[0, 1], [0.0, 10.0 * g0], atol=1e-5)


def test_topk_out_of_range_raises(world):
    from fluxmpi_tpu.models import MoEMLP

    layer = MoEMLP(num_experts=4, d_ff=8, top_k=8)
    x = jnp.ones((1, 4, 8))
    with pytest.raises(ValueError, match="top_k"):
        layer.init(jax.random.PRNGKey(0), x, train=False)


def test_top1_unchanged_by_topk_code(world):
    # The Switch path (top_k=1, the default) must be bit-identical to the
    # pre-top-k formulation: single choice, unnormalized gate.
    from fluxmpi_tpu.models import MoEMLP

    d_model = 8
    # Ample capacity: the oracle below has no drop modeling.
    layer = MoEMLP(num_experts=4, d_ff=16, capacity_factor=8.0)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(2, 6, d_model)).astype(np.float32))
    params = layer.init(jax.random.PRNGKey(0), x, train=False)
    out, state = layer.apply(params, x, train=False, mutable=["losses"])

    p = params["params"]
    logits = np.asarray(x) @ np.asarray(p["router"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    top1 = probs.argmax(-1)
    gate = np.take_along_axis(probs, top1[..., None], -1)[..., 0]
    w1, b1 = np.asarray(p["w1"]), np.asarray(p["b1"])
    w2, b2 = np.asarray(p["w2"]), np.asarray(p["b2"])

    import jax.nn as jnn

    expected = np.zeros_like(np.asarray(out))
    for b in range(2):
        for s in range(6):
            e = top1[b, s]
            h = np.asarray(jnn.gelu(jnp.asarray(np.asarray(x[b, s]) @ w1[e] + b1[e])))
            expected[b, s] = gate[b, s] * (h @ w2[e] + b2[e])
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5)


# ---- expert-choice routing (Zhou et al. 2022) ----


def test_expert_choice_matches_oracle(world):
    # Exact numpy oracle: each expert takes its top-C tokens by router
    # prob; output = sum over picking experts of prob * expert_ffn(token).
    import flax.linen as nn

    from fluxmpi_tpu.models import MoEMLP

    G, S, D, E = 2, 8, 4, 2
    model = MoEMLP(num_experts=E, d_ff=8, capacity_factor=1.0,
                   routing="experts")
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(G, S, D)).astype(np.float32)
    )
    params = model.init(jax.random.PRNGKey(1), x)
    y = np.asarray(model.apply(params, x))

    p = params["params"]
    logits = np.asarray(x.reshape(G, S, D)) @ np.asarray(p["router"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    capacity = S // E  # capacity_factor 1.0
    expected = np.zeros((G, S, D), np.float32)
    for g in range(G):
        for e in range(E):
            top = np.argsort(-probs[g, :, e], kind="stable")[:capacity]
            for s_i in top:
                tok = np.asarray(x)[g, s_i]
                h = np.asarray(
                    nn.gelu(jnp.asarray(tok @ np.asarray(p["w1"][e])
                                        + np.asarray(p["b1"][e])))
                )
                out = h @ np.asarray(p["w2"][e]) + np.asarray(p["b2"][e])
                expected[g, s_i] += probs[g, s_i, e] * out
    np.testing.assert_allclose(y, expected, rtol=1e-4, atol=1e-5)


def test_expert_choice_perfect_balance(world):
    # Structural property: every expert serves EXACTLY its capacity of
    # (token, expert) pairs — even with skewed router inputs that would
    # overflow a token-choice router and drop most tokens.
    from fluxmpi_tpu.models import MoEMLP

    G, S, D, E = 1, 16, 4, 4
    capacity = S // E
    model = MoEMLP(num_experts=E, d_ff=8, capacity_factor=1.0,
                   routing="experts")
    # Near-identical tokens (tiny noise to break ties deterministically):
    # token-choice would pile onto one expert and drop beyond capacity.
    rng = np.random.default_rng(5)
    x = jnp.asarray(
        (np.ones((G, S, D)) + 1e-3 * rng.normal(size=(G, S, D)))
        .astype(np.float32)
    )
    params = model.init(jax.random.PRNGKey(0), x)
    y = np.asarray(model.apply(params, x))

    # Recompute the dispatch from the router: each expert's top-C set has
    # exactly C members, and the layer output matches the per-pair sum —
    # i.e. total service is exactly E*C pairs, no drops, no overflow.
    probs = np.asarray(jax.nn.softmax(
        jnp.asarray(np.asarray(x)[0] @ np.asarray(params["params"]["router"])),
        axis=-1,
    ))
    pair_count = 0
    served_rows = set()
    for e in range(E):
        top = np.argsort(-probs[:, e], kind="stable")[:capacity]
        assert len(top) == capacity
        pair_count += len(top)
        served_rows.update(int(t) for t in top)
    assert pair_count == E * capacity
    # Rows no expert picked must output exactly zero (residual carries).
    unserved = [s_ for s_ in range(S) if s_ not in served_rows]
    for s_ in unserved:
        np.testing.assert_allclose(y[0, s_], 0.0, atol=1e-6)
    # Gradient flows through router and experts.
    g = jax.grad(
        lambda p: jnp.sum(model.apply(p, x) ** 2)
    )(params)
    assert all(
        np.all(np.isfinite(np.asarray(leaf)))
        for leaf in jax.tree_util.tree_leaves(g)
    )


def test_expert_choice_ep_train_step(world):
    # Expert-parallel training with expert-choice routing: dp x ep mesh,
    # expert dim sharded, compiled step, loss drops. (Local mesh — no
    # session-global runtime mutation.)
    from fluxmpi_tpu.models import MoETransformerLM, expert_parallel_rules
    from fluxmpi_tpu.parallel import (
        TrainState, combine_rules, fsdp_rule, make_train_step, shard_tree,
    )
    from fluxmpi_tpu.parallel.train import shard_batch

    mesh = _mesh({"dp": 4, "ep": 2})
    with pytest.warns(UserWarning, match="not causal"):
        model = MoETransformerLM(
            vocab_size=32, max_len=16, num_layers=1, d_model=16,
            num_heads=2, d_ff=32, num_experts=2, mesh=mesh,
            routing="experts",
        )
        rng = np.random.default_rng(3)
        toks = rng.integers(0, 32, size=(8, 16)).astype(np.int32)
        params = {"params": model.init(
            jax.random.PRNGKey(0), jnp.asarray(toks[:2]), train=False
        )["params"]}
    optimizer = optax.adam(1e-2)
    rule = combine_rules(
        expert_parallel_rules(), fsdp_rule(mesh, min_size=10**9)
    )
    state, shardings = shard_tree(
        TrainState.create(params, optimizer), mesh, rule
    )

    def loss_fn(p, ms, b):
        logits = model.apply(p, b, train=True)
        targets = jnp.roll(b, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], targets[:, :-1]
        ).mean(), ms

    step = make_train_step(
        loss_fn, optimizer, mesh=mesh, state_sharding=shardings,
        batch_spec=P(("dp", "ep")),
    )
    batch = shard_batch(
        jnp.asarray(toks), mesh, spec=P(("dp", "ep")),
    )
    losses = []
    for _ in range(8):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_expert_choice_checkpoint_compatible_with_token_choice(world):
    # Same parameter tree: weights trained under one routing family load
    # under the other (the FFN/router params are shared by construction).
    from fluxmpi_tpu.models import MoEMLP

    x = jnp.ones((2, 8, 4), jnp.float32)
    tok = MoEMLP(num_experts=2, d_ff=8)
    ec = MoEMLP(num_experts=2, d_ff=8, routing="experts")
    p_tok = tok.init(jax.random.PRNGKey(0), x)
    out = ec.apply(p_tok, x)  # loads cleanly
    assert np.all(np.isfinite(np.asarray(out)))

    with pytest.raises(ValueError, match="top_k"):
        MoEMLP(num_experts=2, routing="experts", top_k=2).init(
            jax.random.PRNGKey(0), x
        )
    with pytest.raises(ValueError, match="routing"):
        MoEMLP(num_experts=2, routing="bogus").init(jax.random.PRNGKey(0), x)


def test_router_z_loss_sowed(world):
    # ST-MoE router z-loss rides the "losses" collection in both routing
    # families: mean squared logsumexp of router logits, down-weighted by
    # the caller's own coefficient.
    from fluxmpi_tpu.models import MoEMLP

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 8, 4)).astype(np.float32)
    )
    for routing in ("tokens", "experts"):
        model = MoEMLP(num_experts=2, d_ff=8, routing=routing)
        # Strip init-time sown values: passing them back makes sow APPEND,
        # and index [0] would read the init-time constant (zero grad).
        params = {
            "params": model.init(jax.random.PRNGKey(0), x)["params"]
        }
        _, mutated = model.apply(params, x, mutable=["losses"])
        z = mutated["losses"]["moe_router_z_loss"][0]
        # Strictly positive (a structurally-zero z-loss was a caught bug)
        # and it must reach the router weights with nonzero gradient.
        assert np.isfinite(float(z)) and float(z) > 1e-6, (routing, float(z))

        def zloss_of(p):
            _, mut = model.apply(p, x, mutable=["losses"])
            return mut["losses"]["moe_router_z_loss"][0]

        g = jax.grad(zloss_of)(params)
        assert float(jnp.abs(g["params"]["router"]).max()) > 0.0, routing
    # Token-choice value matches the formula from the raw logits.
    model = MoEMLP(num_experts=2, d_ff=8)
    params = {"params": model.init(jax.random.PRNGKey(0), x)["params"]}
    _, mutated = model.apply(params, x, mutable=["losses"])
    logits = np.asarray(x.reshape(2, 8, 4)) @ np.asarray(
        params["params"]["router"]
    )
    expected = float(np.mean(
        np.asarray(jax.scipy.special.logsumexp(jnp.asarray(logits), axis=-1))
        ** 2
    ))
    np.testing.assert_allclose(
        float(mutated["losses"]["moe_router_z_loss"][0]), expected, rtol=1e-5
    )


def test_moe_lm_fused_loss_path(world):
    # The fused targets= head is inherited by the MoE LM (it only
    # overrides make_encoder); losses still sow through mutable state.
    from fluxmpi_tpu.models import MoETransformerLM, collect_moe_losses

    model = MoETransformerLM(
        vocab_size=64, max_len=32, num_layers=1, d_model=32, num_heads=4,
        d_ff=64, num_experts=2,
    )
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 64, size=(2, 16)).astype(np.int32))
    tgts = jnp.asarray(rng.integers(0, 64, size=(2, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), toks, train=False)
    losses, mutated = model.apply(
        params, toks, train=True, targets=tgts, mutable=["losses"]
    )
    assert losses.shape == (2, 16)
    aux, zl = collect_moe_losses(mutated["losses"])
    assert np.isfinite(float(jnp.mean(losses) + aux + zl))


def test_moe_lm_generates(world):
    # decode= forwards through the MoE hook overrides: the KV caches
    # exist and greedy decoding matches the naive full-recompute loop.
    # Ample capacity: with the default capacity_factor the batched
    # forward can DROP over-capacity tokens that single-token decode
    # never drops — a real semantic property of capacity-based MoE, not
    # a cache bug — so the exact-match check needs drop-free routing.
    from fluxmpi_tpu.models import MoETransformerLM, generate

    lm = MoETransformerLM(
        vocab_size=32, max_len=16, num_layers=1, d_model=32, num_heads=4,
        d_ff=64, num_experts=2, capacity_factor=8.0,
    )
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, 32, size=(2, 4)).astype(np.int32))
    variables = lm.init(jax.random.PRNGKey(0), prompt, train=False)
    out = generate(lm, variables, prompt, 5)
    assert out.shape == (2, 9)

    naive = np.asarray(prompt)
    for _ in range(5):
        logits = lm.apply(variables, jnp.asarray(naive), train=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))[:, None]
        naive = np.concatenate([naive, nxt], axis=1)
    np.testing.assert_array_equal(np.asarray(out), naive)
