"""HF GPT-2 checkpoint import: logit-level parity with the torch forward.

The torch model is the ORACLE — an entirely independent implementation
of the same architecture (HF transformers, CPU). A randomly initialized
``GPT2LMHeadModel`` exercises every weight in the mapping without any
network access; pretrained checkpoints use the identical state-dict
layout, so parity here is parity there.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _tiny_gpt2(seed: int = 0):
    cfg = transformers.GPT2Config(
        vocab_size=96,
        n_positions=32,
        n_embd=48,
        n_layer=2,
        n_head=4,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    torch.manual_seed(seed)
    hf = transformers.GPT2LMHeadModel(cfg)
    hf.eval()
    return hf


def test_gpt2_logits_match_torch(world):
    from fluxmpi_tpu.models import lm_from_gpt2

    hf = _tiny_gpt2()
    model, variables = lm_from_gpt2(hf)
    assert model.num_layers == 2 and model.d_model == 48
    assert model.ln_eps == hf.config.layer_norm_epsilon

    rng = np.random.default_rng(0)
    toks = rng.integers(0, 96, size=(3, 17)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.from_numpy(toks)).logits.numpy()
    got = np.asarray(
        model.apply(variables, jnp.asarray(toks.astype(np.int32)),
                    train=False)
    )
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_gpt2_import_decodes_and_trains(world):
    # The imported checkpoint drives the framework's own surfaces:
    # greedy generate matches the torch HF .generate() continuation, and
    # a train step on the imported params runs.
    import optax

    from fluxmpi_tpu.models import generate, lm_from_gpt2
    from fluxmpi_tpu.parallel import TrainState, make_train_step
    from fluxmpi_tpu.parallel.train import replicate, shard_batch

    hf = _tiny_gpt2(seed=1)
    model, variables = lm_from_gpt2(hf)

    prompt = np.asarray([[5, 11, 42, 7]], np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=6, do_sample=False,
            pad_token_id=0,
        ).numpy()
    got = np.asarray(
        generate(model, variables, jnp.asarray(prompt.astype(np.int32)), 6)
    )
    np.testing.assert_array_equal(got, want)

    opt = optax.adam(1e-4)
    ts = TrainState.create(variables["params"], opt)

    def loss_fn(p, ms, batch):
        x, y = batch
        losses = model.apply({"params": p}, x, train=False, targets=y)
        return losses.mean(), ms

    step = make_train_step(loss_fn, opt, donate=False)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 96, size=(8, 16)).astype(np.int32))
    y = jnp.asarray(rng.integers(0, 96, size=(8, 16)).astype(np.int32))
    st, loss = step(replicate(ts), shard_batch((x, y)))
    assert np.isfinite(float(loss))


def test_gpt2_import_drift_guard(world):
    # A config whose converted tree cannot match (simulated by tampering
    # with the state dict) fails loudly at conversion, not silently.
    from fluxmpi_tpu.models import lm_from_gpt2

    hf = _tiny_gpt2()
    sd = hf.state_dict()
    bad = {k: v for k, v in sd.items()}
    bad["transformer.wpe.weight"] = torch.zeros((7, 48))

    class Wrapper:
        config = hf.config

        @staticmethod
        def state_dict():
            return bad

    with pytest.raises(ValueError, match="does not match"):
        lm_from_gpt2(Wrapper())


def test_gpt2_unsupported_config_rejected(world):
    from fluxmpi_tpu.models import lm_from_gpt2

    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=1, n_head=2,
        activation_function="relu",
    )
    hf = transformers.GPT2LMHeadModel(cfg)
    with pytest.raises(ValueError, match="activation_function"):
        lm_from_gpt2(hf)

    cfg2 = transformers.GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=1, n_head=2,
        scale_attn_by_inverse_layer_idx=True,
    )
    with pytest.raises(ValueError, match="scale_attn_by_inverse_layer_idx"):
        lm_from_gpt2(transformers.GPT2LMHeadModel(cfg2))


def test_ln_eps_threads_through_moe(world):
    # ln_eps reaches the LayerNorms inside the MoE stack too
    # (regression: the subclass overrides must forward it). An extreme
    # epsilon must change the forward; if the overrides dropped it, both
    # runs would be identical.
    from fluxmpi_tpu.models import MoETransformerLM

    kw = dict(vocab_size=32, max_len=8, num_layers=1, d_model=16,
              num_heads=2, d_ff=32, num_experts=2)
    toks = jnp.zeros((1, 4), jnp.int32)
    base = MoETransformerLM(**kw)
    big = MoETransformerLM(ln_eps=100.0, **kw)
    variables = base.init(jax.random.PRNGKey(0), toks, train=False)
    a = np.asarray(base.apply(variables, toks, train=False))
    b = np.asarray(big.apply(variables, toks, train=False))
    assert not np.allclose(a, b)
