"""Fleet plane tests (telemetry/fleet.py + the persistent_straggler
rule + scripts/fleet_report.py): target resolution and constructor
validation, the straggler attribution engine (all four causes, the
skew threshold, counter resets, desync on frozen flight sequences),
collector tolerance (dead host, garbage and schema-invalid /status),
snapshot schema + closed fleet.* namespace, configure()/env wiring,
the zero-cost-when-off contract in train_loop, the monitor's skew
gauges, and the E2E acceptance loop: a fault-injected data stall on
one virtual host is named straggler with cause data_stall, the
persistent_straggler anomaly fires exactly once per streak, and the
snapshot bank replays through fleet_report.py and
check_metrics_schema.py."""

import http.server
import json
import os
import socketserver
import subprocess
import sys
import threading
import urllib.request
import warnings

import numpy as np
import pytest

import jax
import optax

from fluxmpi_tpu import faults
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import MetricsRegistry, export, get_registry
from fluxmpi_tpu.telemetry import anomaly as anomaly_mod
from fluxmpi_tpu.telemetry import fleet as fleet_mod
from fluxmpi_tpu.telemetry import goodput as goodput_mod
from fluxmpi_tpu.telemetry.export import Exporter
from fluxmpi_tpu.telemetry.fleet import FleetCollector
from fluxmpi_tpu.telemetry.monitor import TrainingMonitor
from fluxmpi_tpu.telemetry.schema import (
    KNOWN_METRIC_NAMES,
    validate_fleet_snapshot,
    validate_record,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FLEET_REPORT = os.path.join(_REPO, "scripts", "fleet_report.py")
_CHECK_SCHEMA = os.path.join(_REPO, "scripts", "check_metrics_schema.py")
_TOP = os.path.join(_REPO, "scripts", "fluxmpi_top.py")


@pytest.fixture(autouse=True)
def _fleet_reset():
    """Every test leaves the module-level plane disarmed — the
    fault-plane leak rule, enforced at the fixture level so a failing
    assertion cannot leak a collector thread into the next test."""
    yield
    fleet_mod.shutdown()


def _exporter(registry=None):
    exp = Exporter(0, "127.0.0.1", registry=registry, deadline=3600.0)
    exp.start()
    return exp


def _stub_server(body: bytes, status: int = 200):
    """A minimal HTTP server answering every GET with ``body`` — the
    wrong-service / torn-response scrape targets."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    srv = socketserver.TCPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.read()


def _mlp_pieces(world, n=256):
    import jax.numpy as jnp

    from fluxmpi_tpu.models import MLP

    model = MLP(features=(8, 8, 1))

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), np.zeros((2, 1), np.float32))
    )
    return loss_fn, opt, params, ArrayDataset((x, x**2))


# ---------------------------------------------------------------------------
# Construction + target resolution
# ---------------------------------------------------------------------------


def test_resolve_targets_and_validation():
    c = FleetCollector("hostA,hostB:9999", interval=1.0)
    assert c.targets == ("hostA:9307", "hostB:9999")
    with pytest.raises(ValueError):
        FleetCollector([])
    with pytest.raises(ValueError):
        FleetCollector(["h:bogus"])
    with pytest.raises(ValueError):
        FleetCollector(["a", "a"])  # duplicate identity
    with pytest.raises(ValueError):
        FleetCollector(["a"], interval=0)
    with pytest.raises(ValueError):
        FleetCollector(["a"], timeout=0)
    with pytest.raises(ValueError):
        FleetCollector(["a"], straggler_threshold=1.0)
    with pytest.raises(ValueError):
        FleetCollector(["a"], cause_significance=1.5)


def test_parse_metrics_text_demangles_and_skips_foreign():
    text = "\n".join(
        [
            "# HELP fluxmpi_comm_block__seconds histogram",
            'fluxmpi_comm_block__seconds_sum{op="allreduce",path="x"} 1.5',
            "fluxmpi_goodput_wall__seconds 10.0",
            "node_cpu_seconds_total 99",  # foreign exporter: skipped
            "torn line without a number trailing",
        ]
    )
    rows = fleet_mod._parse_metrics_text(text)
    by_name = {r["name"]: r for r in rows}
    assert by_name["comm.block_seconds"]["value"] == 1.5
    assert by_name["comm.block_seconds"]["labels"]["op"] == "allreduce"
    assert by_name["goodput.wall_seconds"]["value"] == 10.0
    assert "node_cpu_seconds_total" not in {r["series"] for r in rows}


# ---------------------------------------------------------------------------
# Attribution engine (unit — no HTTP)
# ---------------------------------------------------------------------------


def _collector2():
    return FleetCollector(["a:1", "b:1"], interval=60.0)


def test_attribution_names_data_stall():
    c = _collector2()
    verdict = c._attribute(
        {
            "a:1": {
                "wall_seconds": 10.0, "updates": 10.0,
                "data_stall_seconds": 6.0, "comm_block_seconds": 0.1,
            },
            "b:1": {"wall_seconds": 10.0, "updates": 100.0},
        }
    )
    assert verdict["straggler"] == "a:1"
    assert verdict["cause"] == "data_stall"
    assert verdict["skew"] == pytest.approx(10.0)


def test_attribution_names_comm_wait():
    c = _collector2()
    verdict = c._attribute(
        {
            "a:1": {
                "wall_seconds": 10.0, "updates": 10.0,
                "data_stall_seconds": 0.1, "comm_block_seconds": 5.0,
            },
            "b:1": {"wall_seconds": 10.0, "updates": 100.0},
        }
    )
    assert verdict["straggler"] == "a:1"
    assert verdict["cause"] == "comm_wait"


def test_attribution_falls_through_to_compute():
    c = _collector2()
    verdict = c._attribute(
        {
            "a:1": {
                "wall_seconds": 10.0, "updates": 10.0,
                "data_stall_seconds": 0.2, "comm_block_seconds": 0.2,
            },
            "b:1": {"wall_seconds": 10.0, "updates": 100.0},
        }
    )
    assert verdict["straggler"] == "a:1"
    assert verdict["cause"] == "compute"


def test_attribution_below_threshold_names_nobody():
    c = _collector2()
    verdict = c._attribute(
        {
            "a:1": {"wall_seconds": 10.0, "updates": 10.0},
            "b:1": {"wall_seconds": 10.0, "updates": 12.0},
        }
    )
    assert verdict["straggler"] is None and verdict["cause"] is None
    assert 1.0 < verdict["skew"] < 1.5


def test_attribution_desync_on_frozen_flight_sequence():
    c = _collector2()
    # Interval 1 primes the delta base (cumulative-as-interval).
    c._prev = {
        "a:1": {"wall_seconds": 10.0, "updates": 10.0, "flight_seq": 50.0},
        "b:1": {"wall_seconds": 10.0, "updates": 10.0, "flight_seq": 50.0},
    }
    # Interval 2: a's launch sequence FROZE while b's advanced.
    verdict = c._attribute(
        {
            "a:1": {"wall_seconds": 20.0, "updates": 10.0, "flight_seq": 50.0},
            "b:1": {"wall_seconds": 20.0, "updates": 20.0, "flight_seq": 90.0},
        }
    )
    assert verdict["straggler"] == "a:1"
    assert verdict["cause"] == "desync"
    assert verdict["seq_lag"] == 40.0


def test_deltas_tolerate_counter_reset():
    c = _collector2()
    c._prev["a:1"] = {"wall_seconds": 100.0, "updates": 90.0}
    # The host restarted: cumulative counters fell. The delta must read
    # the new cumulative value as one interval from zero, never negative.
    out = c._deltas("a:1", {"wall_seconds": 5.0, "updates": 4.0})
    assert out["wall_seconds"] == 5.0 and out["updates"] == 4.0


# ---------------------------------------------------------------------------
# persistent_straggler anomaly rule
# ---------------------------------------------------------------------------


def test_persistent_straggler_fires_once_per_streak(tmp_path, monkeypatch):
    monkeypatch.setenv("FLUXMPI_TPU_ANOMALY_DIR", str(tmp_path))
    det = anomaly_mod.AnomalyDetector(persistent_straggler_intervals=3)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert det.observe_straggler("h1") == []  # streak 1
        assert det.observe_straggler("h1") == []  # streak 2
        events = det.observe_straggler("h1")  # streak 3: fires
        assert len(events) == 1
        ev = events[0]
        assert ev["rule"] == "persistent_straggler"
        assert ev["action"] == "warn"  # never halt: outside the SPMD world
        assert ev["host"] == "h1" and ev["value"] == 3.0
        assert det.observe_straggler("h1") == []  # streak 4: once per streak
        # A clean interval re-arms the rule.
        assert det.observe_straggler(None) == []
        assert det.observe_straggler("h1") == []
        assert det.observe_straggler("h1") == []
        assert len(det.observe_straggler("h1")) == 1


def test_persistent_straggler_host_switch_restarts_streak(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("FLUXMPI_TPU_ANOMALY_DIR", str(tmp_path))
    det = anomaly_mod.AnomalyDetector(persistent_straggler_intervals=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert det.observe_straggler("h1") == []
        assert det.observe_straggler("h2") == []  # blame moved: streak 1
        events = det.observe_straggler("h2")
        assert len(events) == 1 and events[0]["host"] == "h2"


def test_persistent_straggler_validates_intervals():
    with pytest.raises(ValueError):
        anomaly_mod.AnomalyDetector(persistent_straggler_intervals=0)


# ---------------------------------------------------------------------------
# Collector tolerance + snapshot schema + metrics
# ---------------------------------------------------------------------------


def test_collector_tolerates_dead_host():
    reg = MetricsRegistry()
    exp = _exporter(registry=reg)
    exp.note_fleet(wall_seconds=5.0, updates=10.0)
    try:
        c = FleetCollector(
            [f"127.0.0.1:{exp.port}", "127.0.0.1:1"],
            interval=60.0, timeout=0.5, registry=MetricsRegistry(),
        )
        snap = c.collect_once()  # must not raise
        live = snap["hosts"][f"127.0.0.1:{exp.port}"]
        dead = snap["hosts"]["127.0.0.1:1"]
        assert live["alive"] is True and live["stale_seconds"] == pytest.approx(
            0.0, abs=5.0
        )
        assert dead["alive"] is False and dead["stale_seconds"] is None
        assert "unreachable" in dead["error"]
        assert validate_fleet_snapshot(snap) == []
    finally:
        exp.stop()


def test_collector_tolerates_garbage_and_invalid_status():
    torn = _stub_server(b'{"schema": "fluxmpi_tpu.status/v1", "tim')
    foreign = _stub_server(json.dumps({"schema": "acme.metrics/v9"}).encode())
    try:
        targets = [
            f"127.0.0.1:{torn.server_address[1]}",
            f"127.0.0.1:{foreign.server_address[1]}",
        ]
        c = FleetCollector(
            targets, interval=60.0, timeout=1.0, registry=MetricsRegistry()
        )
        snap = c.collect_once()  # must not raise
        torn_row = snap["hosts"][targets[0]]
        foreign_row = snap["hosts"][targets[1]]
        assert torn_row["alive"] is False
        assert "unreachable" in torn_row["error"]
        assert foreign_row["alive"] is False
        assert foreign_row["error"] == "invalid /status record"
        assert snap["attribution"]["straggler"] is None
        assert validate_fleet_snapshot(snap) == []
    finally:
        torn.shutdown()
        foreign.shutdown()


def test_collect_records_closed_namespace_metrics():
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    exp_a, exp_b = _exporter(reg_a), _exporter(reg_b)
    exp_a.note_fleet(
        wall_seconds=10.0, updates=10.0, data_stall_seconds=4.0,
        comm_block_seconds=0.1, flight_seq=10.0,
    )
    exp_b.note_fleet(
        wall_seconds=10.0, updates=100.0, data_stall_seconds=0.1,
        comm_block_seconds=0.1, flight_seq=100.0,
    )
    creg = MetricsRegistry()
    try:
        c = FleetCollector(
            [f"127.0.0.1:{exp_a.port}", f"127.0.0.1:{exp_b.port}"],
            interval=60.0, registry=creg,
        )
        snap = c.collect_once()
        assert snap["attribution"]["cause"] == "data_stall"
        assert snap["attribution"]["flight_seq_lag"] == 90.0
        names = {m["name"] for m in creg.snapshot()}
        assert {
            "fleet.hosts", "fleet.hosts_stale", "fleet.collect_seconds",
            "fleet.straggler_intervals", "fleet.flight_seq_lag",
        } <= names
        assert names <= set(KNOWN_METRIC_NAMES) | {
            n for n in names if not n.startswith("fleet.")
        }
        # The flushed record passes the telemetry schema (the closed
        # fleet.* namespace admits exactly the known names).
        assert validate_record(creg.flush()) == []
        # The read API returns the same model the bank gets.
        assert c.snapshot()["collects"] == snap["collects"]
    finally:
        exp_a.stop()
        exp_b.stop()


def test_validate_fleet_snapshot_rejects_drift():
    assert validate_fleet_snapshot({"schema": "nope"})
    good = {
        "schema": "fluxmpi_tpu.fleet/v1",
        "time_unix": 1.0,
        "collects": 1,
        "hosts": {"h:1": {"alive": True, "stale_seconds": 0.0}},
        "attribution": {"straggler": None, "cause": None, "streak": 0},
        "stragglers": {},
    }
    assert validate_fleet_snapshot(good) == []
    bad_cause = json.loads(json.dumps(good))
    bad_cause["attribution"] = {
        "straggler": "h:1", "cause": "gremlins", "streak": 1,
    }
    assert any("cause" in e for e in validate_fleet_snapshot(bad_cause))
    bad_counts = json.loads(json.dumps(good))
    bad_counts["stragglers"] = {"data_stall": -1}
    assert validate_fleet_snapshot(bad_counts)


# ---------------------------------------------------------------------------
# configure() / env wiring
# ---------------------------------------------------------------------------


def test_configure_forms(monkeypatch):
    monkeypatch.delenv("FLUXMPI_TPU_FLEET", raising=False)
    # None + unset env: no-op, stays disarmed.
    assert fleet_mod.configure(None) is None
    assert not fleet_mod.enabled()
    # Explicit collector installs, arms, and starts.
    c = FleetCollector(["127.0.0.1:1"], interval=60.0)
    assert fleet_mod.configure(c) is c
    assert fleet_mod.enabled() and c.running
    # Idempotent replay keeps the running instance.
    assert fleet_mod.configure(True) is c
    # A replacement collector stops the old one.
    c2 = FleetCollector(["127.0.0.1:2"], interval=60.0)
    fleet_mod.configure(c2)
    assert not c.running and c2.running
    # False disarms and stops.
    assert fleet_mod.configure(False) is None
    assert not fleet_mod.enabled() and not c2.running
    # Env-driven arming with an interval + hosts override.
    monkeypatch.setenv("FLUXMPI_TPU_FLEET", "1")
    monkeypatch.setenv("FLUXMPI_TPU_FLEET_HOSTS", "127.0.0.1:1")
    monkeypatch.setenv("FLUXMPI_TPU_FLEET_INTERVAL", "42.5")
    c3 = fleet_mod.configure(None)
    assert fleet_mod.enabled() and c3.interval == 42.5
    assert c3.targets == ("127.0.0.1:1",)
    # "0" resets.
    monkeypatch.setenv("FLUXMPI_TPU_FLEET", "0")
    fleet_mod.configure(None)
    assert not fleet_mod.enabled() and not c3.running
    with pytest.raises(ValueError):
        fleet_mod.configure(3.14)


def test_env_interval_typo_warns_and_uses_default(monkeypatch):
    monkeypatch.setenv("FLUXMPI_TPU_FLEET_INTERVAL", "fast")
    with pytest.warns(UserWarning, match="FLUXMPI_TPU_FLEET_INTERVAL"):
        assert fleet_mod._env_interval() == 5.0


def test_path_spec_banks_snapshots(tmp_path, monkeypatch):
    bank = tmp_path / "fleet.jsonl"
    exp = _exporter(MetricsRegistry())
    monkeypatch.setenv("FLUXMPI_TPU_FLEET_HOSTS", f"127.0.0.1:{exp.port}")
    try:
        c = fleet_mod.configure(str(bank))
        assert c is not None and c.log == str(bank)
        c.collect_once()
        lines = bank.read_text().splitlines()
        assert len(lines) == 1
        assert validate_fleet_snapshot(json.loads(lines[0])) == []
    finally:
        exp.stop()


# ---------------------------------------------------------------------------
# train_loop / monitor wiring
# ---------------------------------------------------------------------------


def test_train_loop_fleet_off_never_posts_ingredients(world, monkeypatch):
    """The zero-cost contract, monkeypatch-explode style: exporter ON
    but fleet OFF, a run must never touch the FLEET board or the
    collector."""
    assert not fleet_mod.enabled()

    def explode(*a, **k):
        raise AssertionError("fleet plane touched on the fully-off path")

    monkeypatch.setattr(Exporter, "note_fleet", explode)
    monkeypatch.setattr(FleetCollector, "collect_once", explode)
    get_registry().reset()
    export.configure(Exporter(0, "127.0.0.1", deadline=3600.0))
    try:
        loss_fn, opt, params, ds = _mlp_pieces(world)
        loader = DistributedDataLoader(ds, 64, mesh=world)
        step = make_train_step(loss_fn, opt, mesh=world)
        state = replicate(TrainState.create(params, opt, None), world)
        _, summary = train_loop(step, state, loader, epochs=1, flush_every=2)
        assert summary["updates"] == 4
    finally:
        export.shutdown()


def test_monitor_skew_gauges_ride_the_collect(monkeypatch):
    reg = MetricsRegistry()
    reg.histogram("comm.block_seconds", op="allreduce", path="x").observe(0.5)
    mon = TrainingMonitor(registry=reg, interval=2, cross_host=False)
    # Off: no fleet.* gauges on the collect.
    mon.observe_step(0.1)
    summary = mon.observe_step(0.1)
    assert "step_time_skew" not in summary
    # Armed: the same collect publishes the skew trio (single host: a
    # 1.0 ratio and zero spreads — the degenerate-but-schema'd shape).
    fleet_mod.configure(FleetCollector(["127.0.0.1:1"], interval=60.0))
    mon.observe_step(0.1)
    summary = mon.observe_step(0.1)
    assert summary["step_time_skew"] == pytest.approx(1.0)
    assert summary["collective_skew_seconds"] == 0.0
    assert summary["flight_seq_lag"] == 0.0
    names = {m["name"] for m in reg.snapshot()}
    assert {
        "fleet.step_time_skew",
        "fleet.collective_skew_seconds",
        "fleet.flight_seq_lag",
    } <= names


# ---------------------------------------------------------------------------
# fleet_report.py CLI
# ---------------------------------------------------------------------------


def test_fleet_report_exit_codes(tmp_path):
    # Readable input with no fleet snapshots -> exit 1, pointed message.
    plain = tmp_path / "plain.jsonl"
    plain.write_text('{"schema": "fluxmpi_tpu.telemetry/v1"}\n')
    proc = subprocess.run(
        [sys.executable, _FLEET_REPORT, str(plain)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "FLUXMPI_TPU_FLEET" in proc.stderr
    # Missing file -> exit 2.
    proc = subprocess.run(
        [sys.executable, _FLEET_REPORT, str(tmp_path / "missing.jsonl")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2


def test_fleet_report_tolerates_torn_line(tmp_path):
    bank = tmp_path / "torn.jsonl"
    snap = {
        "schema": "fluxmpi_tpu.fleet/v1", "time_unix": 1.0, "collects": 1,
        "hosts": {"h:1": {"alive": True, "stale_seconds": 0.1}},
        "attribution": {
            "straggler": "h:1", "cause": "compute", "skew": 2.0, "streak": 1,
        },
        "stragglers": {"compute": 1},
    }
    bank.write_text(json.dumps(snap) + "\n" + '{"schema": "fluxmpi_tp')
    proc = subprocess.run(
        [sys.executable, _FLEET_REPORT, str(bank)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "skipping" in proc.stderr
    assert "cause compute" in proc.stdout


# ---------------------------------------------------------------------------
# E2E acceptance: fault-injected stall -> attribution -> bank round trip
# ---------------------------------------------------------------------------


def test_e2e_fleet_names_stalled_host(world, tmp_path, monkeypatch):
    """The acceptance loop: two virtual hosts (this process's real run
    + a synthetic healthy peer), a fault-injected data.fetch delay on
    the real one. The collector names the stalled host straggler with
    cause data_stall, persistent_straggler fires exactly once per
    streak, the bank replays through fleet_report.py with the same
    attribution, and every snapshot line passes
    check_metrics_schema.py."""
    monkeypatch.setenv("FLUXMPI_TPU_ANOMALY_DIR", str(tmp_path))
    get_registry().reset()
    bank = tmp_path / "fleet.jsonl"
    # Virtual healthy peer: its own registry + exporter + FLEET board
    # reading as a fast host (tiny per-update wall, no badput).
    exp_b = _exporter(MetricsRegistry())
    exp_b.note_fleet(
        wall_seconds=10.0, step_seconds=9.5, data_stall_seconds=0.1,
        host_idle_seconds=0.4, comm_block_seconds=0.05,
        updates=2000.0, flight_seq=2000.0,
    )
    # The real host: live exporter over the global registry; goodput +
    # fleet planes armed so train_loop posts real ingredients.
    exp_a = Exporter(0, "127.0.0.1", deadline=3600.0)
    export.configure(exp_a)
    goodput_mod.configure(True)
    a_target = f"127.0.0.1:{exp_a.port}"
    b_target = f"127.0.0.1:{exp_b.port}"
    detector = anomaly_mod.AnomalyDetector(persistent_straggler_intervals=2)
    collector = FleetCollector(
        [a_target, b_target], interval=60.0, log=str(bank),
        registry=MetricsRegistry(), detector=detector,
    )
    fleet_mod.configure(collector)
    try:
        loss_fn, opt, params, ds = _mlp_pieces(world)
        loader = DistributedDataLoader(ds, 64, mesh=world)
        step = make_train_step(loss_fn, opt, mesh=world, metrics=True)
        state = replicate(TrainState.create(params, opt, None), world)
        # Six fetches each stall 0.2 s: the run's badput is dominated
        # by the data_stall bucket (>= 1.2 s of a few-second wall).
        with faults.scope("data.fetch:delay=0.2:times=6"):
            _, summary = train_loop(
                step, state, loader, epochs=2, flush_every=2, fuse=False
            )
        status = json.loads(_get(exp_a.port, "/status"))
        board = status["fleet"]  # the per-flush ingredient post
        assert board["updates"] == summary["updates"]
        assert board["data_stall_seconds"] >= 1.0
        # A single-process mesh run issues no explicit comm-layer
        # collectives, so the flight sequence legitimately reads 0 —
        # the key must still be on the board for the collector.
        assert board["flight_seq"] >= 0
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            snaps = [collector.collect_once() for _ in range(3)]
        for snap in snaps:
            assert snap["attribution"]["straggler"] == a_target
            assert snap["attribution"]["cause"] == "data_stall"
            assert validate_fleet_snapshot(snap) == []
        assert [s["attribution"]["streak"] for s in snaps] == [1, 2, 3]
        assert snaps[-1]["stragglers"] == {"data_stall": 3}
        fired = [
            w for w in caught if "persistent_straggler" in str(w.message)
        ]
        assert len(fired) == 1, "once per streak, not per interval"
        # The verdict is on the local /status FLEET board (fluxmpi_top's
        # surface) next to the ingredients.
        board = json.loads(_get(exp_a.port, "/status"))["fleet"]
        assert board["straggler"] == a_target
        assert board["cause"] == "data_stall" and board["collects"] == 3
        top = subprocess.run(
            [sys.executable, _TOP, a_target, "--once"],
            capture_output=True, text=True, timeout=60,
        )
        assert top.returncode == 0, top.stderr
        assert "FLEET" in top.stdout and "data_stall" in top.stdout
        # Bank round trip: fleet_report reads the same attribution back.
        rep = subprocess.run(
            [sys.executable, _FLEET_REPORT, str(bank), "--json"],
            capture_output=True, text=True,
        )
        assert rep.returncode == 0, rep.stderr
        agg = json.loads(rep.stdout)
        assert agg["snapshots"] == 3
        assert agg["attribution"]["straggler"] == a_target
        assert agg["attribution"]["cause"] == "data_stall"
        assert agg["stragglers"] == {"data_stall": 3}
        assert agg["blamed"][a_target]["intervals"] == 3
        # And every bank line is schema-clean.
        chk = subprocess.run(
            [sys.executable, _CHECK_SCHEMA, str(bank)],
            capture_output=True, text=True,
        )
        assert chk.returncode == 0, chk.stdout + chk.stderr
    finally:
        goodput_mod.configure(False)
        export.shutdown()
        exp_b.stop()
