"""Preemption-safe resumable training tests: loader state_dict /
load_state_dict (mid-epoch-exact on the host, native, and device-gather
paths), kill-and-resume equivalence (crash at an injected fault →
resume → bit-identical final state), preemption drain-and-exit, and the
restart-proof budget semantics. Fast chaos tests only — the real-SIGTERM
subprocess variant is slow-marked at the bottom."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import fluxmpi_tpu as fm
from fluxmpi_tpu import faults
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.errors import FaultInjectedError
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.telemetry import MetricsRegistry
from fluxmpi_tpu.utils import CheckpointManager


@pytest.fixture(autouse=True)
def _clean_flags():
    faults.clear()
    fm.clear_preemption()
    yield
    faults.clear()
    fm.clear_preemption()


def _leaves_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        ),
        a, b,
    )


# ---------------------------------------------------------------------------
# Loader state_dict / load_state_dict
# ---------------------------------------------------------------------------


def _dataset(n=64, d=2):
    rng = np.random.default_rng(0)
    return ArrayDataset(
        (rng.normal(size=(n, d)).astype(np.float32),
         np.arange(n, dtype=np.int32))
    )


def _batch_ids(batch):
    # The int leaf identifies which samples a batch holds.
    return np.asarray(jax.device_get(batch[1])).tolist()


@pytest.mark.parametrize("path", ["host", "native", "device_gather"])
def test_loader_mid_epoch_resume_is_exact(world, path):
    kwargs = dict(shuffle=True, seed=11, prefetch=2)
    if path == "device_gather":
        kwargs["device_gather"] = True
    else:
        kwargs["device_gather"] = False
    if path == "host":
        # Defeat the array-backed native fast path: wrap in a plain
        # indexable container so batches assemble sample by sample.
        class Plain:
            def __init__(self, ds):
                self.ds = ds

            def __len__(self):
                return len(self.ds)

            def __getitem__(self, i):
                return self.ds[i]

        data = Plain(_dataset())
    else:
        data = _dataset()

    full = DistributedDataLoader(data, 16, mesh=world, **kwargs)
    reference = []
    for epoch_batches in range(2):  # epochs 0 and 1, 4 batches each
        for b in full:
            reference.append(_batch_ids(b))

    # Consume 2 epochs-worth in an interrupted/resumed pattern: stop the
    # first loader mid-epoch 0, hand its state to a FRESH loader (a new
    # process), finish epoch 0 and run epoch 1 there.
    first = DistributedDataLoader(data, 16, mesh=world, **kwargs)
    it = iter(first)
    got = [_batch_ids(next(it)) for _ in range(2)]  # 2 of 4 batches
    saved = first.state_dict()
    assert saved == {"epoch": 0, "cursor": 2, "seed": 11}
    del first, it

    resumed = DistributedDataLoader(data, 16, mesh=world, **kwargs)
    resumed.load_state_dict(saved)
    for b in resumed:  # rest of epoch 0
        got.append(_batch_ids(b))
    for b in resumed:  # epoch 1 continues the epoch sequence
        got.append(_batch_ids(b))
    assert got == reference


def test_loader_state_at_epoch_end_resumes_next_epoch(world):
    loader = DistributedDataLoader(_dataset(), 16, mesh=world, shuffle=True,
                                   seed=3)
    seq_epoch1 = [_batch_ids(b) for b in loader][:0]  # consume epoch 0
    state = loader.state_dict()
    assert state["cursor"] == len(loader)
    fresh = DistributedDataLoader(_dataset(), 16, mesh=world, shuffle=True,
                                  seed=3)
    fresh.load_state_dict(state)
    ref = DistributedDataLoader(_dataset(), 16, mesh=world, shuffle=True,
                                seed=3)
    ref.set_epoch(1)
    assert [_batch_ids(b) for b in fresh] == [_batch_ids(b) for b in ref]


def test_loader_rejects_foreign_state(world):
    loader = DistributedDataLoader(_dataset(), 16, mesh=world, seed=1)
    with pytest.raises(ValueError, match="seed"):
        loader.load_state_dict({"epoch": 0, "cursor": 1, "seed": 2})
    with pytest.raises(ValueError, match="cursor"):
        loader.load_state_dict({"epoch": 0, "cursor": 99, "seed": 1})


def test_loader_transform_rng_keys_by_absolute_batch_index(world):
    # A resumed pass must hand the transform the SAME per-batch rng
    # streams the uninterrupted pass used — keyed by absolute index.
    draws = {}

    def noisy(batch, rng):
        draws[len(draws)] = float(rng.random())
        return batch

    def run(skip):
        draws.clear()
        loader = DistributedDataLoader(
            _dataset(), 16, mesh=world, seed=5, transform=noisy,
            device_gather=False, prefetch=0,
        )
        if skip:
            loader.load_state_dict({"epoch": 0, "cursor": skip, "seed": 5})
        for _ in loader:
            pass
        return dict(draws)

    uninterrupted = run(0)
    resumed = run(2)
    assert resumed[0] == uninterrupted[2]
    assert resumed[1] == uninterrupted[3]


def test_loader_trace_batch_index_keys_by_absolute_position(world):
    # The data.fetch trace timeline must line up batch-for-batch with the
    # uninterrupted run's: a resumed pass starts at batch `cursor`, not 0.
    from fluxmpi_tpu.telemetry import Tracer, tracing

    loader = DistributedDataLoader(
        _dataset(), 16, mesh=world, seed=11, prefetch=0
    )
    loader.load_state_dict({"epoch": 0, "cursor": 2, "seed": 11})
    tr = Tracer(enabled=True)
    prev = tracing.set_tracer(tr)
    try:
        consumed = sum(1 for _ in loader)
    finally:
        tracing.set_tracer(prev)
    fetches = [e for e in tr.export()["traceEvents"]
               if e["name"] == "data.fetch"]
    assert consumed == 2  # 4-batch epoch resumed at batch 2
    assert [e["args"]["batch"] for e in fetches] == [2, 3]


# ---------------------------------------------------------------------------
# Kill-and-resume equivalence on the training loop
# ---------------------------------------------------------------------------


def _pieces(world, n=128):
    from fluxmpi_tpu.models import MLP

    model = MLP(features=(16, 1))

    def loss_fn(p, ms, b):
        bx, by = b
        return jnp.mean((model.apply(p, bx) - by) ** 2), ms

    opt = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    x = rng.uniform(-2, 2, size=(n, 1)).astype(np.float32)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1)))
    )
    ds = ArrayDataset((x, x**2))

    def fresh():
        return replicate(TrainState.create(params, opt), world)

    def loader():
        # prefetch=0 so a data.fetch fault hit maps 1:1 to a consumer
        # batch (with read-ahead the prefetcher crashes a couple of
        # batches early — same recovery semantics, fuzzier arithmetic).
        return DistributedDataLoader(ds, 32, mesh=world, shuffle=True,
                                     seed=7, device_gather=False, prefetch=0)

    return loss_fn, opt, fresh, loader


@pytest.mark.parametrize("crash_hit", [3, 7])  # mid-epoch 1 and mid-epoch 2
def test_kill_and_resume_reaches_bit_identical_state(world, tmp_path, crash_hit):
    """Crash-at-step-k (injected data.fetch fault) + resume ==
    uninterrupted run, bit-identical final params on the host path —
    including mid-epoch crash points (4-batch epochs, steps span 3)."""
    loss_fn, opt, fresh, loader = _pieces(world)

    step = make_train_step(loss_fn, opt, mesh=world)
    state_ref, summary_ref = train_loop(step, fresh(), loader(), steps=10)
    assert summary_ref["updates"] == 10

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    step2 = make_train_step(loss_fn, opt, mesh=world)
    with faults.scope(f"data.fetch@step={crash_hit}"):
        with pytest.raises(FaultInjectedError):
            train_loop(step2, fresh(), loader(), steps=10,
                       checkpoint=mgr, save_every=2)
    banked = mgr.latest_step()
    assert banked is not None  # something was banked pre-crash

    # "New process": fresh manager, fresh loader, fresh compiled step.
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    step3 = make_train_step(loss_fn, opt, mesh=world)
    state_res, summary = train_loop(step3, fresh(), loader(), steps=10,
                                    checkpoint=mgr2, save_every=2,
                                    resume=True)
    assert summary["resumed_from"] == banked
    assert summary["updates"] == 10
    assert summary["epochs"] == summary_ref["epochs"]
    assert summary["examples"] == summary_ref["examples"]
    _leaves_equal(state_res.params, state_ref.params)
    _leaves_equal(state_res.opt_state, state_ref.opt_state)


def test_kill_and_resume_with_scan_steps(world, tmp_path):
    # Multi-step dispatch: resume replays whole scan groups exactly.
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    state_ref, _ = train_loop(step, fresh(), loader(), steps=8)

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    step2 = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    with faults.scope("data.fetch@step=6"):
        with pytest.raises(FaultInjectedError):
            train_loop(step2, fresh(), loader(), steps=8,
                       checkpoint=mgr, save_every=2)
    step3 = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    state_res, summary = train_loop(step3, fresh(), loader(), steps=8,
                                    checkpoint=mgr, resume=True)
    assert summary["updates"] == 8
    _leaves_equal(state_res.params, state_ref.params)


def test_resume_on_empty_directory_starts_fresh(world, tmp_path):
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    state, summary = train_loop(step, fresh(), loader(), steps=4,
                                checkpoint=mgr, save_every=2, resume=True)
    assert summary["resumed_from"] is None
    assert summary["updates"] == 4
    assert mgr.latest_step() == 4


def test_resume_past_budget_returns_immediately(world, tmp_path):
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), loader(), steps=6, checkpoint=mgr, save_every=2)
    _, summary = train_loop(step, fresh(), loader(), steps=6,
                            checkpoint=mgr, resume=True)
    assert summary["updates"] == 6  # total budget already met: no-op run
    assert summary["resumed_from"] == 6


def test_resume_counts_metrics_and_validation(world, tmp_path):
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), loader(), steps=4, checkpoint=mgr, save_every=2)
    reg = MetricsRegistry()
    _, summary = train_loop(step, fresh(), loader(), steps=8,
                            checkpoint=mgr, save_every=2, resume=True,
                            metrics=reg)
    assert reg.counter("train.resumes").value == 1
    assert summary["updates"] == 8
    with pytest.raises(ValueError, match="save_every requires"):
        train_loop(step, fresh(), loader(), steps=1, save_every=2)
    with pytest.raises(ValueError, match="resume=True requires"):
        train_loop(step, fresh(), loader(), steps=1, resume=True)
    with pytest.raises(ValueError, match="save_every must be"):
        train_loop(step, fresh(), loader(), steps=1, checkpoint=mgr,
                   save_every=0)


def test_resume_reads_manifest_exactly_once(world, tmp_path, monkeypatch):
    """train_loop(resume=True) reads+validates the topology sidecar ONCE
    and passes it through to restore — the PR 6 'known cost' double read
    (read_manifest in the loop, read_manifest again inside
    restore_checkpoint) is gone."""
    from fluxmpi_tpu.utils import manifest as manifest_mod

    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    train_loop(step, fresh(), loader(), steps=4,
               checkpoint=mgr, save_every=2)

    calls = []
    real = manifest_mod.read_manifest

    def counting(path):
        calls.append(path)
        return real(path)

    monkeypatch.setattr(manifest_mod, "read_manifest", counting)
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    _, summary = train_loop(step, fresh(), loader(), steps=8,
                            checkpoint=mgr2, save_every=2, resume=True)
    assert summary["resumed_from"] is not None
    assert len(calls) == 1, calls


def test_resume_epoch_accounting_at_exact_boundary(world, tmp_path):
    """A save landing exactly at the end of a pass must bank that pass
    exactly once — via the in-loop save (crash path) AND via the
    post-drain emergency save (preemption path)."""
    loss_fn, opt, fresh, loader = _pieces(world)  # 4 batches/epoch
    step = make_train_step(loss_fn, opt, mesh=world)

    # Crash path: save at updates=4 (end of epoch 0), crash on the very
    # next fetch (hit 5 is epoch 1's first batch — exhaustion probes
    # never count a hit).
    mgr = CheckpointManager(str(tmp_path / "a"), async_save=False)
    with faults.scope("data.fetch@step=5"):
        with pytest.raises(FaultInjectedError):
            train_loop(step, fresh(), loader(), epochs=3,
                       checkpoint=mgr, save_every=4)
    assert mgr.latest_step() == 4
    _, summary = train_loop(step, fresh(), loader(), epochs=3,
                            checkpoint=mgr, resume=True)
    assert summary["epochs"] == 3 and summary["updates"] == 12

    # Preemption path: the flag lands at the flush closing epoch 0, the
    # loop exits there, and the emergency save (which runs AFTER the
    # pass was counted) must bank the identical accounting.
    mgr2 = CheckpointManager(str(tmp_path / "b"), async_save=False)
    fired = []

    def hook(record):
        if not fired:
            fired.append(True)
            fm.request_preemption()

    _, s2 = train_loop(step, fresh(), loader(), epochs=3, flush_every=4,
                       metrics=hook, checkpoint=mgr2)
    assert s2["preempted"] and s2["updates"] == 4 and s2["epochs"] == 1
    fm.clear_preemption()
    _, s3 = train_loop(step, fresh(), loader(), epochs=3,
                       checkpoint=mgr2, resume=True)
    assert s3["epochs"] == 3 and s3["updates"] == 12


# ---------------------------------------------------------------------------
# Preemption: drain, emergency checkpoint, clean return
# ---------------------------------------------------------------------------


def test_preemption_drains_and_banks_emergency_checkpoint(world, tmp_path):
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)

    def hook(record):
        fm.request_preemption()  # "SIGTERM" lands mid-run

    state, summary = train_loop(step, fresh(), loader(), steps=100,
                                flush_every=3, metrics=hook,
                                checkpoint=mgr)
    assert summary["preempted"] is True
    assert 0 < summary["updates"] < 100  # stopped at a dispatch boundary
    # The emergency checkpoint is committed and resumable...
    assert mgr.latest_step() == summary["updates"]
    # ...and the banked state equals what the loop returned.
    mgr2 = CheckpointManager(str(tmp_path / "run"), async_save=False)
    fm.clear_preemption()
    state_res, summary2 = train_loop(step, fresh(), loader(), steps=100,
                                     checkpoint=mgr2, resume=True)
    assert summary2["resumed_from"] == summary["updates"]
    assert summary2["updates"] == 100
    assert summary2["preempted"] is False


def test_preemption_equivalence_with_uninterrupted(world, tmp_path):
    # Preempt + resume must reproduce the uninterrupted run exactly,
    # like a crash does — preemption is just the polite spelling.
    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    state_ref, _ = train_loop(step, fresh(), loader(), steps=10)

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    fired = []

    def hook(record):
        if not fired:
            fired.append(True)
            fm.request_preemption()

    step2 = make_train_step(loss_fn, opt, mesh=world)
    _, s1 = train_loop(step2, fresh(), loader(), steps=10, flush_every=3,
                       metrics=hook, checkpoint=mgr)
    assert s1["preempted"] and s1["updates"] < 10
    fm.clear_preemption()
    step3 = make_train_step(loss_fn, opt, mesh=world)
    state_res, s2 = train_loop(step3, fresh(), loader(), steps=10,
                               checkpoint=mgr, resume=True)
    assert s2["updates"] == 10
    _leaves_equal(state_res.params, state_ref.params)


def test_preemption_at_ragged_scan_boundary_counts_epoch_once(world,
                                                              tmp_path):
    """Preempting at the FINAL scan group of a ragged epoch (5 batches,
    k=2 → 2 dispatches + a dropped tail) banks the pass exactly once:
    the emergency save must not leave a mid-epoch cursor whose empty
    replay would count the same pass again on resume."""
    loss_fn, opt, fresh, loader = _pieces(world, n=160)  # 5 batches/epoch
    step = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    state_ref, s_ref = train_loop(step, fresh(), loader(), epochs=3)
    assert s_ref["updates"] == 12  # 3 epochs x 2 scan groups x 2 updates

    mgr = CheckpointManager(str(tmp_path / "run"), async_save=False)
    fired = []

    def hook(record):
        if not fired:
            fired.append(True)
            fm.request_preemption()

    step2 = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    # flush_every=4 → the hook fires right after the 2nd (last) scan
    # dispatch of epoch 0, with the ragged tail never dispatched.
    _, s1 = train_loop(step2, fresh(), loader(), epochs=3, flush_every=4,
                       metrics=hook, checkpoint=mgr)
    assert s1["preempted"] and s1["updates"] == 4 and s1["epochs"] == 1
    fm.clear_preemption()
    step3 = make_train_step(loss_fn, opt, mesh=world, scan_steps=2)
    state_res, s2 = train_loop(step3, fresh(), loader(), epochs=3,
                               checkpoint=mgr, resume=True)
    assert s2["epochs"] == 3 and s2["updates"] == 12
    _leaves_equal(state_res.params, state_ref.params)


def test_preemption_emits_trace_instant(world, tmp_path):
    from fluxmpi_tpu.telemetry import Tracer, get_tracer, set_tracer
    from fluxmpi_tpu.telemetry.schema import validate_trace_export

    loss_fn, opt, fresh, loader = _pieces(world)
    step = make_train_step(loss_fn, opt, mesh=world)
    old = get_tracer()
    tracer = Tracer(enabled=True)
    set_tracer(tracer)
    try:
        def hook(record):
            fm.request_preemption()

        _, summary = train_loop(step, fresh(), loader(), steps=100,
                                flush_every=2, metrics=hook)
        assert summary["preempted"] is True
        export = tracer.export()
        assert validate_trace_export(export) == []
        instants = [e for e in export["traceEvents"]
                    if e.get("name") == "train.preemption"]
        assert len(instants) == 1
        assert instants[0]["args"]["step"] == summary["updates"]
    finally:
        set_tracer(old)


def test_sigterm_handler_sets_flag_only(world):
    # The installed handler is signal-safe: it sets the flag, nothing
    # else; uninstall restores the previous handler.
    prev = signal.getsignal(signal.SIGTERM)
    fm.install_preemption_handlers((signal.SIGTERM,))
    try:
        assert not fm.preemption_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(1000):
            if fm.preemption_requested():
                break
        assert fm.preemption_requested()
    finally:
        fm.uninstall_preemption_handlers()
    assert signal.getsignal(signal.SIGTERM) is prev
    assert not fm.preemption_requested()  # uninstall clears the flag


# ---------------------------------------------------------------------------
# Real-SIGTERM subprocess variant (slow)
# ---------------------------------------------------------------------------

_CHILD = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax.numpy as jnp
import jax, optax
import fluxmpi_tpu as fm
from fluxmpi_tpu.data import ArrayDataset, DistributedDataLoader
from fluxmpi_tpu.parallel import TrainState, make_train_step, train_loop
from fluxmpi_tpu.parallel.train import replicate
from fluxmpi_tpu.utils import CheckpointManager
from fluxmpi_tpu.models import MLP

mesh = fm.init(preemption=True)  # installs the SIGTERM/SIGINT handler
model = MLP(features=(16, 1))

def loss_fn(p, ms, b):
    bx, by = b
    return jnp.mean((model.apply(p, bx) - by) ** 2), ms

opt = optax.adam(1e-3)
x = np.linspace(-2, 2, 256, dtype=np.float32)[:, None]
loader = DistributedDataLoader(ArrayDataset((x, x**2)), 32, mesh=mesh)
params = jax.device_get(model.init(jax.random.PRNGKey(0), x[:2]))
state = replicate(TrainState.create(params, opt), mesh)
step = make_train_step(loss_fn, opt, mesh=mesh)
mgr = CheckpointManager(sys.argv[1], async_save=False)
print("READY", flush=True)
state, summary = train_loop(step, state, loader, steps=10**9,
                            checkpoint=mgr, save_every=1000,
                            flush_every=10**9)
print("SUMMARY " + json.dumps(
    {"updates": summary["updates"], "preempted": summary["preempted"],
     "latest": mgr.latest_step()}), flush=True)
"""


@pytest.mark.slow
def test_real_sigterm_preempts_cleanly(world, tmp_path):
    """A real SIGTERM mid-training: the process exits 0 (no traceback),
    reports preempted=True, and leaves a committed checkpoint whose step
    matches the summary."""
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    ckpt_dir = tmp_path / "ckpts"
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, str(script), str(ckpt_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
    )
    try:
        import time as _time

        deadline = _time.monotonic() + 240
        assert proc.stdout.readline().strip() == "READY"
        # Let it train past the first warmup dispatches, then preempt.
        _time.sleep(3.0)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=max(1.0, deadline - _time.monotonic()))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out
    summary_lines = [ln for ln in out.splitlines() if ln.startswith("SUMMARY ")]
    assert summary_lines, out
    summary = json.loads(summary_lines[-1][len("SUMMARY "):])
    assert summary["preempted"] is True
    assert summary["updates"] > 0
    assert summary["latest"] == summary["updates"]
    # Committed on disk: the step dir and its COMMIT marker both exist.
    name = f"step_{summary['updates']:08d}"
    assert (ckpt_dir / name).is_dir()
    assert (ckpt_dir / (name + ".fluxmpi_layout")).exists()
